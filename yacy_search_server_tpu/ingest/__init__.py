"""Streaming ingest — the write path as a first-class subsystem (ISSUE 13).

Every headline so far (BENCH_r06/r07, MULTICHIP_r06, CHAOS_r01) measured
a FROZEN index; the paper's system is a crawler-indexer first: every
node crawls, parses, flushes, merges and tier-promotes *while* serving.
This package gives that write path the same production discipline the
read path earned over rounds 6–16:

- :mod:`ingest.slo` — the **crawl-to-searchable SLO**: documents are
  stamped at pipeline entry (``Switchboard.to_indexer``), the stamp
  rides the IndexingEntry through parse → store → RWI flush → device
  tier pack, and time-to-first-serve lands in its own histogram
  families (``ingest.searchable`` / ``ingest.flushed`` /
  ``ingest.device``) with an ``ingest_slo_searchable`` health rule in
  the M79 engine.  The bounded RAM buffer's blocking backpressure wall
  (``ingest.backpressure``) is counted here too, so a stalled write
  path is attributable, never silent.
- :mod:`ingest.devbuild` — **device-side index build**: the vmapped
  ``_pack_block_batch_kernel`` bit-packs whole runs of posting blocks
  in one dispatch per pow2 row bucket, bit-identical to the host
  ``ops/packed.pack_block`` (parity-pinned), with a registered roofline
  cost model like every kernel family — fresh runs land pre-packed and
  the flush/merge pack stall becomes device work.
- :mod:`ingest.scheduler` — the **merge/promotion scheduler**, actuated
  by the M83 ``merge_scheduler`` actuator: compactions and tier
  promotions DEFER while the serving SLO burns and CATCH UP when the
  node is healthy again, with pinned series, breadcrumbs and the
  no-dead-actuators hygiene gate.

``bench.py --ingest-soak`` proves the whole loop: sustained indexing at
N docs/s under the standard query soak, gating serving p95 regression,
crawl-to-searchable p95 per tier, the deferral actuator engaging under
an injected burst, and zero acked-doc loss across mid-soak kill−9
crash points (committed as INGEST_r01.json; ``--smoke`` is the tier-1
variant).

Import discipline: this package root (and :mod:`slo` / :mod:`scheduler`)
stays jax-free — the crash-chaos subprocess harness imports the RWI
write path in dozens of short-lived interpreters.  Only
:mod:`devbuild` touches jax, and only its call sites import it.
"""

from . import scheduler, slo  # noqa: F401  (jax-free by contract)
