"""Merge/promotion scheduler — the write path's actuator body (ISSUE 13c).

Compactions (RWI run merges) and tier promotions are the write path's
two heavy background moves: a full merge rewrites the run set, and a
promotion ships packed blocks through the same tunnel the query waves
ride.  Until now their timing was ad hoc (the cleanup busy thread
merged whenever a device join flagged a hot term; promotions fired on
every tier miss) — under a serving burn they pile exactly the work the
node can least afford.

This scheduler closes that gap with the M83 actuator discipline: the
``merge_scheduler`` actuator (utils/actuator.py) flips it to DEFERRED
while the ``slo_serving_p95`` burn-rate rule is critical and back after
the engine's hysteresis, emitting a breadcrumb per transition.  While
deferred:

- ``request_merge`` (the cleanup job's merge path) records the ask and
  returns without merging — the SMALLEST ``max_runs`` asked for wins,
  so the catch-up performs the most aggressive compaction requested;
- the devstore's ``_submit_promote`` parks promotions in a deferred set
  (counted; the triggering queries host-serve, which they were already
  doing — a miss never waits on a promotion).

``catch_up()`` (the actuator's recovery edge) runs the pending merge
and resubmits every parked promotion.  Every deferral and catch-up is
counted and exported (``yacy_ingest_total{counter=...}`` +
``yacy_ingest_deferred``), so the no-dead-actuators hygiene gate holds
and a postmortem reads the deferral next to the burn that caused it.

Jax-free by contract (see the package docstring).
"""

from __future__ import annotations

import logging
import threading
import time

log = logging.getLogger("yacy.ingest")


class MergeScheduler:
    """Owns the defer/catch-up state for ONE node's write path.  All
    decisions are taken by the ``merge_scheduler`` actuator on the
    health tick; the write path only ever asks ``defer_promotions()``
    / ``request_merge()`` — one lock-free-ish read on the hot path."""

    def __init__(self, sb):
        self.sb = sb
        self._lock = threading.Lock()
        self.deferred = False
        self.defer_since = 0.0
        # the deferred merge ask: None, or the smallest max_runs asked
        self._pending_merge: int | None = None
        self.merge_deferrals = 0
        self.promote_deferrals = 0     # bumped by devstore._submit_promote
        self.merge_catch_ups = 0
        self.catch_up_merges = 0
        self.catch_up_promotions = 0

    # -- actuation surface (merge_scheduler actuator) ------------------------

    def set_deferred(self, on: bool) -> None:
        with self._lock:
            self.deferred = bool(on)
            self.defer_since = time.monotonic() if on else 0.0

    def defer_promotions(self) -> bool:
        """The devstore's gate: park promotions instead of submitting
        (the hot path reads one attribute; no lock)."""
        return self.deferred

    def note_promote_deferred(self) -> None:
        with self._lock:
            self.promote_deferrals += 1

    def catch_up(self) -> dict:
        """The recovery edge: run the pending merge (smallest-max_runs
        ask wins — the most aggressive compaction requested while
        deferred) and resubmit every parked promotion.  Returns the
        evidence dict the actuator breadcrumb carries."""
        with self._lock:
            pending = self._pending_merge
            self._pending_merge = None
        merged = False
        if pending is not None:
            try:
                merged = bool(self.sb.index.rwi.merge_runs(
                    max_runs=pending))
            except Exception:
                log.warning("catch-up RWI merge failed", exc_info=True)
        ds = getattr(self.sb.index, "devstore", None)
        resumed = 0
        fn = getattr(ds, "resume_promotions", None)
        if fn is not None:
            try:
                resumed = fn()
            except Exception:
                log.warning("catch-up promotion resume failed",
                            exc_info=True)
        with self._lock:
            self.merge_catch_ups += 1
            self.catch_up_merges += int(merged)
            self.catch_up_promotions += resumed
        return {"pending_merge_ran": merged,
                "pending_max_runs": pending,
                "promotions_resumed": resumed}

    # -- write-path surface --------------------------------------------------

    def request_merge(self, max_runs: int = 8) -> bool:
        """The cleanup job's merge entry: defer (counted, smallest ask
        retained) while the serving SLO burns, else merge now.
        Returns True when a merge actually ran."""
        with self._lock:
            if self.deferred:
                self.merge_deferrals += 1
                self._pending_merge = max_runs \
                    if self._pending_merge is None \
                    else min(self._pending_merge, max_runs)
                return False
        return bool(self.sb.index.rwi.merge_runs(max_runs=max_runs))

    # -- observability -------------------------------------------------------

    def pending_merge(self) -> int | None:
        with self._lock:
            return self._pending_merge

    def counters(self) -> dict:
        ds = getattr(self.sb.index, "devstore", None)
        with self._lock:
            return {
                "merge_deferrals": self.merge_deferrals,
                "promote_deferrals": self.promote_deferrals,
                "merge_catch_ups": self.merge_catch_ups,
                "catch_up_merges": self.catch_up_merges,
                "catch_up_promotions": self.catch_up_promotions,
                "deferred": int(self.deferred),
                "pending_merge": int(self._pending_merge is not None),
                "deferred_promotions_parked":
                    len(getattr(ds, "_deferred_promotes", ()) or ()),
            }
