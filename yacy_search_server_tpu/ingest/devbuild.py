"""Device-side index build — the bit-pack as a vmapped kernel (ISSUE 13b).

``ops/packed.pack_block`` runs on host NumPy, one term at a time, on the
flush/merge path — with compressed residency on, packing a fresh run is
a SERVING STALL: the flush thread grinds per-column min/max + bit-lay
loops while query dispatches queue behind the store lock.  This module
moves the lay-down onto the device as ONE vmapped dispatch per pow2 row
bucket (``_pack_block_batch_kernel``), so fresh runs land pre-packed
and the pack stall becomes overlappable device work:

- per block (vmap lane): per-column min/max over the valid rows, the
  minimal bit width via ``lax.clz`` (exact — no float log2), and the
  little-endian straddle-capable lay-down as a scatter-ADD over the
  int32 word stream.  Contributions of distinct values occupy disjoint
  bit ranges within a word, so integer add IS the host packer's OR
  fold — the output words are bit-identical to ``pack_block``'s, column
  offsets, widths and minima included (pinned by tests/test_ingest.py
  over adversarial ranges: all-equal, full int16, negatives, 30-bit
  flags, ragged counts).
- 32-bit only: x64 stays disabled.  ``vmax - vmin`` and ``v - vmin``
  are computed in wrapping int32 and bitcast to uint32 — the true
  difference mod 2^32, exact because the spread of int32 values fits
  uint32.  The hi-word shift guards ``s == 0`` exactly like
  ``ops/packed.unpack_rows_dev`` guards its decode shifts.
- static shapes: rows bucket to pow2 (>= 256) and the batch pads to
  pow2 with ``n=0`` lanes, so a steady ingest soak compiles a handful
  of shapes, not one per flush.

The kernel carries a roofline cost model (``_pack_block_batch_kernel``
in ops/roofline.KERNELS, XLA-cross-checked by tests/test_roofline.py)
and the ingest hygiene gate (tests/test_code_hygiene.py scans this
package) fails any future ingest/ jit kernel without one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..index import postings as P
from ..ops import packed as PK

_INT32_MAX = np.int32(2 ** 31 - 1)
_INT32_MIN = np.int32(-(2 ** 31))

# blocks above this row count pack on host: a transient padded device
# buffer that big has no business on the wave path (the per-term packs
# a real flush produces sit far below it; ops/packed handles the tail)
MAX_DEV_ROWS = 1 << 18

# ... and blocks BELOW this row count pack on host too: the device
# lay-down pads every lane to >= 256 rows, so a 3-row fresh-term block
# would ship ~85x padding — more silicon than the host packer's
# microseconds cost anywhere, and on a CPU backend the waste lands on
# the very core that is serving.  The device build is for RUN-SCALE
# blocks (seed ingests, merges, hot fresh terms), not long-tail stubs.
MIN_DEV_ROWS = 64


def rows_bucket(n: int) -> int:
    """Static pow2 row bucket (>= 256) for one block — bounded compile
    shapes, like ops/dense.rerank_bucket / ops/ann.ann_lane_bucket."""
    return 1 << max(8, (max(n, 1) - 1).bit_length())


@functools.partial(jax.jit, static_argnames=("rows",))
def _pack_block_batch_kernel(f16, fl, dd, n, *, rows: int):
    """Bit-pack ``B`` posting blocks in one dispatch.

    f16: int16 [B, rows, NF]   feats (proxy-ordered, like pack_block's)
    fl:  int32 [B, rows]       flags
    dd:  int32 [B, rows]       docids
    n:   int32 [B]             valid rows per block (rest is padding)

    Returns (words int32 [B, rows * NCOLS], meta int32 [B, META_LEN],
    total_words int32 [B]) — ``words[b, :total_words[b]]`` plus the
    meta (offs ++ widths ++ mins) reconstruct a PackedBlock
    bit-identical to ``ops/packed.pack_block`` on the same rows.
    """

    def one(f16b, flb, ddb, nb):
        i = jnp.arange(rows, dtype=jnp.int32)
        valid = i < nb
        cols = [f16b[:, c].astype(jnp.int32) for c in range(P.NF)]
        cols.append(flb)
        cols.append(ddb)
        words = jnp.zeros(rows * PK.NCOLS, jnp.uint32)
        off = jnp.int32(0)
        offs, widths, mins = [], [], []
        for c in range(PK.NCOLS):
            v = cols[c]
            vmin = jnp.min(jnp.where(valid, v, _INT32_MAX))
            vmax = jnp.max(jnp.where(valid, v, _INT32_MIN))
            # empty lane (batch padding): the host packer's n=0 shape
            vmin = jnp.where(nb > 0, vmin, jnp.int32(0))
            vmax = jnp.where(nb > 0, vmax, jnp.int32(0))
            # true spread mod 2^32 (wrapping int32 subtract, bitcast):
            # exact — an int32 column's spread always fits uint32
            d = lax.bitcast_convert_type(vmax - vmin, jnp.uint32)
            w = jnp.maximum(jnp.int32(1),
                            jnp.int32(32) - lax.clz(d).astype(jnp.int32))
            voff = lax.bitcast_convert_type(v - vmin, jnp.uint32)
            voff = jnp.where(valid, voff, jnp.uint32(0))
            wu = w.astype(jnp.uint32)
            bit = i.astype(jnp.uint32) * wu
            wi = (bit >> 5).astype(jnp.int32) + off
            s = bit & jnp.uint32(31)
            lo = voff << s                 # uint32 wrap = the lo word
            # s == 0: value sits entirely in lo; the >> (32-s) arm is
            # undefined-shift territory, guarded like unpack_rows_dev
            sh = jnp.where(s == jnp.uint32(0), jnp.uint32(1),
                           jnp.uint32(32) - s)
            hi = jnp.where(s == jnp.uint32(0), jnp.uint32(0),
                           voff >> sh)
            # disjoint bit ranges per value => add == the host OR fold;
            # padded lanes contribute zeros, mode="drop" guards the
            # one-past-the-end straddle of the final word
            words = words.at[wi].add(lo, mode="drop")
            words = words.at[wi + 1].add(hi, mode="drop")
            offs.append(off)
            widths.append(w)
            mins.append(vmin)
            off = off + ((nb * w + 31) >> 5)   # word-aligned next column
        meta = jnp.concatenate(
            [jnp.stack(offs), jnp.stack(widths), jnp.stack(mins)])
        return lax.bitcast_convert_type(words, jnp.int32), meta, off

    return jax.vmap(one)(f16, fl, dd, n)


def pack_block_batch(parts) -> list:
    """Pack ``[(feats16, flags, docids), ...]`` into PackedBlocks via
    the device kernel — one dispatch per pow2 row bucket, batch padded
    to pow2 with empty lanes (bounded compile shapes).  Output order
    matches input order; every block is bit-identical to
    ``ops/packed.pack_block`` on the same rows (the parity contract).
    Blocks outside [``MIN_DEV_ROWS``, ``MAX_DEV_ROWS``] take the host
    packer (empty, long-tail stubs, and oversize runs)."""
    out: list = [None] * len(parts)
    groups: dict[int, list] = {}
    for idx, (f16, fl, dd) in enumerate(parts):
        nrows = len(dd)
        if not MIN_DEV_ROWS <= nrows <= MAX_DEV_ROWS:
            out[idx] = PK.pack_block(f16, fl, dd)
        else:
            groups.setdefault(rows_bucket(nrows), []).append(idx)
    for rows, idxs in sorted(groups.items()):
        bpad = 1 << max(0, (len(idxs) - 1).bit_length())
        f16 = np.zeros((bpad, rows, P.NF), np.int16)
        fl = np.zeros((bpad, rows), np.int32)
        dd = np.zeros((bpad, rows), np.int32)
        n = np.zeros(bpad, np.int32)
        for j, idx in enumerate(idxs):
            bf, bl, bd = parts[idx]
            m = len(bd)
            f16[j, :m] = bf
            fl[j, :m] = bl
            dd[j, :m] = bd
            n[j] = m
        words, meta, totals = _pack_block_batch_kernel(f16, fl, dd, n,
                                                       rows=rows)
        words = np.asarray(words)
        meta = np.asarray(meta)
        totals = np.asarray(totals)
        for j, idx in enumerate(idxs):
            m = meta[j]
            out[idx] = PK.PackedBlock(
                words=words[j, :int(totals[j])].copy(),
                count=int(n[j]),
                word_offs=m[:PK.NCOLS].copy(),
                widths=m[PK.NCOLS:2 * PK.NCOLS].copy(),
                mins=m[2 * PK.NCOLS:].copy())
    return out
