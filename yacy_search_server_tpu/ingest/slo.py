"""Crawl-to-searchable SLO — the write path's latency contract (ISSUE 13a).

A crawler-indexer's freshness promise is a LATENCY, not a throughput:
how long after the crawler hands a document to the pipeline can a query
actually find it?  Until now nothing measured that wall — flush and
merge timing were ad hoc side effects of buffer thresholds, invisible
to the health engine.  This module stamps every document at pipeline
entry and propagates the stamp through the write path's tiers:

- ``ingest.searchable`` — entry → ``Segment.store_document`` returned:
  the document answers queries from the RWI RAM buffer (first serve).
- ``ingest.flushed``    — entry → the RWI flush covering it returned:
  the postings are an immutable (and, with a data dir, durable) run.
- ``ingest.device``     — entry → the devstore packed the run's blocks:
  the document serves from the device tier, not the host path.
- ``ingest.backpressure`` — wall a writer spent blocked in the bounded
  RAM buffer (``RWIIndex.wait_capacity``, ISSUE 13 satellite): the SLO
  must SEE backpressure, or a stalled write path reads as "no traffic".

All four are windowed histogram families (utils/histogram.py CANONICAL,
so ``/metrics`` exports them on every node and the
``ingest_slo_searchable`` health rule's series always resolve).  The
tracker is process-global like the histogram registry it feeds; stamps
are monotonic-clock floats carried by value (IndexingEntry field /
``store_document(ingest_stamp=...)``), so the pipeline's decoupled
worker threads need no contextvar plumbing.

Bounds: pending-stamp lists are capped (an ingest burst past the cap
drops stamps with a counter, never memory), and per-run stamp
attachments live in a bounded FIFO — a run that never reaches the
device tier ages out instead of leaking.

Jax-free by contract (see the package docstring): the kill−9 chaos
children import the RWI write path, and with it this module, in
dozens of short-lived interpreters.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from ..utils import histogram

# one family per write-path tier (+ the backpressure wall); registered
# in histogram.CANONICAL so the exposition and the health rule always
# resolve them, and prefixed "ingest." so they never decide a SERVING
# latency verdict (histogram.BACKGROUND_PREFIXES)
FAMILIES = {
    "ingest.searchable": "crawl-to-searchable: pipeline entry -> doc "
                         "servable from the RWI RAM buffer",
    "ingest.flushed": "pipeline entry -> RWI flush covering the doc "
                      "returned (immutable/durable run)",
    "ingest.device": "pipeline entry -> run bit-packed onto the device "
                     "tier (serves from placed blocks)",
    "ingest.backpressure": "writer wall blocked in the bounded RWI RAM "
                           "buffer (counted backpressure)",
}

# bounds: stamps a burst may queue per RWI before drops are counted,
# how many flushed runs may await their device pack concurrently, and
# how many distinct RWI instances may hold pending stamps at once (a
# process owns a handful of segments; churny short-lived stores — test
# suites, rebuilds — age out oldest-first instead of leaking)
MAX_PENDING_STAMPS = 500_000
MAX_PENDING_RUNS = 128
MAX_PENDING_RWIS = 64


class IngestTracker:
    """Process-global stamp registry: pipeline entry times keyed by the
    RWI (pre-flush) and by the frozen run (pre-device-pack)."""

    def __init__(self):
        self._lock = threading.Lock()
        # id(rwi) -> [entry stamps whose docs sit in the RAM buffer]
        self._pending: dict[int, list] = {}
        # id(frozen run) -> [entry stamps], bounded FIFO
        self._run_stamps: "OrderedDict[int, list]" = OrderedDict()
        self.docs_stamped = 0
        self.docs_searchable = 0
        self.docs_flushed = 0
        self.docs_device = 0
        self.stamps_dropped = 0
        self.backpressure_waits = 0
        self.backpressure_wait_ms = 0.0

    # -- stamping ------------------------------------------------------------

    @staticmethod
    def stamp() -> float:
        """A pipeline-entry stamp (monotonic seconds; carried by value
        on the IndexingEntry / store_document call)."""
        return time.monotonic()

    def note_stored(self, rwi, t_entry: float) -> None:
        """The document is searchable (RAM-buffer tier): observe
        entry→now and queue the stamp for the flush covering it."""
        now = time.monotonic()
        histogram.observe("ingest.searchable",
                          max(0.0, (now - t_entry) * 1000.0))
        with self._lock:
            self.docs_stamped += 1
            self.docs_searchable += 1
            pend = self._pending.setdefault(id(rwi), [])
            if len(pend) >= MAX_PENDING_STAMPS:
                self.stamps_dropped += 1
            else:
                pend.append(t_entry)
            while len(self._pending) > MAX_PENDING_RWIS:
                # a discarded-without-close store must not leak its
                # stamp list forever (dicts iterate insertion-first =
                # oldest RWI first; the evicted stamps are counted;
                # never evict the live writer's own list)
                old = next(k for k in self._pending if k != id(rwi))
                self.stamps_dropped += len(self._pending.pop(old))

    def forget(self, rwi) -> None:
        """Drop all stamp state keyed by this RWI (its close() hook):
        CPython reuses addresses, and a successor allocated at the
        freed id must not inherit a dead store's pending stamps."""
        with self._lock:
            self._pending.pop(id(rwi), None)

    def discard(self, stamps: list) -> None:
        """Claimed stamps whose flush will never complete (e.g. every
        covered doc was deleted before the freeze): counted drops, per
        the never-silent contract."""
        if not stamps:
            return
        with self._lock:
            self.stamps_dropped += len(stamps)

    # -- flush propagation ---------------------------------------------------

    def flush_begin(self, rwi) -> list:
        """Atomically claim the stamps whose docs the flush is freezing
        (called under the RWI lock, where the RAM buffer is swapped)."""
        with self._lock:
            return self._pending.pop(id(rwi), [])

    def run_pending(self, run, stamps: list) -> None:
        """Attach claimed stamps to the frozen run BEFORE the device
        listener packs it, so the pack completion can observe the
        device tier (bounded: oldest attachments age out)."""
        if not stamps:
            return
        with self._lock:
            self._run_stamps[id(run)] = stamps
            while len(self._run_stamps) > MAX_PENDING_RUNS:
                _, old = self._run_stamps.popitem(last=False)
                self.stamps_dropped += len(old)

    def flush_done(self, stamps: list) -> None:
        """The flush covering these stamps returned: the postings are
        an immutable (durable, with a data dir) run."""
        if not stamps:
            return
        now = time.monotonic()
        for t in stamps:
            histogram.observe("ingest.flushed",
                              max(0.0, (now - t) * 1000.0))
        with self._lock:
            self.docs_flushed += len(stamps)

    def device_packed(self, run) -> None:
        """The devstore packed this run's blocks: its documents serve
        from the device tier (no-op for runs without stamps — merges,
        surrogate bulk ingests, startup re-packs)."""
        with self._lock:
            stamps = self._run_stamps.pop(id(run), None)
        if not stamps:
            return
        now = time.monotonic()
        for t in stamps:
            histogram.observe("ingest.device",
                              max(0.0, (now - t) * 1000.0))
        with self._lock:
            self.docs_device += len(stamps)

    # -- backpressure (ISSUE 13 satellite) -----------------------------------

    def note_backpressure(self, blocked_ms: float) -> None:
        """One counted blocking wait in the bounded RAM buffer — the
        stamp the SLO sees (the blocked wall also lands inside the
        doc's own crawl-to-searchable latency, by construction)."""
        histogram.observe("ingest.backpressure", max(0.0, blocked_ms))
        with self._lock:
            self.backpressure_waits += 1
            self.backpressure_wait_ms += blocked_ms

    # -- observability -------------------------------------------------------

    def counters(self) -> dict:
        with self._lock:
            return {
                "docs_stamped": self.docs_stamped,
                "docs_searchable": self.docs_searchable,
                "docs_flushed": self.docs_flushed,
                "docs_device": self.docs_device,
                "stamps_dropped": self.stamps_dropped,
                "backpressure_waits": self.backpressure_waits,
                "backpressure_wait_ms": round(self.backpressure_wait_ms,
                                              3),
            }


# THE tracker (process-global, like the histogram registry it feeds)
TRACKER = IngestTracker()
