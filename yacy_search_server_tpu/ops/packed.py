"""Bit-packed posting blocks — per-column minimal widths, decoded on device.

The roofline layer (ops/roofline.py) classifies every posting scorer
HBM-bandwidth-bound, so on-device compression is straight throughput: a
block that streams half the bytes scores in half the wall. The int16
block compaction (M18, ops/ranking.compact_feats) already rode that curve
once — int32 -> int16 halved the scorer bytes and halved the measured
wall. This module continues it to the floor the data itself sets
(arXiv:1406.3170's compact-index stance, applied to the device arena):

- at pack time, every column of a block (the NF compact feature columns,
  the int32 flags bitfield, the docids) gets the MINIMAL bit width that
  spans its min..max range (``bits(max - min)``, floor 1) and is stored
  min-offset ("delta from block min"): value_packed = value - col_min.
  Docids pack the same way — the delta-from-min form of delta packing
  that stays order-free (arena rows are proxy-score ordered, not
  docid-sorted, so consecutive-delta coding would need a permutation on
  every read).
- packed values are laid down MSB-agnostic little-endian into one int32
  word stream, each column's sub-stream starting word-aligned, values
  allowed to straddle a word boundary (arbitrary widths beat
  power-of-two-only widths by ~30% on realistic column ranges; the
  straddle costs one extra word gather per value on decode).
- the device decode is pure shifts/masks/gathers (``unpack_rows_dev``)
  and FUSES into the scorer kernels (index/devstore.py ``*_bp``
  variants): the packed words stream from HBM, rows widen to int32 in
  registers, and the scoring math downstream is bit-identical to the
  int16 path — same values in, same cardinal out, same tie order.

Host twins ``pack_block`` / ``unpack_block`` are exact inverses (the
property tests pin round trips over adversarial ranges: all-equal
columns, full int16 range, negatives, 30-bit flags). ``BP_ORACLES`` maps
every ``*_bp`` device kernel to its NumPy oracle — the hygiene gate
(tests/test_code_hygiene.py) fails any ``*_bp`` kernel without both a
roofline cost model and an oracle entry here.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..index import postings as P

# packed column order: the NF compact feature columns, then the int32
# flags bitfield, then the docids — NCOLS sub-streams per block
NCOLS = P.NF + 2
C_FLAGS = P.NF
C_DOCIDS = P.NF + 1

# meta vector layout (int32 [3 * NCOLS]): per-column word offsets within
# the block, then per-column bit widths, then per-column minima
META_LEN = 3 * NCOLS


def col_width(vmin: int, vmax: int) -> int:
    """Minimal bits spanning vmin..vmax (floor 1 — a constant column
    still packs one zero bit per row, keeping the decode uniform)."""
    return max(1, int(int(vmax) - int(vmin)).bit_length())


@dataclass
class PackedBlock:
    """One bit-packed postings block (host form).

    words: the int32 word stream (all columns, each word-aligned)
    count: rows in the block
    word_offs/widths/mins: int32 [NCOLS] per-column geometry
    """

    words: np.ndarray
    count: int
    word_offs: np.ndarray
    widths: np.ndarray
    mins: np.ndarray

    def meta_vector(self) -> np.ndarray:
        """The decode descriptor the device kernels ship per span."""
        return np.concatenate([self.word_offs, self.widths,
                               self.mins]).astype(np.int32)

    @property
    def row_bits(self) -> int:
        """Payload bits per row (the compression headline; word-align
        padding is amortized away at block sizes)."""
        return int(self.widths.sum())

    @property
    def packed_bytes(self) -> int:
        return int(self.words.nbytes)

    @property
    def int16_bytes(self) -> int:
        """The same rows in the int16 block format (feats16 + flags +
        docids) — the compression denominator."""
        return self.count * (P.NF * 2 + 4 + 4)

    @property
    def compression_ratio(self) -> float:
        return self.int16_bytes / max(self.packed_bytes, 1)


def _pack_column(vals: np.ndarray, w: int, nwords: int) -> np.ndarray:
    """Pack non-negative uint64 values of width `w` bits into `nwords`
    int32 words (little-endian bit order, straddling allowed).

    Vectorized via the same unique+reduceat OR-fold the join bitmaps use
    (np.bitwise_or.at is ~50x slower at block sizes)."""
    n = len(vals)
    out = np.zeros(nwords, np.uint32)
    if n == 0:
        return out.view(np.int32)
    bit = np.arange(n, dtype=np.uint64) * np.uint64(w)
    wi = (bit >> np.uint64(5)).astype(np.int64)
    s = bit & np.uint64(31)
    shifted = vals << s                       # < 2^63: w<=32, s<=31
    lo = (shifted & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (shifted >> np.uint64(32)).astype(np.uint32)
    idx = np.concatenate([wi, wi + 1])
    contrib = np.concatenate([lo, hi])
    nz = contrib != 0
    idx, contrib = idx[nz], contrib[nz]
    if len(idx):
        order = np.argsort(idx, kind="stable")
        idx, contrib = idx[order], contrib[order]
        uw, starts = np.unique(idx, return_index=True)
        out[uw] = np.bitwise_or.reduceat(contrib, starts)
    return out.view(np.int32)


def pack_block(feats16: np.ndarray, flags: np.ndarray,
               docids: np.ndarray) -> PackedBlock:
    """Bit-pack one compact block (the SAME (feats16, flags, docids)
    triple the int16 arena stores, in the same row order — parity with
    the int16 scorer path is by construction: identical values, identical
    tie-breaking row order)."""
    n = len(docids)
    assert feats16.shape == (n, P.NF) and len(flags) == n
    cols: list[np.ndarray] = [feats16[:, c].astype(np.int64)
                              for c in range(P.NF)]
    cols.append(flags.astype(np.int64))
    cols.append(docids.astype(np.int64))
    mins = np.zeros(NCOLS, np.int32)
    widths = np.zeros(NCOLS, np.int32)
    word_offs = np.zeros(NCOLS, np.int32)
    parts: list[np.ndarray] = []
    off = 0
    for c in range(NCOLS):
        v = cols[c]
        vmin = int(v.min()) if n else 0
        vmax = int(v.max()) if n else 0
        w = col_width(vmin, vmax)
        mins[c] = vmin
        widths[c] = w
        word_offs[c] = off
        nwords = (n * w + 31) // 32
        parts.append(_pack_column((v - vmin).astype(np.uint64), w, nwords))
        off += nwords
    words = (np.concatenate(parts) if parts
             else np.empty(0, np.int32))
    return PackedBlock(words=words, count=n, word_offs=word_offs,
                       widths=widths, mins=mins)


def _unpack_column(words: np.ndarray, off: int, w: int, vmin: int,
                   n: int) -> np.ndarray:
    """Exact inverse of _pack_column (int64 values)."""
    wu = words.view(np.uint32).astype(np.uint64)
    bit = np.arange(n, dtype=np.uint64) * np.uint64(w)
    wi = off + (bit >> np.uint64(5)).astype(np.int64)
    s = bit & np.uint64(31)
    lo = wu[wi]
    hi = wu[np.minimum(wi + 1, len(wu) - 1)]
    mask = (np.uint64(1) << np.uint64(w)) - np.uint64(1)
    val = ((lo | (hi << np.uint64(32))) >> s) & mask
    return val.astype(np.int64) + vmin


def unpack_block(pb: PackedBlock) -> tuple[np.ndarray, np.ndarray,
                                           np.ndarray]:
    """(feats16, flags, docids) — bit-exact inverse of pack_block, and
    the NumPy half of every *_bp kernel oracle."""
    n = pb.count
    f16 = np.zeros((n, P.NF), np.int16)
    for c in range(P.NF):
        f16[:, c] = _unpack_column(pb.words, int(pb.word_offs[c]),
                                   int(pb.widths[c]), int(pb.mins[c]),
                                   n).astype(np.int16)
    fl = _unpack_column(pb.words, int(pb.word_offs[C_FLAGS]),
                        int(pb.widths[C_FLAGS]), int(pb.mins[C_FLAGS]),
                        n).astype(np.int32)
    dd = _unpack_column(pb.words, int(pb.word_offs[C_DOCIDS]),
                        int(pb.widths[C_DOCIDS]), int(pb.mins[C_DOCIDS]),
                        n).astype(np.int32)
    return f16, fl, dd


# ---------------------------------------------------------------------------
# Device decode — the traced helper the *_bp kernels fuse
# ---------------------------------------------------------------------------

def unpack_rows_dev(uwords, wbase, meta, row0, rows: int):
    """Decode `rows` rows starting at (traced) `row0` of the packed
    block at word base `wbase`; returns (feats int32 [rows, NF],
    flags int32 [rows], docids int32 [rows]).

    `uwords` is the whole packed-words arena bit-cast to uint32 (cast
    once per kernel, free); `meta` the block's int32 [META_LEN] decode
    descriptor. All arithmetic is shifts/masks over two gathered words
    per value (straddle-capable); out-of-range gathers clip — rows past
    the block's true count decode garbage that the caller's in-span
    predicate masks before any use, exactly like the int16 kernels'
    overrun tiles. Fusing this into the scorer is the whole point: the
    packed words are the ONLY HBM stream, and XLA widens in registers."""
    offs = meta[:NCOLS]
    widths = meta[NCOLS:2 * NCOLS]
    mins = meta[2 * NCOLS:]
    i = row0 + jnp.arange(rows, dtype=jnp.int32)
    nw = uwords.shape[0]
    cols = []
    for c in range(NCOLS):
        w = widths[c]
        bit = i * w
        wi = wbase + offs[c] + (bit >> 5)
        s = (bit & 31).astype(jnp.uint32)
        lo = uwords[jnp.clip(wi, 0, nw - 1)]
        hi = uwords[jnp.clip(wi + 1, 0, nw - 1)]
        # mask: w==32 would overflow the 1<<w form; both `where` arms
        # evaluate, so the shift amount is clamped to stay defined
        wq = jnp.minimum(w, 31).astype(jnp.uint32)
        mask = jnp.where(w >= 32, jnp.uint32(0xFFFFFFFF),
                         (jnp.uint32(1) << wq) - jnp.uint32(1))
        # s==0 means the value sits entirely in `lo`; the hi<<32 arm is
        # undefined-shift territory, guarded by the where select
        hipart = jnp.where(s == jnp.uint32(0), jnp.uint32(0),
                           hi << (jnp.uint32(32) - s))
        val = ((lo >> s) | hipart) & mask
        cols.append(val.astype(jnp.int32) + mins[c])
    f = jnp.stack(cols[:P.NF], axis=1)
    return f, cols[C_FLAGS], cols[C_DOCIDS]


def bitcast_words(pwords):
    """The once-per-kernel uint32 view of the packed-words arena."""
    return lax.bitcast_convert_type(pwords, jnp.uint32)


# ---------------------------------------------------------------------------
# NumPy oracles — one per *_bp device kernel (hygiene-gated)
# ---------------------------------------------------------------------------

def bp_topk_oracle(pb: PackedBlock, profile, language: str, k: int,
                   stats: dict | None = None,
                   lang_filter: int | None = None,
                   flag_bit: int | None = None,
                   from_days: int | None = None,
                   to_days: int | None = None):
    """Reference answer for the packed-decode scorers: unpack the block
    host-side, score with the canonical host twin
    (ops/ranking.cardinal_from_stats_host — bit-exact integer parts vs
    the device kernel), apply the same constraint mask, and take the
    top-k under the pinned tie order (score DESC, then block row order —
    lax.top_k's lowest-index tie-break over the same rows).

    `stats=None` recomputes normalization over the (masked) block like
    the exact scan; passing the frozen pack stats reproduces the pruned
    path's score domain."""
    from .ranking import cardinal_from_stats_host, pack_stats_host
    f16, fl, dd = unpack_block(pb)
    n = pb.count
    keep = np.ones(n, bool)
    if lang_filter is not None and lang_filter != 0:
        keep &= f16[:, P.F_LANGUAGE].astype(np.int32) == lang_filter
    if flag_bit is not None and flag_bit >= 0:
        keep &= ((fl >> flag_bit) & 1) == 1
    if from_days is not None:
        keep &= f16[:, P.F_LASTMOD].astype(np.int32) >= from_days
    if to_days is not None:
        keep &= f16[:, P.F_LASTMOD].astype(np.int32) <= to_days
    if stats is None:
        if not keep.any():
            return (np.empty(0, np.int64), np.empty(0, np.int32))
        stats = pack_stats_host(f16[keep], fl[keep])
    s = cardinal_from_stats_host(f16, fl, stats, profile,
                                 P.pack_language(language))
    s = np.where(keep, s, np.int64(-(2 ** 63 - 1)))
    order = np.argsort(-s, kind="stable")[:k]
    order = order[keep[order]]
    return s[order], dd[order]


# kernel name -> (oracle callable, one-line contract). The hygiene gate
# demands an entry for EVERY jitted *_bp kernel in index/devstore.py —
# a packed-decode kernel without a NumPy oracle has no parity anchor.
BP_ORACLES: dict[str, tuple] = {
    "_rank_pruned_batch1_bp_kernel": (
        bp_topk_oracle,
        "frozen pack stats + first-tile prefix; the tail bound walk is "
        "verified by the int16 twin's proof (same pmax side-table)"),
    "_rank_scan_batch_bp_kernel": (
        bp_topk_oracle,
        "exact two-pass scan semantics: stats over the constraint-masked "
        "rows, then score + top-k, identical to _rank_scan_batch_kernel"),
}
