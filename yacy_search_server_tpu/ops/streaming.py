"""Streaming block scorer — unbounded postings through a running top-k.

The long-context story of this framework (SURVEY.md §5): the reference's
unbounded dimensions (per-term postings lists, result sets) are handled
there by LSM splits and bounded heaps; on TPU the analogous mechanism is
*streaming* — postings blocks flow tile-by-tile through the scoring
kernel while a running top-k rides in the carry of a `lax.scan`, the
same shape ring attention gives long sequences (block in, running state
through). Two layers:

- `scan_score_topk`: device-resident [n, NF] block processed in fixed
  tiles under one jit — peak live memory is one tile + the carry, so a
  block bigger than any single fused-scoring working set still scores.
- `stream_score_topk`: host-side driver feeding device tiles from a
  numpy array (or any chunk iterator) — blocks larger than device HBM
  score in bounded memory, merging each tile's top-k into the running
  result exactly like SearchEvent's bounded heap absorbed RWI entries
  (reference: SearchEvent.java:809 rwiStack heap loop).

Stats (min/max normalization bounds) must be block-global, so both
drivers take precomputed `stats` — for streamed blocks the caller
accumulates them per chunk via `merge_stats` (min/min, max/max, sum)
before the scoring pass, mirroring parallel/mesh.py's cross-shard merge.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index import postings as P
from .ranking import cardinal_from_stats, local_stats

NEG_INF32 = -(2**31 - 1)


def merge_stats(a: dict | None, b: dict) -> dict:
    """Combine per-chunk stats (same laws as the mesh pmin/pmax/psum)."""
    if a is None:
        return b
    return {
        "col_min": jnp.minimum(a["col_min"], b["col_min"]),
        "col_max": jnp.maximum(a["col_max"], b["col_max"]),
        "tf_min": jnp.minimum(a["tf_min"], b["tf_min"]),
        "tf_max": jnp.maximum(a["tf_max"], b["tf_max"]),
        "host_counts": a["host_counts"] + b["host_counts"],
    }


def _merge_topk(run_s, run_d, new_s, new_d, k: int):
    s = jnp.concatenate([run_s, new_s])
    d = jnp.concatenate([run_d, new_d])
    # lint: tie-ok(the running top-k precedes the new tile in the
    # concat and earlier tiles hold lower docids, so top_k's
    # lowest-index tie-break keeps equal scores docid-ASC across
    # the whole stream)
    top_s, idx = jax.lax.top_k(s, k)
    return top_s, d[idx]


@partial(jax.jit, static_argnames=("k", "tile"))
def scan_score_topk(feats16: jnp.ndarray, flags: jnp.ndarray,
                    docids: jnp.ndarray, valid: jnp.ndarray,
                    hostids: jnp.ndarray, stats: dict,
                    norm_coeffs: jnp.ndarray, flag_bits: jnp.ndarray,
                    flag_shifts: jnp.ndarray, domlength_coeff: jnp.ndarray,
                    tf_coeff: jnp.ndarray, language_coeff: jnp.ndarray,
                    authority_coeff: jnp.ndarray, language_pref: jnp.ndarray,
                    k: int, tile: int = 1 << 20):
    """Device streaming: score in `tile`-row slices under lax.scan with a
    running (scores, docids) top-k carry. Inputs of any length are padded
    to a whole number of tiles here (padding rows are invalid and score
    the sentinel). NB: the outputs are fixed-shape [k]; when fewer than k
    valid rows exist the tail carries docid -1 at the sentinel score —
    host callers filter `docids >= 0` (stream_score_topk does)."""
    n = feats16.shape[0]
    npad = max(tile, ((n + tile - 1) // tile) * tile)
    if npad != n:
        pad = npad - n
        feats16 = jnp.pad(feats16, ((0, pad), (0, 0)))
        flags = jnp.pad(flags, (0, pad))
        docids = jnp.pad(docids, (0, pad), constant_values=-1)
        valid = jnp.pad(valid, (0, pad), constant_values=False)
        hostids = jnp.pad(hostids, (0, pad))
    steps = npad // tile
    f = feats16.reshape(steps, tile, P.NF)
    fl = flags.reshape(steps, tile)
    dd = docids.reshape(steps, tile)
    vv = valid.reshape(steps, tile)
    hh = hostids.reshape(steps, tile)

    init = (jnp.full((k,), NEG_INF32, jnp.int32),
            jnp.full((k,), -1, jnp.int32))

    def step(carry, xs):
        run_s, run_d = carry
        tf16, tfl, tdd, tvv, thh = xs
        s = cardinal_from_stats(tf16, tvv, thh, stats, norm_coeffs,
                                flag_bits, flag_shifts, domlength_coeff,
                                tf_coeff, language_coeff, authority_coeff,
                                language_pref, fast_div=True, flags=tfl)
        # lint: tie-ok(per-tile prefilter: rows are docid-ordered so
        # lowest-index ties are docid-ASC, and _merge_topk preserves
        # that order across tiles)
        tile_s, tile_i = jax.lax.top_k(s, min(k, tile))
        return _merge_topk(run_s, run_d, tile_s, tdd[tile_i], k), None

    (top_s, top_d), _ = jax.lax.scan(step, init, (f, fl, dd, vv, hh))
    return top_s, top_d


def stream_score_topk(feats: np.ndarray, flags: np.ndarray,
                      docids: np.ndarray, hostids: np.ndarray,
                      ranker_consts: tuple, language_pref,
                      k: int = 100, chunk: int = 1 << 21):
    """Host streaming: numpy block -> device chunks -> running top-k.

    Peak device memory is one chunk regardless of block size; two passes
    (stats, then score) keep normalization block-global. Returns
    (scores, docids) np arrays, best-first.

    The domain-authority signal needs block-global per-host counts that
    this driver does not accumulate — streamed scoring always behaves as
    if the profile's authority guard is off (authority <= 12, the
    default); use the one-shot kernel for authority-boosted profiles."""
    n = len(docids)
    if n == 0:
        return (np.empty(0, np.int32), np.empty(0, np.int32))

    # pass 1: accumulate block-global stats chunk by chunk
    stats = None
    for lo in range(0, n, chunk):
        hi = min(n, lo + chunk)
        cs = local_stats(jnp.asarray(feats[lo:hi]),
                         jnp.ones(hi - lo, bool),
                         jnp.asarray(hostids[lo:hi]),
                         num_hosts=1, with_host_counts=False)
        stats = merge_stats(stats, cs)

    # pass 2: score chunks, merge into the running top-k
    run_s = jnp.full((k,), NEG_INF32, jnp.int32)
    run_d = jnp.full((k,), -1, jnp.int32)
    for lo in range(0, n, chunk):
        hi = min(n, lo + chunk)
        s = cardinal_from_stats(
            jnp.asarray(feats[lo:hi]), jnp.ones(hi - lo, bool),
            jnp.asarray(hostids[lo:hi]), stats, *ranker_consts,
            language_pref, fast_div=feats.dtype == np.int16,
            flags=jnp.asarray(flags[lo:hi]))
        kk = min(k, hi - lo)
        # lint: tie-ok(per-chunk prefilter: rows are docid-ordered so
        # lowest-index ties are docid-ASC, and _merge_topk preserves
        # that order across chunks)
        tile_s, tile_i = jax.lax.top_k(s, kk)
        run_s, run_d = _merge_topk(
            run_s, run_d, tile_s,
            jnp.asarray(docids[lo:hi])[tile_i], k)
    s_np, d_np = np.asarray(run_s), np.asarray(run_d)
    keep = d_np >= 0
    return s_np[keep], d_np[keep]
