"""Device compute kernels: ranking, top-k, joins.

This package is the TPU compute path of the framework — batched JAX/XLA
kernels replacing the reference's concurrent Java scoring code
(reference: source/net/yacy/search/ranking/ReferenceOrder.java,
source/net/yacy/cora/sorting/WeakPriorityBlockingQueue.java).
"""
