"""Batched ranking kernel — ReferenceOrder as one XLA program.

Capability equivalent of the reference's query-time scorer (reference:
source/net/yacy/search/ranking/ReferenceOrder.java:51-265 and
RankingProfile.java:82-341). The reference normalizes posting attributes
with a distributor thread + N NormalizeWorker threads that stream-decode
rows and accumulate global min/max under benign races, then scores each
posting with `cardinal` = sum over ~25 signals of
(normalized-to-0..255 value << coefficient). Here the entire construct is
one batched kernel:

    min/max  = masked column reduce over the postings block
    norm     = (x - min) * 256 // (max - min)        (0 when max == min)
    cardinal = sum_s (norm_s or 255-flag) << coeff_s
    top-k    = jax.lax.top_k over the scores

which XLA fuses into a few passes over HBM; there are no threads, no
poison pills, and no tolerated min/max races (SURVEY.md §5: the reference
catches ArithmeticException from concurrent min/max mutation —
SearchEvent.java:811-815; batching removes the race by construction).

Scores are int32: max single signal is 256 << 15 (~8.4e6), ~30 signals
never exceeds 2^31. Integer division matches Java semantics for the
non-negative attribute values involved (both truncate toward zero).

A BM25 kernel (ops/bm25.py semantics inline here) complements cardinal for
the BASELINE.json configs: the reference has no BM25 of its own (scoring
is cardinal + Solr-side relevance); BM25 over the same dense blocks is the
TPU build's first-stage text relevance.
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index import postings as P
from ..utils.bitfield import (
    FLAG_APP_DC_CREATOR, FLAG_APP_DC_DESCRIPTION, FLAG_APP_DC_IDENTIFIER,
    FLAG_APP_DC_SUBJECT, FLAG_APP_DC_TITLE, FLAG_APP_EMPHASIZED,
    FLAG_CAT_HASAPP, FLAG_CAT_HASAUDIO, FLAG_CAT_HASIMAGE,
    FLAG_CAT_HASVIDEO, FLAG_CAT_INDEXOF,
)

# content domains (reference: cora/document/analysis/Classification.ContentDomain)
CD_ALL, CD_TEXT, CD_IMAGE, CD_AUDIO, CD_VIDEO, CD_APP = -1, 0, 1, 2, 3, 4


@dataclass
class RankingProfile:
    """The 32 shift coefficients, defaults per content domain.

    Names and default values follow the reference
    (RankingProfile.java:92-124); (de)serialization uses the same
    `name=value,...` external form so profiles survive the P2P search wire
    (reference: toExternalString, used in Protocol.java:957).
    """

    domlength: int = 10
    date: int = 9
    wordsintitle: int = 2
    wordsintext: int = 3
    phrasesintext: int = 0
    llocal: int = 0
    lother: int = 7
    urllength: int = 6
    urlcomps: int = 7
    hitcount: int = 1
    posintext: int = 4
    posofphrase: int = 0
    posinphrase: int = 0
    authority: int = 5
    worddistance: int = 10
    appurl: int = 12
    appdescr: int = 14      # app_dc_title ("description of page" legacy name)
    appauthor: int = 1      # app_dc_creator
    apptags: int = 2        # app_dc_subject
    appref: int = 10        # app_dc_description (anchor text)
    appemph: int = 5
    catindexof: int = 0
    cathasimage: int = 0
    cathasaudio: int = 0
    cathasvideo: int = 0
    cathasapp: int = 0
    tf: int = 8
    language: int = 2
    citation: int = 10
    # post-ranking predicates (applied host-side in SearchEvent.post_ranking)
    urlcompintoplist: int = 2
    descrcompintoplist: int = 2
    prefer: int = 0

    @staticmethod
    def for_contentdom(cd: int) -> "RankingProfile":
        p = RankingProfile()
        p.cathasapp = 15 if cd == CD_APP else 0
        p.cathasaudio = 15 if cd == CD_AUDIO else 0
        p.cathasimage = 15 if cd == CD_IMAGE else 0
        p.cathasvideo = 15 if cd == CD_VIDEO else 0
        p.catindexof = 0 if cd in (CD_TEXT, CD_ALL) else 15
        return p

    def to_external_string(self) -> str:
        return ",".join(f"{f.name}={getattr(self, f.name)}" for f in fields(self))

    @staticmethod
    def from_external_string(s: str) -> "RankingProfile":
        p = RankingProfile()
        if not s:
            return p
        s = s.strip()
        if s.startswith("{") and s.endswith("}"):
            s = s[1:-1].strip()
        parts = s.split("&") if "&" in s else s.split(",")
        valid = {f.name for f in fields(p)}
        for part in parts:
            if "=" not in part:
                continue
            k, _, v = part.strip().partition("=")
            if k in valid:
                try:
                    setattr(p, k, max(0, min(15, int(v))))
                except ValueError:
                    pass
        return p

    # -- kernel parameter vectors -------------------------------------------

    def norm_coeffs(self) -> np.ndarray:
        """int32 [NF]-aligned shift coefficients for normalized attributes.

        Index i applies to feature column i of index/postings.py. Sign
        convention: positive = higher-is-better (direct), negative =
        lower-is-better (the reference's `256 - norm` inversion).
        """
        c = np.zeros(P.NF, dtype=np.int32)
        c[P.F_LASTMOD] = self.date
        c[P.F_WORDS_IN_TITLE] = self.wordsintitle
        c[P.F_WORDS_IN_TEXT] = self.wordsintext
        c[P.F_PHRASES_IN_TEXT] = self.phrasesintext
        c[P.F_LLOCAL] = self.llocal
        c[P.F_LOTHER] = self.lother
        c[P.F_URL_LENGTH] = -self.urllength
        c[P.F_URL_COMPS] = -self.urlcomps
        c[P.F_HITCOUNT] = self.hitcount
        c[P.F_POSINTEXT] = -self.posintext
        c[P.F_POSINPHRASE] = -self.posinphrase
        c[P.F_POSOFPHRASE] = -self.posofphrase
        c[P.F_WORDDISTANCE] = -self.worddistance
        return c

    def flag_coeffs(self) -> tuple[np.ndarray, np.ndarray]:
        """(flag bit positions, shift coefficients) for the 255<<coeff terms."""
        pairs = [
            (FLAG_APP_DC_IDENTIFIER, self.appurl),
            (FLAG_APP_DC_TITLE, self.appdescr),
            (FLAG_APP_DC_CREATOR, self.appauthor),
            (FLAG_APP_DC_SUBJECT, self.apptags),
            (FLAG_APP_DC_DESCRIPTION, self.appref),
            (FLAG_APP_EMPHASIZED, self.appemph),
            (FLAG_CAT_INDEXOF, self.catindexof),
            (FLAG_CAT_HASIMAGE, self.cathasimage),
            (FLAG_CAT_HASAUDIO, self.cathasaudio),
            (FLAG_CAT_HASVIDEO, self.cathasvideo),
            (FLAG_CAT_HASAPP, self.cathasapp),
        ]
        bits = np.array([b for b, _ in pairs], dtype=np.int32)
        shifts = np.array([s for _, s in pairs], dtype=np.int32)
        return bits, shifts


# direct (higher-is-better) columns never invert; flags column is special
_NORM_DIRECT = np.zeros(P.NF, dtype=bool)
for _i in (P.F_LASTMOD, P.F_WORDS_IN_TITLE, P.F_WORDS_IN_TEXT,
           P.F_PHRASES_IN_TEXT, P.F_LLOCAL, P.F_LOTHER, P.F_HITCOUNT):
    _NORM_DIRECT[_i] = True


def _masked_minmax(feats: jnp.ndarray, valid: jnp.ndarray):
    """Column-wise min/max over valid rows (int32 sentinels elsewhere)."""
    big = jnp.int32(2**31 - 1)
    small = jnp.int32(-(2**31 - 1))
    v = valid[:, None]
    col_min = jnp.min(jnp.where(v, feats, big), axis=0)
    col_max = jnp.max(jnp.where(v, feats, small), axis=0)
    return col_min, col_max


def local_stats(feats: jnp.ndarray, valid: jnp.ndarray, hostids: jnp.ndarray,
                num_hosts: int, with_host_counts: bool = True) -> dict:
    """Per-block normalization statistics (pure shard-local reduces).

    Returned stats combine across shards with (min, max, min, max, sum):
    the sharded path (parallel/mesh.py) runs this per doc-shard, merges via
    lax.pmin/pmax/psum over the mesh axis, and feeds the merged stats to
    `cardinal_from_stats` — bitwise identical to the single-device path.

    `with_host_counts=False` skips the (expensive) per-host scatter-add —
    legitimate whenever the profile's authority guard is off (the
    reference also skips the domain-count accumulation then,
    ReferenceOrder.java:255)."""
    col_min, col_max = _masked_minmax(feats, valid)
    tfv = _term_frequency(feats)
    tf_min = jnp.min(jnp.where(valid, tfv, jnp.inf))
    tf_max = jnp.max(jnp.where(valid, tfv, -jnp.inf))
    if with_host_counts:
        host_counts = jax.ops.segment_sum(valid.astype(jnp.int32), hostids,
                                          num_segments=num_hosts)
    else:
        host_counts = jnp.zeros(1, dtype=jnp.int32)
    return {"col_min": col_min, "col_max": col_max,
            "tf_min": tf_min, "tf_max": tf_max, "host_counts": host_counts}


def _term_frequency(feats: jnp.ndarray) -> jnp.ndarray:
    """hitcount / (wordsintext + wordsintitle + 1)
    (WordReferenceVars.termFrequency semantics)."""
    return feats[:, P.F_HITCOUNT].astype(jnp.float32) / (
        feats[:, P.F_WORDS_IN_TEXT].astype(jnp.int32)
        + feats[:, P.F_WORDS_IN_TITLE].astype(jnp.int32) + 1
    ).astype(jnp.float32)


def _norm_div_exact_fast(prod: jnp.ndarray, safe_span: jnp.ndarray) -> jnp.ndarray:
    """floor(prod / span) without integer division (TPUs emulate int div
    expensively): f32-reciprocal estimate + /-1 integer correction.

    EXACT when prod <= 2^23 (f32 represents the product exactly and the
    estimate is within +-1 of the true quotient) — guaranteed for compact
    int16 blocks where prod = diff * 256 <= 2^15 * 256 = 2^23."""
    q0 = (prod.astype(jnp.float32)
          * (1.0 / safe_span.astype(jnp.float32))[None, :]).astype(jnp.int32)
    r = prod - q0 * safe_span[None, :]
    return q0 + (r >= safe_span[None, :]).astype(jnp.int32) \
        - (r < 0).astype(jnp.int32)


def cardinal_from_stats(feats: jnp.ndarray, valid: jnp.ndarray,
                        hostids: jnp.ndarray, stats: dict,
                        norm_coeffs: jnp.ndarray,
                        flag_bits: jnp.ndarray, flag_shifts: jnp.ndarray,
                        domlength_coeff: jnp.ndarray, tf_coeff: jnp.ndarray,
                        language_coeff: jnp.ndarray,
                        authority_coeff: jnp.ndarray,
                        language_pref: jnp.ndarray,
                        fast_div: bool = False,
                        flags: jnp.ndarray | None = None) -> jnp.ndarray:
    """Score rows against precomputed (possibly cross-shard) statistics.

    `feats` may be int16 (compact block) — expressions promote to int32
    elementwise, so XLA reads the narrow array from HBM and widens in
    registers; `flags` then carries the int32 bitfields separately (the
    compact block zeroes that column). No full-width copy is ever
    materialized."""
    col_min, col_max = stats["col_min"], stats["col_max"]
    span = col_max - col_min
    safe_span = jnp.maximum(span, 1)

    prod = (feats.astype(jnp.int32) - col_min[None, :]) * 256
    if fast_div:
        norm = _norm_div_exact_fast(prod, safe_span)
    else:
        norm = prod // safe_span[None, :]
    norm = jnp.where(span[None, :] == 0, 0, norm)
    direct = jnp.asarray(_NORM_DIRECT)
    # inverted attributes score (256 - norm), but stay 0 when span == 0
    inv = jnp.where(span[None, :] == 0, 0, 256 - norm)
    contrib = jnp.where(direct[None, :], norm, inv)
    shifts = jnp.abs(norm_coeffs)
    per_col = contrib << shifts[None, :]
    # columns with no coefficient at all (flags, doctype, language, domlength)
    active = jnp.asarray(
        np.array([True] * P.NF, dtype=bool)
        & ~np.isin(np.arange(P.NF), [P.F_FLAGS, P.F_DOCTYPE, P.F_LANGUAGE,
                                     P.F_DOMLENGTH]))
    score = jnp.sum(jnp.where(active[None, :], per_col, 0), axis=1)

    # domlength: stored pre-normalized 0..255; (256 - v) << coeff
    score = score + ((256 - feats[:, P.F_DOMLENGTH].astype(jnp.int32))
                     << domlength_coeff)

    # term frequency: hitcount / (wordsintext + wordsintitle + 1), min/max
    # normalized to 0..255 (WordReferenceVars.termFrequency semantics)
    tf = _term_frequency(feats)
    tf_min, tf_max = stats["tf_min"], stats["tf_max"]
    tf_span = tf_max - tf_min
    tf_norm = jnp.where(
        tf_span > 0, ((tf - tf_min) * 256.0 / jnp.maximum(tf_span, 1e-9)),
        0.0).astype(jnp.int32)
    score = score + (tf_norm << tf_coeff)

    # language preference match: 255 << coeff
    score = score + jnp.where(
        feats[:, P.F_LANGUAGE].astype(jnp.int32) == language_pref,
        jnp.int32(255) << language_coeff, 0)

    # appearance/category flags: 255 << coeff each
    if flags is None:
        flags = feats[:, P.F_FLAGS].astype(jnp.int32)
    flag_hit = (flags[:, None] >> flag_bits[None, :]) & 1
    score = score + jnp.sum(flag_hit * (255 << flag_shifts[None, :]), axis=1)

    # authority: domain-frequency score, only when coeff > 12
    # (ReferenceOrder.java:255 guard); counts precomputed in stats so they
    # can be psum'd across doc shards. A single-entry counts array means
    # the caller disabled authority at trace time (the guard is false):
    # skip the gather+divide entirely instead of computing a dead branch.
    counts = stats["host_counts"]
    if counts.shape[0] > 1:
        maxdom = jnp.max(counts)
        auth = (counts[hostids] << 8) // (1 + maxdom)
        score = score + jnp.where(authority_coeff > 12,
                                  auth << authority_coeff, 0)

    return jnp.where(valid, score, jnp.int32(-(2**31 - 1)))


def cardinal_scores(feats: jnp.ndarray, valid: jnp.ndarray,
                    hostids: jnp.ndarray, norm_coeffs: jnp.ndarray,
                    flag_bits: jnp.ndarray, flag_shifts: jnp.ndarray,
                    domlength_coeff: jnp.ndarray, tf_coeff: jnp.ndarray,
                    language_coeff: jnp.ndarray, authority_coeff: jnp.ndarray,
                    language_pref: jnp.ndarray) -> jnp.ndarray:
    """int32 cardinal score per posting row (invalid rows score MIN).

    Vectorized ReferenceOrder.cardinal (ReferenceOrder.java:223-265):
    every `(x-min)<<8 / (max-min) << coeff` term becomes a masked column
    op; the authority signal's ConcurrentScoreMap of host counts
    (ReferenceOrder.java:213-216) becomes a segment-sum over hostids.
    Single-device composition of local_stats + cardinal_from_stats.
    """
    stats = local_stats(feats, valid, hostids, num_hosts=feats.shape[0])
    return cardinal_from_stats(feats, valid, hostids, stats, norm_coeffs,
                               flag_bits, flag_shifts, domlength_coeff,
                               tf_coeff, language_coeff, authority_coeff,
                               language_pref)


# ---------------------------------------------------------------------------
# Compact device blocks — int16 features + separate int32 flags
# ---------------------------------------------------------------------------
# The scorer is HBM-bandwidth-bound: a 10M-row int32 block is 680 MB per
# scan. Every posting attribute except the flag bitfield is small by
# construction (hitcount <= 255, positions <= 2^15, day counts < 2^15), so
# the device-resident form halves the bytes: int16 [n, NF] with the flags
# column zeroed, plus one int32 [n] flags array. Values are clipped into
# int16 range at pack time — part of the block format, applied identically
# on every read path.

INT16_MAX = 32767


def compact_feats(feats: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """int32 [n, NF] -> (int16 [n, NF] with flags zeroed, int32 [n] flags)."""
    flags = np.ascontiguousarray(feats[:, P.F_FLAGS]).astype(np.int32)
    small = np.clip(feats, -INT16_MAX - 1, INT16_MAX).astype(np.int16)
    small[:, P.F_FLAGS] = 0
    return small, flags


def cardinal_scores16(feats16: jnp.ndarray, flags: jnp.ndarray,
                      valid: jnp.ndarray, hostids: jnp.ndarray,
                      stats: dict | None, norm_coeffs: jnp.ndarray,
                      flag_bits: jnp.ndarray, flag_shifts: jnp.ndarray,
                      domlength_coeff: jnp.ndarray, tf_coeff: jnp.ndarray,
                      language_coeff: jnp.ndarray,
                      authority_coeff: jnp.ndarray,
                      language_pref: jnp.ndarray,
                      with_authority: bool = True) -> jnp.ndarray:
    """Compact-block scorer: reads half the bytes of the int32 path and
    normalizes with the exact fast division. Identical scores to
    cardinal_scores over `compact_feats`-clipped int32 input.

    `with_authority` is the TRACE-TIME authority guard (profile.authority
    > 12, known host-side): when False the per-host scatter/gather is
    never built into the program."""
    if stats is None:
        # NB: the flags column's min/max come out 0 (the compact block
        # zeroes that column) — harmless: normalization masks the flags
        # column out entirely; the bitfield scores via `flags` below
        stats = local_stats(feats16, valid, hostids,
                            num_hosts=feats16.shape[0],
                            with_host_counts=with_authority)
    return cardinal_from_stats(feats16, valid, hostids, stats, norm_coeffs,
                               flag_bits, flag_shifts, domlength_coeff,
                               tf_coeff, language_coeff, authority_coeff,
                               language_pref, fast_div=True, flags=flags)


@partial(jax.jit, static_argnames=("k", "with_authority"))
def score_topk16(feats16: jnp.ndarray, flags: jnp.ndarray,
                 docids: jnp.ndarray, valid: jnp.ndarray,
                 hostids: jnp.ndarray, norm_coeffs: jnp.ndarray,
                 flag_bits: jnp.ndarray, flag_shifts: jnp.ndarray,
                 domlength_coeff: jnp.ndarray, tf_coeff: jnp.ndarray,
                 language_coeff: jnp.ndarray, authority_coeff: jnp.ndarray,
                 language_pref: jnp.ndarray, k: int,
                 with_authority: bool = True):
    """Fused compact-block cardinal + top-k (bandwidth-halved score_topk)."""
    scores = cardinal_scores16(feats16, flags, valid, hostids, None,
                               norm_coeffs, flag_bits, flag_shifts,
                               domlength_coeff, tf_coeff, language_coeff,
                               authority_coeff, language_pref,
                               with_authority=with_authority)
    # lint: tie-ok(lax.top_k breaks ties by lowest input index and the candidate rows are docid-ordered, so equal scores surface docid-ASC — the pinned discipline, asserted by the tie tests in test_ranking)
    top_scores, top_idx = jax.lax.top_k(scores, k)
    return top_scores, docids[top_idx], top_idx


@partial(jax.jit, static_argnames=("k", "with_authority"))
def score_topk16_packed(feats16: jnp.ndarray, flags: jnp.ndarray,
                        docids: jnp.ndarray, valid: jnp.ndarray,
                        hostids: jnp.ndarray, norm_coeffs: jnp.ndarray,
                        flag_bits: jnp.ndarray, flag_shifts: jnp.ndarray,
                        domlength_coeff: jnp.ndarray,
                        tf_coeff: jnp.ndarray,
                        language_coeff: jnp.ndarray,
                        authority_coeff: jnp.ndarray,
                        language_pref: jnp.ndarray, k: int,
                        with_authority: bool = True):
    """score_topk16 with a packed [2k] int32 output (scores ++ docids):
    ONE device->host transfer per query — through a remote tunnel every
    separately fetched array is its own round trip, and the upload path
    (CardinalRanker.rank over a candidate block) paid two."""
    s, d, _ = score_topk16(feats16, flags, docids, valid, hostids,
                           norm_coeffs, flag_bits, flag_shifts,
                           domlength_coeff, tf_coeff, language_coeff,
                           authority_coeff, language_pref, k,
                           with_authority=with_authority)
    return jnp.concatenate([s, d])


@partial(jax.jit, static_argnames=("k",))
def score_topk(feats: jnp.ndarray, docids: jnp.ndarray, valid: jnp.ndarray,
               hostids: jnp.ndarray, norm_coeffs: jnp.ndarray,
               flag_bits: jnp.ndarray, flag_shifts: jnp.ndarray,
               domlength_coeff: jnp.ndarray, tf_coeff: jnp.ndarray,
               language_coeff: jnp.ndarray, authority_coeff: jnp.ndarray,
               language_pref: jnp.ndarray, k: int):
    """Fused cardinal + top-k: the device replacement for the rwiStack heap
    (reference: SearchEvent.java:809 bounded WeakPriorityBlockingQueue)."""
    scores = cardinal_scores(feats, valid, hostids, norm_coeffs, flag_bits,
                             flag_shifts, domlength_coeff, tf_coeff,
                             language_coeff, authority_coeff, language_pref)
    # lint: tie-ok(lax.top_k breaks ties by lowest input index and the candidate rows are docid-ordered, so equal scores surface docid-ASC — the pinned discipline, asserted by the tie tests in test_ranking)
    top_scores, top_idx = jax.lax.top_k(scores, k)
    return top_scores, docids[top_idx], top_idx


def pad_to(n: int, tile: int = 128) -> int:
    """Round up to a tile multiple (lane dimension friendly); min one tile."""
    return max(tile, ((n + tile - 1) // tile) * tile)


def hostid_array(docids: np.ndarray, hosthashes: list[bytes] | np.ndarray) -> np.ndarray:
    """Map per-row host hashes to dense int ids (for the authority kernel)."""
    _, ids = np.unique(np.asarray(hosthashes), return_inverse=True)
    return ids.astype(np.int32)


# below this candidate count the kernel dispatch overhead (and, through a
# remote tunnel, the device round trip) dwarfs the scoring work: score on
# the host instead. 4096×NF int64 numpy ops run in ~0.1ms; a CPU-backend
# jit dispatch costs ~10ms and a tunnel round trip ~110ms (BASELINE.md).
SMALL_RANK_N = 4096


# columns carrying normalized contributions (flags/doctype/language/
# domlength are handled by their own terms)
_ACTIVE_COLS = ~np.isin(
    np.arange(P.NF), [P.F_FLAGS, P.F_DOCTYPE, P.F_LANGUAGE, P.F_DOMLENGTH])


def pack_stats_host(feats16: np.ndarray, flags: np.ndarray) -> dict:
    """Normalization stats over a compact block (numpy twin of
    local_stats, all rows valid) — float32 tf to match the kernel."""
    f = feats16.astype(np.int32)
    tf = f[:, P.F_HITCOUNT].astype(np.float32) / (
        f[:, P.F_WORDS_IN_TEXT] + f[:, P.F_WORDS_IN_TITLE] + 1
    ).astype(np.float32)
    return {
        "col_min": f.min(axis=0).astype(np.int32),
        "col_max": f.max(axis=0).astype(np.int32),
        "tf_min": np.float32(tf.min()),
        "tf_max": np.float32(tf.max()),
    }


def cardinal_from_stats_host(feats16: np.ndarray, flags: np.ndarray,
                             stats: dict, prof: "RankingProfile",
                             language_pref: int,
                             hostids: np.ndarray | None = None) -> np.ndarray:
    """Numpy twin of cardinal_from_stats over a compact block. Integer
    parts are bit-exact vs the device kernel; tf normalization runs in
    float32 like the kernel (so host and device agree on the same input).
    The single canonical host twin: CardinalRanker's small-candidate fast
    path and devstore's pack-time proxy ordering both call this."""
    f = feats16.astype(np.int32)
    col_min, col_max = stats["col_min"], stats["col_max"]
    span = col_max - col_min
    safe = np.maximum(span, 1)
    norm = ((f - col_min[None, :]) * 256) // safe[None, :]
    norm = np.where(span[None, :] == 0, 0, norm)
    inv = np.where(span[None, :] == 0, 0, 256 - norm)
    contrib = np.where(_NORM_DIRECT[None, :], norm, inv)
    per_col = contrib << np.abs(prof.norm_coeffs())[None, :]
    score = np.where(_ACTIVE_COLS[None, :], per_col, 0).sum(
        axis=1, dtype=np.int64)
    score += (256 - f[:, P.F_DOMLENGTH]) << prof.domlength
    tf = f[:, P.F_HITCOUNT].astype(np.float32) / (
        f[:, P.F_WORDS_IN_TEXT] + f[:, P.F_WORDS_IN_TITLE] + 1
    ).astype(np.float32)
    tf_span = stats["tf_max"] - stats["tf_min"]
    tf_norm = np.where(
        tf_span > 0,
        (tf - stats["tf_min"]) * np.float32(256.0) / max(tf_span, 1e-9),
        0.0).astype(np.int32)
    score += tf_norm.astype(np.int64) << prof.tf
    score += np.where(f[:, P.F_LANGUAGE] == language_pref,
                      255 << prof.language, 0)
    bits, shifts = prof.flag_coeffs()
    hit = (flags[:, None] >> bits[None, :]) & 1
    score += (hit * (255 << shifts[None, :])).sum(axis=1, dtype=np.int64)
    if prof.authority > 12 and hostids is not None and len(f):
        counts = np.bincount(hostids, minlength=int(hostids.max()) + 1)
        auth = (counts[hostids].astype(np.int64) << 8) // (1 + counts.max())
        score += auth << prof.authority
    return score.astype(np.int64)


def cardinal_scores_host(feats: np.ndarray, profile: "RankingProfile",
                         language: str = "en",
                         hostids: np.ndarray | None = None) -> np.ndarray:
    """Pure-numpy scorer for small candidate sets (the P2P fan-out's
    per-peer searches and tiny-term queries, where a device dispatch per
    query would dominate end-to-end latency). Scores the SAME compact
    int16 representation the device path scores (compact_feats clip +
    float32 tf), so host and device agree on every input."""
    feats16, flags = compact_feats(np.asarray(feats, dtype=np.int32))
    stats = pack_stats_host(feats16, flags)
    return cardinal_from_stats_host(feats16, flags, stats, profile,
                                    P.pack_language(language), hostids)


class CardinalRanker:
    """Host-side wrapper: pad → upload → score_topk, profile baked in."""

    def __init__(self, profile: RankingProfile | None = None,
                 language: str = "en"):
        self.profile = profile or RankingProfile()
        self._lang_str = language
        self._consts = None   # device constants, built on first device rank

    def _device_consts(self):
        """Lazy device upload of the profile constants: a ranker whose
        every query takes the small-n host path (tiny peers, sparse terms)
        must never pay the 11 per-constant transfers at construction —
        SearchEvent builds one ranker per query."""
        if self._consts is None:
            bits, shifts = self.profile.flag_coeffs()
            self._consts = (
                jnp.asarray(self.profile.norm_coeffs()),
                jnp.asarray(bits), jnp.asarray(shifts),
                jnp.int32(self.profile.domlength),
                jnp.int32(self.profile.tf),
                jnp.int32(self.profile.language),
                jnp.int32(self.profile.authority),
                jnp.int32(P.pack_language(self._lang_str)))
        return self._consts

    # constant accessors (kernel call sites and the multichip dryrun read
    # these; they trigger the lazy device upload)
    @property
    def _norm(self):
        return self._device_consts()[0]

    @property
    def _bits(self):
        return self._device_consts()[1]

    @property
    def _shifts(self):
        return self._device_consts()[2]

    @property
    def _dl(self):
        return self._device_consts()[3]

    @property
    def _tf(self):
        return self._device_consts()[4]

    @property
    def _lang_c(self):
        return self._device_consts()[5]

    @property
    def _auth(self):
        return self._device_consts()[6]

    @property
    def _lang(self):
        return self._device_consts()[7]

    def rank(self, plist, hosthashes=None, k: int = 10):
        """(scores, docids) best-first over a PostingsList."""
        n = len(plist)
        if n == 0:
            return np.empty(0, np.int32), np.empty(0, np.int32)
        if n <= SMALL_RANK_N:
            # host fast path: no kernel dispatch for tiny candidate sets
            hostids = (hostid_array(plist.docids, hosthashes)
                       if hosthashes is not None else None)
            s = cardinal_scores_host(plist.feats, self.profile,
                                     self._lang_str, hostids)
            order = np.argsort(-s, kind="stable")[:k]
            return s[order], plist.docids[order]
        npad = pad_to(n)
        feats = np.zeros((npad, P.NF), np.int32)
        feats[:n] = plist.feats
        docids = np.full(npad, -1, np.int32)
        docids[:n] = plist.docids
        valid = np.zeros(npad, bool)
        valid[:n] = True
        hostids = np.zeros(npad, np.int32)
        if hosthashes is not None:
            hostids[:n] = hostid_array(plist.docids, hosthashes)
        kk = min(k, npad)
        feats16, flags = compact_feats(feats)
        norm, bits, shifts, dl, tf, lang_c, auth, lang = self._device_consts()
        out = score_topk16_packed(
            jnp.asarray(feats16), jnp.asarray(flags),
            jnp.asarray(docids), jnp.asarray(valid),
            jnp.asarray(hostids),
            norm, bits, shifts, dl, tf, lang_c, auth, lang, kk,
            with_authority=self.profile.authority > 12)
        host = np.asarray(out)       # one packed fetch (scores ++ docids)
        s, d = host[:kk], host[kk:]
        keep = d >= 0
        keep &= s > -(2**31 - 1)
        return s[keep][:k], d[keep][:k]


# ---------------------------------------------------------------------------
# BM25 — dense doc×term first-stage relevance (BASELINE.json configs)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("k",))
def bm25_topk(tf: jnp.ndarray, doclen: jnp.ndarray, df: jnp.ndarray,
              ndocs: jnp.ndarray, valid: jnp.ndarray, docids: jnp.ndarray,
              k: int, k1: float = 1.2, b: float = 0.75):
    """BM25 over a dense [docs, terms] tf block + top-k.

    tf:     float32/int32 [n, t] term frequencies for the query terms
    doclen: int32 [n] document lengths (words)
    df:     int32 [t] document frequencies of the query terms
    ndocs:  scalar corpus size
    """
    tf = tf.astype(jnp.float32)
    dl = doclen.astype(jnp.float32)
    avgdl = jnp.sum(jnp.where(valid, dl, 0.0)) / jnp.maximum(
        jnp.sum(valid.astype(jnp.float32)), 1.0)
    idf = jnp.log(1.0 + (ndocs.astype(jnp.float32) - df + 0.5) / (df + 0.5))
    denom = tf + k1 * (1.0 - b + b * (dl / jnp.maximum(avgdl, 1e-6))[:, None])
    score = jnp.sum(idf[None, :] * tf * (k1 + 1.0) / jnp.maximum(denom, 1e-9),
                    axis=1)
    score = jnp.where(valid, score, -jnp.inf)
    # lint: tie-ok(lax.top_k breaks ties by lowest input index and the candidate rows are docid-ordered, so equal scores surface docid-ASC — the pinned discipline, asserted by the tie tests in test_ranking)
    top_scores, top_idx = jax.lax.top_k(score, k)
    return top_scores, docids[top_idx]


def bm25_scores_np(tf: np.ndarray, doclen: np.ndarray, df: np.ndarray,
                   ndocs: int, k1: float = 1.2, b: float = 0.75) -> np.ndarray:
    """Numpy oracle for tests/benchmarks (identical math)."""
    tf = tf.astype(np.float64)
    dl = doclen.astype(np.float64)
    avgdl = dl.mean() if len(dl) else 1.0
    idf = np.log(1.0 + (ndocs - df + 0.5) / (df + 0.5))
    denom = tf + k1 * (1.0 - b + b * (dl / max(avgdl, 1e-6))[:, None])
    return (idf[None, :] * tf * (k1 + 1.0) / np.maximum(denom, 1e-9)).sum(axis=1)
