"""IVF ANN kernel family — dense-first candidate generation (ISSUE 11).

M81 made dense vectors a *rescoring* signal: the forward-index rerank
can only reorder candidates the sparse stage already found, so a query
that sparse retrieval misses can never be recovered by the dense path.
This family inverts that (arxiv 2110.06051): a clustered (IVF-style)
device-resident index makes dense a first-class candidate *generator*,
with the compact-index discipline of arxiv 1406.3170 applied to the
vectors themselves — int8 quantization with a per-vector scale keeps
10M+ vectors inside the same HBM budget as the postings.

Two kernels, both riding the devstore issue→completer pipeline as the
``ann`` part kind (index/devstore._dispatch_anns):

- **centroid assignment** — ONE (B,dim)×(dim,C) bf16 MXU matmul per
  dispatch wave: every queued dense-first query's vector contracts
  against the shared centroid matrix in a single dispatch, returning
  each slot's ``nprobe`` nearest cluster ids.
- **probe + fuse** — batched gathers over the contiguous per-cluster
  int8 vector slabs (index/annstore.AnnVectorIndex lays clusters out
  as contiguous row runs, so probe lanes are arange windows, not
  scattered indices), f16 dequant fused into the scoring matmul
  (``sims = (q·int8_rows) * scale``), the fixed-scale cardinal boost
  (ops/dense.DENSE_BOOST_SCALE — one score domain with the sparse
  first stage), and a (score DESC, docid ASC) two-key sort: the pinned
  tie discipline, so solo/batched/cached dense-first answers can never
  disagree on ties.  Sparse candidates ride the SAME kernel as extra
  lanes carrying their cardinal scores — the fused list is one kernel
  output, not a host merge of two score domains.

NumPy oracles (``ann_assign_np`` / ``ann_fuse_np``) pin bit-parity at
the exact-scoring stage (the matmul over the quantized vectors is
exact — only the IVF candidate restriction is approximate) and double
as the host-fallback path during device loss.  ``ANN_ORACLES`` is the
hygiene registry: tests/test_code_hygiene.py demands an entry — and a
roofline cost model — for every ``_ann_*`` jit kernel here.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .dense import DENSE_BOOST_SCALE

# default probe width: clusters scored per query. The serving knob is
# index.ann.nprobe (devstore.ann_nprobe); this is the bench/test anchor
# the recall gate is stated at.
ANN_DEFAULT_NPROBE = 8
# per-query probe lane budget (pow2): bounds the gather width of one
# fuse dispatch — the index.ann.probeLanes knob. Probes past the budget
# are dropped whole-cluster (counted, never silently truncated mid-
# cluster, which would make the candidate set depend on slab order).
ANN_DEFAULT_PROBE_LANES = 1 << 15
# pad lanes/keys
_NEG = -(2 ** 31 - 1)
_INT_MAX = 2 ** 31 - 1


def ann_lane_bucket(n: int, cap: int) -> int:
    """Static pow2 lane bucket (>=256) for one fuse slot, capped at the
    probe-lane budget's bucket — bounded compile shapes, like
    ops/dense.rerank_bucket."""
    b = 1 << max(8, (max(n, 1) - 1).bit_length())
    return min(b, 1 << max(8, (max(cap, 1) - 1).bit_length()))


def ann_topk_bucket(k: int, nb: int) -> int:
    """Static pow2 output bucket for the fused top-k: oversampled 2x so
    the host-side dedup (a docid reachable both as a probe lane and a
    sparse lane) still fills k, clamped to the lane bucket."""
    return min(nb, 1 << max(4, (2 * max(k, 1) - 1).bit_length()))


# -- centroid assignment -----------------------------------------------------

@functools.partial(jax.jit, static_argnames=("np_", "c_real"))
def _ann_assign_batch_kernel(cent, qv, np_: int, c_real: int):
    """ONE (B,dim)×(dim,C) bf16 MXU matmul per dispatch wave: the whole
    wave's query vectors against the device-resident centroid matrix,
    top-``np_`` centroid ids per slot (f32 accumulate; ties resolve by
    centroid id ASC — lax.top_k orders ties by input position, which IS
    the centroid id).  Pad slots (zero vectors) cost nothing extra and
    their ids are ignored by the dispatcher.  ``c_real`` masks the
    pow2-pad centroid rows to -inf: a zero pad row's sim (0.0) would
    otherwise outrank every real cluster with NEGATIVE similarity and
    silently shrink the probe set for anti-aligned queries."""
    sims = jnp.dot(qv.astype(jnp.bfloat16),
                   cent.astype(jnp.bfloat16).T,
                   preferred_element_type=jnp.float32)    # (B, C)
    sims = jnp.where(jnp.arange(cent.shape[0])[None, :] < c_real,
                     sims, -jnp.inf)
    # lint: tie-ok(ties resolve by centroid id ASC: top_k orders
    # ties by input position, which IS the centroid id — see the
    # docstring)
    _s, ids = lax.top_k(sims, np_)
    return ids.astype(jnp.int32)


def ann_assign_np(cent, qv, nprobe: int) -> np.ndarray:
    """CPU oracle for _ann_assign_batch_kernel (and the host-fallback
    assignment during device loss): bf16-rounded inputs like the MXU
    matmul, f32 accumulation, ties by centroid id ASC."""
    import ml_dtypes
    sims = (np.asarray(qv).astype(ml_dtypes.bfloat16).astype(np.float32)
            @ np.asarray(cent).astype(ml_dtypes.bfloat16)
            .astype(np.float32).T)
    # argsort on (-sim, id): stable sort gives id-ASC ties like top_k
    return np.argsort(-sims, axis=-1, kind="stable")[..., :nprobe] \
        .astype(np.int32)


# -- probe + fuse ------------------------------------------------------------

def pack_ann_fuse_row(qvec: np.ndarray, rows: np.ndarray,
                      docids: np.ndarray, sparse: np.ndarray,
                      alpha: float, nb: int) -> np.ndarray:
    """ONE fused int32 descriptor for one dense-first slot (the
    pack_rerank_row discipline: a dispatch wave is one host->device
    transfer, not one per argument).

    Layout: ``[n_valid, alpha_bits, rows[nb], docids[nb], sparse[nb],
    qvec_bits[dim]]``.  Three lane kinds share the arrays:

    - probe lane: ``rows[i] >= 0`` into the hot slab, ``docids[i] = -1``
      (the kernel resolves the docid from the resident slab docid
      column), ``sparse[i] = 0``;
    - sparse-candidate lane: ``docids[i] >= 0`` with its cardinal score
      in ``sparse[i]``; ``rows[i]`` is its hot-slab row or -1 when the
      vector is outside the hot tier (scores sparse+0 — vector absence
      must never drop a sparse result);
    - pad lane (``i >= n_valid``): masked to NEG_INF/INT32_MAX keys.
    """
    n = len(rows)
    dim = len(qvec)
    row = np.zeros(2 + 3 * nb + dim, np.int32)
    row[0] = n
    row[1] = np.float32(alpha).view(np.int32)
    row[2:2 + n] = np.asarray(rows, np.int32)
    row[2 + nb:2 + nb + n] = np.asarray(docids, np.int32)
    row[2 + 2 * nb:2 + 2 * nb + n] = np.asarray(sparse, np.int32)
    row[2 + 3 * nb:] = np.asarray(qvec, np.float32).view(np.int32)
    return row


@functools.partial(jax.jit, static_argnames=("nb", "bs", "k"))
def _ann_fuse_batch_packed_kernel(slab, scales, sdocids, qi,
                                  nb: int, bs: int, k: int):
    """Batched IVF probe + dense/sparse fusion against the hot int8
    slab, packed I/O: ``qi`` [bs, 2+3*nb+dim] descriptors
    (pack_ann_fuse_row), output [bs, 2*k] = fused scores ++ docids.

    Each slot gathers its lanes' int8 vectors, dequantizes INSIDE the
    scoring matmul (bf16 contract × per-vector f16 scale — the int8
    rows never materialize as f16 in HBM), adds the fixed-scale
    cardinal boost to the lanes' sparse scores (dense_boost_topk
    semantics: one score domain with the sparse first stage), and sorts
    by (score DESC, docid ASC) — the pinned tie discipline. Lanes
    outside the slab (row -1: a sparse candidate without a hot vector)
    score sparse+0; pad lanes sort last."""
    dim = slab.shape[1]
    cap = slab.shape[0]
    nvalid = qi[:, 0]
    alpha = lax.bitcast_convert_type(qi[:, 1], jnp.float32)
    rows = qi[:, 2:2 + nb]
    docids = qi[:, 2 + nb:2 + 2 * nb]
    sparse = qi[:, 2 + 2 * nb:2 + 3 * nb]
    qvecs = lax.bitcast_convert_type(qi[:, 2 + 3 * nb:], jnp.float32)
    cr = jnp.clip(rows, 0, cap - 1)
    g = slab[cr]                                   # (bs, nb, dim) int8
    sims = jnp.einsum("bd,bnd->bn", qvecs.astype(jnp.bfloat16),
                      g.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
    in_slab = (rows >= 0) & (rows < cap)
    sims = jnp.where(in_slab, sims * scales[cr].astype(jnp.float32), 0.0)
    # probe lanes resolve their docid from the resident slab column;
    # sparse lanes carry theirs explicitly
    dd = jnp.where(docids >= 0, docids,
                   jnp.where(in_slab, sdocids[cr], jnp.int32(_INT_MAX)))
    boost = jnp.round(sims * alpha[:, None]
                      * DENSE_BOOST_SCALE).astype(jnp.int32)
    lanes = jnp.arange(nb)[None, :]
    valid = (lanes < nvalid[:, None]) & (dd != _INT_MAX)
    final = jnp.where(valid, sparse + boost, jnp.int32(_NEG))
    skey = -final
    # masked lanes carry INT32_MAX as BOTH tie key and output docid —
    # consumers drop them by docid, so a pad lane can never leak a
    # real docid with a NEG score
    tkey = jnp.where(valid, dd, jnp.int32(_INT_MAX))

    def one(sk, tk, f):
        # two-key (score DESC, docid ASC) sort; tkey doubles as payload
        _sk, _tk, fs, ds = lax.sort((sk, tk, f, tk), num_keys=2)
        return fs[:k], ds[:k]

    fs, ds = jax.vmap(one)(skey, tkey, final)
    return jnp.concatenate([fs, ds], axis=1)


def ann_fuse_np(slab, scales, sdocids, rows, docids, sparse, qvec,
                alpha: float, k: int):
    """CPU oracle for one _ann_fuse_batch_packed_kernel slot — and the
    host scoring path for warm/cold (non-device-resident) probe lanes
    and the device-loss fallback: bf16-rounded matmul inputs like the
    kernel, f32 accumulation, identical fixed-scale boost and the SAME
    (score DESC, docid ASC) tie discipline.  Accumulation order may
    differ from the device dot by a few float ulps (compare rounded-
    boost closeness per docid, not bit-exact scores); device paths
    among THEMSELVES are bit-exact at a shared compile shape.

    Returns (scores[<=k], docids[<=k]) over the VALID lanes only."""
    import ml_dtypes
    rows = np.asarray(rows, np.int64)
    docids = np.asarray(docids, np.int64)
    sparse = np.asarray(sparse, np.int64)
    cap = slab.shape[0]
    in_slab = (rows >= 0) & (rows < cap)
    cr = np.clip(rows, 0, cap - 1)
    g = np.asarray(slab[cr]).astype(ml_dtypes.bfloat16).astype(np.float32)
    q = np.asarray(qvec).astype(ml_dtypes.bfloat16).astype(np.float32)
    sims = g @ q
    sims = np.where(in_slab,
                    sims * np.asarray(scales[cr], np.float32), 0.0)
    dd = np.where(docids >= 0, docids,
                  np.where(in_slab, np.asarray(sdocids)[cr], _INT_MAX))
    boost = np.round(sims * np.float32(alpha)
                     * np.float32(DENSE_BOOST_SCALE)).astype(np.int64)
    final = sparse + boost
    ok = dd != _INT_MAX
    final, dd = final[ok], dd[ok]
    order = np.lexsort((dd, -final))[:k]
    return final[order].astype(np.int64), dd[order].astype(np.int32)


def fuse_dedup(scores: np.ndarray, docids: np.ndarray,
               k: int) -> tuple[np.ndarray, np.ndarray]:
    """Collapse duplicate docids in a (score DESC, docid ASC)-ordered
    fused list, keeping the FIRST (= best-scored: a docid reachable
    both as a probe lane and as a sparse lane keeps its
    sparse+boost entry, which dominates its boost-only twin), then trim
    to k. Stable, so the tie discipline survives."""
    seen: set = set()
    keep = np.zeros(len(docids), bool)
    for i, d in enumerate(docids.tolist()):
        if d not in seen:
            seen.add(d)
            keep[i] = True
    return scores[keep][:k], docids[keep][:k]


def merge_fused(parts: list, k: int) -> tuple[np.ndarray, np.ndarray]:
    """Merge independently-ordered fused (scores, docids) part lists
    (device lanes + host-scored warm/cold lanes) under the pinned
    (score DESC, docid ASC) discipline, dedup best-first, trim to k."""
    if not parts:
        return np.empty(0, np.int64), np.empty(0, np.int32)
    s = np.concatenate([np.asarray(p[0], np.int64) for p in parts])
    d = np.concatenate([np.asarray(p[1], np.int32) for p in parts])
    order = np.lexsort((d, -s))
    return fuse_dedup(s[order], d[order], k)


# hygiene registry (tests/test_code_hygiene.py): every _ann_* jit
# kernel must carry a NumPy oracle here AND a roofline cost model in
# ops/roofline.KERNELS — a new ANN kernel cannot land unregistered.
ANN_ORACLES: dict[str, object] = {
    "_ann_assign_batch_kernel": ann_assign_np,
    "_ann_fuse_batch_packed_kernel": ann_fuse_np,
}
