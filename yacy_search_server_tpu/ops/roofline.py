"""Roofline cost accounting — every serving kernel gets a silicon number.

The perf story so far measured kernels against CPU twins (bench.py
`vs_baseline`); nothing said how far from the HARDWARE's ceiling a kernel
runs (VERDICT r5 weak #7: "no hardware-relative utilization number exists
anywhere"). This module is the analytical half of that accounting:

- a **cost model registry**: for each named serving kernel, closed-form
  FLOPs / bytes-moved as functions of its shape parameters. Two byte
  models per kernel, because they answer different questions:

  * ``bytes``  — COMPULSORY traffic: operands that must stream from HBM
    plus results written back, assuming perfect fusion (the roofline
    denominator — achieved GB/s against the HBM peak is only meaningful
    over bytes that physically must move).
  * ``xla_bytes`` — fusion-boundary traffic as XLA's HloCostAnalysis
    models it (operand + output bytes of each fusion, whole operand
    arrays counted for dynamic-slice reads). Coefficients are calibrated
    against ``jax.jit(...).lower().compile().cost_analysis()`` on the CPU
    backend and PINNED BY TEST (tests/test_roofline.py: within 10% on 3
    representative shapes per kernel) — a kernel edit that changes the
    dataflow breaks the pin and forces the model to be re-derived.

  ``flops`` follows XLA's arithmetic-op counting (elementwise int ops
  count as flops), so one number serves both the cross-check and the
  achieved-FLOP/s roofline axis.

- a **per-device peak table** (TPU generations + the CPU test backend),
  overridable via config/env — utilization is stated against a DECLARED
  peak, never a guessed one.

- the **roofline verdict**: arithmetic intensity (flops/byte) against the
  device ridge point classifies each kernel compute- vs memory-bound;
  ``util_pct`` is achieved-vs-peak along the BINDING axis.

Loop-carried kernels (lax.scan / fori_loop bodies) are modeled per
executed step and multiplied by the trip count — XLA's cost analysis
counts a loop body ONCE regardless of trip count, so the cross-check for
those kernels compares the per-step body cost (see tests).

References: Williams et al., "Roofline: an insightful visual performance
model" (CACM 2009); arXiv:2110.06051 and arXiv:1406.3170 frame the dense
rerank and postings/top-k efficiency in exactly these absolute
compute/byte terms.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from ..index import postings as P

# compact-block row: int16 feats + int32 flags + int32 docids
ROW_BYTES = P.NF * 2 + 4 + 4
# + the tombstone-bitmap gather (bool per row)
ROW_BYTES_DEAD = ROW_BYTES + 1


@dataclass(frozen=True)
class Cost:
    """One kernel execution's analytical cost."""

    flops: float       # arithmetic ops (XLA counting conventions)
    bytes: float       # compulsory HBM traffic (roofline denominator)
    xla_bytes: float   # fusion-boundary traffic (cost_analysis parity)

    @property
    def intensity(self) -> float:
        """Arithmetic intensity in FLOPs per compulsory byte."""
        return self.flops / max(self.bytes, 1.0)


@dataclass(frozen=True)
class DevicePeak:
    """Declared hardware ceilings for one device kind."""

    name: str
    flops_per_s: float     # dense-compute peak (bf16 MXU on TPU)
    bytes_per_s: float     # HBM bandwidth peak

    @property
    def ridge(self) -> float:
        """Intensity (flops/byte) where the roofline bends."""
        return self.flops_per_s / self.bytes_per_s


# Published peaks per device generation (the `device_kind` strings jax
# reports). v5e: 197 TFLOP/s bf16, 819 GB/s HBM. The CPU entry is a
# deliberately conservative single-core envelope for the test backend —
# utilization numbers on CPU are for plumbing tests, not claims.
PEAKS: dict[str, DevicePeak] = {
    "tpu v5 lite": DevicePeak("TPU v5e", 197e12, 819e9),
    "tpu v5e": DevicePeak("TPU v5e", 197e12, 819e9),
    "tpu v4": DevicePeak("TPU v4", 275e12, 1228e9),
    "tpu v3": DevicePeak("TPU v3", 123e12, 900e9),
    "tpu v2": DevicePeak("TPU v2", 46e12, 700e9),
    "cpu": DevicePeak("CPU (1-core envelope)", 5e10, 2.5e10),
}


def device_peak(device=None) -> DevicePeak:
    """The peak table entry for a jax device (env/config overridable:
    YACY_ROOFLINE_PEAK_FLOPS / YACY_ROOFLINE_PEAK_GBPS take precedence —
    deployments on unlisted silicon declare their own ceiling)."""
    kind = "cpu"
    if device is not None:
        kind = getattr(device, "device_kind", "cpu").lower()
    else:
        try:
            import jax
            kind = jax.devices()[0].device_kind.lower()
        except Exception:   # no backend at all: the CPU envelope stands
            kind = "cpu"
    peak = PEAKS.get(kind)
    if peak is None:
        # unknown accelerator: fall back by family, never crash serving
        peak = next((p for k, p in PEAKS.items()
                     if k != "cpu" and k in kind), PEAKS["cpu"])
    env_f = os.environ.get("YACY_ROOFLINE_PEAK_FLOPS")
    env_b = os.environ.get("YACY_ROOFLINE_PEAK_GBPS")
    if env_f or env_b:
        peak = DevicePeak(
            peak.name + " (overridden)",
            float(env_f) if env_f else peak.flops_per_s,
            float(env_b) * 1e9 if env_b else peak.bytes_per_s)
    return peak


@dataclass(frozen=True)
class RooflinePoint:
    """A kernel execution placed on the roofline."""

    kernel: str
    flops: float
    bytes: float
    wall_s: float
    achieved_flops_per_s: float
    achieved_bytes_per_s: float
    intensity: float
    bound: str          # "memory" | "compute"
    util_pct: float     # achieved vs peak along the binding axis


def roofline_point(kernel: str, cost: Cost, wall_s: float,
                   peak: DevicePeak) -> RooflinePoint:
    """Place one measured execution against the device roofline."""
    wall_s = max(wall_s, 1e-9)
    af = cost.flops / wall_s
    ab = cost.bytes / wall_s
    bound = "memory" if cost.intensity < peak.ridge else "compute"
    if bound == "memory":
        util = 100.0 * ab / peak.bytes_per_s
    else:
        util = 100.0 * af / peak.flops_per_s
    # 6 decimals: the fusion collectives move a few KiB behind a
    # multi-device dispatch wall — 3 digits rounds their util to 0.0.
    return RooflinePoint(kernel, cost.flops, cost.bytes, wall_s,
                         af, ab, cost.intensity, bound, round(util, 6))


# ---------------------------------------------------------------------------
# Cost models
# ---------------------------------------------------------------------------
# Per-row coefficient provenance: compulsory bytes are counted from the
# arrays the kernel streams (ROW_BYTES per candidate row, plus gathers /
# side-tables / outputs); flops and xla_bytes coefficients are calibrated
# against the CPU-backend HloCostAnalysis (jax 0.4.37) and pinned by
# tests/test_roofline.py — each entry's comment records the fit.
#
# Loop-carried kernels (lax.scan / fori_loop / lax.map bodies) are modeled
# PER EXECUTED STEP × trip count; HloCostAnalysis counts a loop body once
# regardless of trip count, so their cross-check compares the unit-trip
# cost (tests pass the one-step shape).

# cardinal scorer over a compact block (ops/ranking.cardinal_scores16):
# stats + normalize + shifted sum + tf + flags. XLA (no-authority trace):
# 529 flops/row, 438.3 xla-bytes/row, constant over n in [4k, 131k]
_CARDINAL_FLOPS_ROW = 529.0
_CARDINAL_XBYTES_ROW = 438.3
# + fused lax.top_k (score_topk16): 544 / 454.3 per row at serving k's
_TOPK16_FLOPS_ROW = 544.0
_TOPK16_XBYTES_ROW = 454.3
# int32 twin (score_topk): 456 flops/row, 587.6 xla-bytes/row (wider
# reads, no int16 widening ops)
_TOPK32_FLOPS_ROW = 456.0
_TOPK32_XBYTES_ROW = 587.6
# scan_score_topk loop body (stats precomputed; score + merge per tile):
# 439 flops/row; 59 xla-bytes/row on a >=2-step trace
_SCAN_FLOPS_ROW = 439.0
_SCAN_XBYTES_ROW = 59.0
# streaming stats pass (ops/ranking.local_stats, no host counts)
_STATS_FLOPS_ROW = 113.0
_STATS_XBYTES_ROW = 387.3
# devstore streamed spans kernel: stats + score passes per tile plus the
# constraint mask; each span's fori body counts once: 673 flops and
# 587 xla-bytes per (span, TILE-row)
_SPANS_FLOPS_ROW = 673.0
_SPANS_XBYTES_ROW = 587.0
# b=1 vmapped pruned kernel: one scored tile per slot; vmap (unlike
# lax.map) scales the count with bs: 453 flops/row; xla bytes are a
# 36.4/row slope over the scored tiles plus the whole-operand arena
# arrays (dynamic_slice reads charge the full operand in the XLA model)
_PRUNED1_FLOPS_ROW = 453.0
_PRUNED1_XBYTES_ROW = 36.4
# pruned escalation kernel body (lax.map slot × fori tile, counted once)
_PRUNEDB_FLOPS_ROW = 449.0
_PRUNEDB_XBYTES_ROW = 64.6
# sort-merge join: fit over (r, m) at n_inc=1/n_exc=0, bs=1:
# flops = 560·r + 34·m; xla_bytes = 762·r + 90·m
_JOIN_FLOPS_R, _JOIN_FLOPS_M = 560.0, 34.0
_JOIN_XBYTES_R, _JOIN_XBYTES_M = 762.2, 90.1
# bitmap-membership join: 607 flops/row·slot; 747 xla-bytes/row·slot
# plus the side-table operands
_JOINBM_FLOPS_ROW = 607.0
_JOINBM_XBYTES_ROW = 747.2


def _c_cardinal_scores16(n: int) -> Cost:
    return Cost(flops=_CARDINAL_FLOPS_ROW * n,
                bytes=ROW_BYTES * n + 4 * n,      # feats+flags + i32 out
                xla_bytes=_CARDINAL_XBYTES_ROW * n)


def _c_score_topk16(n: int, k: int = 16) -> Cost:
    return Cost(flops=_TOPK16_FLOPS_ROW * n,
                bytes=ROW_BYTES * n + 8 * k,
                xla_bytes=_TOPK16_XBYTES_ROW * n)


def _c_score_topk(n: int, k: int = 16) -> Cost:
    return Cost(flops=_TOPK32_FLOPS_ROW * n,
                bytes=(P.NF * 4 + 8) * n + 8 * k,
                xla_bytes=_TOPK32_XBYTES_ROW * n)


def _c_scan_score_topk(n: int, k: int = 16, tile: int = 1 << 20) -> Cost:
    steps = max(1, -(-n // tile))
    rows = steps * tile
    return Cost(flops=_SCAN_FLOPS_ROW * rows,
                bytes=ROW_BYTES * rows + 8 * k,
                xla_bytes=_SCAN_XBYTES_ROW * rows)


def _c_stream_score_topk(n: int, k: int = 100, chunk: int = 1 << 21) -> Cost:
    # host driver, not a jit kernel: two device passes (stats, then
    # score+merge) over every chunk — the composition of the calibrated
    # local_stats and scan-body coefficients
    return Cost(flops=(_STATS_FLOPS_ROW + _SCAN_FLOPS_ROW) * n,
                bytes=2 * ROW_BYTES * n + 8 * k,
                xla_bytes=(_STATS_XBYTES_ROW + _SCAN_XBYTES_ROW) * n)


def _c_rank_spans(rows: int, n_spans: int = 8, k: int = 16,
                  with_stats_pass: bool = True) -> Cost:
    """The exact streaming scan (_rank_spans_kernel): stats + score
    passes over `rows` tile-rows (sum of span counts rounded up to whole
    tiles). The cross-check shape is rows = n_spans × TILE (one fori
    step per unrolled span slot). `with_stats_pass=False` models the
    cached-ext-stats twin: pass 1 skipped, half the streamed reads
    (673 = 113 stats + 560 score per row — the coefficients compose)."""
    if with_stats_pass:
        flops, xbytes, passes = _SPANS_FLOPS_ROW, _SPANS_XBYTES_ROW, 2
    else:
        flops = _SPANS_FLOPS_ROW - _STATS_FLOPS_ROW
        xbytes = _SPANS_XBYTES_ROW - _STATS_XBYTES_ROW
        passes = 1
    return Cost(flops=flops * rows,
                bytes=passes * ROW_BYTES_DEAD * rows + 8 * k,
                xla_bytes=xbytes * rows)


def _c_rank_pruned_batch1(bs: int, tile: int = 32_768, maxt: int = 64,
                          k: int = 16, cap: int = 0, doc_cap: int = 0,
                          tcap: int = 0) -> Cost:
    """The steady-state b=1 batched pruned kernel: each slot scores ONE
    proxy-best tile and bound-walks its pmax tail. cap/doc_cap/tcap are
    the arena capacities (whole-operand terms in the XLA byte model)."""
    rows = bs * tile
    return Cost(flops=_PRUNED1_FLOPS_ROW * rows,
                bytes=ROW_BYTES_DEAD * rows + 4 * bs * maxt + 8 * bs * k,
                xla_bytes=_PRUNED1_XBYTES_ROW * rows
                + ROW_BYTES * cap + doc_cap + 4 * tcap)


def _c_rank_pruned(b: int, tile: int = 32_768, bs: int = 1,
                   k: int = 16) -> Cost:
    """The escalation pruned kernel: `b` scored tiles per slot (lax.map
    over slots; unit-trip cost = one tile body)."""
    rows = bs * b * tile
    return Cost(flops=_PRUNEDB_FLOPS_ROW * rows,
                bytes=ROW_BYTES_DEAD * rows + 8 * bs * k,
                xla_bytes=_PRUNEDB_XBYTES_ROW * rows)


def _c_rank_join(r: int, m: int = 0, n_inc: int = 1, n_exc: int = 0,
                 bs: int = 1, k: int = 16) -> Cost:
    """Sort-merge device conjunction: rare span of `r` rows, one (r+m)
    sort-merge membership per partner segment of `m` rows (`n_inc` +
    `n_exc` partner memberships, the kernel statics' counts)."""
    partners = max(n_inc + n_exc, 1)
    flops = bs * r * (_JOIN_FLOPS_R + 146.0 * (partners - 1)) \
        + bs * _JOIN_FLOPS_M * m * partners
    # compulsory: rare rows once; per partner 12 B of gathered columns
    # per lane + the (docid, pos) segment streamed for the sort
    comp = bs * (ROW_BYTES_DEAD * r + partners * (12 * r + 8 * m) + 8 * k)
    return Cost(flops=flops, bytes=comp,
                xla_bytes=bs * (_JOIN_XBYTES_R * r
                                + 292.0 * r * (partners - 1)
                                + _JOIN_XBYTES_M * m * partners))


def _c_rank_join_bm(r: int, n_inc: int = 1, n_exc: int = 0, bs: int = 1,
                    k: int = 16, doc_cap: int = 0, jcap: int = 0,
                    nslots: int = 0, nwords: int = 0) -> Cost:
    """Bitmap-membership conjunction: 2 gathers per lane per partner
    instead of the (r+m) sort — O(r) regardless of partner size."""
    partners = max(n_inc + n_exc, 1)
    flops = bs * r * (_JOINBM_FLOPS_ROW + 160.0 * (partners - 1))
    comp = bs * (ROW_BYTES_DEAD * r + partners * 20 * r + 8 * k)
    side = doc_cap + 8 * jcap + 8 * nslots * nwords
    return Cost(flops=flops, bytes=comp,
                xla_bytes=bs * (_JOINBM_XBYTES_ROW
                                + 300.0 * (partners - 1)) * r + side)


def _c_bm25_topk(n: int, t: int = 3, k: int = 16) -> Cost:
    # XLA fit: flops = (6t + 10)/row and xla_bytes = (4t + 43.5)/row,
    # exact at t in {3, 5, 8}
    return Cost(flops=(6.0 * t + 10.0) * n,
                bytes=(4 * t + 8) * n + 8 * k,
                xla_bytes=(4.0 * t + 43.5) * n)


def _c_hybrid_rerank(n: int, dim: int = 256, k: int = 100) -> Cost:
    # matvec (2·dim) + normalize/blend/top_k; XLA: (4·dim + 11) flops
    # and (4·dim + 43.5) bytes per row at dim 256. Compulsory traffic is
    # the f32 doc-matrix read (bf16 cast happens in registers)
    return Cost(flops=(4.0 * dim + 11.0) * n,
                bytes=4 * n * dim + 5 * n + 8 * k,
                xla_bytes=(4.0 * dim + 43.5) * n)


def _c_hybrid_rerank_batch(n: int, b: int = 16, dim: int = 256,
                           k: int = 100) -> Cost:
    """The MXU case: B queries amortize one doc-matrix read. XLA fit:
    flops = 2·b·n·dim + 11·b·n + 2·dim·n; bytes = 12·dim·n + 43.6·b·n."""
    return Cost(flops=2.0 * b * n * dim + 11.0 * b * n + 2.0 * dim * n,
                bytes=4 * n * dim + b * (5 * n + 8 * k),
                xla_bytes=12.0 * dim * n + 43.6 * b * n)


def _c_dense_boost(n: int, dim: int = 256, k: int = 100) -> Cost:
    return Cost(flops=(4.0 * dim + 22.0) * n,
                bytes=4 * n * dim + 9 * n + 8 * k,
                xla_bytes=(4.0 * dim + 29.0) * n)


# batched forward-index rerank (the hybrid second stage as a batcher
# kernel family): per candidate lane one dim-wide bf16 dot (2·dim) +
# blend/round + the two-key (score, docid) tie sort ≈ 545, plus a
# per-slot descriptor decode ≈ 650. XLA bytes: the whole-operand
# forward index (gather charges the full array) + 2128/lane + 3086/slot
# — exact at (nb, bs) in {16..1024}×{4..16}, dim 256 (jax 0.4.37 CPU)
_RERANK_FLOPS_LANE_EXTRA = 545.0
_RERANK_FLOPS_SLOT = 650.0
_RERANK_XBYTES_LANE = 2128.0
_RERANK_XBYTES_SLOT = 3086.0


def _c_rerank_fwd_batch(bs: int = 16, nb: int = 128, dim: int = 256,
                        cap: int = 0) -> Cost:
    """_rerank_fwd_batch_packed_kernel: bs slots × nb candidate lanes
    gathering from a [cap, dim] f16 forward index. Compulsory traffic:
    the gathered doc vectors (2·dim B/lane), the fused descriptor in,
    the packed scores++docids out."""
    lanes = bs * nb
    return Cost(flops=(2.0 * dim + _RERANK_FLOPS_LANE_EXTRA) * lanes
                + _RERANK_FLOPS_SLOT * bs,
                bytes=2 * dim * lanes + 4 * (2 + 2 * nb + dim) * bs
                + 8 * lanes,
                xla_bytes=2 * cap * dim + _RERANK_XBYTES_LANE * lanes
                + _RERANK_XBYTES_SLOT * bs)


# bit-packed (*_bp) fused-decode scorers: the compulsory HBM stream is
# the PACKED bytes (row_bits/8 per row — the whole point of the format)
# plus the tombstone gather and outputs; decode adds ~6 int ops per
# value (two word reads folded by shifts/masks) on top of the scoring
# flops. XLA model: per-row slope + per-pw-word slope (each decode
# gather charges the packed-words operand in HloCostAnalysis, so the
# arena capacity enters with a multi-gather coefficient) + the dead/
# pmax operands. Fits exact to <0.5% over bs in {1..16} × pw_cap in
# {2^18, 2^20} (jax 0.4.x CPU); pinned by tests/test_roofline.py.
_PRUNED1_BP_FLOPS_ROW = 890.0
_PRUNED1_BP_FLOPS_PW = 5.0
_PRUNED1_BP_XBYTES_ROW = 56.5
_PRUNED1_BP_XBYTES_PW = 28.0
_SCAN_BP_FLOPS_ROW = 1775.0
_SCAN_BP_XBYTES_ROW = 847.0
_SCAN_BP_XBYTES_PW = 88.0


def _c_rank_pruned_batch1_bp(bs: int, tile: int = 32_768, maxt: int = 64,
                             k: int = 16, row_bits: float = 160.0,
                             pw_cap: int = 0, doc_cap: int = 0,
                             tcap: int = 0) -> Cost:
    """The b=1 pruned kernel over bit-packed spans: each slot decodes +
    scores ONE tile straight from the packed words. Compulsory bytes =
    packed payload (row_bits/8 per row) — compression is throughput on
    a memory-bound roofline."""
    rows = bs * tile
    return Cost(flops=_PRUNED1_BP_FLOPS_ROW * rows
                + _PRUNED1_BP_FLOPS_PW * pw_cap,
                bytes=(row_bits / 8.0 + 1) * rows + 4 * bs * maxt
                + 8 * bs * k,
                xla_bytes=_PRUNED1_BP_XBYTES_ROW * rows
                + _PRUNED1_BP_XBYTES_PW * pw_cap + doc_cap + 4 * tcap)


def _c_rank_scan_batch_bp(rows: int, k: int = 16, bs: int = 1,
                          row_bits: float = 160.0, pw_cap: int = 0,
                          doc_cap: int = 0) -> Cost:
    """Exact two-pass scan over bit-packed spans (stats, then score):
    the packed payload streams twice, like the int16 scan's two passes
    over ROW_BYTES."""
    return Cost(flops=_SCAN_BP_FLOPS_ROW * rows + pw_cap,
                bytes=2 * (row_bits / 8.0 + 1) * rows + 8 * k,
                xla_bytes=_SCAN_BP_XBYTES_ROW * rows
                + _SCAN_BP_XBYTES_PW * pw_cap + 2 * doc_cap)


# device-side index build (ingest/devbuild.py, ISSUE 13b): the vmapped
# bit-pack of B posting blocks.  Per value: min/max reduce share, width
# derivation, offset/shift math and the two scatter-add lanes — ~43.5
# flops/value × NCOLS values/row ≈ 826 flops/row, plus per-ROW reduce
# setup XLA amortizes across lanes (76/row) and per-LANE meta/clz work
# (5277/lane).  XLA bytes: the int16+int32 operand reads and the uint32
# word-stream carried through 2·NCOLS scatter fusions (1165.5 B/row)
# plus the per-lane meta build (4718 B/lane).  Both fits <1% over bs in
# {2..16} × rows in {256..4096} (jax 0.4.x CPU); pinned by
# tests/test_roofline.py.  Compulsory traffic: the block rows once in
# (ROW_BYTES + 8) and the PACKED payload out (row_bits/8 per row) —
# the same accounting the *_bp scorers state their reads in.
_PACK_FLOPS_ROW = 826.0
_PACK_FLOPS_ROWS = 76.0
_PACK_FLOPS_LANE = 5277.0
_PACK_FLOPS_CONST = 418.0
_PACK_XBYTES_ROW = 1165.5
_PACK_XBYTES_LANE = 4718.0
_PACK_XBYTES_CONST = 6474.0


def _c_pack_block_batch(bs: int, rows: int,
                        row_bits: float = 160.0) -> Cost:
    """_pack_block_batch_kernel: bs vmap lanes bit-packing rows-row
    blocks (ingest device build)."""
    n = bs * rows
    return Cost(flops=_PACK_FLOPS_ROW * n + _PACK_FLOPS_ROWS * rows
                + _PACK_FLOPS_LANE * bs + _PACK_FLOPS_CONST,
                bytes=(ROW_BYTES + 8) * n + (row_bits / 8.0) * n
                + 4.0 * (3 * (P.NF + 2) + 1) * bs,
                xla_bytes=_PACK_XBYTES_ROW * n + _PACK_XBYTES_LANE * bs
                + _PACK_XBYTES_CONST)


# dense-first IVF ANN family (ops/ann.py, ISSUE 11).  Assignment is
# the (B,dim)×(dim,C) bf16 matmul (+ per-element top-k overhead XLA
# counts as 2·dim·(C+bs)); fuse is per-lane work (int8 gather + dequant
# matmul + fused boost + two-key sort — the per-lane constants fit jax
# 0.4.x CPU to <0.5% at dim 256 over bs in {4..16} × nb in {1k..16k} ×
# cap in {2^16, 2^20}; pinned by tests/test_roofline.py) plus the slab
# operands (cap·(dim+6): int8 rows + f16 scale + int32 docid — the
# quantized residency IS the byte win, arxiv 1406.3170 applied to
# vectors).
_ANN_FUSE_FLOPS_LANE = 1078.0
_ANN_FUSE_XBYTES_LANE = 2120.0


def _c_ann_assign(bs: int, dim: int = 256, C: int = 1024,
                  np_: int = 8) -> Cost:
    """Centroid assignment: ONE (B,dim)×(dim,C) bf16 matmul per wave."""
    return Cost(flops=2.0 * dim * (bs * C + C + bs),
                bytes=2 * C * dim + 4 * bs * dim + 4 * bs * np_,
                xla_bytes=10.0 * C * dim + 4.0 * bs * C
                + 12.0 * bs * dim)


def _c_ann_fuse(bs: int, nb: int, dim: int = 256, cap: int = 0,
                k: int = 16) -> Cost:
    """IVF probe + dense/sparse fusion: batched int8 gathers over the
    hot slab with dequant fused into the scoring matmul. Compulsory
    bytes = the gathered quantized lanes + packed descriptors + fused
    top-k out; the XLA model charges the whole slab operand set per
    dispatch (gather semantics in HloCostAnalysis)."""
    lanes = bs * nb
    desc = 4.0 * (2 + 3 * nb + dim) * bs
    return Cost(flops=_ANN_FUSE_FLOPS_LANE * lanes,
                bytes=(dim + 6.0) * lanes + desc + 8.0 * bs * k,
                xla_bytes=_ANN_FUSE_XBYTES_LANE * lanes
                + (dim + 6.0) * cap)


# fused all-gather+top-k fusion collective (parallel/mesh.py, ISSUE 12b):
# each shard ships its exact local top-k — the wire payload is
# 8 B x k x n_shards (score+docid), never full score rows — and the
# tie-pinned two-key merge sorts the G = n_shards*k gathered rows.  The
# XLA model is the empirical CPU fit (exact over k in {16..128} x ndev
# in {4,8} x rows in {256..4096}; pinned by tests/test_roofline.py):
# local two-key sort streams ~24 B/row, the gathered merge ~32 B/row,
# both with the n*log2(n) comparison count a sort costs.


def _log2(n: float) -> float:
    import math
    return math.log2(max(n, 2.0))


def _c_all_gather_topk(k: int, ndev: int, rows: int = 256) -> Cost:
    g = ndev * k
    return Cost(flops=1.08 * rows * _log2(rows) + 1.1 * g * _log2(g)
                + 120.0,
                bytes=8.0 * rows + 8.0 * g + 8.0 * k,
                xla_bytes=24.0 * rows + 32.0 * g + 40.0 * k + 80.0)


def _c_all_gather_topk_pallas(k: int, ndev: int, rows: int = 256) -> Cost:
    """Ring remote-DMA variant: per device the ring moves (ndev-1)
    hops x 8 B x k — same k-scaling payload, expressed as ICI traffic
    instead of a gather buffer; the merge epilogue is shared with the
    lax variant so its sort terms are identical."""
    g = ndev * k
    return Cost(flops=1.08 * rows * _log2(rows) + 1.1 * g * _log2(g)
                + 120.0,
                bytes=8.0 * rows + 8.0 * k * (ndev - 1) + 8.0 * k,
                xla_bytes=24.0 * rows + 32.0 * g + 40.0 * k + 80.0)


def _c_power_iterate(n: int, edges: int, iters: int = 1) -> Cost:
    """BlockRank power iteration (ops/blockrank._power_iterate_sparse):
    per-iteration segment-sum over the edge list, × the trip count (the
    while body counts once in the XLA model; iters=1 is the cross-check
    shape). Fit: flops = 4·e + 11·n + 13; bytes = 20·e + 57.5·n + 366."""
    return Cost(flops=(4.0 * edges + 11.0 * n + 13.0) * iters,
                bytes=(12 * edges + 8 * n) * iters,
                xla_bytes=(20.0 * edges + 57.5 * n + 366.0) * iters)


# kernel name -> cost fn; names match the python symbol the kernel is
# defined under (tests/test_code_hygiene.py walks the sources and demands
# an entry — or an explicit exemption — for every named jit kernel in
# ops/ and index/devstore.py)
KERNELS: dict[str, object] = {
    "cardinal_scores16": _c_cardinal_scores16,
    "score_topk16": _c_score_topk16,
    "score_topk": _c_score_topk,
    "scan_score_topk": _c_scan_score_topk,
    "stream_score_topk": _c_stream_score_topk,
    "bm25_topk": _c_bm25_topk,
    "hybrid_rerank_topk": _c_hybrid_rerank,
    "hybrid_rerank_topk_batch": _c_hybrid_rerank_batch,
    "dense_boost_topk": _c_dense_boost,
    "_power_iterate_sparse": _c_power_iterate,
    "_rank_spans_kernel": _c_rank_spans,
    "_rank_pruned_kernel": _c_rank_pruned,
    "_rank_pruned_batch1_kernel": _c_rank_pruned_batch1,
    "_rank_pruned_batch_kernel": _c_rank_pruned,
    "_rank_scan_batch_kernel": _c_rank_spans,
    "_rank_join_batch_kernel": _c_rank_join,
    "_rank_join_bm_batch_kernel": _c_rank_join_bm,
    # packed-I/O variants (one transfer each way per dispatch): the
    # wrapped body IS the unpacked kernel, so the cost model is shared —
    # the concat epilogue is noise against the row streams
    "score_topk16_packed": _c_score_topk16,
    "_rerank_fwd_batch_packed_kernel": _c_rerank_fwd_batch,
    "_rank_spans_packed_kernel": _c_rank_spans,
    "_rank_pruned_batch1_packed_kernel": _c_rank_pruned_batch1,
    "_rank_scan_batch_packed_kernel": _c_rank_spans,
    "_rank_join_batch_packed_kernel": _c_rank_join,
    "_rank_join_bm_batch_packed_kernel": _c_rank_join_bm,
    # bit-packed fused-decode variants (compressed residency): cost
    # models count the PACKED bytes — the compression ratio is the
    # roofline-visible win
    "_rank_pruned_batch1_bp_kernel": _c_rank_pruned_batch1_bp,
    "_rank_scan_batch_bp_kernel": _c_rank_scan_batch_bp,
    # dense-first IVF ANN family (ISSUE 11): assignment matmul + the
    # probe/fuse gather kernel — the hygiene gate additionally demands
    # a NumPy oracle in ops/ann.ANN_ORACLES for every _ann_* kernel
    "_ann_assign_batch_kernel": _c_ann_assign,
    "_ann_fuse_batch_packed_kernel": _c_ann_fuse,
    # device-side index build (ISSUE 13b): the write path's vmapped
    # bit-pack — fresh runs land pre-packed, parity-pinned bit-identical
    # to ops/packed.pack_block (tests/test_ingest.py)
    "_pack_block_batch_kernel": _c_pack_block_batch,
    # fused all-gather+top-k fusion collective (ISSUE 12b): the lax
    # implementation every mesh fusion site shares, and the Pallas
    # remote-DMA ring variant for TPU ICI — gathered bytes scale with
    # k, not corpus rows (the r5 motivation: full score rows shipped)
    "all_gather_topk": _c_all_gather_topk,
    "_all_gather_topk_pallas": _c_all_gather_topk_pallas,
}

# jit-compiled functions that are NOT serving kernels used to be
# exempted here; that second suppression registry is gone — the lint
# engine's one exemption grammar (a costmodel-ok lint comment on the
# kernel def, see utils/lint) carries them now, so every exemption in
# the repo audits with a single grep.  The dict stays (empty) because
# the kernel-cost-model checker still unions it, which keeps old
# branches linting.
EXEMPT: dict[str, str] = {}


def cost(kernel: str, **shape) -> Cost:
    """The analytical cost of one `kernel` execution at `shape`."""
    fn = KERNELS.get(kernel)
    if fn is None:
        raise KeyError(f"no cost model registered for kernel {kernel!r}")
    return fn(**shape)


def registered() -> list[str]:
    return sorted(KERNELS)


def xla_cost(jitfn, *args, **kwargs) -> tuple[float, float]:
    """(flops, bytes accessed) from XLA's compiled cost analysis, or
    (nan, nan) when the backend doesn't expose it."""
    try:
        analysis = jitfn.lower(*args, **kwargs).compile().cost_analysis()
    except Exception:
        return float("nan"), float("nan")
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not analysis:
        return float("nan"), float("nan")
    return (float(analysis.get("flops", float("nan"))),
            float(analysis.get("bytes accessed", float("nan"))))


def ascii_table(points: list[RooflinePoint], peak: DevicePeak) -> str:
    """The achieved-vs-peak table (BASELINE/README artifact form)."""
    head = (f"device peak: {peak.name} — "
            f"{peak.flops_per_s / 1e12:.1f} TFLOP/s, "
            f"{peak.bytes_per_s / 1e9:.0f} GB/s, "
            f"ridge {peak.ridge:.1f} flops/byte")
    rows = [head,
            f"{'kernel':<28}{'GFLOPs':>9}{'MB':>9}{'int.':>7}"
            f"{'GF/s':>9}{'GB/s':>8}{'bound':>9}{'util%':>8}"]
    for p in points:
        rows.append(
            f"{p.kernel:<28}{p.flops / 1e9:>9.3f}{p.bytes / 1e6:>9.1f}"
            f"{p.intensity:>7.1f}{p.achieved_flops_per_s / 1e9:>9.2f}"
            f"{p.achieved_bytes_per_s / 1e9:>8.2f}{p.bound:>9}"
            f"{p.util_pct:>8.2f}")
    return "\n".join(rows)
