"""Dense semantic encoding + hybrid rerank kernel — M7 (BASELINE config #5).

New capability beyond the reference (aligned with PAPERS.md efficient
neural-ranking techniques): a first-stage sparse search (RWI/BM25 or
cardinal) followed by a dense cosine rerank on device.  TPU-first design:

- document/query embeddings are fixed-dim float vectors; doc embeddings
  live as one dense ``[n, dim]`` block per segment (MXU-friendly),
- the rerank is ONE fused kernel: bf16 matmul (query x doc block on the
  MXU) -> blend with the normalized sparse score -> top-k,
- the encoder is a deterministic hashed n-gram projection (a linear
  "SBERT-shaped" text encoder with no learned weights — zero-egress
  substitute; any [text -> dim-vector] model drops in, e.g. a flax
  sentence encoder, without touching the kernel).
"""

from __future__ import annotations

import functools
from zlib import crc32

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

DIM = 256
_SEED = 0x5EED
# bump when the feature hash/embedding scheme changes: persisted doc
# vectors must be re-encoded to stay comparable with query vectors
# (migration._d_reencode_dense)
ENCODER_VERSION = 2


def _stable_hash(s: str) -> int:
    """Deterministic 32-bit hash, C-speed (zlib.crc32 — python's hash()
    is salted per process; a pure-python FNV was the indexing write
    path's single largest cost at ~1M calls per 800 documents)."""
    return crc32(s.encode("utf-8"))


class HashingEncoder:
    """Signed feature-hashing of word + char-trigram features into `dim`
    buckets, L2-normalized — deterministic across processes/peers (doc
    vectors computed at index time on one node must match query vectors
    computed on another).

    Vectorized (ISSUE 11 satellite): the per-feature python accumulate
    loop is now ONE ``np.add.at`` scatter per text — and one per BATCH
    in ``encode_batch`` — with a bounded (feature -> bucket, sign)
    cache in front of the crc32, since a corpus's word/trigram
    vocabulary repeats massively across documents.  Bit-deterministic
    with the loop it replaces: ``np.add.at`` is unbuffered and applies
    updates in index order, which IS the old accumulation order, and
    ``_stable_hash`` still decides every bucket/sign."""

    # bounded word cache: a corpus's vocabulary repeats massively, but
    # a crawl's long tail must not grow an unbounded dict (cleared
    # wholesale at the cap — correctness never depends on a hit)
    _CACHE_MAX = 1 << 18

    def __init__(self, dim: int = DIM):
        self.dim = dim
        self._cache: dict[str, tuple[np.ndarray, np.ndarray]] = {}

    def _features(self, text: str):
        words = [w for w in text.lower().split() if w]
        for w in words[:512]:
            yield "w:" + w, 1.0
            padded = f"^{w}$"
            for i in range(len(padded) - 2):
                yield "t:" + padded[i:i + 3], 0.5

    def _word_arrays(self, w: str):
        """One word's (buckets, signed weights) — the word feature then
        its char trigrams, exactly the _features order — cached: the
        crc32 + modulo per trigram runs once per distinct word, not
        once per occurrence."""
        got = self._cache.get(w)
        if got is not None:
            return got
        feats = ["w:" + w]
        wts = [1.0]
        padded = f"^{w}$"
        for i in range(len(padded) - 2):
            feats.append("t:" + padded[i:i + 3])
            wts.append(0.5)
        dim = self.dim
        bs = np.empty(len(feats), dtype=np.int64)
        sg = np.empty(len(feats), dtype=np.float32)
        for j, f in enumerate(feats):
            h = _stable_hash(f)
            bs[j] = (h >> 1) % dim
            sg[j] = (1.0 if (h & 1) else -1.0) * wts[j]
        if len(self._cache) > self._CACHE_MAX:
            self._cache.clear()
        got = (bs, sg)
        self._cache[w] = got
        return got

    def _feature_arrays(self, text: str):
        """(buckets, signed weights) for one text, in feature order —
        the scatter input whose in-order application matches the legacy
        accumulate loop bit for bit."""
        words = [w for w in text.lower().split() if w][:512]
        if not words:
            return (np.empty(0, dtype=np.int64),
                    np.empty(0, dtype=np.float32))
        parts = [self._word_arrays(w) for w in words]
        return (np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]))

    def encode(self, text: str) -> np.ndarray:
        v = np.zeros(self.dim, dtype=np.float32)
        b, w = self._feature_arrays(text)
        if len(b):
            np.add.at(v, b, w)
        n = float(np.linalg.norm(v))
        return v / n if n > 0 else v

    def encode_batch(self, texts: list[str]) -> np.ndarray:
        """Batched encode: ONE 2-d np.add.at scatter for the whole
        batch (the flattened per-text feature runs keep each row's
        update order, so every row is bit-identical to encode())."""
        if not texts:
            return np.zeros((0, self.dim), dtype=np.float32)
        v = np.zeros((len(texts), self.dim), dtype=np.float32)
        rows: list[np.ndarray] = []
        cols: list[np.ndarray] = []
        wts: list[np.ndarray] = []
        for i, t in enumerate(texts):
            b, w = self._feature_arrays(t)
            if len(b):
                rows.append(np.full(len(b), i, dtype=np.int64))
                cols.append(b)
                wts.append(w)
        if rows:
            np.add.at(v, (np.concatenate(rows), np.concatenate(cols)),
                      np.concatenate(wts))
        for i in range(len(texts)):
            n = float(np.linalg.norm(v[i]))
            if n > 0:
                v[i] /= n
        return v


# -- fused rerank kernel -----------------------------------------------------

@functools.partial(jax.jit, static_argnames=("k",))
def hybrid_rerank_topk(qvec: jnp.ndarray, doc_vecs: jnp.ndarray,
                       sparse_scores: jnp.ndarray, valid: jnp.ndarray,
                       alpha: jnp.ndarray, k: int):
    """One fused device step: cosine(q, docs) on the MXU in bf16, blended
    with min/max-normalized sparse scores, masked top-k.

        final = (1-alpha) * norm(sparse) + alpha * cosine

    Returns (scores[k], indices[k]).  Replaces nothing in the reference —
    this is the hybrid second stage the reference lacks.
    """
    sims = jnp.dot(doc_vecs.astype(jnp.bfloat16),
                   qvec.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)
    s = sparse_scores.astype(jnp.float32)
    big = jnp.float32(1e30)
    smin = jnp.min(jnp.where(valid, s, big))
    smax = jnp.max(jnp.where(valid, s, -big))
    span = jnp.maximum(smax - smin, 1e-6)
    s_norm = jnp.where(valid, (s - smin) / span, 0.0)
    final = (1.0 - alpha) * s_norm + alpha * sims
    final = jnp.where(valid, final, -jnp.inf)
    # lint: tie-ok(lax.top_k breaks ties by lowest input index and the candidate rows are docid-ordered, so equal scores surface docid-ASC — the pinned discipline, asserted by the tie tests in test_dense/test_ranking)
    return jax.lax.top_k(final, k)


@functools.partial(jax.jit, static_argnames=("k",))
def hybrid_rerank_topk_batch(qvecs: jnp.ndarray, doc_vecs: jnp.ndarray,
                             sparse_scores: jnp.ndarray,
                             valid: jnp.ndarray, alpha: jnp.ndarray,
                             k: int):
    """Batched hybrid rerank: B concurrent queries against ONE shared
    doc matrix in a single (B,dim)x(dim,N) bf16 matmul — the MXU shape a
    single matvec can't reach (VERDICT r4 #5: a lone query's cosine is
    HBM-bound at ~1% MXU utilization; a 16-wide batch amortizes the doc
    matrix read across every slot). Per-slot normalize/blend/top-k vmap.

    qvecs (B,dim); sparse_scores, valid (B,N). Returns
    (scores[B,k], indices[B,k]) — slot i identical to the solo kernel on
    (qvecs[i], sparse_scores[i], valid[i])."""
    sims = jnp.dot(qvecs.astype(jnp.bfloat16),
                   doc_vecs.astype(jnp.bfloat16).T,
                   preferred_element_type=jnp.float32)   # (B, N)

    def one(sim, s, v):
        big = jnp.float32(1e30)
        smin = jnp.min(jnp.where(v, s, big))
        smax = jnp.max(jnp.where(v, s, -big))
        span = jnp.maximum(smax - smin, 1e-6)
        s_norm = jnp.where(v, (s - smin) / span, 0.0)
        final = (1.0 - alpha) * s_norm + alpha * sim
        # lint: tie-ok(lax.top_k breaks ties by lowest input index and the candidate rows are docid-ordered, so equal scores surface docid-ASC — the pinned discipline, asserted by the tie tests in test_dense; the vmapped
        # per-slot kernel shares the outer kernel's row order)
        return jax.lax.top_k(jnp.where(v, final, -jnp.inf), k)

    return jax.vmap(one)(sims, sparse_scores.astype(jnp.float32), valid)


# one score domain: dense similarity maps into the CARDINAL integer
# domain as an additive boost with a FIXED scale (the magnitude of one
# maxed-out cardinal signal, 255 << 15) — never rescaled by the local
# batch's score range, so fusion ordering across peers/batches is stable
# (VERDICT r1 weak #6: the old path stretched blended [0,2) scores by
# max(scores)/2, making remote fusion depend on the local batch max)
DENSE_BOOST_SCALE = float(255 << 15)


@functools.partial(jax.jit, static_argnames=("k",))
def dense_boost_topk(qvec: jnp.ndarray, doc_vecs: jnp.ndarray,
                     sparse_scores: jnp.ndarray, valid: jnp.ndarray,
                     alpha: jnp.ndarray, k: int):
    """Fused cosine + fixed-scale cardinal boost + masked top-k.

        final = sparse_cardinal + round(cosine * alpha * DENSE_BOOST_SCALE)

    Input and output scores live in the same cardinal integer domain as
    the sparse first stage; (scores[k], indices[k]) best-first."""
    sims = jnp.dot(doc_vecs.astype(jnp.bfloat16),
                   qvec.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)
    # int32 domain (x64 is off): cardinal scores stay < 2^28 and the
    # boost < 2^23, so the sum never wraps
    boost = jnp.round(sims * alpha * DENSE_BOOST_SCALE).astype(jnp.int32)
    final = sparse_scores.astype(jnp.int32) + boost
    final = jnp.where(valid, final, jnp.int32(-(2**31 - 1)))
    # lint: tie-ok(lax.top_k breaks ties by lowest input index and the
    # candidate rows are docid-ordered, so equal scores surface
    # docid-ASC — the pinned discipline, asserted by the tie tests in
    # test_dense)
    return jax.lax.top_k(final, k)


# -- batched serving rerank over the device-resident forward index ----------
#
# The serving path's rerank (cardinal-domain boost, one score domain with
# the sparse first stage) as a BATCHED kernel family: B concurrent
# queries' candidate sets gather their doc vectors from one device-
# resident forward index (index/dense.DenseVectorStore.device_block) and
# contract against their query vectors in a single bf16 MXU dispatch —
# the (B,dim)x(dim,N) shape hybrid_rerank_topk_batch proved (7.08x CPU)
# finally wired into serving, riding the devstore _QueryBatcher's
# issue→completer pipeline like every other kernel family.
#
# Tie discipline (arxiv 1807.05798): the final order is (score DESC,
# then internal docid ASC) — pinned so solo/batched/packed/cached rerank
# paths can never disagree on ties, which would flap the versioned
# top-k result cache between bit-different answers of equal score.

# candidate-count buckets (pow2, min 16) bound the compile-shape count;
# pad lanes carry docid -1 and are masked by the per-slot valid count
RERANK_MAX_N = 1 << 14


def rerank_bucket(n: int) -> int:
    """Static candidate-lane bucket for one rerank slot."""
    return 1 << max(4, (max(n, 1) - 1).bit_length())


def pack_rerank_row(qvec: np.ndarray, sparse_scores: np.ndarray,
                    docids: np.ndarray, alpha: float, nb: int) -> np.ndarray:
    """ONE fused int32 descriptor for one rerank slot — qvec (bit-cast
    float32), sparse cardinal scores, candidate docids and the blend
    alpha ride a single host buffer, so a dispatch wave is one
    host->device transfer (each separate argument is a full round trip
    through a remote tunnel — the M78 packing lesson).

    Layout: [n_valid, alpha_bits, docids[nb], sparse[nb], qvec_bits[dim]].
    """
    n = len(docids)
    dim = len(qvec)
    row = np.zeros(2 + 2 * nb + dim, np.int32)
    row[0] = n
    row[1] = np.float32(alpha).view(np.int32)
    row[2:2 + n] = np.asarray(docids, np.int32)
    row[2 + nb:2 + nb + n] = np.asarray(sparse_scores, np.int32)
    row[2 + 2 * nb:] = np.asarray(qvec, np.float32).view(np.int32)
    return row


@functools.partial(jax.jit, static_argnames=("nb", "bs"))
def _rerank_fwd_batch_packed_kernel(fwd, qi, nb: int, bs: int):
    """Batched cardinal-domain dense rerank against the device-resident
    forward index, packed I/O: `qi` [bs, 2 + 2*nb + dim] fused
    descriptors (pack_rerank_row), output [bs, 2*nb] = scores ++ docids
    per slot — ONE transfer each way per dispatch wave.

    Each slot gathers its candidates' doc vectors from `fwd`
    ([cap, dim] float16), contracts them against its query vector in
    bf16 (f32 accumulate — the MXU shape), adds the fixed-scale boost
    into the sparse cardinal scores (dense_boost_topk semantics, slot
    for slot), and sorts by (score DESC, docid ASC) — the pinned tie
    discipline. Candidates OUTSIDE the forward index's coverage (no
    vector stored yet) keep their sparse score with zero boost — vector
    absence must never drop a sparse result. Pad lanes (beyond a slot's
    n_valid) sort last with NEG_INF scores."""
    dim = fwd.shape[1]
    cap = fwd.shape[0]
    nvalid = qi[:, 0]
    alpha = lax.bitcast_convert_type(qi[:, 1], jnp.float32)
    docids = qi[:, 2:2 + nb]
    sparse = qi[:, 2 + nb:2 + 2 * nb]
    qvecs = lax.bitcast_convert_type(qi[:, 2 + 2 * nb:], jnp.float32)
    dv = fwd[jnp.clip(docids, 0, cap - 1)]          # (bs, nb, dim) gather
    sims = jnp.einsum("bd,bnd->bn", qvecs.astype(jnp.bfloat16),
                      dv.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
    in_cov = (docids >= 0) & (docids < cap)
    sims = jnp.where(in_cov, sims, 0.0)
    boost = jnp.round(sims * alpha[:, None]
                      * DENSE_BOOST_SCALE).astype(jnp.int32)
    lanes = jnp.arange(nb)[None, :]
    valid = lanes < nvalid[:, None]
    neg = jnp.int32(-(2 ** 31 - 1))
    final = jnp.where(valid, sparse + boost, neg)
    # (score DESC, docid ASC): ascending two-key sort on (-score, docid);
    # pad lanes tie-key to INT32_MAX so they stay behind real candidates
    skey = -final
    tkey = jnp.where(valid, docids, jnp.int32(2 ** 31 - 1))

    def one(sk, tk, f, d):
        _sk, _tk, fs, ds = lax.sort((sk, tk, f, d), num_keys=2)
        return fs, ds

    fs, ds = jax.vmap(one)(skey, tkey, final, docids)
    return jnp.concatenate([fs, ds], axis=1)


def rerank_fwd_np(qvec, fwd, sparse_scores, docids, alpha):
    """CPU oracle for _rerank_fwd_batch_packed_kernel (one slot):
    bf16-rounded matmul inputs like the kernel, float32 accumulation,
    and the SAME (score DESC, docid ASC) tie discipline. Accumulation
    order may still differ from the device dot (a few units of rounded
    boost) — compare closeness per docid, not bit-exact scores; device
    paths among THEMSELVES are bit-exact at a shared compile shape."""
    import ml_dtypes
    docids = np.asarray(docids, np.int64)
    in_cov = (docids >= 0) & (docids < fwd.shape[0])
    dv = fwd[np.clip(docids, 0, fwd.shape[0] - 1)]
    sims = (dv.astype(ml_dtypes.bfloat16).astype(np.float32)
            @ np.asarray(qvec).astype(ml_dtypes.bfloat16)
            .astype(np.float32))
    sims = np.where(in_cov, sims, 0.0)
    boost = np.round(sims * np.float32(alpha)
                     * np.float32(DENSE_BOOST_SCALE)).astype(np.int32)
    final = np.asarray(sparse_scores, np.int32) + boost
    order = np.lexsort((docids, -final.astype(np.int64)))
    return final[order], np.asarray(docids, np.int32)[order]


def dense_boost_topk_np(qvec, doc_vecs, sparse_scores, valid, alpha, k):
    """CPU oracle for dense_boost_topk: bf16-rounded inputs like the
    kernel's MXU matmul, float32 accumulation. Accumulation order may
    still differ from the device — compare orderings/closeness, not
    bit-exact scores."""
    import ml_dtypes
    sims = (doc_vecs.astype(ml_dtypes.bfloat16).astype(np.float32)
            @ qvec.astype(ml_dtypes.bfloat16).astype(np.float32))
    boost = np.round(sims * np.float32(alpha)
                     * np.float32(DENSE_BOOST_SCALE)).astype(np.int32)
    final = sparse_scores.astype(np.int32) + boost
    final = np.where(valid, final, np.int32(-(2**31 - 1)))
    idx = np.argsort(-final, kind="stable")[:k]
    return final[idx], idx


def hybrid_rerank_topk_np(qvec, doc_vecs, sparse_scores, valid, alpha, k):
    """CPU oracle with identical math (float32 cosine)."""
    sims = doc_vecs.astype(np.float32) @ qvec.astype(np.float32)
    s = sparse_scores.astype(np.float32)
    sv = s[valid]
    smin = sv.min() if sv.size else 0.0
    smax = sv.max() if sv.size else 0.0
    span = max(smax - smin, 1e-6)
    s_norm = np.where(valid, (s - smin) / span, 0.0)
    final = (1.0 - alpha) * s_norm + alpha * sims
    final = np.where(valid, final, -np.inf)
    idx = np.argsort(-final, kind="stable")[:k]
    return final[idx], idx
