"""BlockRank — host-level citation rank as a device power iteration.

Capability equivalent of the reference's offline citation ranking
(reference: source/net/yacy/search/ranking/BlockRank.java:50 — iterative
rank evaluation over exported webgraph indexes — and
CollectionConfiguration's postprocessing that writes the normalized
host citation rank into cr_host_norm_d for query-time boosting). The
reference iterates Java maps; here the host link graph becomes a dense
column-stochastic matrix and the rank vector is a jnp power iteration —
one matmul per step on the MXU, converging in tens of steps for the
host counts a node ever sees.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

DAMPING = 0.85
MAX_ITERS = 50
TOL = 1e-9


@partial(jax.jit, static_argnames=("n",))
def _power_iterate_sparse(srcs: jnp.ndarray, dsts: jnp.ndarray,
                          weights: jnp.ndarray, dangling: jnp.ndarray,
                          damping: jnp.ndarray, n: int) -> jnp.ndarray:
    """Damped power iteration over an EDGE LIST (segment-sum per step):
    the host graph is sparse, so no n x n matrix is ever materialized —
    memory is O(edges + hosts) instead of O(hosts^2)."""
    r0 = jnp.full((n,), 1.0 / n, dtype=jnp.float32)
    teleport = (1.0 - damping) / n

    def body(state):
        r, _delta, i = state
        contrib = jax.ops.segment_sum(weights * r[srcs], dsts,
                                      num_segments=n)
        dangling_mass = jnp.sum(jnp.where(dangling, r, 0.0)) / n
        r2 = teleport + damping * (contrib + dangling_mass)
        return r2, jnp.max(jnp.abs(r2 - r)), i + 1

    def cond(state):
        _r, delta, i = state
        return (delta > TOL) & (i < MAX_ITERS)

    r, _, _ = jax.lax.while_loop(cond, body, (r0, jnp.float32(1.0),
                                              jnp.int32(0)))
    return r


def host_ranks(web_structure, damping: float = DAMPING) -> dict[str, float]:
    """host -> rank in [0, 1] (max-normalized), from the host link graph."""
    # node set = every source host plus every link target
    hosts = set(web_structure.source_hosts())
    for h in list(hosts):
        hosts.update(web_structure.outgoing(h).keys())
    hosts = sorted(hosts)
    if not hosts:
        return {}
    idx = {h: i for i, h in enumerate(hosts)}
    n = len(hosts)
    srcs: list[int] = []
    dsts: list[int] = []
    weights: list[float] = []
    dangling = np.zeros(n, dtype=bool)
    for h in hosts:
        out = web_structure.outgoing(h)
        total = sum(out.values())
        if total <= 0:
            dangling[idx[h]] = True     # rank mass spreads uniformly
            continue
        for target, count in out.items():
            srcs.append(idx[h])
            dsts.append(idx[target])
            weights.append(count / total)
    if not srcs:        # no edges at all: uniform ranks
        return {h: 1.0 for h in hosts}
    r = np.asarray(_power_iterate_sparse(
        jnp.asarray(np.array(srcs, np.int32)),
        jnp.asarray(np.array(dsts, np.int32)),
        jnp.asarray(np.array(weights, np.float32)),
        jnp.asarray(dangling), jnp.float32(damping), n))
    peak = float(r.max()) or 1.0
    return {h: float(r[idx[h]]) / peak for h in hosts}


def host_ranks_from_edges(webgraph, damping: float = DAMPING) -> dict[str, float]:
    """host -> rank from the per-edge webgraph store (index/webgraph.py) —
    the real-edge path the reference feeds from exported webgraph indexes
    (BlockRank.java:50 loads webgraph dumps; here the edge store IS the
    graph, no export round-trip). Cross-host edges aggregate into the same
    column-stochastic form as host_ranks(); in-host edges are excluded,
    matching the host-matrix semantics."""
    hosts, srcs, dsts, counts = webgraph.host_edge_arrays()
    n = len(hosts)
    if n == 0:
        return {}
    if len(srcs) == 0:
        return {h: 1.0 for h in hosts}
    # per-source out-degree normalization (column-stochastic transition)
    out_total = np.zeros(n, dtype=np.float32)
    np.add.at(out_total, srcs, counts)
    weights = counts / out_total[srcs]
    dangling = out_total == 0.0
    r = np.asarray(_power_iterate_sparse(
        jnp.asarray(srcs), jnp.asarray(dsts), jnp.asarray(weights),
        jnp.asarray(dangling), jnp.float32(damping), n))
    peak = float(r.max()) or 1.0
    return {h: float(r[i]) / peak for i, h in enumerate(hosts)}


def postprocess_segment(segment, web_structure, damping: float = DAMPING,
                        ranks: dict[str, float] | None = None) -> int:
    """Write cr_host_norm_d for every indexed doc from its host's rank
    (the reference's postprocessing pass over the collection). Returns
    docs updated. Pass precomputed `ranks` to avoid re-iterating."""
    if ranks is None:
        ranks = host_ranks(web_structure, damping)
    if not ranks:
        return 0
    # webgraph edges written AFTER this pass carry both endpoints'
    # rank partitions (source/target_cr_host_norm_i — edge rows are
    # immutable, so the fill happens at write time)
    segment._host_ranks = ranks
    meta = segment.metadata
    updated = 0
    for docid in range(meta.capacity()):
        if meta.is_deleted(docid):
            continue
        host = meta.text_value(docid, "host_s")
        r = ranks.get(host)
        if r is not None:
            # cr_host_norm_i: the reference's integer partition of the
            # normalized rank (a 0..10 boost bucket)
            meta.set_fields(docid, cr_host_norm_d=r,
                            cr_host_norm_i=int(round(r * 10)))
            updated += 1
    return updated
