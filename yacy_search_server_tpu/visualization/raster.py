"""RasterPlotter — software raster canvas with PNG output.

Capability equivalent of the reference's visualization substrate
(reference: source/net/yacy/visualization/RasterPlotter.java — an int[]
RGB canvas with dot/line/circle/text primitives and its own PNG encoder,
backing the network graphics, access grids and profiling graphs). Here
the canvas is a numpy uint8 [h, w, 3] array — drawing is vectorized where
it matters — and the PNG encoder is a minimal stdlib-zlib implementation
(no external imaging dependency).
"""

from __future__ import annotations

import math
import struct
import zlib

import numpy as np

# 5x7 bitmap font for the uppercase/digit subset the graphs label with
_FONT = {
    "A": "0E110E1F11", "B": "1E111E111E", "C": "0E1110110E", "D": "1E11111E00",
    "E": "1F101E101F", "F": "1F101E1010", "G": "0E1013110F", "H": "11111F1111",
    "I": "0E0404040E", "J": "010101110E", "K": "1112141212", "L": "1010101F00",
    "M": "111B151111", "N": "1119151311", "O": "0E1111110E", "P": "1E111E1010",
    "Q": "0E1111120D", "R": "1E111E1211", "S": "0F100E011E", "T": "1F04040404",
    "U": "111111110E", "V": "1111110A04", "W": "1111151B11", "X": "110A040A11",
    "Y": "110A040404", "Z": "1F0204081F", "0": "0E1915130E", "1": "040C04040E",
    "2": "0E0106081F", "3": "1E010E011E", "4": "02060A1F02", "5": "1F101E011E",
    "6": "0E101E110E", "7": "1F01020408", "8": "0E110E110E", "9": "0E110F010E",
    ".": "0000000404", "-": "00001F0000", " ": "0000000000", ":": "0004000400",
    "/": "0102040810", "_": "000000001F",
}


class RasterPlotter:
    def __init__(self, width: int, height: int,
                 background: tuple[int, int, int] = (255, 255, 255)):
        self.width = width
        self.height = height
        self.pix = np.empty((height, width, 3), dtype=np.uint8)
        self.pix[:] = background

    # -- primitives ----------------------------------------------------------

    def dot(self, x: int, y: int, color, radius: int = 0) -> None:
        if radius <= 0:
            if 0 <= x < self.width and 0 <= y < self.height:
                self.pix[y, x] = color
            return
        y0, y1 = max(0, y - radius), min(self.height, y + radius + 1)
        x0, x1 = max(0, x - radius), min(self.width, x + radius + 1)
        if y0 >= y1 or x0 >= x1:
            return
        yy, xx = np.mgrid[y0:y1, x0:x1]
        mask = (yy - y) ** 2 + (xx - x) ** 2 <= radius * radius
        self.pix[y0:y1, x0:x1][mask] = color

    def line(self, x0: int, y0: int, x1: int, y1: int, color) -> None:
        n = max(abs(x1 - x0), abs(y1 - y0), 1)
        xs = np.linspace(x0, x1, n + 1).round().astype(int)
        ys = np.linspace(y0, y1, n + 1).round().astype(int)
        ok = (xs >= 0) & (xs < self.width) & (ys >= 0) & (ys < self.height)
        self.pix[ys[ok], xs[ok]] = color

    def circle(self, cx: int, cy: int, radius: int, color) -> None:
        steps = max(8, int(2 * math.pi * radius))
        ang = np.linspace(0, 2 * math.pi, steps)
        xs = (cx + radius * np.cos(ang)).round().astype(int)
        ys = (cy + radius * np.sin(ang)).round().astype(int)
        ok = (xs >= 0) & (xs < self.width) & (ys >= 0) & (ys < self.height)
        self.pix[ys[ok], xs[ok]] = color

    def rect(self, x0: int, y0: int, x1: int, y1: int, color,
             fill: bool = False) -> None:
        x0, x1 = sorted((max(0, x0), min(self.width - 1, x1)))
        y0, y1 = sorted((max(0, y0), min(self.height - 1, y1)))
        if fill:
            self.pix[y0:y1 + 1, x0:x1 + 1] = color
        else:
            self.pix[y0, x0:x1 + 1] = color
            self.pix[y1, x0:x1 + 1] = color
            self.pix[y0:y1 + 1, x0] = color
            self.pix[y0:y1 + 1, x1] = color

    def sector(self, cx: int, cy: int, radius: int,
               a0: float, a1: float, color) -> None:
        """Filled pie sector from angle a0 to a1 (radians, clockwise from
        12 o'clock — the pie-chart convention of the reference's
        peer-load picture). Vectorized: one angle/radius mask over the
        bounding box."""
        y0, y1 = max(0, cy - radius), min(self.height, cy + radius + 1)
        x0, x1 = max(0, cx - radius), min(self.width, cx + radius + 1)
        if y0 >= y1 or x0 >= x1 or a1 <= a0:
            return
        yy, xx = np.mgrid[y0:y1, x0:x1]
        dx, dy = xx - cx, yy - cy
        inside = dx * dx + dy * dy <= radius * radius
        # angle measured clockwise from 12 o'clock
        ang = np.mod(np.arctan2(dx, -dy), 2 * math.pi)
        if a1 - a0 >= 2 * math.pi - 1e-9:
            mask = inside
        else:
            lo, hi = np.mod(a0, 2 * math.pi), np.mod(a1, 2 * math.pi)
            if lo <= hi:
                mask = inside & (ang >= lo) & (ang < hi)
            else:                      # sector wraps past 12 o'clock
                mask = inside & ((ang >= lo) | (ang < hi))
        self.pix[y0:y1, x0:x1][mask] = color

    def text(self, x: int, y: int, s: str, color) -> None:
        cx = x
        for ch in s.upper():
            glyph = _FONT.get(ch)
            if glyph is None:
                cx += 6
                continue
            rows = [int(glyph[i:i + 2], 16) for i in range(0, 10, 2)]
            for ry, bits in enumerate(rows):
                for rx in range(5):
                    if bits & (1 << (4 - rx)):
                        px, py = cx + rx, y + ry
                        if 0 <= px < self.width and 0 <= py < self.height:
                            self.pix[py, px] = color
            cx += 6

    # -- PNG output ----------------------------------------------------------

    def png_bytes(self) -> bytes:
        """Minimal PNG: 8-bit RGB, filter 0 rows, one zlib IDAT."""
        def chunk(tag: bytes, data: bytes) -> bytes:
            return (struct.pack(">I", len(data)) + tag + data
                    + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF))

        ihdr = struct.pack(">IIBBBBB", self.width, self.height, 8, 2, 0, 0, 0)
        raw = np.concatenate(
            [np.concatenate(([0], row.reshape(-1))).astype(np.uint8)
             for row in self.pix]).tobytes()
        return (b"\x89PNG\r\n\x1a\n"
                + chunk(b"IHDR", ihdr)
                + chunk(b"IDAT", zlib.compress(raw, 6))
                + chunk(b"IEND", b""))
