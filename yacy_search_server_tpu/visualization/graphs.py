"""Network + web-structure graphics over the raster canvas.

Capability equivalents of the reference's graph renderers (reference:
source/net/yacy/peers/graphics/NetworkGraph.java — peers placed on the
DHT ring circle by their hash position, my node highlighted, transfer
beams; WebStructurePicture_p — host link graph with force-ish placement).
"""

from __future__ import annotations

import math

import numpy as np

from ..parallel.distribution import LONG_MAX
from .raster import RasterPlotter

BG = (8, 8, 32)
RING = (64, 96, 160)
PEER = (80, 220, 120)
PEER_PASSIVE = (150, 150, 90)
ME = (255, 80, 80)
TEXT = (200, 200, 220)
EDGE = (70, 110, 70)
NODE = (120, 200, 240)


def network_graph(seeddb, width: int = 480, height: int = 480,
                  dist=None) -> RasterPlotter:
    """The DHT ring picture: every peer at angle = ring position / 2^63."""
    img = RasterPlotter(width, height, background=BG)
    cx, cy = width // 2, height // 2
    r = min(width, height) // 2 - 40
    img.circle(cx, cy, r, RING)

    def place(seed, color, radius):
        ang = 2 * math.pi * (seed.ring_position() / LONG_MAX) - math.pi / 2
        x = int(cx + r * math.cos(ang))
        y = int(cy + r * math.sin(ang))
        img.dot(x, y, color, radius=radius)
        img.text(x + 6, y - 3, seed.name[:12], TEXT)
        return x, y

    passive = seeddb.passive_seeds()   # locked copies: gossip threads
    active = seeddb.active_seeds()     # mutate the underlying dicts
    for s in passive:
        place(s, PEER_PASSIVE, 2)
    for s in active:
        place(s, PEER, 3)
    mx, my = place(seeddb.my_seed, ME, 5)
    img.line(cx, cy, mx, my, ME)
    img.text(10, 10, f"PEERS: {len(active)} ACTIVE "
                     f"{len(passive)} PASSIVE", TEXT)
    return img


def access_picture(tracker, peer_name: str, seeddb=None,
                   width: int = 1024, height: int = 576,
                   cellsize: int = 18) -> RasterPlotter:
    """Live access-grid picture: this peer centered on a hex-dot grid,
    hosts that accessed it in the last 10 minutes stacked down the left
    edge with beams to the center (beam brightness ~ access count), and
    connected remote peers down the right edge (capability equivalent of
    the reference's incoming-access / outgoing-connection columns;
    reference: htroot/AccessPicture_p.java:108-218 over
    serverAccessTracker + ConnectionInfo)."""
    img = RasterPlotter(width, height, background=BG)
    # hex lattice: offset every other row by half a cell
    for gy, y in enumerate(range(cellsize // 2, height, cellsize)):
        xoff = cellsize // 2 if gy % 2 else 0
        for x in range(xoff + cellsize // 2, width, cellsize):
            img.dot(x, y, (24, 24, 56))
    cx, cy = width // 2, height // 2
    img.dot(cx, cy, PEER, radius=6)
    img.circle(cx, cy, 12, RING)
    img.text(cx - 40, cy - 24, "THIS YACY PEER", TEXT)
    img.text(cx - 3 * len(peer_name), cy + 16, peer_name[:20].upper(), TEXT)

    slots = max(1, (height - 40) // (2 * cellsize))
    hosts = tracker.access_hosts()[:slots] if tracker is not None else []
    for i, (host, count) in enumerate(hosts):
        y = 20 + i * 2 * cellsize
        # brightness scales with access count (the reference scales by
        # recency bucket; count is the equivalent live signal here)
        g = min(255, 96 + 16 * count)
        img.line(70, y, cx - 14, cy, (40, g // 2, 40))
        img.dot(64, y, (64, g, 64), radius=3)
        img.text(4, y - 3, f"{host[:10].upper()} {count}", TEXT)

    peers = (seeddb.active_seeds()[:slots]
             if seeddb is not None else [])
    for i, s in enumerate(peers):
        y = 20 + i * 2 * cellsize
        img.line(cx + 14, cy, width - 70, y, (70, 70, 110))
        img.dot(width - 64, y, PEER_PASSIVE, radius=3)
        img.text(width - 60, y - 3, s.name[:10].upper(), TEXT)
    img.text(10, height - 14,
             f"{len(hosts)} ACCESS HOSTS  {len(peers)} PEERS", TEXT)
    return img


# thread-group slices of the peer-load pie and their colors (the
# reference's CircleThreadPiece groups, PeerLoadPicture.java:29-34)
_LOAD_GROUPS = {
    "dht-distribution": ("DHT-DISTRIBUTION", (119, 136, 153)),
    "peer-ping": ("YACY CORE", (255, 230, 160)),
}
_IDLE_COLOR = (170, 255, 170)
_MISC_COLOR = (190, 50, 180)


def peer_load_picture(registry, width: int = 800, height: int = 600,
                      showidle: bool = True) -> RasterPlotter:
    """Pie chart of where the node's busy threads spend their cycles:
    idle vs busy per thread group (capability equivalent of the
    reference's thread-load pie, htroot/PeerLoadPicture.java over
    BusyThread exec/sleep times; here the BusyThread analog counts
    busy/idle cycles weighted by their sleep intervals)."""
    img = RasterPlotter(width, height, background=BG)
    idle_t, misc_t = 0.0, 0.0
    groups = {k: 0.0 for k in _LOAD_GROUPS}
    names = registry.names() if registry is not None else []
    for name in names:
        th = registry.get(name)
        if th is None:
            continue
        busy = th.busy_cycles * max(th.busy_sleep_s, 0.01)
        idle_t += th.idle_cycles * max(th.idle_sleep_s, 0.01)
        matched = False
        for key in _LOAD_GROUPS:
            if key in name:
                groups[key] += busy
                matched = True
                break
        if not matched:
            misc_t += busy
    slices = [(label, groups[key], color)
              for key, (label, color) in _LOAD_GROUPS.items()
              if groups[key] > 0]
    if misc_t > 0:
        slices.append(("MISC", misc_t, _MISC_COLOR))
    if showidle and idle_t > 0:
        slices.append(("IDLE", idle_t, _IDLE_COLOR))
    total = sum(v for _, v, _ in slices)
    cx, cy = width // 2, height // 2
    r = min(width, height) // 2 - 60
    if total <= 0:
        img.circle(cx, cy, r, RING)
        img.text(cx - 40, cy, "NO LOAD DATA", TEXT)
        return img
    ang = 0.0
    ly = 16
    for label, v, color in slices:
        span = 2 * math.pi * v / total
        img.sector(cx, cy, r, ang, ang + span, color)
        mid = ang + span / 2
        lx = int(cx + (r + 14) * math.sin(mid))
        lyy = int(cy - (r + 14) * math.cos(mid))
        img.text(min(lx, width - 6 * len(label) - 2), lyy,
                 label, TEXT)
        img.rect(8, ly, 18, ly + 8, color, fill=True)
        img.text(24, ly + 1, f"{label} {100 * v / total:.0f}", TEXT)
        ly += 14
        ang += span
    img.circle(cx, cy, r, RING)
    return img


def search_event_picture(seeddb, event, width: int = 640,
                         height: int = 480) -> RasterPlotter:
    """Picture of ONE search event on the DHT ring: the asked remote
    peers at their ring positions with beams from this peer — bright
    for peers that returned results, dim for silent ones (capability
    equivalent of the reference's per-event network picture,
    htroot/SearchEventPicture.java via
    NetworkGraph.getSearchEventPicture)."""
    img = RasterPlotter(width, height, background=BG)
    cx, cy = width // 2, height // 2
    r = min(width, height) // 2 - 50
    img.circle(cx, cy, r, RING)
    img.dot(cx, cy, ME, radius=5)
    my = getattr(seeddb, "my_seed", None) if seeddb is not None else None
    img.text(cx + 8, cy - 3,
             (my.name if my is not None else "ME")[:12], TEXT)
    asked = list(getattr(event, "asked_peers", []) or [])
    returned = set((getattr(event, "result_peer_hashes", None) or ()))
    for s in asked:
        ang = 2 * math.pi * (s.ring_position() / LONG_MAX) - math.pi / 2
        x = int(cx + r * math.cos(ang))
        y = int(cy + r * math.sin(ang))
        hot = s.hash in returned
        img.line(cx, cy, x, y, PEER if hot else (60, 80, 60))
        img.dot(x, y, PEER if hot else PEER_PASSIVE, radius=3 if hot else 2)
        img.text(x + 6, y - 3, s.name[:12], TEXT)
    q = getattr(getattr(event, "query", None), "querystring", "")
    img.text(10, 10, f"SEARCH: {q[:40].upper()}", TEXT)
    img.text(10, height - 14,
             f"{len(asked)} PEERS ASKED  {len(returned)} ANSWERED", TEXT)
    return img


def web_structure_graph(web_structure, width: int = 640, height: int = 480,
                        max_hosts: int = 24) -> RasterPlotter:
    """Host link graph: top hosts on a circle, edges for host->host links."""
    img = RasterPlotter(width, height, background=BG)
    cx, cy = width // 2, height // 2
    r = min(width, height) // 2 - 60
    hosts = [h for h, _ in web_structure.top_hosts(max_hosts)]
    if not hosts:
        img.text(20, height // 2, "NO STRUCTURE DATA", TEXT)
        return img
    pos: dict[str, tuple[int, int]] = {}
    for i, h in enumerate(hosts):
        ang = 2 * math.pi * i / len(hosts) - math.pi / 2
        pos[h] = (int(cx + r * math.cos(ang)), int(cy + r * math.sin(ang)))
    for h in hosts:
        hx, hy = pos[h]
        for target, count in web_structure.outgoing(h).items():
            if target in pos:
                img.line(hx, hy, *pos[target], EDGE)
    for h in hosts:
        hx, hy = pos[h]
        refs = web_structure.references_count(h)
        img.dot(hx, hy, NODE, radius=min(3 + refs, 10))
        img.text(hx + 8, hy - 3, h[:18], TEXT)
    img.text(10, 10, f"HOSTS: {len(hosts)}", TEXT)
    return img
