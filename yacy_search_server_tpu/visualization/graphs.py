"""Network + web-structure graphics over the raster canvas.

Capability equivalents of the reference's graph renderers (reference:
source/net/yacy/peers/graphics/NetworkGraph.java — peers placed on the
DHT ring circle by their hash position, my node highlighted, transfer
beams; WebStructurePicture_p — host link graph with force-ish placement).
"""

from __future__ import annotations

import math

import numpy as np

from ..parallel.distribution import LONG_MAX
from .raster import RasterPlotter

BG = (8, 8, 32)
RING = (64, 96, 160)
PEER = (80, 220, 120)
PEER_PASSIVE = (150, 150, 90)
ME = (255, 80, 80)
TEXT = (200, 200, 220)
EDGE = (70, 110, 70)
NODE = (120, 200, 240)


def network_graph(seeddb, width: int = 480, height: int = 480,
                  dist=None) -> RasterPlotter:
    """The DHT ring picture: every peer at angle = ring position / 2^63."""
    img = RasterPlotter(width, height, background=BG)
    cx, cy = width // 2, height // 2
    r = min(width, height) // 2 - 40
    img.circle(cx, cy, r, RING)

    def place(seed, color, radius):
        ang = 2 * math.pi * (seed.ring_position() / LONG_MAX) - math.pi / 2
        x = int(cx + r * math.cos(ang))
        y = int(cy + r * math.sin(ang))
        img.dot(x, y, color, radius=radius)
        img.text(x + 6, y - 3, seed.name[:12], TEXT)
        return x, y

    passive = seeddb.passive_seeds()   # locked copies: gossip threads
    active = seeddb.active_seeds()     # mutate the underlying dicts
    for s in passive:
        place(s, PEER_PASSIVE, 2)
    for s in active:
        place(s, PEER, 3)
    mx, my = place(seeddb.my_seed, ME, 5)
    img.line(cx, cy, mx, my, ME)
    img.text(10, 10, f"PEERS: {len(active)} ACTIVE "
                     f"{len(passive)} PASSIVE", TEXT)
    return img


def web_structure_graph(web_structure, width: int = 640, height: int = 480,
                        max_hosts: int = 24) -> RasterPlotter:
    """Host link graph: top hosts on a circle, edges for host->host links."""
    img = RasterPlotter(width, height, background=BG)
    cx, cy = width // 2, height // 2
    r = min(width, height) // 2 - 60
    hosts = [h for h, _ in web_structure.top_hosts(max_hosts)]
    if not hosts:
        img.text(20, height // 2, "NO STRUCTURE DATA", TEXT)
        return img
    pos: dict[str, tuple[int, int]] = {}
    for i, h in enumerate(hosts):
        ang = 2 * math.pi * i / len(hosts) - math.pi / 2
        pos[h] = (int(cx + r * math.cos(ang)), int(cy + r * math.sin(ang)))
    for h in hosts:
        hx, hy = pos[h]
        for target, count in web_structure.outgoing(h).items():
            if target in pos:
                img.line(hx, hy, *pos[target], EDGE)
    for h in hosts:
        hx, hy = pos[h]
        refs = web_structure.references_count(h)
        img.dot(hx, hy, NODE, radius=min(3 + refs, 10))
        img.text(hx + 8, hy - 3, h[:18], TEXT)
    img.text(10, 10, f"HOSTS: {len(hosts)}", TEXT)
    return img
