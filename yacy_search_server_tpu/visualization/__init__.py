from .raster import RasterPlotter

__all__ = ["RasterPlotter"]
