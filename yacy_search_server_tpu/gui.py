"""Desktop GUI — tray-style control window for the running node.

Capability equivalent of the reference's tray/GUI (reference:
source/net/yacy/gui/Tray.java + gui/YaCyApp.java — an AWT system-tray
icon whose menu opens the search page in the browser and triggers
shutdown; the `-gui` verb starts the node with it). Implemented over
tkinter when a display is available; on headless hosts (every server
deployment, and this build image) it degrades to opening the browser /
doing nothing — the reference's tray is equally inert headless.
"""

from __future__ import annotations

import os
import threading
import webbrowser


def display_available() -> bool:
    """A GUI can only appear with a display server and tkinter."""
    if not (os.environ.get("DISPLAY") or os.environ.get("WAYLAND_DISPLAY")
            or os.name == "nt"):
        return False
    try:
        import tkinter  # noqa: F401
    except ImportError:
        return False
    return True


def open_browser(url: str, opener=None) -> bool:
    """Open the node's search page (Tray menu 'Search' / startup
    browser-popup behavior)."""
    try:
        return (opener or webbrowser.open)(url)
    except Exception:
        return False


class Tray:
    """Control window: status line + Open-Search + Shutdown buttons
    (the tray menu's actions; tkinter has no portable tray API, so this
    is a small always-on-top window like YaCyApp's console)."""

    def __init__(self, base_url: str, on_shutdown, peer_name: str = ""):
        self.base_url = base_url
        self.on_shutdown = on_shutdown
        self.peer_name = peer_name
        self._root = None

    def run(self) -> None:
        """Blocking mainloop; returns when the window closes or
        shutdown is picked. No-op without a display."""
        if not display_available():
            return
        import tkinter as tk
        root = tk.Tk()
        self._root = root
        root.title(f"YaCy-TPU {self.peer_name}".strip())
        root.attributes("-topmost", True)
        tk.Label(root, text=f"serving on {self.base_url}").pack(
            padx=12, pady=6)
        tk.Button(root, text="Open search page",
                  command=lambda: open_browser(self.base_url)).pack(
            fill="x", padx=12, pady=2)

        def _shutdown():
            try:
                self.on_shutdown()
            finally:
                root.destroy()
        tk.Button(root, text="Shutdown node", command=_shutdown).pack(
            fill="x", padx=12, pady=(2, 10))
        root.protocol("WM_DELETE_WINDOW", root.destroy)
        root.mainloop()
        self._root = None

    def close(self) -> None:
        root = self._root
        if root is not None:
            try:
                root.after(0, root.destroy)
            except Exception:
                import logging
                logging.getLogger("gui").debug(
                    "tk teardown raced window close", exc_info=True)


def run_gui(base_url: str, shutdown_event: threading.Event,
            peer_name: str = "") -> None:
    """The -gui verb body: browser popup + control window; falls back to
    just the browser popup on headless boxes. A REMOTE shutdown
    (Steering servlet / -shutdown verb) must also close the window, or
    the blocked mainloop would keep the node's port and DATA lock."""
    open_browser(base_url)
    tray = Tray(base_url, shutdown_event.set, peer_name)
    watcher = threading.Thread(
        target=lambda: (shutdown_event.wait(), tray.close()),
        name="gui-shutdown-watch", daemon=True)
    watcher.start()
    tray.run()
