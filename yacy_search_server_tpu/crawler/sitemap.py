"""Sitemap ingestion — XML sitemaps and sitemap indexes onto the frontier.

Capability equivalent of the reference's sitemap machinery (reference:
source/net/yacy/document/parser/sitemapParser.java — urlset/sitemapindex
XML incl. gzip; CrawlStacker.enqueueEntriesAsynchronous feeding parsed
locations to the frontier; robots.txt Sitemap: discovery handled by
crawler/robots.py). The importer pulls nested sitemap indexes through
the normal loader (cache, politeness, size caps apply).
"""

from __future__ import annotations

import gzip
import re
import xml.etree.ElementTree as ET

from .loader import CacheStrategy
from .request import Request

MAX_NESTED_SITEMAPS = 64
MAX_URLS = 50_000   # per-sitemap cap (the sitemap.org protocol limit)

_NS = re.compile(r"\{[^}]*\}")


def _strip_ns(tag: str) -> str:
    return _NS.sub("", tag).lower()


def parse_sitemap(content: bytes) -> tuple[list[dict], list[str]]:
    """-> (url entries [{loc, lastmod, priority}], nested sitemap locs)."""
    if content[:2] == b"\x1f\x8b":
        try:
            content = gzip.decompress(content)
        except OSError:
            return [], []
    try:
        root = ET.fromstring(content)
    except ET.ParseError:
        return [], []
    urls: list[dict] = []
    nested: list[str] = []
    kind = _strip_ns(root.tag)
    for node in root:
        tag = _strip_ns(node.tag)
        if tag not in ("url", "sitemap"):
            continue
        entry: dict = {}
        for child in node:
            entry[_strip_ns(child.tag)] = (child.text or "").strip()
        loc = entry.get("loc", "")
        if not loc:
            continue
        if kind == "sitemapindex" or tag == "sitemap":
            nested.append(loc)
        else:
            urls.append(entry)
        if len(urls) >= MAX_URLS:
            break
    return urls, nested


class SitemapImporter:
    """Load a sitemap (recursing through indexes) and stack every location."""

    def __init__(self, loader, stacker, profile_handle: str):
        self.loader = loader
        self.stacker = stacker
        self.profile_handle = profile_handle

    def import_sitemap(self, sitemap_url: str) -> int:
        stacked = 0
        seen: set[str] = set()
        queue = [sitemap_url]
        while queue and len(seen) < MAX_NESTED_SITEMAPS:
            sm = queue.pop(0)
            if sm in seen:
                continue
            seen.add(sm)
            resp = self.loader.load(Request(sm), CacheStrategy.IFFRESH)
            if resp.status != 200:
                continue
            urls, nested = parse_sitemap(resp.content)
            queue.extend(nested)
            for entry in urls:
                reason = self.stacker.stack(Request(
                    url=entry["loc"], profile_handle=self.profile_handle,
                    depth=0))
                if reason is None:
                    stacked += 1
        return stacked
