"""Snapshots — page revision archive with an inventory/archive state machine.

Capability equivalent of the reference's snapshot subsystem (reference:
source/net/yacy/crawler/data/Snapshots.java:61 — revisions stored under
SNAPSHOTS/<state>/<hosthash>/<depth>/<urlhash>.<date>.* — and
Transactions.java:57-247 — the INVENTORY/ARCHIVE state machine where
fresh snapshots land in INVENTORY, may be replaced by newer loads, and
`commit` moves a revision to ARCHIVE permanently). The reference shells
out to wkhtmltopdf/convert for PDF/image renditions; here the archived
rendition is the loaded content itself (the framework never shells out),
which keeps every revision queryable and diffable.
"""

from __future__ import annotations

import os
import time

from ..utils.hashes import hosthash, url2hash

INVENTORY = "INVENTORY"
ARCHIVE = "ARCHIVE"


class Snapshots:
    def __init__(self, data_dir: str | None = None):
        self.data_dir = data_dir
        if data_dir:
            for state in (INVENTORY, ARCHIVE):
                os.makedirs(os.path.join(data_dir, state), exist_ok=True)

    def _dir(self, state: str, urlhash: bytes, depth: int) -> str | None:
        if not self.data_dir:
            return None
        hh = hosthash(urlhash).decode("ascii", "replace")
        return os.path.join(self.data_dir, state, hh, str(depth))

    @staticmethod
    def _fname(urlhash: bytes, date_s: float, ext: str) -> str:
        stamp = time.strftime("%Y%m%d%H%M%S", time.gmtime(date_s))
        return f"{urlhash.decode('ascii', 'replace')}.{stamp}.{ext}"

    # -- store/load -----------------------------------------------------------

    def store(self, url: str, content: bytes, depth: int = 0,
              date_s: float | None = None, ext: str = "html",
              state: str = INVENTORY, replace_inventory: bool = True) -> str | None:
        """Store one revision; INVENTORY keeps only the newest revision per
        url (replaceable working copy), ARCHIVE accumulates (permanent)."""
        uh = url2hash(url)
        d = self._dir(state, uh, depth)
        if d is None:
            return None
        os.makedirs(d, exist_ok=True)
        if state == INVENTORY and replace_inventory:
            for old in self._revision_files(INVENTORY, uh):
                try:
                    os.remove(old)
                except OSError:
                    pass
        path = os.path.join(d, self._fname(
            uh, date_s if date_s is not None else time.time(), ext))
        path = self._uncollide(path)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(content)
        os.replace(tmp, path)
        return path

    @staticmethod
    def _uncollide(path: str) -> str:
        """Archived revisions are permanent: a same-second revision must
        get a fresh name, never overwrite."""
        if not os.path.exists(path):
            return path
        base, ext = path.rsplit(".", 1)
        i = 1
        while os.path.exists(f"{base}-{i}.{ext}"):
            i += 1
        return f"{base}-{i}.{ext}"

    def _revision_files(self, state: str, urlhash: bytes) -> list[str]:
        if not self.data_dir:
            return []
        hh = hosthash(urlhash).decode("ascii", "replace")
        base = os.path.join(self.data_dir, state, hh)
        prefix = urlhash.decode("ascii", "replace") + "."
        out = []
        if not os.path.isdir(base):
            return out
        for depth in os.listdir(base):
            dd = os.path.join(base, depth)
            if not os.path.isdir(dd):
                continue
            for fn in os.listdir(dd):
                if fn.startswith(prefix) and not fn.endswith(".tmp"):
                    out.append(os.path.join(dd, fn))
        return sorted(out)

    def revisions(self, url: str, state: str | None = None) -> list[str]:
        uh = url2hash(url)
        states = (state,) if state else (INVENTORY, ARCHIVE)
        return [p for s in states for p in self._revision_files(s, uh)]

    def load(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    # -- state machine (Transactions semantics) -------------------------------

    def commit(self, url: str) -> int:
        """Move every INVENTORY revision of `url` to ARCHIVE (permanent).
        Returns revisions moved (Transactions.commit)."""
        uh = url2hash(url)
        moved = 0
        for src in self._revision_files(INVENTORY, uh):
            rel = os.path.relpath(src, os.path.join(self.data_dir, INVENTORY))
            dst = os.path.join(self.data_dir, ARCHIVE, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            os.replace(src, self._uncollide(dst))
            moved += 1
        return moved

    def delete(self, url: str, state: str | None = None) -> int:
        uh = url2hash(url)
        states = (state,) if state else (INVENTORY, ARCHIVE)
        n = 0
        for s in states:
            for p in self._revision_files(s, uh):
                try:
                    os.remove(p)
                    n += 1
                except OSError:
                    pass
        return n

    def size(self, state: str) -> int:
        if not self.data_dir:
            return 0
        n = 0
        for _root, _dirs, files in os.walk(os.path.join(self.data_dir, state)):
            n += sum(1 for f in files if not f.endswith(".tmp"))
        return n


# -- renditions (Html2Image shell-outs, gated) ---------------------------

def _which(binary: str) -> str | None:
    import shutil
    return shutil.which(binary)


def wkhtmltopdf_available() -> bool:
    """The reference's PDF rendition path shells out to wkhtmltopdf
    (Transactions.java:69,239 via Html2Image); availability-gated here
    the same way."""
    return _which("wkhtmltopdf") is not None


def render_pdf(url: str, out_path: str, renderer=None,
               timeout_s: float = 60.0) -> bool:
    """Render a live url to PDF via wkhtmltopdf (or an injected
    `renderer(url, out_path) -> bool` for tests/alternatives). Returns
    False when no renderer is available — a declared degradation, never
    an error (the reference logs and continues too)."""
    if renderer is not None:
        return bool(renderer(url, out_path))
    binary = _which("wkhtmltopdf")
    if binary is None:
        return False
    import subprocess
    try:
        proc = subprocess.run(
            [binary, "--quiet", url, out_path],
            timeout=timeout_s, capture_output=True)
        return proc.returncode == 0 and os.path.exists(out_path)
    except (subprocess.TimeoutExpired, OSError):
        return False
