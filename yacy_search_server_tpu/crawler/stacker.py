"""CrawlStacker — admission control for discovered URLs.

Capability equivalent of the reference's stacker (reference:
source/net/yacy/crawler/CrawlStacker.java:65-415: the WorkflowTask that
checks every discovered url — protocol support, profile match, depth,
double-occurrence against frontier and index, recrawl age — then routes
it to the LOCAL / GLOBAL / NOLOAD frontier stack; GLOBAL urls are the
DHT-vertical-partition remote-crawl delegation path).
"""

from __future__ import annotations

import time
from urllib.parse import urlsplit

from ..utils.eventtracker import EClass, StageTimer
from .frontier import NoticedURL, StackType
from .profile import CrawlProfile
from .request import Request

SUPPORTED_SCHEMES = {"http", "https", "file"}


class CrawlStacker:
    def __init__(self, noticed: NoticedURL, profiles: dict[str, CrawlProfile],
                 segment=None, blacklist=None, robots=None,
                 accept_global: bool = False):
        self.noticed = noticed
        self.profiles = profiles
        self.segment = segment          # index/segment.Segment (url dedup)
        self.blacklist = blacklist      # callable(url) -> str | None reason
        self.robots = robots            # robots.RobotsTxt
        self.accept_global = accept_global
        self.stacked = 0
        self.rejected: dict[str, int] = {}

    def _reject(self, reason: str) -> str:
        self.rejected[reason] = self.rejected.get(reason, 0) + 1
        return reason

    # -- checks (checkAcceptanceInitially / checkAcceptanceChangeable) ------

    def check_acceptance(self, req: Request,
                         profile: CrawlProfile) -> str | None:
        url = req.url
        parts = urlsplit(url)
        if parts.scheme.lower() not in SUPPORTED_SCHEMES:
            return self._reject(f"unsupported scheme {parts.scheme}")
        if not parts.netloc and parts.scheme.lower() != "file":
            return self._reject("no host")
        if len(url) > 2048:
            return self._reject("url too long")
        if req.depth > profile.depth:
            return self._reject("depth limit")
        if not profile.crawl_allowed(url):
            return self._reject("profile must(not)match")
        if self.blacklist is not None:
            reason = self.blacklist(url)
            if reason:
                return self._reject(f"blacklisted: {reason}")
        if self.noticed.exists_in_any(url):
            return self._reject("already in frontier")
        if self.segment is not None:
            from ..utils.hashes import url2hash
            meta = self.segment.metadata.get_by_urlhash(url2hash(url))
            if meta is not None:
                days = meta.get("load_date_days_i")
                last_s = days * 86400.0 if days else None
                if not profile.recrawl_due(last_s):
                    return self._reject("already indexed, not due")
        if self.robots is not None and not self.robots.is_allowed(url):
            return self._reject("robots disallow")
        return None

    # -- stacking -----------------------------------------------------------

    def stack(self, req: Request) -> str | None:
        """Admit one url; returns None on success else rejection reason."""
        with StageTimer(EClass.CRAWL, "stackCrawl", 1):
            profile = self.profiles.get(req.profile_handle)
            if profile is None:
                return self._reject("unknown profile")
            reason = self.check_acceptance(req, profile)
            if reason:
                return reason
            # routing (CrawlStacker.stackCrawl: local vs global): urls for
            # other peers' DHT ranges go GLOBAL when remote indexing is on
            stack = StackType.LOCAL
            if profile.remote_indexing and self.accept_global \
                    and req.depth > 0:
                stack = StackType.GLOBAL
            self.noticed.push(stack, req)
            self.stacked += 1
            return None

    def enqueue_entries(self, anchors, source_urlhash: bytes,
                        profile_handle: str, depth: int) -> int:
        """Stack every hyperlink discovered in a parsed document
        (CrawlStacker.enqueueEntries)."""
        n = 0
        for a in anchors:
            url = a.url if hasattr(a, "url") else str(a)
            name = getattr(a, "text", "")
            req = Request(url=url, profile_handle=profile_handle,
                          referrer_hash=source_urlhash, name=name,
                          depth=depth)
            if self.stack(req) is None:
                n += 1
        return n
