"""Minimal SMB2 client — the built-in smb:// loader.

Capability equivalent of the reference's SMB crawling support
(reference: source/net/yacy/crawler/retrieval/SMBLoader.java:39-60,
which rides the jcifs library): the crawler must fetch files and
directory listings from SMB shares out of the box. This is a
from-the-spec implementation of the SMB 2.0.2 dialect subset the
loader needs — NEGOTIATE, SESSION_SETUP (anonymous/guest NTLMSSP, or
authenticated via url userinfo), TREE_CONNECT, CREATE, READ,
QUERY_DIRECTORY, CLOSE — over direct TCP 445 ([MS-SMB2] message
layouts; no third-party SMB library ships in this image).

Anonymous/guest is the crawler's normal posture (the reference passes
jcifs guest credentials for public shares); NTLMv2 single-exchange auth
covers credentialed intranet crawls.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import socket
import struct
import time
from urllib.parse import unquote, urlsplit

SMB2_MAGIC = b"\xfeSMB"
# commands
CMD_NEGOTIATE = 0x0000
CMD_SESSION_SETUP = 0x0001
CMD_TREE_CONNECT = 0x0003
CMD_TREE_DISCONNECT = 0x0004
CMD_CREATE = 0x0005
CMD_CLOSE = 0x0006
CMD_READ = 0x0008
CMD_QUERY_DIRECTORY = 0x000E
# NT status
STATUS_OK = 0x00000000
STATUS_MORE_PROCESSING = 0xC0000016
STATUS_NO_MORE_FILES = 0x80000006
STATUS_END_OF_FILE = 0xC0000011

_DIALECT = 0x0202    # SMB 2.0.2: the floor every server speaks

# NTLMSSP flags: UNICODE | REQUEST_TARGET | NTLM | ALWAYS_SIGN |
# ANONYMOUS(when no creds) | EXTENDED_SESSIONSECURITY | 56/128
_NTLM_BASE = 0x00000001 | 0x00000004 | 0x00000200 | 0x00008000 | 0x00080000
_NTLM_ANON = 0x00000800


class SMBError(OSError):
    pass


def _md4(data: bytes) -> bytes:
    """MD4 (RFC 1320) for the NTLM hash — OpenSSL 3 ships with md4
    disabled, so hashlib cannot be relied on for it."""
    try:
        return hashlib.new("md4", data).digest()
    except ValueError:
        pass
    msg = bytearray(data)
    ml = len(data) * 8
    msg.append(0x80)
    while len(msg) % 64 != 56:
        msg.append(0)
    msg += struct.pack("<Q", ml)
    h = [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476]

    def lrot(x, c):
        x &= 0xFFFFFFFF
        return ((x << c) | (x >> (32 - c))) & 0xFFFFFFFF

    for off in range(0, len(msg), 64):
        x = struct.unpack("<16I", msg[off:off + 64])
        a, b, c, d = h
        # round 1: F = (b & c) | (~b & d); roles rotate a->d->c->b
        for i in range(16):
            k, s = i, (3, 7, 11, 19)[i % 4]
            if i % 4 == 0:
                a = lrot(a + ((b & c) | (~b & d)) + x[k], s)
            elif i % 4 == 1:
                d = lrot(d + ((a & b) | (~a & c)) + x[k], s)
            elif i % 4 == 2:
                c = lrot(c + ((d & a) | (~d & b)) + x[k], s)
            else:
                b = lrot(b + ((c & d) | (~c & a)) + x[k], s)
        # round 2: G = (b & c) | (b & d) | (c & d)
        order2 = (0, 4, 8, 12, 1, 5, 9, 13, 2, 6, 10, 14, 3, 7, 11, 15)
        for i in range(16):
            k, s = order2[i], (3, 5, 9, 13)[i % 4]
            if i % 4 == 0:
                a = lrot(a + ((b & c) | (b & d) | (c & d)) + x[k]
                         + 0x5A827999, s)
            elif i % 4 == 1:
                d = lrot(d + ((a & b) | (a & c) | (b & c)) + x[k]
                         + 0x5A827999, s)
            elif i % 4 == 2:
                c = lrot(c + ((d & a) | (d & b) | (a & b)) + x[k]
                         + 0x5A827999, s)
            else:
                b = lrot(b + ((c & d) | (c & a) | (d & a)) + x[k]
                         + 0x5A827999, s)
        # round 3: H = b ^ c ^ d
        order3 = (0, 8, 4, 12, 2, 10, 6, 14, 1, 9, 5, 13, 3, 11, 7, 15)
        for i in range(16):
            k, s = order3[i], (3, 9, 11, 15)[i % 4]
            if i % 4 == 0:
                a = lrot(a + (b ^ c ^ d) + x[k] + 0x6ED9EBA1, s)
            elif i % 4 == 1:
                d = lrot(d + (a ^ b ^ c) + x[k] + 0x6ED9EBA1, s)
            elif i % 4 == 2:
                c = lrot(c + (d ^ a ^ b) + x[k] + 0x6ED9EBA1, s)
            else:
                b = lrot(b + (c ^ d ^ a) + x[k] + 0x6ED9EBA1, s)
        h = [(v + w) & 0xFFFFFFFF for v, w in zip(h, (a, b, c, d))]
    return struct.pack("<4I", *h)


def _header(cmd: int, msg_id: int, session_id: int = 0,
            tree_id: int = 0, credits: int = 31) -> bytes:
    return struct.pack(
        "<4sHHIHHIIQIIQ16s",
        SMB2_MAGIC, 64, 0, 0, cmd, credits, 0, 0,
        msg_id, 0xFEFF, tree_id, session_id, b"\0" * 16)


class SMB2Client:
    """One connection to one share. Usage:

        with SMB2Client("host", "share") as c:
            names = c.listdir("dir/sub")
            data = c.read_file("dir/sub/file.txt")
    """

    def __init__(self, host: str, share: str, port: int = 445,
                 username: str = "", password: str = "",
                 domain: str = "", timeout: float = 10.0):
        self.host, self.share = host, share
        self.username, self.password, self.domain = (username, password,
                                                     domain)
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._msg_id = 0
        self._session_id = 0
        self._tree_id = 0
        self._negotiate()
        self._session_setup()
        self._tree_connect()

    # -- transport -----------------------------------------------------------

    def _send_recv(self, cmd: int, body: bytes) -> tuple[int, bytes]:
        """One request/response; returns (nt_status, response body)."""
        hdr = _header(cmd, self._msg_id, self._session_id, self._tree_id)
        self._msg_id += 1
        pkt = hdr + body
        self._sock.sendall(struct.pack(">I", len(pkt)) + pkt)
        raw = self._recv_exact(4)
        (ln,) = struct.unpack(">I", raw)
        resp = self._recv_exact(ln)
        if resp[:4] != SMB2_MAGIC:
            raise SMBError("not an SMB2 response")
        status = struct.unpack_from("<I", resp, 8)[0]
        self._last_tree_id = struct.unpack_from("<I", resp, 36)[0]
        sid = struct.unpack_from("<Q", resp, 40)[0]
        if sid and not self._session_id:
            self._session_id = sid
        return status, resp[64:]

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            got = self._sock.recv(n - len(buf))
            if not got:
                raise SMBError("connection closed")
            buf += got
        return buf

    # -- handshake -----------------------------------------------------------

    def _negotiate(self) -> None:
        body = struct.pack("<HHHH4x16s8x", 36, 1, 1, 0,
                           os.urandom(16)) + struct.pack("<H", _DIALECT)
        status, resp = self._send_recv(CMD_NEGOTIATE, body)
        if status != STATUS_OK:
            raise SMBError(f"negotiate failed: 0x{status:08x}")
        dialect = struct.unpack_from("<H", resp, 4)[0]
        if dialect != _DIALECT:
            raise SMBError(f"server chose unsupported dialect "
                           f"0x{dialect:04x}")

    def _session_setup(self) -> None:
        type1 = self._ntlm_type1()
        status, resp = self._send_recv(CMD_SESSION_SETUP,
                                       self._setup_body(type1))
        if status == STATUS_OK:
            return            # server granted without a challenge
        if status != STATUS_MORE_PROCESSING:
            raise SMBError(f"session setup failed: 0x{status:08x}")
        off, ln = struct.unpack_from("<HH", resp, 4)
        blob = resp[off - 64:off - 64 + ln]
        type3 = self._ntlm_type3(blob)
        status, _ = self._send_recv(CMD_SESSION_SETUP,
                                    self._setup_body(type3))
        if status != STATUS_OK:
            raise SMBError(f"authentication failed: 0x{status:08x}")

    @staticmethod
    def _setup_body(token: bytes) -> bytes:
        # SecurityBufferOffset is from the SMB2 header start (64 + 24)
        return struct.pack("<HBBIIHHQ", 25, 0, 1, 0, 0, 88,
                           len(token), 0) + token

    def _ntlm_type1(self) -> bytes:
        flags = _NTLM_BASE | (0 if self.password else _NTLM_ANON)
        return (b"NTLMSSP\0" + struct.pack("<I", 1)
                + struct.pack("<I", flags)
                + struct.pack("<HHI", 0, 0, 0)     # domain (empty)
                + struct.pack("<HHI", 0, 0, 0))    # workstation (empty)

    def _ntlm_type3(self, type2: bytes) -> bytes:
        """Anonymous (empty responses) or NTLMv2 over the challenge."""
        if not type2.startswith(b"NTLMSSP\0"):
            # some servers wrap in SPNEGO; find the embedded NTLMSSP
            i = type2.find(b"NTLMSSP\0")
            if i < 0:
                raise SMBError("no NTLM challenge in security blob")
            type2 = type2[i:]
        challenge = type2[24:32]
        user = self.username.encode("utf-16le")
        dom = self.domain.encode("utf-16le")
        if self.password:
            # NTLMv2: HMAC-MD5 chain over the server challenge + a
            # client blob ([MS-NLMP] 3.3.2)
            ntlm_hash = _md4(self.password.encode("utf-16le"))
            v2_key = hmac.new(
                ntlm_hash,
                (self.username.upper() + self.domain).encode("utf-16le"),
                "md5").digest()
            ts = int((time.time() + 11644473600) * 10_000_000)
            cblob = (b"\x01\x01" + b"\0" * 6 + struct.pack("<Q", ts)
                     + os.urandom(8) + b"\0" * 4
                     + self._type2_target_info(type2) + b"\0" * 4)
            proof = hmac.new(v2_key, challenge + cblob, "md5").digest()
            nt_resp = proof + cblob
            lm_resp = b"\0" * 24
        else:
            nt_resp = b""
            lm_resp = b"\0"      # 1-byte LM response marks ANONYMOUS
        flags = _NTLM_BASE | (0 if self.password else _NTLM_ANON)
        payload_off = 64 + 8     # fixed part of the type-3 message
        fields = []
        payload = b""

        def field(data: bytes) -> None:
            nonlocal payload
            fields.append(struct.pack("<HHI", len(data), len(data),
                                      payload_off + len(payload)))
            payload += data

        field(lm_resp)
        field(nt_resp)
        field(dom)
        field(user)
        field(b"")               # workstation
        field(b"")               # session key
        return (b"NTLMSSP\0" + struct.pack("<I", 3) + b"".join(fields)
                + struct.pack("<I", flags) + payload)

    @staticmethod
    def _type2_target_info(type2: bytes) -> bytes:
        ln, _maxlen, off = struct.unpack_from("<HHI", type2, 40)
        return type2[off:off + ln]

    def _tree_connect(self) -> None:
        path = f"\\\\{self.host}\\{self.share}".encode("utf-16le")
        body = struct.pack("<HHHH", 9, 0, 72, len(path)) + path
        status, resp = self._send_recv(CMD_TREE_CONNECT, body)
        if status != STATUS_OK:
            raise SMBError(f"tree connect failed: 0x{status:08x}")
        # TreeId lives in the response HEADER; re-read it from there is
        # awkward with our framing, so issue: headers were consumed in
        # _send_recv — stash tree id by re-parsing is done there instead.
        # (TreeId is at header offset 36; _send_recv keeps the raw resp.)
        self._tree_id = self._last_tree_id

    # -- files ---------------------------------------------------------------

    def _create(self, path: str, directory: bool) -> tuple[bytes, int]:
        name = path.replace("/", "\\").strip("\\").encode("utf-16le")
        body = struct.pack(
            "<HBBIQQIIIIIHHII", 57, 0, 0, 2, 0, 0,
            0x00120089,                       # read/attrs access
            0x10 if directory else 0,         # FILE_ATTRIBUTE_DIRECTORY
            7,                                # share read/write/delete
            1,                                # FILE_OPEN
            0x21 if directory else 0x40,      # dir|reparse / non-dir
            120, len(name), 0, 0) + (name or b"\0\0")
        status, resp = self._send_recv(CMD_CREATE, body)
        if status != STATUS_OK:
            raise SMBError(f"open failed for {path!r}: 0x{status:08x}")
        eof = struct.unpack_from("<Q", resp, 48)[0]
        file_id = resp[64:80]
        return file_id, eof

    def _close(self, file_id: bytes) -> None:
        body = struct.pack("<HHI", 24, 0, 0) + file_id
        self._send_recv(CMD_CLOSE, body)

    def read_file(self, path: str, max_size: int = 64 << 20) -> bytes:
        fid, eof = self._create(path, directory=False)
        try:
            if eof > max_size:
                raise SMBError(f"file exceeds max size: {eof}")
            out = bytearray()     # bytes += would be O(n^2) at 64 MB
            off = 0
            while off < eof:
                chunk = min(65536, eof - off)
                body = struct.pack("<HBBIQ16sIIIHH", 49, 0x50, 0, chunk,
                                   off, fid, 0, 0, 0, 0, 0) + b"\0"
                status, resp = self._send_recv(CMD_READ, body)
                if status == STATUS_END_OF_FILE:
                    break
                if status != STATUS_OK:
                    raise SMBError(f"read failed: 0x{status:08x}")
                doff = resp[2]
                dlen = struct.unpack_from("<I", resp, 4)[0]
                out += resp[doff - 64:doff - 64 + dlen]
                off += dlen
                if dlen == 0:
                    break
            return bytes(out)
        finally:
            self._close(fid)

    def listdir(self, path: str = "") -> list[tuple[str, bool, int]]:
        """[(name, is_dir, size)] via FileDirectoryInformation."""
        fid, _eof = self._create(path, directory=True)
        try:
            pattern = "*".encode("utf-16le")
            out: list[tuple[str, bool, int]] = []
            first = True
            while True:
                body = struct.pack("<HBBI16sHHI", 33, 1, 0, 0, fid,
                                   96, len(pattern), 65536) + pattern
                status, resp = self._send_recv(CMD_QUERY_DIRECTORY, body)
                if status == STATUS_NO_MORE_FILES:
                    break
                if status != STATUS_OK:
                    if first:
                        raise SMBError(
                            f"listing failed: 0x{status:08x}")
                    break
                first = False
                boff = struct.unpack_from("<H", resp, 2)[0]
                blen = struct.unpack_from("<I", resp, 4)[0]
                buf = resp[boff - 64:boff - 64 + blen]
                pos = 0
                while True:
                    nxt = struct.unpack_from("<I", buf, pos)[0]
                    eof = struct.unpack_from("<Q", buf, pos + 40)[0]
                    attrs = struct.unpack_from("<I", buf, pos + 56)[0]
                    nlen = struct.unpack_from("<I", buf, pos + 60)[0]
                    name = buf[pos + 64:pos + 64 + nlen].decode(
                        "utf-16le", "replace")
                    if name not in (".", ".."):
                        out.append((name, bool(attrs & 0x10), eof))
                    if nxt == 0:
                        break
                    pos += nxt
            return out
        finally:
            self._close(fid)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def smb_fetch(url: str, timeout: float = 10.0,
              max_size: int = 64 << 20,
              addr_guard=None) -> tuple[int, dict, bytes]:
    """Loader driver: fetch an smb:// url (file bytes, or an HTML
    directory listing the parser can follow — the reference's SMBLoader
    emits exactly such listing pages for directories). `addr_guard`
    (ipaddress -> refuse bool) pins the connection to a vetted
    resolution, same contract as the HTTP transport."""
    import ipaddress

    parts = urlsplit(url)
    host = parts.hostname or ""
    user = unquote(parts.username or "")
    password = unquote(parts.password or "")
    segs = [s for s in (parts.path or "").split("/") if s]
    if not host or not segs:
        return 400, {"x-error": "smb url needs //host/share"}, b""
    share, path = segs[0], "/".join(unquote(s) for s in segs[1:])
    if addr_guard is not None:
        # resolve once, vet, and CONNECT TO the vetted address (the
        # UNC path keeps the hostname; only the socket target pins)
        try:
            infos = socket.getaddrinfo(host, parts.port or 445,
                                       type=socket.SOCK_STREAM)
        except OSError as e:
            return 599, {"x-error": f"resolve failed: {e}"}, b""
        host = ""
        for info in infos:
            if not addr_guard(ipaddress.ip_address(info[4][0])):
                host = info[4][0]
                break
        if not host:
            return 403, {"x-error": "refused address"}, b""
    try:
        with SMB2Client(host, share, port=parts.port or 445,
                        username=user, password=password,
                        timeout=timeout) as c:
            is_dir = (parts.path or "").endswith("/") or not path
            if not is_dir:
                try:
                    data = c.read_file(path, max_size=max_size)
                    return 200, {"content-type":
                                 "application/octet-stream"}, data
                except SMBError:
                    is_dir = True       # open-as-file failed: try listing
            entries = c.listdir(path)
            base = url.rstrip("/")
            rows = "".join(
                f'<a href="{base}/{name}{"/" if d else ""}">{name}</a><br>'
                for name, d, _sz in sorted(entries))
            page = (f"<html><head><title>Index of {url}</title></head>"
                    f"<body><h1>Index of {url}</h1>{rows}</body></html>")
            return 200, {"content-type": "text/html"}, page.encode()
    except (OSError, SMBError) as e:
        return 599, {"x-error": str(e)}, b""
