"""HTCache — the shared page cache (compressed content + response headers).

Capability equivalent of the reference's HTCache (reference:
source/net/yacy/crawler/data/Cache.java:59-130: gzip-compressed content in
an ArrayStack BLOB plus response headers in a MapHeap). Here: content is
gzip-compressed into sharded files keyed by url-hash, headers are a json
sidecar, and a bounded in-RAM ARC-ish buffer fronts the disk store. A
pure-RAM mode (data_dir=None) backs tests and proxy-only setups.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
from base64 import urlsafe_b64encode

from ..utils.hashes import url2hash

RAM_BUFFER_MAX = 256


def _keys(urlhash: bytes) -> tuple[str, str]:
    k = urlsafe_b64encode(urlhash).decode("ascii").rstrip("=")
    return k[:2], k


class HTCache:
    def __init__(self, data_dir: str | None = None,
                 max_content_bytes: int = 10 * 1024 * 1024):
        self.data_dir = data_dir
        self.max_content_bytes = max_content_bytes
        self._ram: dict[bytes, tuple[bytes, dict, float]] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)

    # -- store ---------------------------------------------------------------

    def store(self, url: str, content: bytes, headers: dict | None = None) -> bool:
        if len(content) > self.max_content_bytes:
            return False
        h = url2hash(url)
        headers = dict(headers or {})
        headers["x-cache-date"] = time.time()
        headers["x-cache-url"] = url
        with self._lock:
            self._ram[h] = (content, headers, time.time())
            while len(self._ram) > RAM_BUFFER_MAX:
                self._ram.pop(next(iter(self._ram)))
        if self.data_dir:
            shard, key = _keys(h)
            d = os.path.join(self.data_dir, shard)
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, key + ".gz"), "wb") as f:
                f.write(gzip.compress(content))
            with open(os.path.join(d, key + ".json"), "w",
                      encoding="utf-8") as f:
                json.dump(headers, f)
        return True

    # -- load ----------------------------------------------------------------

    def _paths(self, urlhash: bytes) -> tuple[str, str] | None:
        if not self.data_dir:
            return None
        shard, key = _keys(urlhash)
        d = os.path.join(self.data_dir, shard)
        return os.path.join(d, key + ".gz"), os.path.join(d, key + ".json")

    def has(self, url: str) -> bool:
        h = url2hash(url)
        with self._lock:
            if h in self._ram:
                return True
        p = self._paths(h)
        return p is not None and os.path.exists(p[0])

    def get(self, url: str) -> tuple[bytes, dict] | None:
        h = url2hash(url)
        with self._lock:
            hit = self._ram.get(h)
            if hit is not None:
                self.hits += 1
                return hit[0], hit[1]
        p = self._paths(h)
        if p and os.path.exists(p[0]):
            try:
                with open(p[0], "rb") as f:
                    content = gzip.decompress(f.read())
                headers = {}
                if os.path.exists(p[1]):
                    with open(p[1], encoding="utf-8") as f:
                        headers = json.load(f)
                with self._lock:
                    self.hits += 1
                return content, headers
            except (OSError, json.JSONDecodeError):
                pass
        with self._lock:
            self.misses += 1
        return None

    def age_s(self, url: str) -> float | None:
        got = self.get(url)
        if got is None:
            return None
        ts = got[1].get("x-cache-date")
        return (time.time() - ts) if ts else None

    def clear(self) -> int:
        """Delete every cached response (bin/clearcache.sh /
        ConfigHTCache_p clear); returns files removed."""
        removed = 0
        if self.data_dir and os.path.isdir(self.data_dir):
            for root, _dirs, names in os.walk(self.data_dir):
                for n in names:
                    try:
                        os.remove(os.path.join(root, n))
                        removed += 1
                    except OSError:
                        pass
        with self._lock:
            self._ram.clear()
        return removed

    def delete(self, url: str) -> None:
        h = url2hash(url)
        with self._lock:
            self._ram.pop(h, None)
        p = self._paths(h)
        if p:
            for path in p:
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
