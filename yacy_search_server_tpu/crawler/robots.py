"""robots.txt — per-host rules cache with deny/delay lookup.

Capability equivalent of the reference's robots machinery (reference:
source/net/yacy/crawler/robots/RobotsTxt.java:61 and RobotsTxtParser.java):
fetch+parse a host's robots.txt once, cache the parsed entry with a TTL,
answer `is_allowed(url, agent)` and `crawl_delay(agent)`. Matching is
longest-rule-wins with Allow beating Disallow on ties (the de-facto
standard the reference approximates with prefix matching); `*` wildcards
and `$` anchors are supported.

The fetcher is injected (a callable url -> bytes|None) so the cache works
over the loader dispatcher, the test transport, or nothing at all (no
robots.txt = allow all).
"""

from __future__ import annotations

import fnmatch
import re
import threading
import time
from dataclasses import dataclass, field
from urllib.parse import urlsplit

DEFAULT_TTL_S = 7 * 24 * 3600


def _rule_to_regex(rule: str) -> re.Pattern:
    # robots rules: '*' any chars, '$' end anchor, else prefix match
    anchored = rule.endswith("$")
    if anchored:
        rule = rule[:-1]
    parts = [re.escape(p) for p in rule.split("*")]
    pat = ".*".join(parts)
    return re.compile("^" + pat + ("$" if anchored else ""))


@dataclass
class RobotsEntry:
    disallow: list[str] = field(default_factory=list)
    allow: list[str] = field(default_factory=list)
    crawl_delay_s: float = 0.0
    sitemaps: list[str] = field(default_factory=list)
    fetched_s: float = field(default_factory=time.time)

    def __post_init__(self):
        self._rules = (
            [(r, _rule_to_regex(r), False) for r in self.disallow if r]
            + [(r, _rule_to_regex(r), True) for r in self.allow if r])

    def is_allowed(self, path: str) -> bool:
        best_len, best_allow = -1, True
        for rule, rx, allow in self._rules:
            if rx.match(path):
                ln = len(rule)
                if ln > best_len or (ln == best_len and allow):
                    best_len, best_allow = ln, allow
        return best_allow


def parse_robots(content: str, agent: str = "yacy-tpu") -> RobotsEntry:
    """Parse robots.txt for `agent`, falling back to the '*' group."""
    groups: dict[str, RobotsEntry] = {}
    current: list[str] = []
    seen_rule_since_agent = True
    for raw in content.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line or ":" not in line:
            continue
        key, _, value = line.partition(":")
        key, value = key.strip().lower(), value.strip()
        if key == "user-agent":
            if seen_rule_since_agent:
                current = []
                seen_rule_since_agent = False
            name = value.lower()
            groups.setdefault(name, RobotsEntry())
            current.append(name)
        elif key in ("disallow", "allow", "crawl-delay", "sitemap"):
            if key == "sitemap":
                for g in groups.values():
                    g.sitemaps.append(value)
                # sitemap lines are global; also record when no group yet
                groups.setdefault("*", RobotsEntry())
                if value not in groups["*"].sitemaps:
                    groups["*"].sitemaps.append(value)
                continue
            seen_rule_since_agent = True
            for name in current:
                g = groups[name]
                if key == "disallow":
                    g.disallow.append(value)
                elif key == "allow":
                    g.allow.append(value)
                else:
                    try:
                        g.crawl_delay_s = float(value)
                    except ValueError:
                        pass
    chosen = None
    agent_l = agent.lower()
    for name, g in groups.items():
        if name != "*" and name in agent_l:
            chosen = g
            break
    if chosen is None:
        chosen = groups.get("*", RobotsEntry())
    return RobotsEntry(disallow=chosen.disallow, allow=chosen.allow,
                       crawl_delay_s=chosen.crawl_delay_s,
                       sitemaps=chosen.sitemaps)


class RobotsTxt:
    """Per-host robots cache. `fetcher(url) -> bytes | None`."""

    def __init__(self, fetcher=None, agent: str = "yacy-tpu",
                 ttl_s: float = DEFAULT_TTL_S):
        self.fetcher = fetcher
        self.agent = agent
        self.ttl_s = ttl_s
        self._cache: dict[str, RobotsEntry] = {}
        self._lock = threading.Lock()

    def _entry(self, url: str) -> RobotsEntry:
        parts = urlsplit(url)
        hostport = parts.netloc
        with self._lock:
            e = self._cache.get(hostport)
            if e is not None and (time.time() - e.fetched_s) < self.ttl_s:
                return e
        content = None
        if self.fetcher is not None:
            robots_url = f"{parts.scheme or 'http'}://{hostport}/robots.txt"
            try:
                content = self.fetcher(robots_url)
            except Exception:
                content = None
        if content is None:
            e = RobotsEntry()     # no robots.txt -> allow all
        else:
            if isinstance(content, bytes):
                content = content.decode("utf-8", "replace")
            e = parse_robots(content, self.agent)
        with self._lock:
            self._cache[hostport] = e
        return e

    def is_allowed(self, url: str) -> bool:
        parts = urlsplit(url)
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        return self._entry(url).is_allowed(path)

    def crawl_delay_s(self, url: str) -> float:
        return self._entry(url).crawl_delay_s

    def sitemaps(self, url: str) -> list[str]:
        return self._entry(url).sitemaps
