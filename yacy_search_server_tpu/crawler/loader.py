"""LoaderDispatcher — protocol-dispatching page loader with cache strategies.

Capability equivalent of the reference's loader stack (reference:
source/net/yacy/repository/LoaderDispatcher.java:70-203 — cache strategies
NOCACHE/IFEXIST/IFFRESH/CACHEONLY, per-URL in-flight dedup — and
crawler/retrieval/HTTPLoader.java / FileLoader.java). Protocols: http(s)
via urllib with redirect + size caps, file:// for local corpora, plus an
injectable `transport` callable so tests and the simulated P2P network
run with zero egress.
"""

from __future__ import annotations

import http.client
import ipaddress
import os
import socket
import ssl
import threading
import time
from urllib.parse import urlsplit
from urllib.request import (HTTPHandler, HTTPRedirectHandler, HTTPSHandler,
                            Request as UrlRequest)
from urllib.request import build_opener

from ..utils import histogram, tracing
from .cache import HTCache
from .latency import Latency
from .request import Request, Response


class CacheStrategy:
    NOCACHE = "nocache"      # never use the cache
    IFFRESH = "iffresh"      # use cache if younger than freshness limit
    IFEXIST = "ifexist"      # use cache whenever present
    CACHEONLY = "cacheonly"  # never hit the network


DEFAULT_AGENT = "yacy-tpu/1.0 (+https://yacy.net/bot.html)"
MAX_REDIRECTS = 5


class _CappedRedirectHandler(HTTPRedirectHandler):
    max_redirections = MAX_REDIRECTS


class _FilteredRedirectHandler(_CappedRedirectHandler):
    """Redirect handler that re-applies a caller's URL filter on every
    hop — a fetch whose initial target passed an SSRF guard must not be
    redirected into a refused address (httpd forward proxy)."""

    def __init__(self, url_filter):
        self._url_filter = url_filter

    def redirect_request(self, req, fp, code, msg, headers, newurl):
        if not self._url_filter(newurl):
            raise OSError(f"redirect target refused: {newurl}")
        return super().redirect_request(req, fp, code, msg, headers, newurl)


_OPENER = build_opener(_CappedRedirectHandler)


class _PinnedHTTPConnection(http.client.HTTPConnection):
    """Connection that resolves ONCE, vets the RESOLVED address with the
    opener's addr_guard, and connects to that same address — closing the
    DNS-rebinding TOCTOU where a hostname passes the URL check and then
    re-resolves to loopback at fetch time (server/netguard.py)."""

    addr_guard = staticmethod(lambda a: False)   # set per instance

    def _vetted_connect(self):
        infos = socket.getaddrinfo(self.host, self.port,
                                   type=socket.SOCK_STREAM)
        last = None
        for info in infos:
            ip = info[4][0]
            if self.addr_guard(ipaddress.ip_address(ip)):
                last = OSError(f"refused address for {self.host}: {ip}")
                continue
            return socket.create_connection((ip, self.port),
                                            timeout=self.timeout)
        raise last or OSError(f"no address for {self.host}")

    def connect(self):
        self.sock = self._vetted_connect()


class _PinnedHTTPSConnection(_PinnedHTTPConnection,
                             http.client.HTTPSConnection):
    def connect(self):
        sock = self._vetted_connect()
        self.sock = self._context.wrap_socket(
            sock, server_hostname=self.host)


_SSL_CONTEXT: ssl.SSLContext | None = None


def _ssl_context() -> ssl.SSLContext:
    """One shared verify context: create_default_context re-parses the
    CA bundle from disk (~ms) — per-connection creation would tax every
    hop on the guarded proxy path. wrap_socket on a shared context is
    thread-safe."""
    global _SSL_CONTEXT
    if _SSL_CONTEXT is None:
        _SSL_CONTEXT = ssl.create_default_context()
    return _SSL_CONTEXT


def _conn_factory(cls, guard):
    def make(host, timeout=None, context=None):
        conn = (cls(host, timeout=timeout,
                    context=context or _ssl_context())
                if cls is _PinnedHTTPSConnection
                else cls(host, timeout=timeout))
        conn.addr_guard = guard
        return conn
    return make


class _PinnedHTTPHandler(HTTPHandler):
    def __init__(self, addr_guard):
        super().__init__()
        self._guard = addr_guard

    def http_open(self, req):
        return self.do_open(
            _conn_factory(_PinnedHTTPConnection, self._guard), req)


class _PinnedHTTPSHandler(HTTPSHandler):
    def __init__(self, addr_guard):
        super().__init__()
        self._guard = addr_guard

    def https_open(self, req):
        return self.do_open(
            _conn_factory(_PinnedHTTPSConnection, self._guard), req)


def _pinned_opener(url_filter, addr_guard):
    """build_opener wiring for the pinned connection classes above."""
    handlers = [_PinnedHTTPHandler(addr_guard),
                _PinnedHTTPSHandler(addr_guard)]
    if url_filter is not None:
        handlers.append(_FilteredRedirectHandler(url_filter))
    else:
        handlers.append(_CappedRedirectHandler())
    return build_opener(*handlers)


class LoaderDispatcher:
    def __init__(self, cache: HTCache | None = None,
                 latency: Latency | None = None,
                 transport=None,
                 agent: str = DEFAULT_AGENT,
                 max_size: int = 10 * 1024 * 1024,
                 timeout_s: float = 10.0,
                 freshness_s: float = 24 * 3600.0):
        self.cache = cache or HTCache()
        self.latency = latency or Latency()
        self.transport = transport   # (url, headers) -> (status, headers, bytes)
        # injectable SMB client: (url) -> (status, headers, bytes)
        self.smb_driver = None
        self.agent = agent
        self.max_size = max_size
        self.timeout_s = timeout_s
        self.freshness_s = freshness_s
        self._inflight: dict[str, threading.Event] = {}
        self._lock = threading.Lock()

    # -- cache policy --------------------------------------------------------

    def _try_cache(self, url: str, strategy: str) -> Response | None:
        if strategy == CacheStrategy.NOCACHE:
            return None
        got = self.cache.get(url)
        if got is None:
            return None
        content, headers = got
        if strategy == CacheStrategy.IFFRESH:
            ts = headers.get("x-cache-date", 0.0)
            if (time.time() - ts) > self.freshness_s:
                return None
        return Response(Request(url), status=200, headers=headers,
                        content=content, from_cache=True)

    # -- transports ----------------------------------------------------------

    def _fetch_http(self, url: str, url_filter=None,
                    addr_guard=None) -> tuple[int, dict, bytes]:
        if self.transport is not None:
            return self.transport(url, {"User-Agent": self.agent})
        req = UrlRequest(url, headers={"User-Agent": self.agent})
        if addr_guard is not None:
            opener = _pinned_opener(url_filter, addr_guard)
        else:
            opener = _OPENER if url_filter is None \
                else build_opener(_FilteredRedirectHandler(url_filter))
        with opener.open(req, timeout=self.timeout_s) as resp:  # nosec - crawler
            content = resp.read(self.max_size + 1)
            if len(content) > self.max_size:
                raise OSError(f"content exceeds max size {self.max_size}")
            headers = {k.lower(): v for k, v in resp.headers.items()}
            # non-HTTP handlers (ftp) return status=None on success —
            # urllib raises on failure, so a None here means 200
            status = resp.status if resp.status is not None else 200
            return status, headers, content

    def _fetch_file(self, url: str) -> tuple[int, dict, bytes]:
        path = urlsplit(url).path
        if not os.path.exists(path):
            return 404, {}, b""
        size = os.path.getsize(path)
        if size > self.max_size:
            raise OSError(f"file exceeds max size {self.max_size}")
        with open(path, "rb") as f:
            content = f.read()
        ext = os.path.splitext(path)[1].lstrip(".").lower()
        mime = {"html": "text/html", "htm": "text/html", "txt": "text/plain",
                "xml": "application/xml", "pdf": "application/pdf",
                "csv": "text/csv", "json": "application/json"}.get(
                    ext, "application/octet-stream")
        return 200, {"content-type": mime}, content

    # -- public API ----------------------------------------------------------

    def load(self, request: Request,
             strategy: str = CacheStrategy.IFEXIST,
             url_filter=None, addr_guard=None) -> Response:
        """`url_filter` (url -> bool), when given, is applied to every
        HTTP redirect hop; hops it refuses abort the fetch (the initial
        URL is the caller's own responsibility to check). `addr_guard`
        (ipaddress -> refuse bool) additionally pins each connection to
        a vetted resolution (netguard; DNS-rebinding defense)."""
        url = request.url
        cached = self._try_cache(url, strategy)
        if cached is not None:
            cached.request = request
            return cached
        if strategy == CacheStrategy.CACHEONLY:
            return Response(request, status=404,
                            headers={"x-error": "not in cache"})

        # per-URL in-flight dedup (LoaderDispatcher.java:181-191): a second
        # loader for the same url waits, then serves from cache. Each loader
        # only ever pops/sets the event it registered itself — a waiter that
        # times out while the first fetch is still running proceeds without
        # one, so it cannot release the first loader's waiters early.
        my_ev = None
        with self._lock:
            ev = self._inflight.get(url)
            if ev is None:
                my_ev = self._inflight[url] = threading.Event()
        if my_ev is None:
            ev.wait(self.timeout_s)
            cached = self._try_cache(url, CacheStrategy.IFEXIST)
            if cached is not None:
                cached.request = request
                return cached
            # the first loader failed (or is still running): try ourselves
            with self._lock:
                if url not in self._inflight:
                    my_ev = self._inflight[url] = threading.Event()

        scheme = urlsplit(url).scheme.lower()
        t0 = time.monotonic()
        try:
            if scheme == "ftp" and addr_guard is not None:
                # urllib's FTPHandler has no connect-time pin: a guarded
                # (non-admin SSRF-sensitive) surface must not fetch ftp
                # at all rather than fetch it unpinned
                return Response(request, status=403, headers={
                    "x-error": "ftp refused on guarded surface"})
            if scheme in ("http", "https", "ftp"):
                # ftp rides urllib's built-in FTPHandler (the reference's
                # FTPLoader is its own client; capability, not mechanism)
                status, headers, content = self._fetch_http(
                    url, url_filter, addr_guard=addr_guard)
            elif scheme == "file":
                status, headers, content = self._fetch_file(url)
            elif scheme == "smb":
                # SMB rides the BUILT-IN SMB2 client (crawler/smbclient
                # .py — the reference bundles jcifs for the same job,
                # SMBLoader.java:39-60); an injected driver overrides it
                if self.smb_driver is not None:
                    status, headers, content = self.smb_driver(url)
                else:
                    from .smbclient import smb_fetch
                    status, headers, content = smb_fetch(
                        url, timeout=self.timeout_s,
                        max_size=self.max_size, addr_guard=addr_guard)
            else:
                return Response(request, status=501,
                                headers={"x-error": f"scheme {scheme}"})
            elapsed = time.monotonic() - t0
            # crawler fetch wall -> windowed histogram (ISSUE 4): the
            # health engine's frontier/fetch rules read this family
            histogram.observe("crawler.fetch", elapsed * 1000.0,
                              tracing.current_trace_id())
            if request.host:
                self.latency.update_after_load(request.host, elapsed)
            resp = Response(request, status=status, headers=headers,
                            content=content, fetch_time_s=elapsed)
            if status == 200 and content:
                self.cache.store(url, content, headers)
            return resp
        except Exception as e:
            return Response(request, status=599,
                            headers={"x-error": str(e)})
        finally:
            if my_ev is not None:
                with self._lock:
                    if self._inflight.get(url) is my_ev:
                        del self._inflight[url]
                my_ev.set()
