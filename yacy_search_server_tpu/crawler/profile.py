"""Crawl profiles — per-crawl configuration and URL admission patterns.

Capability equivalent of the reference's CrawlProfile (reference:
source/net/yacy/crawler/data/CrawlProfile.java): must(not)match regexes
for crawling and indexing, depth, recrawl age, per-domain page limit,
index/store flags, agent, collections. Profiles serialize to plain dicts
(the reference stores them row-encoded in a MapHeap; here the profile
registry persists them as json — crawler/switchboard.py).
"""

from __future__ import annotations

import hashlib
import re
import time
from dataclasses import asdict, dataclass, field

MATCH_ALL = ".*"
MATCH_NEVER = ""


def _compile(pattern: str):
    if pattern in ("", None):
        return None
    return re.compile(pattern)


@dataclass
class CrawlProfile:
    name: str
    start_url: str = ""
    depth: int = 0
    crawler_url_must_match: str = MATCH_ALL
    crawler_url_must_not_match: str = MATCH_NEVER
    indexing_url_must_match: str = MATCH_ALL
    indexing_url_must_not_match: str = MATCH_NEVER
    recrawl_if_older_s: int = -1          # -1: never re-load known urls
    domain_max_pages: int = -1            # -1: unlimited
    crawling_q: bool = True               # allow urls with query strings
    follow_frames: bool = True
    obey_html_robots_noindex: bool = True
    index_text: bool = True
    index_media: bool = True
    store_ht_cache: bool = True
    remote_indexing: bool = False         # push discovered urls to peers
    snapshot_depth: int = -1
    agent_name: str = "yacy-tpu"
    collections: tuple[str, ...] = ("user",)
    handle: str = ""
    created_s: float = field(default_factory=time.time)

    def __post_init__(self):
        if not self.handle:
            seed = f"{self.name}|{self.start_url}|{self.created_s}"
            self.handle = hashlib.sha1(seed.encode()).hexdigest()[:12]
        self._cm = _compile(self.crawler_url_must_match)
        self._cn = _compile(self.crawler_url_must_not_match)
        self._im = _compile(self.indexing_url_must_match)
        self._in = _compile(self.indexing_url_must_not_match)

    # -- admission ----------------------------------------------------------

    def crawl_allowed(self, url: str) -> bool:
        # fullmatch: the reference uses Pattern.matches, which anchors the
        # pattern over the whole URL — a substring search would let
        # `https?://example\.org/.*` admit any URL merely containing it
        if not self.crawling_q and "?" in url:
            return False
        if self._cm is not None and not self._cm.fullmatch(url):
            return False
        if self._cn is not None and self._cn.fullmatch(url):
            return False
        return True

    def index_allowed(self, url: str) -> bool:
        if self._im is not None and not self._im.fullmatch(url):
            return False
        if self._in is not None and self._in.fullmatch(url):
            return False
        return True

    def recrawl_due(self, last_seen_s: float | None) -> bool:
        """Should a url already in the index be loaded again?"""
        if last_seen_s is None:
            return True
        if self.recrawl_if_older_s < 0:
            return False
        return (time.time() - last_seen_s) > self.recrawl_if_older_s

    # -- persistence --------------------------------------------------------

    def to_dict(self) -> dict:
        d = asdict(self)
        d["collections"] = list(self.collections)
        return d

    @staticmethod
    def from_dict(d: dict) -> "CrawlProfile":
        d = dict(d)
        d["collections"] = tuple(d.get("collections", ("user",)))
        return CrawlProfile(**d)


def default_profiles() -> dict[str, CrawlProfile]:
    """The reference's built-in profile set (CrawlSwitchboard defaults)."""
    defaults = {
        "snippetLocalText": CrawlProfile(
            "snippetLocalText", depth=0, index_text=True, index_media=True,
            store_ht_cache=True),
        "snippetGlobalText": CrawlProfile(
            "snippetGlobalText", depth=0, index_text=True, index_media=True,
            recrawl_if_older_s=30 * 24 * 3600),
        "remote": CrawlProfile(
            "remote", depth=0, index_text=True, index_media=True,
            remote_indexing=False),
        "surrogate": CrawlProfile(
            "surrogate", depth=0, index_text=True, index_media=True,
            store_ht_cache=False),
    }
    return defaults
