"""Crawler — acquisition layer: frontier, politeness, loaders, cache.

Capability equivalent of the reference's crawler layer (reference:
source/net/yacy/crawler/ + repository/LoaderDispatcher.java, SURVEY.md §1
L3): host-balanced frontier queues, per-host politeness from measured
latency + robots.txt, admission control, protocol loaders with a shared
page cache, and crawl profiles.
"""

from .profile import CrawlProfile
from .request import Request, Response
