"""Crawl frontier — per-host queues balanced by politeness.

Capability equivalent of the reference's frontier (reference:
source/net/yacy/crawler/HostBalancer.java:64, HostQueue.java:64 and
data/NoticedURL.java): one depth-ordered queue per host, a balancer that
round-robins over hosts honoring each host's politeness cool-down, and
the NoticedURL facade with LOCAL / GLOBAL / REMOTE / NOLOAD stacks.

Persistence: each host queue journals pushes/pops to a jsonl file under
`data_dir/<hostkey>/` and compacts on close, replacing the reference's
per-depth kelondro Table stacks with the same recover-on-restart
guarantee.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from urllib.parse import urlsplit

from urllib.parse import quote, unquote

from ..index import integrity
from ..index.colstore import journal_append
from .latency import Latency
from .request import Request


def host_key(url: str) -> str:
    """Filename-safe, bijective encoding of the URL's netloc."""
    netloc = urlsplit(url).netloc.lower()
    return quote(netloc, safe="") or "_nohost"


def host_of_key(hk: str) -> str:
    return unquote(hk)


class HostQueue:
    """Depth-ordered FIFO per host: smallest depth first (breadth-first
    crawling, HostQueue.java depth-stack semantics)."""

    def __init__(self, hostkey: str, data_dir: str | None = None):
        self.hostkey = hostkey
        self._depths: dict[int, deque[Request]] = {}
        self._known: set[bytes] = set()
        self._size = 0
        self._lock = threading.Lock()
        self._journal = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._journal_path = os.path.join(data_dir, f"{hostkey}.jsonl")
            self._replay()
            self._journal = open(self._journal_path, "a", encoding="utf-8")

    def _replay(self) -> None:
        if not os.path.exists(self._journal_path):
            return
        alive: dict[str, Request] = {}
        # shared scaffold (integrity.journal_records): torn-tail repair
        # before the append-mode reopen, crc + decode classification.
        # A dropped op re-crawls a URL at worst — never fatal.
        for rec in integrity.journal_records(self._journal_path,
                                             "frontier"):
            if rec.get("op") == "push":
                r = Request.from_dict(rec["req"])
                alive[r.url] = r
            elif rec.get("op") == "pop":
                alive.pop(rec.get("url", ""), None)
        for r in alive.values():
            self._push_mem_locked(r)

    def _push_mem_locked(self, req: Request) -> bool:
        h = req.urlhash()
        if h in self._known:
            return False
        self._known.add(h)
        self._depths.setdefault(req.depth, deque()).append(req)
        self._size += 1
        return True

    def push(self, req: Request) -> bool:
        with self._lock:
            if not self._push_mem_locked(req):
                return False
            if self._journal:
                # shared append+fsync helper (ISSUE 10 satellite): the
                # old bare flush() left acked pushes in the page cache
                journal_append(self._journal, json.dumps(
                    {"op": "push", "req": req.to_dict()}))
            return True

    # pop records skip the fsync barrier (sync=False): losing one on
    # power loss REPLAYS the pop's URL — a re-crawl, the safe
    # direction — while a per-pop disk barrier would cap the whole
    # crawler at the disk's fsync rate

    def pop(self) -> Request | None:
        with self._lock:
            for depth in sorted(self._depths):
                q = self._depths[depth]
                if q:
                    req = q.popleft()
                    self._size -= 1
                    self._known.discard(req.urlhash())
                    if not q:
                        del self._depths[depth]
                    if self._journal:
                        journal_append(self._journal, json.dumps(
                            {"op": "pop", "url": req.url}),
                            sync=False)
                    return req
            return None

    def __len__(self) -> int:
        return self._size

    def close(self) -> None:
        with self._lock:
            if self._journal:
                self._journal.close()
                # compact: rewrite only alive entries
                reqs = [r for d in sorted(self._depths)
                        for r in self._depths[d]]
                with open(self._journal_path, "w", encoding="utf-8") as f:
                    for r in reqs:
                        f.write(integrity.crc_line(json.dumps(
                            {"op": "push", "req": r.to_dict()})) + "\n")
                self._journal = None


class HostBalancer:
    """Round-robin over host queues weighted by politeness cool-down
    (HostBalancer.java:341-532 semantics: prefer hosts whose wait is 0,
    skip sleeping hosts, never starve)."""

    def __init__(self, latency: Latency | None = None,
                 data_dir: str | None = None):
        self.latency = latency or Latency()
        self.data_dir = data_dir
        self._queues: dict[str, HostQueue] = {}
        self._rr: deque[str] = deque()
        self._lock = threading.Lock()
        # recover journaled host queues from a previous run
        if data_dir and os.path.isdir(data_dir):
            for fn in sorted(os.listdir(data_dir)):
                if fn.endswith(".jsonl"):
                    hk = fn[:-len(".jsonl")]
                    q = HostQueue(hk, data_dir)
                    if len(q):
                        self._queues[hk] = q
                        self._rr.append(hk)
                    else:
                        q.close()

    def clear(self) -> int:
        """Drop every pending request (the queue monitor's clear
        action); journals compact empty via close. Returns dropped."""
        with self._lock:
            dropped = sum(len(q) for q in self._queues.values())
            for q in self._queues.values():
                # empty the queue FIRST so close() compacts the journal
                # to nothing (a bare close would resurrect the entries
                # at next startup)
                while q.pop() is not None:
                    pass
                q.close()
            self._queues.clear()
            self._rr.clear()
            return dropped

    def push(self, req: Request) -> bool:
        hk = host_key(req.url)
        with self._lock:
            q = self._queues.get(hk)
            if q is None:
                q = self._queues[hk] = HostQueue(hk, self.data_dir)
                self._rr.append(hk)
        return q.push(req)

    def pop(self) -> tuple[Request | None, float]:
        """(request, suggested_sleep_s). request None when all hosts are
        cooling down (sleep>0) or the frontier is empty (sleep==0)."""
        with self._lock:
            n = len(self._rr)
            if n == 0:
                return None, 0.0
            best_wait = float("inf")
            for _ in range(n):
                hk = self._rr[0]
                self._rr.rotate(-1)
                q = self._queues.get(hk)
                if q is None or len(q) == 0:
                    continue
                host = host_of_key(hk)
                wait = self.latency.waiting_remaining_s(host)
                if wait <= 0.0:
                    req = q.pop()
                    if req is not None:
                        return req, 0.0
                else:
                    best_wait = min(best_wait, wait)
            if best_wait != float("inf"):
                return None, best_wait
            return None, 0.0

    def __len__(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def has_url(self, url: str) -> bool:
        hk = host_key(url)
        with self._lock:
            q = self._queues.get(hk)
        if q is None:
            return False
        h = Request(url).urlhash()
        with q._lock:
            return h in q._known

    def close(self) -> None:
        with self._lock:
            for q in self._queues.values():
                q.close()


class StackType:
    LOCAL = "local"
    GLOBAL = "global"
    REMOTE = "remote"
    NOLOAD = "noload"


class NoticedURL:
    """The four-stack frontier facade (NoticedURL.java): LOCAL for our own
    crawls, GLOBAL for urls destined for other peers' crawl delegation,
    REMOTE for urls other peers asked us to crawl, NOLOAD for urls whose
    metadata is indexed without fetching."""

    def __init__(self, latency: Latency | None = None,
                 data_dir: str | None = None):
        self.latency = latency or Latency()
        sub = (lambda s: os.path.join(data_dir, s)) if data_dir else (
            lambda s: None)
        self.stacks: dict[str, HostBalancer] = {
            s: HostBalancer(self.latency, sub(s))
            for s in (StackType.LOCAL, StackType.GLOBAL, StackType.REMOTE,
                      StackType.NOLOAD)}

    def push(self, stack: str, req: Request) -> bool:
        return self.stacks[stack].push(req)

    def pop(self, stack: str) -> tuple[Request | None, float]:
        return self.stacks[stack].pop()

    def size(self, stack: str) -> int:
        return len(self.stacks[stack])

    def clear(self, stack: str) -> int:
        """Drop every pending request of one stack (the queue monitor's
        clear action); returns requests dropped."""
        return self.stacks[stack].clear()

    def exists_in_any(self, url: str) -> bool:
        return any(b.has_url(url) for b in self.stacks.values())

    def close(self) -> None:
        for b in self.stacks.values():
            b.close()
