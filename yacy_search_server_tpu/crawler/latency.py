"""Per-host politeness — measured latency drives the crawl delay.

Capability equivalent of the reference's latency model (reference:
source/net/yacy/crawler/data/Latency.java:43,149): per-host record of
measured fetch times, last-access timestamp, and robots crawl-delay; the
frontier asks `waiting_remaining(host)` before popping a url for that
host and skips hosts still in their cool-down.

Delay model (Latency.waitingRemainingGuessed semantics): the politeness
delay is max(minimum_delta, robots crawl-delay, flux-factor * average
fetch time), counted from the last access.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

MIN_DELTA_S = 0.5          # minimumLocalDelta analog (intranet: lower)
MAX_DELAY_S = 30.0         # never wait longer than this
FLUX_FACTOR = 1.5          # multiple of avg fetch time to wait


@dataclass
class HostStats:
    count: int = 0
    time_sum_s: float = 0.0
    last_access_s: float = 0.0
    robots_delay_s: float = 0.0
    dns_s: float = 0.0

    @property
    def average_s(self) -> float:
        return self.time_sum_s / self.count if self.count else 0.0


class Latency:
    def __init__(self, min_delta_s: float = MIN_DELTA_S):
        self.min_delta_s = min_delta_s
        self._hosts: dict[str, HostStats] = {}
        self._lock = threading.Lock()

    def _get(self, host: str) -> HostStats:
        with self._lock:
            st = self._hosts.get(host)
            if st is None:
                st = self._hosts[host] = HostStats()
            return st

    def update_after_load(self, host: str, elapsed_s: float) -> None:
        st = self._get(host)
        with self._lock:
            st.count += 1
            st.time_sum_s += elapsed_s
            st.last_access_s = time.time()

    def update_robots_delay(self, host: str, delay_s: float) -> None:
        self._get(host).robots_delay_s = min(delay_s, MAX_DELAY_S)

    def wanted_delay_s(self, host: str) -> float:
        st = self._get(host)
        delay = max(self.min_delta_s, st.robots_delay_s,
                    FLUX_FACTOR * st.average_s)
        return min(delay, MAX_DELAY_S)

    def waiting_remaining_s(self, host: str) -> float:
        """Seconds until `host` may be accessed again (0 = now)."""
        st = self._get(host)
        if st.last_access_s == 0.0:
            return 0.0
        due = st.last_access_s + self.wanted_delay_s(host)
        return max(0.0, due - time.time())

    def snapshot(self) -> dict[str, HostStats]:
        with self._lock:
            return dict(self._hosts)
