"""CrawlQueues — the busy-thread crawl jobs and the error cache.

Capability equivalent of the reference's crawl driver (reference:
source/net/yacy/crawler/data/CrawlQueues.java:73-460: `coreCrawlJob`
pulls from the frontier into loader worker threads, robots re-checks,
error-cache bookkeeping; remote-crawl jobs arrive in M5's peer layer).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..utils.eventtracker import EClass, StageTimer
from .frontier import NoticedURL, StackType
from .loader import CacheStrategy, LoaderDispatcher
from .profile import CrawlProfile
from .request import Request, Response


class ErrorCache:
    """Failed-url store for the crawl monitor (reference:
    source/net/yacy/search/index/ErrorCache.java — Solr-backed there, so
    fail reasons survive restarts; here a bounded map with a jsonl
    journal carrying the same (url, reason, ts) surface). The journal
    compacts on load AND once it exceeds 10x the retained entries, so
    its size stays proportional to max_entries even under a flood of
    failures."""

    def __init__(self, max_entries: int = 1000,
                 data_dir: str | None = None):
        import json
        import os
        self.max_entries = max_entries
        self._entries: dict[bytes, tuple[str, str, float]] = {}
        self._lock = threading.Lock()
        self._journal = None
        self._journal_lines = 0
        if data_dir:
            from ..index import integrity
            os.makedirs(data_dir, exist_ok=True)
            path = os.path.join(data_dir, "errors.jsonl")
            if os.path.exists(path):
                # shared scaffold (integrity.journal_records): torn-
                # tail repair + crc/decode classification; the
                # compaction below rewrites the file anyway, but the
                # damage must be COUNTED
                for rec in integrity.journal_records(path, "errors"):
                    try:
                        self._entries[rec["h"].encode()] = (
                            rec["u"], rec["r"], float(rec["t"]))
                    except (ValueError, KeyError, TypeError):
                        continue
                while len(self._entries) > max_entries:
                    self._entries.pop(next(iter(self._entries)))
            self._path = path
            self._compact_locked()

    def _compact_locked(self) -> None:
        """Rewrite the journal to the retained entries (caller holds the
        lock or is the constructor)."""
        import json
        import os
        from ..index import integrity
        if self._journal:
            self._journal.close()
        tmp = self._path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for h, (u, r, t) in self._entries.items():
                f.write(integrity.crc_line(
                    json.dumps({"h": h.decode("ascii", "replace"),
                                "u": u, "r": r, "t": t})) + "\n")
        os.replace(tmp, self._path)
        self._journal = open(self._path, "a", encoding="utf-8")
        self._journal_lines = len(self._entries)

    def push(self, urlhash: bytes, url: str, reason: str) -> None:
        import json
        now = time.time()
        with self._lock:
            self._entries[urlhash] = (url, reason, now)
            while len(self._entries) > self.max_entries:
                self._entries.pop(next(iter(self._entries)))
            if self._journal:
                from ..index.colstore import journal_append
                # shared append helper; sync=False — the error cache is
                # advisory (bounded, compacted at load): a lost row just
                # re-fetches a failing URL, while a per-error fsync
                # would turn a failure flood into a disk-barrier flood
                journal_append(self._journal, json.dumps(
                    {"h": urlhash.decode("ascii", "replace"),
                     "u": url, "r": reason, "t": now}), sync=False)
                self._journal_lines += 1
                # in-run compaction: a flood of failures must not grow
                # the journal past a small multiple of the retained set
                if self._journal_lines > 10 * self.max_entries:
                    self._compact_locked()

    def has(self, urlhash: bytes) -> bool:
        with self._lock:
            return urlhash in self._entries

    def reason(self, urlhash: bytes) -> str | None:
        with self._lock:
            e = self._entries.get(urlhash)
            return e[1] if e else None

    def recent(self, n: int = 100) -> list[tuple[str, str, float]]:
        with self._lock:
            return list(self._entries.values())[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def close(self) -> None:
        with self._lock:
            if self._journal:
                self._journal.close()
                self._journal = None


class CrawlQueues:
    def __init__(self, noticed: NoticedURL, loader: LoaderDispatcher,
                 profiles: dict[str, CrawlProfile], robots=None,
                 indexer=None, workers: int = 4,
                 data_dir: str | None = None):
        self.noticed = noticed
        self.loader = loader
        self.profiles = profiles
        self.robots = robots
        self.indexer = indexer          # callable(Response, CrawlProfile)
        self.error_cache = ErrorCache(data_dir=data_dir)
        self.pool = ThreadPoolExecutor(max_workers=workers,
                                       thread_name_prefix="crawl-worker")
        self.loaded = 0
        self._open = True
        self._lock = threading.Lock()

    # -- the busy-thread job (CrawlQueues.coreCrawlJob) ---------------------

    def core_crawl_job(self, stack: str = StackType.LOCAL) -> bool:
        """Pop one url and schedule its load; True if work was done."""
        req, _sleep = self.noticed.pop(stack)
        if req is None:
            return False
        self.pool.submit(self._load_and_index, req)
        return True

    def _load_and_index(self, req: Request) -> None:
        profile = self.profiles.get(req.profile_handle)
        if profile is None:
            self.error_cache.push(req.urlhash(), req.url, "unknown profile")
            return
        try:
            with StageTimer(EClass.CRAWL, "load", 1):
                if self.robots is not None and \
                        not self.robots.is_allowed(req.url):
                    self.error_cache.push(req.urlhash(), req.url,
                                          "robots disallow")
                    return
                strategy = (CacheStrategy.IFFRESH
                            if profile.recrawl_if_older_s >= 0
                            else CacheStrategy.IFEXIST)
                resp = self.loader.load(req, strategy)
            if resp.status != 200:
                self.error_cache.push(
                    req.urlhash(), req.url,
                    resp.headers.get("x-error", f"status {resp.status}"))
                return
            with self._lock:
                self.loaded += 1
            if self.indexer is not None:
                self.indexer(resp, profile)
        except Exception as e:       # worker threads must never die silently
            self.error_cache.push(req.urlhash(), req.url,
                                  f"{type(e).__name__}: {e}")

    def drain(self, stack: str = StackType.LOCAL,
              max_urls: int = 10_000, timeout_s: float = 60.0) -> int:
        """Synchronously crawl until the stack is empty (test/CLI path)."""
        t_end = time.time() + timeout_s
        n = 0
        while time.time() < t_end and n < max_urls:
            req, sleep_s = self.noticed.pop(stack)
            if req is None:
                if sleep_s <= 0:
                    break
                time.sleep(min(sleep_s, 0.2))
                continue
            self._load_and_index(req)
            n += 1
        return n

    def close(self) -> None:
        with self._lock:
            if not self._open:
                return
            self._open = False
        self.pool.shutdown(wait=True)
        self.error_cache.close()
