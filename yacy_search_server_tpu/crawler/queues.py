"""CrawlQueues — the busy-thread crawl jobs and the error cache.

Capability equivalent of the reference's crawl driver (reference:
source/net/yacy/crawler/data/CrawlQueues.java:73-460: `coreCrawlJob`
pulls from the frontier into loader worker threads, robots re-checks,
error-cache bookkeeping; remote-crawl jobs arrive in M5's peer layer).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..utils.eventtracker import EClass, StageTimer
from .frontier import NoticedURL, StackType
from .loader import CacheStrategy, LoaderDispatcher
from .profile import CrawlProfile
from .request import Request, Response


class ErrorCache:
    """Failed-url store for the crawl monitor (reference:
    source/net/yacy/search/index/ErrorCache.java — Solr-backed there,
    bounded in-RAM map with the same (url, reason, ts) surface here)."""

    def __init__(self, max_entries: int = 1000):
        self.max_entries = max_entries
        self._entries: dict[bytes, tuple[str, str, float]] = {}
        self._lock = threading.Lock()

    def push(self, urlhash: bytes, url: str, reason: str) -> None:
        with self._lock:
            self._entries[urlhash] = (url, reason, time.time())
            while len(self._entries) > self.max_entries:
                self._entries.pop(next(iter(self._entries)))

    def has(self, urlhash: bytes) -> bool:
        with self._lock:
            return urlhash in self._entries

    def recent(self, n: int = 100) -> list[tuple[str, str, float]]:
        with self._lock:
            return list(self._entries.values())[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class CrawlQueues:
    def __init__(self, noticed: NoticedURL, loader: LoaderDispatcher,
                 profiles: dict[str, CrawlProfile], robots=None,
                 indexer=None, workers: int = 4):
        self.noticed = noticed
        self.loader = loader
        self.profiles = profiles
        self.robots = robots
        self.indexer = indexer          # callable(Response, CrawlProfile)
        self.error_cache = ErrorCache()
        self.pool = ThreadPoolExecutor(max_workers=workers,
                                       thread_name_prefix="crawl-worker")
        self.loaded = 0
        self._open = True
        self._lock = threading.Lock()

    # -- the busy-thread job (CrawlQueues.coreCrawlJob) ---------------------

    def core_crawl_job(self, stack: str = StackType.LOCAL) -> bool:
        """Pop one url and schedule its load; True if work was done."""
        req, _sleep = self.noticed.pop(stack)
        if req is None:
            return False
        self.pool.submit(self._load_and_index, req)
        return True

    def _load_and_index(self, req: Request) -> None:
        profile = self.profiles.get(req.profile_handle)
        if profile is None:
            self.error_cache.push(req.urlhash(), req.url, "unknown profile")
            return
        try:
            with StageTimer(EClass.CRAWL, "load", 1):
                if self.robots is not None and \
                        not self.robots.is_allowed(req.url):
                    self.error_cache.push(req.urlhash(), req.url,
                                          "robots disallow")
                    return
                strategy = (CacheStrategy.IFFRESH
                            if profile.recrawl_if_older_s >= 0
                            else CacheStrategy.IFEXIST)
                resp = self.loader.load(req, strategy)
            if resp.status != 200:
                self.error_cache.push(
                    req.urlhash(), req.url,
                    resp.headers.get("x-error", f"status {resp.status}"))
                return
            with self._lock:
                self.loaded += 1
            if self.indexer is not None:
                self.indexer(resp, profile)
        except Exception as e:       # worker threads must never die silently
            self.error_cache.push(req.urlhash(), req.url,
                                  f"{type(e).__name__}: {e}")

    def drain(self, stack: str = StackType.LOCAL,
              max_urls: int = 10_000, timeout_s: float = 60.0) -> int:
        """Synchronously crawl until the stack is empty (test/CLI path)."""
        t_end = time.time() + timeout_s
        n = 0
        while time.time() < t_end and n < max_urls:
            req, sleep_s = self.noticed.pop(stack)
            if req is None:
                if sleep_s <= 0:
                    break
                time.sleep(min(sleep_s, 0.2))
                continue
            self._load_and_index(req)
            n += 1
        return n

    def close(self) -> None:
        with self._lock:
            if not self._open:
                return
            self._open = False
        self.pool.shutdown(wait=True)
