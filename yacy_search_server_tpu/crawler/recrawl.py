"""Recrawl — periodic re-fetch of stale indexed documents.

Capability equivalent of the reference's recrawl machinery (reference:
source/net/yacy/crawler/RecrawlBusyThread.java — a busy thread that
queries the fulltext for documents whose load date passed a staleness
horizon and stacks them back onto the frontier — and the autocrawl
startup path Switchboard.initAutocrawl). Selection here is a columnar
scan over load_date_days (one vectorized compare instead of a Solr
query), feeding the normal admission pipeline so robots/blacklist checks
re-apply.
"""

from __future__ import annotations

import time

import numpy as np

from .request import Request

DEFAULT_STALE_AGE_DAYS = 30
DEFAULT_CHUNK = 100


class RecrawlJob:
    def __init__(self, segment, stacker, profile_handle: str,
                 stale_age_days: int = DEFAULT_STALE_AGE_DAYS,
                 chunk: int = DEFAULT_CHUNK):
        self.segment = segment
        self.stacker = stacker
        self.profile_handle = profile_handle
        self.stale_age_days = stale_age_days
        self.chunk = chunk
        self.stacked_total = 0
        # rolling cursor so successive rounds cover the whole index
        self._cursor = 0
        # a doc stays "stale" in metadata until its re-fetch lands; the
        # cooldown stops the job from re-stacking it every round meanwhile
        self.cooldown_s = 3600.0
        self._recently: dict[int, float] = {}

    def _stale_docids(self, today_days: int) -> list[int]:
        meta = self.segment.metadata
        n = meta.capacity()
        if n == 0:
            return []
        load_days = meta.int_column("load_date_days_i")[:n]
        alive = meta.alive_mask()[:n]
        stale = alive & (load_days > 0) \
            & (load_days <= today_days - self.stale_age_days)
        ids = np.nonzero(stale)[0]
        if len(ids) == 0:
            return []
        # resume after the cursor; wrap around
        pos = np.searchsorted(ids, self._cursor)
        ordered = np.concatenate([ids[pos:], ids[:pos]])
        return ordered[: self.chunk].tolist()

    def job(self) -> bool:
        """One recrawl round (BusyThread contract: True = did work)."""
        today = int(time.time() // 86400)
        docids = self._stale_docids(today)
        if not docids:
            return False
        now = time.time()
        self._recently = {d: t for d, t in self._recently.items()
                          if now - t < self.cooldown_s}
        stacked = 0
        for docid in docids:
            if docid in self._recently:
                continue
            url = self.segment.metadata.text_value(docid, "sku")
            if not url:
                continue
            self._recently[docid] = now
            reason = self.stacker.stack(Request(
                url=url, profile_handle=self.profile_handle, depth=0))
            if reason is None:
                stacked += 1
        self._cursor = docids[-1] + 1
        self.stacked_total += stacked
        return stacked > 0
