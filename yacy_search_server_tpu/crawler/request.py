"""Crawl work items — Request (frontier entry) and Response (fetch result).

Capability equivalent of the reference's crawl entry pair (reference:
source/net/yacy/crawler/retrieval/Request.java and Response.java): the
request is the serializable frontier row (url, referrer, anchor name,
depth, profile handle, scheduling info); the response couples it with
fetch outcome and decides document type and indexability.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from urllib.parse import urlsplit

from ..utils.hashes import url2hash


@dataclass
class Request:
    url: str
    profile_handle: str = ""
    referrer_hash: bytes = b""
    name: str = ""                 # anchor text that discovered the url
    depth: int = 0
    appdate_s: float = field(default_factory=time.time)

    def urlhash(self) -> bytes:
        return url2hash(self.url)

    @property
    def host(self) -> str:
        return urlsplit(self.url).netloc.lower()

    def to_dict(self) -> dict:
        return {"url": self.url, "profile_handle": self.profile_handle,
                "referrer_hash": self.referrer_hash.decode("ascii", "replace"),
                "name": self.name, "depth": self.depth,
                "appdate_s": self.appdate_s}

    @staticmethod
    def from_dict(d: dict) -> "Request":
        return Request(url=d["url"], profile_handle=d.get("profile_handle", ""),
                       referrer_hash=d.get("referrer_hash", "").encode("ascii"),
                       name=d.get("name", ""), depth=int(d.get("depth", 0)),
                       appdate_s=float(d.get("appdate_s", 0.0)))


# WARC surrogates bypass the parser registry (importer-handled)
_EXTRA_INDEXABLE_PREFIXES = ("application/warc",)


@dataclass
class Response:
    request: Request
    status: int = 200
    headers: dict = field(default_factory=dict)
    content: bytes = b""
    from_cache: bool = False
    fetch_time_s: float = 0.0

    @property
    def url(self) -> str:
        return self.request.url

    def mime_type(self) -> str:
        ct = self.headers.get("content-type", "") or self.headers.get(
            "Content-Type", "")
        return ct.split(";", 1)[0].strip().lower()

    def charset(self) -> str | None:
        ct = self.headers.get("content-type", "") or self.headers.get(
            "Content-Type", "")
        for part in ct.split(";")[1:]:
            k, _, v = part.strip().partition("=")
            if k.lower() == "charset":
                return v.strip("'\" ").lower() or None
        return None

    def indexable(self) -> str | None:
        """None if indexable, else the denial reason (Response.shallIndex
        semantics)."""
        if self.status != 200:
            return f"bad status {self.status}"
        if not self.content:
            return "empty content"
        mime = self.mime_type()
        if mime:
            # the parser registry is the single authority on what can be
            # turned into an indexable document (TextParser.supports)
            from ..document.parser.registry import supports
            if not supports(self.url, mime) and not any(
                    mime.startswith(p)
                    for p in _EXTRA_INDEXABLE_PREFIXES):
                return f"unindexable mime {mime}"
        return None
