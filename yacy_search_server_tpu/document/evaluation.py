"""Page-technology evaluation — the ext_* schema field family.

Capability equivalent of the reference's parser evaluation model
(reference: source/net/yacy/cora/document/analysis/Classification.java
neighborhood; the schema consumers are CollectionSchema.ext_ads_txt/_val,
ext_cms_txt/_val, ext_community_txt/_val, ext_maps_txt/_val,
ext_title_txt/_val, ext_tracker_txt/_val — filled per document from
pattern matches over the page source). The model here is a compact
built-in pattern table over the categories the schema names; operators
can extend ``PATTERNS`` at runtime (the reference's model is likewise a
data table, not code).

Each category yields (names, counts): the detected technology names and
how often each one's signature appeared — stored positionally as
ext_<cat>_txt / ext_<cat>_val.
"""

from __future__ import annotations

import re

# category -> [(technology-name, compiled-signature)]
PATTERNS: dict[str, list[tuple[str, re.Pattern]]] = {
    "ads": [
        ("adsense", re.compile(
            r"pagead2\.googlesyndication|adsbygoogle", re.I)),
        ("doubleclick", re.compile(r"doubleclick\.net", re.I)),
        ("amazonads", re.compile(r"amazon-adsystem\.com", re.I)),
        ("taboola", re.compile(r"taboola\.com", re.I)),
    ],
    "cms": [
        ("wordpress", re.compile(r"wp-content|wp-includes|wordpress", re.I)),
        ("joomla", re.compile(r"/media/jui/|joomla", re.I)),
        ("drupal", re.compile(r"drupal\.js|sites/default/files|drupal",
                              re.I)),
        ("typo3", re.compile(r"typo3conf|typo3temp|typo3", re.I)),
        ("mediawiki", re.compile(r"mediawiki|/wiki/index\.php", re.I)),
        ("shopify", re.compile(r"cdn\.shopify\.com", re.I)),
    ],
    "community": [
        ("disqus", re.compile(r"disqus\.com/embed|disqus", re.I)),
        ("facebook", re.compile(
            r"connect\.facebook\.net|facebook\.com/plugins", re.I)),
        ("vbulletin", re.compile(r"vbulletin", re.I)),
        ("phpbb", re.compile(r"phpbb", re.I)),
        ("discourse", re.compile(r"discourse", re.I)),
    ],
    "maps": [
        ("googlemaps", re.compile(
            r"maps\.google\.|maps\.googleapis\.com", re.I)),
        ("openstreetmap", re.compile(
            r"openstreetmap\.org|osm\.org", re.I)),
        ("leaflet", re.compile(r"leaflet(\.js|\.css)", re.I)),
        ("openlayers", re.compile(r"openlayers|ol\.js", re.I)),
    ],
    "title": [
        ("phpbb", re.compile(r"powered by phpbb", re.I)),
        ("vbulletin", re.compile(r"powered by vbulletin", re.I)),
        ("mediawiki", re.compile(r"- wikipedia|mediawiki", re.I)),
    ],
    "tracker": [
        ("googleanalytics", re.compile(
            r"google-analytics\.com|googletagmanager|gtag\(", re.I)),
        ("matomo", re.compile(r"matomo\.js|piwik\.js|piwik\.php", re.I)),
        ("hotjar", re.compile(r"hotjar\.com", re.I)),
        ("facebookpixel", re.compile(r"fbevents\.js", re.I)),
    ],
}

CATEGORIES = tuple(PATTERNS)


def evaluate_page(html: str, title: str = "") -> dict[str, tuple[list[str],
                                                                 list[int]]]:
    """Match every category's signatures; returns
    {category: (names, counts)} with only the matched names included.
    `title` feeds the "title" category (generator banners live there);
    every other category scans the raw page source."""
    out: dict[str, tuple[list[str], list[int]]] = {}
    for cat, rules in PATTERNS.items():
        src = title if cat == "title" else html
        names: list[str] = []
        counts: list[int] = []
        if src:
            for name, rx in rules:
                # finditer: counting must not materialize every match
                # over the full page source on the indexing hot path
                n = sum(1 for _ in rx.finditer(src))
                if n:
                    names.append(name)
                    counts.append(n)
        out[cat] = (names, counts)
    return out
