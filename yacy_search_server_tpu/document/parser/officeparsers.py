"""Office-format parsers — OOXML, OpenDocument, RTF, EPUB.

Capability equivalents of the reference's office parser set (reference:
source/net/yacy/document/parser/docParser.java, ooxmlParser.java,
odtParser.java, rtfParser.java, epubParser.java — which lean on POI and
odfutils jars).  OOXML and ODF are zip+XML containers, so they are parsed
natively here: extract the content XML parts, strip tags, read the
metadata part for title/author/keywords.  RTF is de-markup'd with a
control-word stripper; EPUB is a zip of XHTML chapters fed through the
html parser.
"""

from __future__ import annotations

import io
import re
import zipfile
import xml.etree.ElementTree as ET

from ..document import Document
from .errors import ParserError


def _xml_text(data: bytes) -> str:
    """All character data of an XML part, space-joined, tag-boundary safe."""
    try:
        root = ET.fromstring(data)
    except ET.ParseError:
        return ""
    return " ".join(t.strip() for t in root.itertext() if t.strip())


def _zip_of(content: bytes) -> zipfile.ZipFile:
    try:
        return zipfile.ZipFile(io.BytesIO(content))
    except zipfile.BadZipFile as e:
        raise ParserError(f"not a zip container: {e}") from e


_DC_RE = ".//{http://purl.org/dc/elements/1.1/}"


def _ooxml_core_props(zf: zipfile.ZipFile) -> dict:
    out = {}
    try:
        root = ET.fromstring(zf.read("docProps/core.xml"))
    except (KeyError, ET.ParseError):
        return out
    for k, tag in (("title", "title"), ("author", "creator"),
                   ("description", "description"), ("keywords", "subject")):
        el = root.find(_DC_RE + tag)
        if el is not None and el.text:
            out[k] = el.text
    kw = root.find(".//{http://schemas.openxmlformats.org/package/2006/"
                   "metadata/core-properties}keywords")
    if kw is not None and kw.text:
        out["keywords"] = kw.text
    return out


def parse_ooxml(url: str, content: bytes,
                charset: str | None = None) -> list[Document]:
    """docx/xlsx/pptx: concatenate the text of the content XML parts."""
    zf = _zip_of(content)
    names = zf.namelist()
    parts = [n for n in names if
             n == "word/document.xml"
             or re.match(r"word/(header|footer)\d*\.xml$", n)
             or re.match(r"xl/sharedStrings\.xml$", n)
             or re.match(r"ppt/slides/slide\d+\.xml$", n)
             or re.match(r"ppt/notesSlides/notesSlide\d+\.xml$", n)]
    texts = []
    for n in sorted(parts):
        texts.append(_xml_text(zf.read(n)))
    if not any(texts):
        raise ParserError("no text parts in ooxml container")
    props = _ooxml_core_props(zf)
    text = "\n".join(t for t in texts if t)
    mime = ("application/vnd.openxmlformats-officedocument"
            ".wordprocessingml.document")
    return [Document(url=url, mime_type=mime,
                     title=props.get("title", "") or text[:120],
                     author=props.get("author", ""),
                     description=props.get("description", ""),
                     keywords=[k.strip() for k in
                               props.get("keywords", "").split(",")
                               if k.strip()],
                     text=text)]


def parse_odf(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    """odt/ods/odp: content.xml carries the body, meta.xml the metadata."""
    zf = _zip_of(content)
    try:
        text = _xml_text(zf.read("content.xml"))
    except KeyError as e:
        raise ParserError("no content.xml in odf container") from e
    title = author = description = ""
    keywords: list[str] = []
    try:
        meta = ET.fromstring(zf.read("meta.xml"))
        for el in meta.iter():
            tag = el.tag.rsplit("}", 1)[-1]
            if tag == "title" and el.text:
                title = el.text
            elif tag == "creator" and el.text:
                author = el.text
            elif tag == "description" and el.text:
                description = el.text
            elif tag == "keyword" and el.text:
                keywords.append(el.text)
    except (KeyError, ET.ParseError):
        pass
    if not text:
        raise ParserError("empty odf document")
    return [Document(url=url, mime_type="application/vnd.oasis.opendocument.text",
                     title=title or text[:120], author=author,
                     description=description, keywords=keywords, text=text)]


_RTF_CONTROL = re.compile(rb"\\([a-z]{1,32})(-?\d{1,10})?[ ]?|\\'[0-9a-f]{2}"
                          rb"|\\[^a-z]|[{}]|\r|\n")


def parse_rtf(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    if not content.startswith(b"{\\rtf"):
        raise ParserError("not an rtf file")
    # drop binary/skippable groups (fonttbl, pict, stylesheet...)
    body = re.sub(rb"{\\(?:fonttbl|colortbl|stylesheet|info|pict)[^{}]*(?:{[^{}]*})*[^{}]*}",
                  b" ", content)

    def repl(m: re.Match) -> bytes:
        tok = m.group(0)
        if tok.startswith(b"\\'"):
            try:
                return bytes([int(tok[2:], 16)])
            except ValueError:
                return b""
        if m.group(1) in (b"par", b"line", b"tab", b"sect", b"page"):
            return b"\n"
        return b""

    raw = _RTF_CONTROL.sub(repl, body)
    text = re.sub(r"[ \t]+", " ", raw.decode(charset or "latin-1", "replace")).strip()
    if not text:
        raise ParserError("empty rtf document")
    return [Document(url=url, mime_type="application/rtf",
                     title=text.split("\n", 1)[0][:120], text=text)]


def parse_epub(url: str, content: bytes,
               charset: str | None = None) -> list[Document]:
    from .htmlparser import parse_html
    zf = _zip_of(content)
    chapters = [n for n in zf.namelist()
                if n.lower().endswith((".xhtml", ".html", ".htm"))]
    if not chapters:
        raise ParserError("no xhtml chapters in epub")
    main: Document | None = None
    for n in sorted(chapters):
        try:
            docs = parse_html(f"{url}#{n}", zf.read(n), charset)
        except ParserError:
            continue
        for d in docs:
            if main is None:
                main = d
                main.url = url
                main.mime_type = "application/epub+zip"
            else:
                main.merge(d)
    if main is None:
        raise ParserError("no parsable chapters in epub")
    # OPF metadata (title/creator) overrides chapter-derived title
    for n in zf.namelist():
        if n.lower().endswith(".opf"):
            try:
                root = ET.fromstring(zf.read(n))
                t = root.find(_DC_RE + "title")
                c = root.find(_DC_RE + "creator")
                if t is not None and t.text:
                    main.title = t.text
                if c is not None and c.text:
                    main.author = c.text
            except ET.ParseError:
                pass
            break
    return [main]
