"""Office-format parsers — OOXML, OpenDocument, RTF, EPUB.

Capability equivalents of the reference's office parser set (reference:
source/net/yacy/document/parser/docParser.java, ooxmlParser.java,
odtParser.java, rtfParser.java, epubParser.java — which lean on POI and
odfutils jars).  OOXML and ODF are zip+XML containers, so they are parsed
natively here: extract the content XML parts, strip tags, read the
metadata part for title/author/keywords.  RTF is de-markup'd with a
control-word stripper; EPUB is a zip of XHTML chapters fed through the
html parser.
"""

from __future__ import annotations

import io
import re
import zipfile
import xml.etree.ElementTree as ET

from ..document import Document
from .errors import ParserError


def _xml_text(data: bytes) -> str:
    """All character data of an XML part, space-joined, tag-boundary safe."""
    try:
        root = ET.fromstring(data)
    except ET.ParseError:
        return ""
    return " ".join(t.strip() for t in root.itertext() if t.strip())


def _zip_of(content: bytes) -> zipfile.ZipFile:
    try:
        return zipfile.ZipFile(io.BytesIO(content))
    except zipfile.BadZipFile as e:
        raise ParserError(f"not a zip container: {e}") from e


_DC_RE = ".//{http://purl.org/dc/elements/1.1/}"


def _ooxml_core_props(zf: zipfile.ZipFile) -> dict:
    out = {}
    try:
        root = ET.fromstring(zf.read("docProps/core.xml"))
    except (KeyError, ET.ParseError):
        return out
    for k, tag in (("title", "title"), ("author", "creator"),
                   ("description", "description"), ("keywords", "subject")):
        el = root.find(_DC_RE + tag)
        if el is not None and el.text:
            out[k] = el.text
    kw = root.find(".//{http://schemas.openxmlformats.org/package/2006/"
                   "metadata/core-properties}keywords")
    if kw is not None and kw.text:
        out["keywords"] = kw.text
    return out


def parse_ooxml(url: str, content: bytes,
                charset: str | None = None) -> list[Document]:
    """docx/xlsx/pptx: concatenate the text of the content XML parts."""
    zf = _zip_of(content)
    names = zf.namelist()
    parts = [n for n in names if
             n == "word/document.xml"
             or re.match(r"word/(header|footer)\d*\.xml$", n)
             or re.match(r"xl/sharedStrings\.xml$", n)
             or re.match(r"ppt/slides/slide\d+\.xml$", n)
             or re.match(r"ppt/notesSlides/notesSlide\d+\.xml$", n)]
    texts = []
    for n in sorted(parts):
        texts.append(_xml_text(zf.read(n)))
    if not any(texts):
        raise ParserError("no text parts in ooxml container")
    props = _ooxml_core_props(zf)
    text = "\n".join(t for t in texts if t)
    mime = ("application/vnd.openxmlformats-officedocument"
            ".wordprocessingml.document")
    return [Document(url=url, mime_type=mime,
                     title=props.get("title", "") or text[:120],
                     author=props.get("author", ""),
                     description=props.get("description", ""),
                     keywords=[k.strip() for k in
                               props.get("keywords", "").split(",")
                               if k.strip()],
                     text=text)]


def parse_odf(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    """odt/ods/odp: content.xml carries the body, meta.xml the metadata."""
    zf = _zip_of(content)
    try:
        text = _xml_text(zf.read("content.xml"))
    except KeyError as e:
        raise ParserError("no content.xml in odf container") from e
    title = author = description = ""
    keywords: list[str] = []
    try:
        meta = ET.fromstring(zf.read("meta.xml"))
        for el in meta.iter():
            tag = el.tag.rsplit("}", 1)[-1]
            if tag == "title" and el.text:
                title = el.text
            elif tag == "creator" and el.text:
                author = el.text
            elif tag == "description" and el.text:
                description = el.text
            elif tag == "keyword" and el.text:
                keywords.append(el.text)
    except (KeyError, ET.ParseError):
        pass
    if not text:
        raise ParserError("empty odf document")
    return [Document(url=url, mime_type="application/vnd.oasis.opendocument.text",
                     title=title or text[:120], author=author,
                     description=description, keywords=keywords, text=text)]


_RTF_CONTROL = re.compile(rb"\\([a-z]{1,32})(-?\d{1,10})?[ ]?|\\'[0-9a-f]{2}"
                          rb"|\\[^a-z]|[{}]|\r|\n")


# destination groups whose content is data, not document text
_RTF_SKIP_DESTS = (b"fonttbl", b"colortbl", b"stylesheet", b"info",
                   b"pict", b"themedata", b"colorschememapping",
                   b"latentstyles", b"datastore", b"generator",
                   b"listtable", b"listoverridetable", b"rsidtbl",
                   b"xmlnstbl", b"operator", b"header", b"footer")
_RTF_DEST_RE = re.compile(
    rb"{\\\*?\\?(" + b"|".join(_RTF_SKIP_DESTS) + rb")\b")


def _rtf_strip_destinations(content: bytes) -> bytes:
    """Remove skippable destination groups with real brace matching
    (nested groups defeat any single regex)."""
    out = bytearray()
    pos = 0
    while True:
        m = _RTF_DEST_RE.search(content, pos)
        if m is None:
            out += content[pos:]
            return bytes(out)
        out += content[pos:m.start()]
        depth = 0
        i = m.start()
        while i < len(content):
            c = content[i]
            if c == 0x7B and (i == 0 or content[i - 1] != 0x5C):
                depth += 1
            elif c == 0x7D and content[i - 1] != 0x5C:
                depth -= 1
                if depth == 0:
                    break
            i += 1
        pos = i + 1


def parse_rtf(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    if not content.startswith(b"{\\rtf"):
        raise ParserError("not an rtf file")
    # the declared codepage governs \'xx byte escapes: \ansicpgNNNN
    # (10000 = MacRoman), bare \mac, else cp1252
    codec = "cp1252"
    m = re.search(rb"\\ansicpg(\d+)", content[:256])
    if m:
        cpg = int(m.group(1))
        codec = "mac_roman" if cpg == 10000 else f"cp{cpg}"
    elif re.search(rb"\\mac\b", content[:64]):
        codec = "mac_roman"
    body = _rtf_strip_destinations(content)

    # single decoding pass over tokens: \'xx bytes decode via the
    # document codec, \uN emits the code point and SKIPS the following
    # \ucN fallback items (chars or \'xx escapes) per the RTF spec
    parts: list[str] = []
    uc_skip = 1     # current \ucN value (default 1)
    pending_skip = 0
    pos = 0
    for m in _RTF_CONTROL.finditer(body):
        gap = body[pos:m.start()]
        if gap:
            if pending_skip:
                skip = min(pending_skip, len(gap))
                gap = gap[skip:]
                pending_skip -= skip
            if gap:
                parts.append(gap.decode("ascii", "replace"))
        pos = m.end()
        tok = m.group(0)
        if tok.startswith(b"\\'"):
            if pending_skip:
                pending_skip -= 1
                continue
            try:
                parts.append(bytes([int(tok[2:], 16)]).decode(
                    codec, "replace"))
            except (ValueError, LookupError):
                pass
            continue
        word, num = m.group(1), m.group(2)
        if word == b"u" and num:
            cp = int(num)
            parts.append(chr(cp + 65536 if cp < 0 else cp))
            pending_skip = uc_skip
        elif word == b"uc" and num:
            uc_skip = int(num)
        elif word in (b"par", b"line", b"tab", b"sect", b"page"):
            parts.append("\n")
    tail = body[pos:]
    if tail:
        parts.append(tail.decode("ascii", "replace"))
    text = re.sub(r"[ \t]+", " ", "".join(parts)).strip()
    if not text:
        raise ParserError("empty rtf document")
    return [Document(url=url, mime_type="application/rtf",
                     title=text.split("\n", 1)[0][:120], text=text)]


def parse_epub(url: str, content: bytes,
               charset: str | None = None) -> list[Document]:
    from .htmlparser import parse_html
    zf = _zip_of(content)
    chapters = [n for n in zf.namelist()
                if n.lower().endswith((".xhtml", ".html", ".htm"))]
    if not chapters:
        raise ParserError("no xhtml chapters in epub")
    main: Document | None = None
    for n in sorted(chapters):
        try:
            docs = parse_html(f"{url}#{n}", zf.read(n), charset)
        except ParserError:
            continue
        for d in docs:
            if main is None:
                main = d
                main.url = url
                main.mime_type = "application/epub+zip"
            else:
                main.merge(d)
    if main is None:
        raise ParserError("no parsable chapters in epub")
    # OPF metadata (title/creator) overrides chapter-derived title
    for n in zf.namelist():
        if n.lower().endswith(".opf"):
            try:
                root = ET.fromstring(zf.read(n))
                t = root.find(_DC_RE + "title")
                c = root.find(_DC_RE + "creator")
                if t is not None and t.text:
                    main.title = t.text
                if c is not None and c.text:
                    main.author = c.text
            except ET.ParseError:
                pass
            break
    return [main]
