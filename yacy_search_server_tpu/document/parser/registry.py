"""TextParser — MIME/extension dispatch + archive recursion.

Capability equivalent of the reference's parser registry (reference:
source/net/yacy/document/TextParser.java:78-160: initParser calls for ~30
parsers, mime+extension double dispatch, recursion into archives, and the
`parseSource` entry used by the indexing pipeline). Archive formats
(zip/tar/gz/bz2/xz) recurse into member documents, which merge into the
enclosing archive document's identity like the reference's
`ZIPParser`/`tarParser` do.
"""

from __future__ import annotations

import bz2
import gzip
import io
import lzma
import os
import tarfile
import zipfile
from urllib.parse import urlsplit

from ..document import Document
from .appparsers import parse_apk, parse_dwg, parse_mm, parse_sid
from .htmlparser import parse_html
from .swfparser import parse_swf
from .pdfparser import parse_pdf
from .mediaparsers import parse_audio, parse_image, parse_torrent
from .officeparsers import parse_epub, parse_odf, parse_ooxml, parse_rtf
from .oleparsers import parse_doc, parse_ole, parse_ppt, parse_xls
from .textparsers import parse_csv, parse_json, parse_ps, parse_text, \
    parse_vcf
from .xmlparsers import is_feed, parse_feed, parse_generic_xml

MAX_ARCHIVE_MEMBERS = 200
MAX_RECURSION = 3


from .errors import ParserError  # noqa: E402  (re-export, shared type)


def _ext(url: str) -> str:
    parts = urlsplit(url)
    # archive members carry their name in the fragment (url#member.html)
    path = parts.fragment or parts.path
    return os.path.splitext(path)[1].lstrip(".").lower()


# mime -> parser
_MIME_PARSERS = {
    "text/html": parse_html,
    "application/x-shockwave-flash": parse_swf,
    "application/xhtml+xml": parse_html,
    "text/plain": parse_text,
    "text/csv": parse_csv,
    "text/vcard": parse_vcf,
    "text/x-vcard": parse_vcf,
    "application/json": parse_json,
    "application/pdf": parse_pdf,
    "application/xml": parse_generic_xml,
    "text/xml": parse_generic_xml,
    "application/rss+xml": parse_feed,
    "application/atom+xml": parse_feed,
    # office containers
    "application/vnd.openxmlformats-officedocument.wordprocessingml.document":
        parse_ooxml,
    "application/vnd.openxmlformats-officedocument.spreadsheetml.sheet":
        parse_ooxml,
    "application/vnd.openxmlformats-officedocument.presentationml.presentation":
        parse_ooxml,
    "application/vnd.oasis.opendocument.text": parse_odf,
    "application/vnd.oasis.opendocument.spreadsheet": parse_odf,
    "application/vnd.oasis.opendocument.presentation": parse_odf,
    "application/rtf": parse_rtf, "text/rtf": parse_rtf,
    "application/epub+zip": parse_epub,
    # legacy binary office (OLE2/CFB containers)
    "application/msword": parse_doc,
    "application/vnd.ms-excel": parse_xls, "application/msexcel": parse_xls,
    "application/vnd.ms-powerpoint": parse_ppt,
    "application/mspowerpoint": parse_ppt,
    "application/vnd.visio": parse_ole,
    # OpenOffice 1.x (same zip/content.xml shape as ODF)
    "application/vnd.sun.xml.writer": parse_odf,
    # postscript
    "application/postscript": parse_ps,
    # media
    "image/png": parse_image, "image/jpeg": parse_image,
    "image/gif": parse_image, "image/tiff": parse_image,
    "audio/mpeg": parse_audio, "audio/mp3": parse_audio,
    "audio/ogg": parse_audio, "application/ogg": parse_audio,
    "audio/flac": parse_audio, "audio/x-flac": parse_audio,
    "audio/x-wav": parse_audio, "audio/wav": parse_audio,
    "audio/x-aiff": parse_audio, "audio/mp4": parse_audio,
    "application/x-bittorrent": parse_torrent,
    # application formats (round 5: the last four registry formats)
    "application/vnd.android.package-archive": parse_apk,
    "application/dwg": parse_dwg, "applications/vnd.dwg": parse_dwg,
    "application/freemind": parse_mm, "application/x-freemind": parse_mm,
    "audio/prs.sid": parse_sid, "audio/psid": parse_sid,
    "audio/x-psid": parse_sid, "audio/sidtune": parse_sid,
    "audio/x-sidtune": parse_sid,
}

_EXT_PARSERS = {
    "html": parse_html, "htm": parse_html, "xhtml": parse_html,
    "swf": parse_swf,
    "txt": parse_text, "md": parse_text, "rst": parse_text,
    "csv": parse_csv, "json": parse_json, "vcf": parse_vcf,
    "pdf": parse_pdf, "xml": parse_generic_xml,
    "rss": parse_feed, "atom": parse_feed,
    "docx": parse_ooxml, "xlsx": parse_ooxml, "pptx": parse_ooxml,
    "ppsx": parse_ooxml,
    "odt": parse_odf, "ods": parse_odf, "odp": parse_odf,
    "sxw": parse_odf, "sxc": parse_odf, "sxi": parse_odf,
    "rtf": parse_rtf, "epub": parse_epub,
    "doc": parse_doc, "xls": parse_xls, "ppt": parse_ppt, "pps": parse_ppt,
    "vsd": parse_ole, "vst": parse_ole,
    "vdx": parse_generic_xml, "vtx": parse_generic_xml,
    "ps": parse_ps,
    "png": parse_image, "jpg": parse_image, "jpeg": parse_image,
    "gif": parse_image, "tif": parse_image, "tiff": parse_image,
    "mp3": parse_audio, "ogg": parse_audio, "oga": parse_audio,
    "flac": parse_audio, "wav": parse_audio, "aiff": parse_audio,
    "aif": parse_audio, "m4a": parse_audio,
    "torrent": parse_torrent,
    "apk": parse_apk, "dwg": parse_dwg, "mm": parse_mm, "sid": parse_sid,
}

_ARCHIVE_MIMES = {"application/zip", "application/x-zip-compressed",
                  "application/gzip", "application/x-gzip",
                  "application/x-tar", "application/x-bzip2",
                  "application/x-xz", "application/x-7z-compressed"}
_ARCHIVE_EXTS = {"zip", "gz", "tgz", "tbz2", "txz", "tar", "bz2", "xz",
                 "7z"}


def supported_mime(mime: str) -> bool:
    return (mime in _MIME_PARSERS or mime in _ARCHIVE_MIMES
            or mime.startswith("text/"))


def supports(url: str, mime: str | None = None) -> bool:
    if mime and supported_mime(mime.split(";")[0].strip().lower()):
        return True
    return _ext(url) in _EXT_PARSERS or _ext(url) in _ARCHIVE_EXTS


def _parse_archive(url: str, mime: str, content: bytes, charset,
                   depth: int) -> list[Document]:
    ext = _ext(url)
    docs: list[Document] = []

    def recurse(member_name: str, data: bytes) -> None:
        member_url = url + "#" + member_name
        try:
            docs.extend(_parse(member_url, None, data, charset, depth + 1))
        except ParserError:
            pass

    if mime in ("application/zip", "application/x-zip-compressed") or \
            ext == "zip":
        try:
            with zipfile.ZipFile(io.BytesIO(content)) as zf:
                for info in zf.infolist()[:MAX_ARCHIVE_MEMBERS]:
                    if info.is_dir():
                        continue
                    recurse(info.filename, zf.read(info))
        except zipfile.BadZipFile as e:
            raise ParserError(f"bad zip: {e}") from e
    elif mime in ("application/x-tar",) or \
            ext in ("tar", "tgz", "tbz2", "txz") or \
            url.endswith((".tar.gz", ".tar.bz2", ".tar.xz")):
        try:
            # mode r:* lets tarfile undo the gz/bz2/xz layer itself
            with tarfile.open(fileobj=io.BytesIO(content), mode="r:*") as tf:
                for member in tf.getmembers()[:MAX_ARCHIVE_MEMBERS]:
                    if not member.isfile():
                        continue
                    f = tf.extractfile(member)
                    if f is not None:
                        recurse(member.name, f.read())
        except tarfile.TarError as e:
            raise ParserError(f"bad tar: {e}") from e
    elif mime in ("application/gzip", "application/x-gzip") or ext == "gz":
        try:
            inner = gzip.decompress(content)
        except OSError as e:
            raise ParserError(f"bad gzip: {e}") from e
        recurse(os.path.basename(urlsplit(url).path)[:-3] or "member", inner)
    elif mime == "application/x-bzip2" or ext == "bz2":
        try:
            inner = bz2.decompress(content)
        except OSError as e:
            raise ParserError(f"bad bzip2: {e}") from e
        recurse(os.path.basename(urlsplit(url).path)[:-4] or "member", inner)
    elif mime == "application/x-xz" or ext == "xz":
        try:
            inner = lzma.decompress(content)
        except lzma.LZMAError as e:
            raise ParserError(f"bad xz: {e}") from e
        recurse(os.path.basename(urlsplit(url).path)[:-3] or "member", inner)
    elif mime == "application/x-7z-compressed" or ext == "7z":
        import lzma as _lzma
        import struct as _struct

        from .sevenzip import SevenZip
        try:
            members = SevenZip(content).files[:MAX_ARCHIVE_MEMBERS]
        except (IndexError, ValueError, _struct.error,
                _lzma.LZMAError) as e:
            raise ParserError(f"bad 7z: {e}") from e
        for name, data in members:
            recurse(name, data)
    else:
        raise ParserError(f"unsupported archive {mime or ext}")
    return docs


def _parse(url: str, mime: str | None, content: bytes,
           charset: str | None, depth: int) -> list[Document]:
    if depth > MAX_RECURSION:
        return []
    mime = (mime or "").split(";")[0].strip().lower()
    ext = _ext(url)

    if mime in _ARCHIVE_MIMES or (not mime and ext in _ARCHIVE_EXTS):
        return _parse_archive(url, mime, content, charset, depth)

    parser = _MIME_PARSERS.get(mime)
    if parser is None:
        parser = _EXT_PARSERS.get(ext)
    if parser is None and mime.startswith("text/"):
        parser = parse_text
    if parser is None and not mime:
        # last resort: sniff
        head = content[:256].lstrip().lower()
        if head.startswith((b"<!doctype html", b"<html")):
            parser = parse_html
        elif head.startswith(b"%pdf"):
            parser = parse_pdf
        elif content.startswith(b"\xd0\xcf\x11\xe0"):   # OLE2/CFB
            parser = parse_ole
        elif head.startswith(b"<?xml"):
            parser = parse_feed if is_feed(content) else parse_generic_xml
        else:
            parser = parse_text
    if parser is None:
        raise ParserError(f"no parser for mime={mime} ext={ext}")
    if parser is parse_generic_xml and is_feed(content):
        parser = parse_feed
    return parser(url, content, charset)


def parse_source(url: str, mime: str | None, content: bytes,
                 charset: str | None = None) -> list[Document]:
    """Parse raw fetched bytes into Documents (TextParser.parseSource)."""
    if not content:
        raise ParserError("empty content")
    docs = _parse(url, mime, content, charset, 0)
    if not docs:
        raise ParserError("parser produced no documents")
    return docs
