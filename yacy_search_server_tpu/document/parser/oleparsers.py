"""Legacy binary Office parsers — .doc / .xls / .ppt over a CFB reader.

Capability equivalent of the reference's POI-backed parsers (reference:
source/net/yacy/document/parser/docParser.java, xlsParser.java,
pptParser.java — Apache POI HWPF/HSSF/HSLF). No POI exists here, so this
module implements the container and the text-bearing record structures
directly:

- `CompoundFile`: the OLE2/CFB container ([MS-CFB]): 512-byte sectors,
  FAT/miniFAT chains, directory tree, mini-stream indirection.
- `.doc`: Word 97-2003 ([MS-DOC]) — FIB offsets to the Clx piece table
  in the table stream; each piece is cp1252 ("compressed") or UTF-16LE
  text in the WordDocument stream. Falls back to a printable-run scan
  when the piece table is absent/corrupt.
- `.xls`: BIFF8 ([MS-XLS]) — SST shared strings (with CONTINUE-record
  string splicing) plus the pre-BIFF8 LABEL records.
- `.ppt`: PowerPoint 97-2003 ([MS-PPT]) — recursive record walk
  collecting TextCharsAtom (UTF-16LE) and TextBytesAtom (cp1252).
- document metadata (title/author/keywords/comments) from the
  \\x05SummaryInformation property-set stream ([MS-OLEPS]).
"""

from __future__ import annotations

import io
import re
import struct

from ..document import DT_TEXT, Document
from .errors import ParserError

_CFB_MAGIC = b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1"
_FREESECT = 0xFFFFFFFF
_ENDOFCHAIN = 0xFFFFFFFE


class CompoundFile:
    """Minimal [MS-CFB] reader: named streams out of an OLE2 container."""

    def __init__(self, data: bytes):
        if len(data) < 512 or not data.startswith(_CFB_MAGIC):
            raise ParserError("not a compound file")
        self.data = data
        (self.sector_shift, self.mini_shift) = struct.unpack_from(
            "<HH", data, 30)
        self.sector_size = 1 << self.sector_shift
        self.mini_size = 1 << self.mini_shift
        (self.num_fat,) = struct.unpack_from("<I", data, 44)
        (self.dir_start,) = struct.unpack_from("<I", data, 48)
        (self.mini_cutoff,) = struct.unpack_from("<I", data, 56)
        (self.minifat_start,) = struct.unpack_from("<I", data, 60)
        (self.num_minifat,) = struct.unpack_from("<I", data, 64)
        (self.difat_start,) = struct.unpack_from("<I", data, 68)
        (self.num_difat,) = struct.unpack_from("<I", data, 72)
        self.fat = self._load_fat()
        self.minifat = self._load_minifat()
        self.entries = self._load_directory()
        root = next((e for e in self.entries if e["type"] == 5), None)
        if root is None:
            raise ParserError("cfb: no root entry")
        self.mini_stream = self._read_chain(root["start"], root["size"])

    def _sector(self, n: int) -> bytes:
        off = 512 + n * self.sector_size
        return self.data[off:off + self.sector_size]

    def _load_fat(self) -> list[int]:
        # DIFAT: 109 entries in the header, then chained DIFAT sectors
        difat: list[int] = list(struct.unpack_from("<109I", self.data, 76))
        next_difat = self.difat_start
        for _ in range(self.num_difat):
            if next_difat in (_FREESECT, _ENDOFCHAIN):
                break
            sec = self._sector(next_difat)
            vals = struct.unpack(f"<{self.sector_size // 4}I", sec)
            difat.extend(vals[:-1])
            next_difat = vals[-1]
        fat: list[int] = []
        for s in difat:
            if s in (_FREESECT, _ENDOFCHAIN):
                continue
            sec = self._sector(s)
            if len(sec) == self.sector_size:
                fat.extend(struct.unpack(f"<{self.sector_size // 4}I", sec))
        return fat

    def _load_minifat(self) -> list[int]:
        out: list[int] = []
        s = self.minifat_start
        seen = set()
        while s not in (_FREESECT, _ENDOFCHAIN) and s not in seen \
                and s < len(self.fat):
            seen.add(s)
            sec = self._sector(s)
            out.extend(struct.unpack(f"<{self.sector_size // 4}I", sec))
            s = self.fat[s]
        return out

    def _read_chain(self, start: int, size: int) -> bytes:
        out = io.BytesIO()
        s = start
        seen = set()
        while s not in (_FREESECT, _ENDOFCHAIN) and s not in seen \
                and s < len(self.fat):
            seen.add(s)
            out.write(self._sector(s))
            s = self.fat[s]
        return out.getvalue()[:size]

    def _read_mini_chain(self, start: int, size: int) -> bytes:
        out = io.BytesIO()
        s = start
        seen = set()
        while s not in (_FREESECT, _ENDOFCHAIN) and s not in seen \
                and s < len(self.minifat):
            seen.add(s)
            off = s * self.mini_size
            out.write(self.mini_stream[off:off + self.mini_size])
            s = self.minifat[s]
        return out.getvalue()[:size]

    def _load_directory(self) -> list[dict]:
        raw = self._read_chain(self.dir_start, len(self.data))
        entries = []
        for off in range(0, len(raw) - 127, 128):
            name_len = struct.unpack_from("<H", raw, off + 64)[0]
            if name_len < 2 or name_len > 64:
                continue
            name = raw[off:off + name_len - 2].decode("utf-16-le", "replace")
            etype = raw[off + 66]
            start = struct.unpack_from("<I", raw, off + 116)[0]
            size = struct.unpack_from("<Q", raw, off + 120)[0]
            entries.append({"name": name, "type": etype,
                            "start": start, "size": size})
        return entries

    def stream(self, name: str) -> bytes | None:
        for e in self.entries:
            if e["name"] == name and e["type"] == 2:
                if e["size"] < self.mini_cutoff:
                    return self._read_mini_chain(e["start"], e["size"])
                return self._read_chain(e["start"], e["size"])
        return None


# -- SummaryInformation ([MS-OLEPS]) ------------------------------------

_PIDSI = {2: "title", 3: "subject", 4: "author",
          5: "keywords", 6: "comments"}


def _summary_info(cfb: CompoundFile) -> dict[str, str]:
    raw = cfb.stream("\x05SummaryInformation")
    if not raw or len(raw) < 48:
        return {}
    try:
        (nsets,) = struct.unpack_from("<I", raw, 24)
        if nsets < 1:
            return {}
        (sec_off,) = struct.unpack_from("<I", raw, 44)
        (_size, nprops) = struct.unpack_from("<II", raw, sec_off)
        out: dict[str, str] = {}
        for i in range(min(nprops, 64)):
            pid, poff = struct.unpack_from("<II", raw, sec_off + 8 + 8 * i)
            field = _PIDSI.get(pid)
            if field is None:
                continue
            base = sec_off + poff
            (vtype,) = struct.unpack_from("<I", raw, base)
            if vtype == 30:      # VT_LPSTR (codepage string)
                (ln,) = struct.unpack_from("<I", raw, base + 4)
                val = raw[base + 8:base + 8 + ln].split(b"\0")[0].decode(
                    "cp1252", "replace")
            elif vtype == 31:    # VT_LPWSTR
                (ln,) = struct.unpack_from("<I", raw, base + 4)
                val = raw[base + 8:base + 8 + 2 * ln].decode(
                    "utf-16-le", "replace").split("\0")[0]
            else:
                continue
            out[field] = val.strip()
        return out
    except struct.error:
        return {}


# -- .doc ([MS-DOC]) -----------------------------------------------------

_CONTROL_RE = re.compile(r"[\x00-\x08\x0b\x0c\x0e-\x1f\x7f]")


def _doc_text(cfb: CompoundFile) -> str:
    word = cfb.stream("WordDocument")
    if word is None or len(word) < 0x200:
        raise ParserError("doc: no WordDocument stream")
    flags = struct.unpack_from("<H", word, 0x000A)[0]
    table_name = "1Table" if flags & 0x0200 else "0Table"
    table = cfb.stream(table_name) or cfb.stream("0Table") \
        or cfb.stream("1Table")
    try:
        fc_clx = struct.unpack_from("<I", word, 0x01A2)[0]
        lcb_clx = struct.unpack_from("<I", word, 0x01A6)[0]
        if table is not None and lcb_clx and fc_clx + lcb_clx <= len(table):
            return _doc_pieces(word, table[fc_clx:fc_clx + lcb_clx])
    except (struct.error, ParserError):
        pass
    # degraded: printable-run scan of the text area (still finds the
    # visible content of ordinary single-piece documents)
    return _printable_runs(word)


def _doc_pieces(word: bytes, clx: bytes) -> str:
    # Clx = zero or more Prc (clxt=1) then one Pcdt (clxt=2)
    pos = 0
    while pos < len(clx) and clx[pos] == 1:
        (cb,) = struct.unpack_from("<H", clx, pos + 1)
        pos += 3 + cb
    if pos >= len(clx) or clx[pos] != 2:
        raise ParserError("doc: no piece table")
    (lcb,) = struct.unpack_from("<I", clx, pos + 1)
    plc = clx[pos + 5:pos + 5 + lcb]
    n = (lcb - 4) // 12
    cps = struct.unpack_from(f"<{n + 1}I", plc, 0)
    parts: list[str] = []
    for i in range(n):
        fc_raw = struct.unpack_from("<I", plc, 4 * (n + 1) + 8 * i + 2)[0]
        nchars = cps[i + 1] - cps[i]
        if fc_raw & 0x40000000:      # fCompressed: cp1252, fc is doubled
            fc = (fc_raw & 0x3FFFFFFF) >> 1
            parts.append(word[fc:fc + nchars].decode("cp1252", "replace"))
        else:
            fc = fc_raw & 0x3FFFFFFF
            parts.append(word[fc:fc + 2 * nchars].decode("utf-16-le",
                                                         "replace"))
    text = "".join(parts)
    return _CONTROL_RE.sub(" ", text.replace("\r", "\n")).strip()


# latin letters/digits/punctuation only: the fallback scans arbitrary
# binary, where a permissive \w class would "find" CJK-range garbage in
# compressed data decoded as UTF-16
_RUN_CLASS = r"[A-Za-z0-9À-ſ \t.,;:!?&()\-\'\"/]"


def _looks_like_text(run: str) -> bool:
    """Keep only runs that are mostly word characters with spaces —
    compressed binary decoded as text has few spaces and odd casing."""
    if len(run) < 8:
        return False
    alnum = sum(c.isalnum() or c == " " for c in run)
    return alnum / len(run) >= 0.85 and " " in run.strip()


def _printable_runs(raw: bytes, min_run: int = 8) -> str:
    """Fallback text recovery: contiguous cp1252/utf-16 printable runs."""
    pattern = _RUN_CLASS + "{%d,}" % min_run
    runs = [r for r in re.findall(pattern, raw.decode("utf-16-le", "ignore"))
            if _looks_like_text(r)]
    if not runs:
        runs = [r for r in re.findall(pattern, raw.decode("cp1252", "ignore"))
                if _looks_like_text(r)]
    return "\n".join(r.strip() for r in runs if r.strip())


# -- .xls (BIFF8 [MS-XLS]) ----------------------------------------------


def _xls_text(cfb: CompoundFile) -> str:
    book = cfb.stream("Workbook") or cfb.stream("Book")
    if book is None:
        raise ParserError("xls: no Workbook stream")
    texts: list[str] = []
    pos = 0
    while pos + 4 <= len(book):
        rtype, rlen = struct.unpack_from("<HH", book, pos)
        payload = book[pos + 4:pos + 4 + rlen]
        if rtype == 0x00FC:              # SST
            # splice CONTINUE records; boundaries re-state the flag byte,
            # handled inside _sst_strings via the boundary list
            cont_bounds = []
            end = pos + 4 + rlen
            buf = bytearray(payload)
            while end + 4 <= len(book):
                ntype, nlen = struct.unpack_from("<HH", book, end)
                if ntype != 0x003C:      # CONTINUE
                    break
                cont_bounds.append(len(buf))
                buf.extend(book[end + 4:end + 4 + nlen])
                end += 4 + nlen
            texts.extend(_sst_strings(bytes(buf), cont_bounds))
        elif rtype == 0x0204 and rlen > 8:   # LABEL (pre-BIFF8 cell text)
            (cch,) = struct.unpack_from("<H", payload, 6)
            texts.append(payload[8:8 + cch].decode("cp1252", "replace"))
        pos += 4 + rlen
    return "\n".join(t for t in texts if t.strip())


def _sst_strings(buf: bytes, cont_bounds: list[int]) -> list[str]:
    out: list[str] = []
    try:
        (_total, unique) = struct.unpack_from("<II", buf, 0)
        pos = 8
        for _ in range(min(unique, 100_000)):
            if pos + 3 > len(buf):
                break
            (cch,) = struct.unpack_from("<H", buf, pos)
            flags = buf[pos + 2]
            pos += 3
            crun = cbext = 0
            if flags & 0x08:     # rich text
                (crun,) = struct.unpack_from("<H", buf, pos)
                pos += 2
            if flags & 0x04:     # far east ext
                (cbext,) = struct.unpack_from("<I", buf, pos)
                pos += 4
            chars: list[str] = []
            remaining = cch
            high = bool(flags & 0x01)
            while remaining > 0:
                # a CONTINUE boundary inside the character data restates
                # the grbit byte
                boundary = next((b for b in cont_bounds
                                 if pos < b <= pos + remaining *
                                 (2 if high else 1)), None)
                take = remaining
                if boundary is not None:
                    take = min(remaining,
                               (boundary - pos) // (2 if high else 1))
                if high:
                    chars.append(buf[pos:pos + 2 * take].decode(
                        "utf-16-le", "replace"))
                    pos += 2 * take
                else:
                    chars.append(buf[pos:pos + take].decode(
                        "cp1252", "replace"))
                    pos += take
                remaining -= take
                if remaining > 0 and boundary is not None:
                    high = bool(buf[pos] & 0x01)
                    pos += 1
            out.append("".join(chars))
            pos += 4 * crun + cbext
    except (struct.error, IndexError):
        pass
    return out


# -- .ppt ([MS-PPT]) -----------------------------------------------------


def _ppt_text(cfb: CompoundFile) -> str:
    doc = cfb.stream("PowerPoint Document")
    if doc is None:
        raise ParserError("ppt: no PowerPoint Document stream")
    texts: list[str] = []

    def walk(data: bytes, depth: int = 0) -> None:
        if depth > 16:
            return
        pos = 0
        while pos + 8 <= len(data):
            ver_inst, rtype, rlen = struct.unpack_from("<HHI", data, pos)
            payload = data[pos + 8:pos + 8 + rlen]
            if (ver_inst & 0x000F) == 0x000F:      # container
                walk(payload, depth + 1)
            elif rtype == 0x0FA0:                  # TextCharsAtom (UTF-16)
                texts.append(payload.decode("utf-16-le", "replace"))
            elif rtype == 0x0FA8:                  # TextBytesAtom (cp1252)
                texts.append(payload.decode("cp1252", "replace"))
            pos += 8 + rlen
    walk(doc)
    joined = "\n".join(t.replace("\r", "\n").strip() for t in texts
                       if t.strip())
    return _CONTROL_RE.sub(" ", joined)


# -- public parsers ------------------------------------------------------


def _make_doc(url: str, text: str, info: dict[str, str],
              mime: str) -> list[Document]:
    if not text.strip() and not info:
        raise ParserError(f"{mime}: no text recovered")
    return [Document(
        url=url, mime_type=mime, title=info.get("title", ""),
        author=info.get("author", ""),
        description=info.get("comments", ""),
        keywords=[k for k in re.split(r"[,;\s]+",
                                      info.get("keywords", "")) if k],
        text=text, doctype=DT_TEXT)]


def parse_doc(url: str, content: bytes, charset=None) -> list[Document]:
    """Word 97-2003 (reference: docParser.java via POI HWPF)."""
    cfb = CompoundFile(content)
    return _make_doc(url, _doc_text(cfb), _summary_info(cfb),
                     "application/msword")


def parse_xls(url: str, content: bytes, charset=None) -> list[Document]:
    """Excel 97-2003 (reference: xlsParser.java via POI HSSF)."""
    cfb = CompoundFile(content)
    return _make_doc(url, _xls_text(cfb), _summary_info(cfb),
                     "application/msexcel")


def parse_ppt(url: str, content: bytes, charset=None) -> list[Document]:
    """PowerPoint 97-2003 (reference: pptParser.java via POI HSLF)."""
    cfb = CompoundFile(content)
    return _make_doc(url, _ppt_text(cfb), _summary_info(cfb),
                     "application/mspowerpoint")


def parse_ole(url: str, content: bytes, charset=None) -> list[Document]:
    """Extension-agnostic CFB dispatch: sniff by contained streams
    (vsd and friends fall through to a printable-run scan)."""
    cfb = CompoundFile(content)
    names = {e["name"] for e in cfb.entries}
    if "WordDocument" in names:
        return parse_doc(url, content, charset)
    if "Workbook" in names or "Book" in names:
        return parse_xls(url, content, charset)
    if "PowerPoint Document" in names:
        return parse_ppt(url, content, charset)
    # unknown OLE app (Visio etc.): best-effort text recovery
    best = ""
    for e in cfb.entries:
        if e["type"] == 2 and e["size"] > 64:
            s = cfb.stream(e["name"])
            if s:
                t = _printable_runs(s)
                if len(t) > len(best):
                    best = t
    return _make_doc(url, best, _summary_info(cfb), "application/x-ole")
