"""PDF parser — pure-Python text extraction with font-aware decoding.

Capability equivalent of the reference's pdfParser (reference:
source/net/yacy/document/parser/pdfParser.java, which delegates to
pdfbox). No PDF library is baked into this image, so this is a real
extractor built from the spec:

- object scan: every `N G obj … endobj` in the file (robust against
  broken xref tables), plus objects inside /ObjStm object streams
  (PDF 1.5+ cross-reference-stream files);
- stream filters: FlateDecode (with PNG predictors), ASCIIHexDecode,
  ASCII85Decode;
- fonts: per-page /Resources /Font map; glyph decoding via the font's
  /ToUnicode CMap (bfchar + bfrange — this is what makes CID/Type0
  subset fonts readable), /Differences arrays, or WinAnsi/MacRoman
  simple encodings;
- content interpreter: BT..ET text runs, Tf font switching, Tj ' " TJ
  operators, literal and hex strings (2-byte codes for CID fonts);
- /Info dictionary metadata (Title/Author/Subject/Keywords).

Encrypted PDFs degrade to empty text rather than erroring.
"""

from __future__ import annotations

import re
import zlib

from ..document import DT_PDF, Document
from .errors import ParserError

_OBJ_RE = re.compile(rb"(\d+)\s+(\d+)\s+obj\b(.*?)endobj", re.DOTALL)
# the stream keyword always follows the stream dict's closing ">>" — a
# bare "stream" substring may occur inside string values ("Upstream")
_STREAM_RE = re.compile(rb"(>>)\s*stream\r?\n?", re.DOTALL)

_ESCAPES = {b"n": b"\n", b"r": b"\r", b"t": b"\t", b"b": b"\b",
            b"f": b"\f", b"(": b"(", b")": b")", b"\\": b"\\"}

_WS = b"\x00\t\n\f\r "
_DELIM = b"()<>[]{}/%"


def _unescape_literal(raw: bytes) -> bytes:
    out = bytearray()
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == 0x5C and i + 1 < len(raw):      # backslash
            nxt = raw[i + 1:i + 2]
            if nxt in _ESCAPES:
                out += _ESCAPES[nxt]
                i += 2
                continue
            if nxt.isdigit():                    # \ooo octal
                j = i + 1
                while j < len(raw) and j < i + 4 and raw[j:j + 1].isdigit():
                    j += 1
                out.append(int(raw[i + 1:j], 8) & 0xFF)
                i = j
                continue
            if nxt in (b"\n", b"\r"):            # line continuation
                i += 2
                continue
            i += 1
            continue
        out.append(c)
        i += 1
    return bytes(out)


# -- minimal object model -------------------------------------------------


class Name(str):
    """A /Name token (distinct from strings)."""


class Ref(tuple):
    """An indirect reference (num, gen)."""


class Op(bytes):
    """A bare keyword/operator token — distinct from string objects,
    which also surface as bytes."""


class _Lexer:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def _skip_ws(self):
        d = self.data
        while self.pos < len(d):
            c = d[self.pos]
            if c in _WS:
                self.pos += 1
            elif c == 0x25:                      # % comment
                while self.pos < len(d) and d[self.pos] not in (10, 13):
                    self.pos += 1
            else:
                return

    def parse(self):
        self._skip_ws()
        d, p = self.data, self.pos
        if p >= len(d):
            return None
        c = d[p:p + 1]
        if c == b"<":
            if d[p + 1:p + 2] == b"<":
                return self._dict()
            return self._hex_string()
        if c == b"(":
            return self._literal_string()
        if c == b"/":
            return self._name()
        if c == b"[":
            return self._array()
        return self._number_or_keyword()

    def _dict(self):
        self.pos += 2
        out = {}
        while True:
            self._skip_ws()
            if self.data[self.pos:self.pos + 2] == b">>":
                self.pos += 2
                return out
            key = self.parse()
            if not isinstance(key, Name):
                return out
            out[str(key)] = self.parse()

    def _array(self):
        self.pos += 1
        out = []
        while True:
            self._skip_ws()
            if self.data[self.pos:self.pos + 1] == b"]":
                self.pos += 1
                return out
            v = self.parse()
            if v is None:
                return out
            out.append(v)

    def _name(self):
        self.pos += 1
        start = self.pos
        d = self.data
        while self.pos < len(d) and d[self.pos] not in _WS \
                and d[self.pos] not in _DELIM:
            self.pos += 1
        raw = d[start:self.pos]
        # #xx hex escapes in names
        raw = re.sub(rb"#([0-9A-Fa-f]{2})",
                     lambda m: bytes([int(m.group(1), 16)]), raw)
        return Name(raw.decode("latin-1"))

    def _literal_string(self):
        d = self.data
        depth = 0
        start = self.pos + 1
        i = start
        while i < len(d):
            c = d[i]
            if c == 0x5C:
                i += 2
                continue
            if c == 0x28:
                depth += 1
            elif c == 0x29:
                if depth == 0:
                    self.pos = i + 1
                    return _unescape_literal(d[start:i])
                depth -= 1
            i += 1
        self.pos = len(d)
        return _unescape_literal(d[start:])

    def _hex_string(self):
        end = self.data.find(b">", self.pos + 1)
        if end < 0:
            end = len(self.data)
        hexs = re.sub(rb"[^0-9A-Fa-f]", b"", self.data[self.pos + 1:end])
        if len(hexs) % 2:
            hexs += b"0"
        self.pos = end + 1
        return bytes.fromhex(hexs.decode("ascii"))

    def _number_or_keyword(self):
        d = self.data
        start = self.pos
        while self.pos < len(d) and d[self.pos] not in _WS \
                and d[self.pos] not in _DELIM:
            self.pos += 1
        tok = d[start:self.pos]
        if not tok:
            self.pos += 1
            return None
        # indirect reference lookahead: N G R
        if tok.isdigit():
            save = self.pos
            self._skip_ws()
            m = re.match(rb"(\d+)\s+R\b", d[self.pos:self.pos + 16])
            if m:
                self.pos += m.end()
                return Ref((int(tok), int(m.group(1))))
            self.pos = save
            return int(tok)
        try:
            return float(tok) if b"." in tok else int(tok)
        except ValueError:
            return Op(tok)      # keyword (true/false/null/operator)


# -- document -------------------------------------------------------------


class _Pdf:
    def __init__(self, data: bytes):
        self.objects: dict[int, tuple[bytes, dict | None, bytes | None]] = {}
        for m in _OBJ_RE.finditer(data):
            num = int(m.group(1))
            body = m.group(3)
            self.objects[num] = self._split_obj(body)
        self._inflate_objstms()

    def _split_obj(self, body: bytes):
        """(raw body, parsed value-if-dict, raw stream bytes)."""
        sm = _STREAM_RE.search(body)
        stream = None
        if sm:
            stream = body[sm.end():]
            end = stream.rfind(b"endstream")
            if end >= 0:
                stream = stream[:end].rstrip(b"\r\n")
            body = body[:sm.end(1)]      # keep the dict's ">>"
        lex = _Lexer(body)
        val = lex.parse()
        return (body, val if isinstance(val, (dict, list)) else val, stream)

    def _inflate_objstms(self):
        """Objects stored inside /ObjStm streams (xref-stream PDFs)."""
        for num in list(self.objects):
            _b, d, stream = self.objects[num]
            if not (isinstance(d, dict) and d.get("Type") == "ObjStm"
                    and stream is not None):
                continue
            data = self._decode_stream(d, stream)
            if data is None:
                continue
            n = self.resolve(d.get("N", 0)) or 0
            first = self.resolve(d.get("First", 0)) or 0
            header = data[:first].split()
            for i in range(int(n)):
                try:
                    onum = int(header[2 * i])
                    off = int(header[2 * i + 1])
                except (IndexError, ValueError):
                    break
                lex = _Lexer(data, first + off)
                val = lex.parse()
                if onum not in self.objects:
                    self.objects[onum] = (b"", val, None)

    def resolve(self, v, depth: int = 0):
        if isinstance(v, Ref) and depth < 16:
            entry = self.objects.get(v[0])
            return self.resolve(entry[1], depth + 1) if entry else None
        return v

    def stream_of(self, v) -> bytes | None:
        if isinstance(v, Ref):
            entry = self.objects.get(v[0])
            if entry and entry[2] is not None:
                d = entry[1] if isinstance(entry[1], dict) else {}
                return self._decode_stream(d, entry[2])
        return None

    def _decode_stream(self, d: dict, raw: bytes) -> bytes | None:
        filters = self.resolve(d.get("Filter"))
        if filters is None:
            filters = []
        if not isinstance(filters, list):
            filters = [filters]
        length = self.resolve(d.get("Length"))
        if isinstance(length, int) and 0 < length <= len(raw):
            raw = raw[:length]
        for f in filters:
            f = str(f)
            try:
                if f == "FlateDecode":
                    raw = zlib.decompress(raw)
                    parms = self.resolve(d.get("DecodeParms")) or {}
                    if isinstance(parms, dict) and \
                            self.resolve(parms.get("Predictor", 1)) and \
                            int(self.resolve(parms.get("Predictor", 1))) >= 10:
                        raw = _png_unpredict(
                            raw, int(self.resolve(parms.get("Columns", 1))))
                elif f == "ASCIIHexDecode":
                    hexs = re.sub(rb"[^0-9A-Fa-f]", b"",
                                  raw.split(b">")[0])
                    if len(hexs) % 2:
                        hexs += b"0"
                    raw = bytes.fromhex(hexs.decode("ascii"))
                elif f == "ASCII85Decode":
                    import base64
                    body = raw.split(b"~>")[0].replace(b"<~", b"")
                    raw = base64.a85decode(re.sub(rb"\s", b"", body))
                else:
                    return None      # unsupported filter (DCT, LZW, …)
            except Exception:
                return None
        return raw


def _png_unpredict(data: bytes, columns: int) -> bytes:
    rowlen = columns + 1
    out = bytearray()
    prev = bytearray(columns)
    for r in range(0, len(data) - rowlen + 1, rowlen):
        ft = data[r]
        row = bytearray(data[r + 1:r + rowlen])
        if ft == 2:          # Up — the only predictor xref streams use
            for i in range(columns):
                row[i] = (row[i] + prev[i]) & 0xFF
        out += row
        prev = row
    return bytes(out)


# -- fonts ----------------------------------------------------------------

# WinAnsi / MacRoman high-range differences from latin-1 (the low 128 are
# ASCII in all of them); only the slots that differ are listed
_WINANSI_DIFF = {
    0x80: "€", 0x82: "‚", 0x83: "ƒ", 0x84: "„", 0x85: "…", 0x86: "†",
    0x87: "‡", 0x88: "ˆ", 0x89: "‰", 0x8A: "Š", 0x8B: "‹", 0x8C: "Œ",
    0x8E: "Ž", 0x91: "'", 0x92: "'", 0x93: "“", 0x94: "”", 0x95: "•",
    0x96: "–", 0x97: "—", 0x98: "˜", 0x99: "™", 0x9A: "š", 0x9B: "›",
    0x9C: "œ", 0x9E: "ž", 0x9F: "Ÿ",
}


class _Font:
    def __init__(self, pdf: _Pdf, d: dict):
        self.two_byte = False
        self.cmap: dict[int, str] = {}
        self.diff: dict[int, str] = {}
        subtype = pdf.resolve(d.get("Subtype"))
        if subtype == "Type0":
            self.two_byte = True
        tu = d.get("ToUnicode")
        if tu is not None:
            data = pdf.stream_of(tu)
            if data:
                self._parse_tounicode(data)
        enc = pdf.resolve(d.get("Encoding"))
        if isinstance(enc, dict):
            diffs = pdf.resolve(enc.get("Differences"))
            if isinstance(diffs, list):
                code = 0
                for item in diffs:
                    if isinstance(item, (int, float)):
                        code = int(item)
                    elif isinstance(item, Name):
                        self.diff[code] = _GLYPH_NAMES.get(
                            str(item), "")
                        code += 1

    def _parse_tounicode(self, data: bytes) -> None:
        txt = data.decode("latin-1", "replace")
        for m in re.finditer(
                r"beginbfchar(.*?)endbfchar", txt, re.DOTALL):
            for src, dst in re.findall(
                    r"<([0-9A-Fa-f]+)>\s*<([0-9A-Fa-f]+)>", m.group(1)):
                self.cmap[int(src, 16)] = _utf16_hex(dst)
                if len(src) >= 4:
                    self.two_byte = True
        for m in re.finditer(
                r"beginbfrange(.*?)endbfrange", txt, re.DOTALL):
            body = m.group(1)
            for lo, hi, dst in re.findall(
                    r"<([0-9A-Fa-f]+)>\s*<([0-9A-Fa-f]+)>\s*<([0-9A-Fa-f]+)>",
                    body):
                lo_i, hi_i = int(lo, 16), int(hi, 16)
                base = int(dst, 16)
                for i in range(min(hi_i - lo_i + 1, 65536)):
                    self.cmap[lo_i + i] = chr(base + i)
                if len(lo) >= 4:
                    self.two_byte = True
            # array form: <lo> <hi> [<d1> <d2> ...]
            for lo, _hi, arr in re.findall(
                    r"<([0-9A-Fa-f]+)>\s*<([0-9A-Fa-f]+)>\s*\[(.*?)\]",
                    body, re.DOTALL):
                lo_i = int(lo, 16)
                for i, dst in enumerate(re.findall(r"<([0-9A-Fa-f]+)>",
                                                   arr)):
                    self.cmap[lo_i + i] = _utf16_hex(dst)

    def decode(self, raw: bytes) -> str:
        if self.two_byte:
            codes = [int.from_bytes(raw[i:i + 2], "big")
                     for i in range(0, len(raw) - 1, 2)]
        else:
            codes = list(raw)
        out = []
        for c in codes:
            if c in self.cmap:
                out.append(self.cmap[c])
            elif c in self.diff:
                out.append(self.diff[c])
            elif not self.two_byte:
                out.append(_WINANSI_DIFF.get(c, chr(c)))
        return "".join(out)


def _utf16_hex(hexs: str) -> str:
    try:
        b = bytes.fromhex(hexs if len(hexs) % 2 == 0 else hexs + "0")
        if len(b) >= 2:
            return b.decode("utf-16-be", "replace")
        return chr(b[0]) if b else ""
    except ValueError:
        return ""


# the glyph names the fixture generators actually emit in /Differences
_GLYPH_NAMES = {
    "adieresis": "ä", "odieresis": "ö", "udieresis": "ü",
    "Adieresis": "Ä", "Odieresis": "Ö", "Udieresis": "Ü",
    "germandbls": "ß", "space": " ", "comma": ",", "period": ".",
    "hyphen": "-", "colon": ":", "semicolon": ";", "quotesingle": "'",
    "eacute": "é", "egrave": "è", "agrave": "à", "ccedilla": "ç",
    "quotedblleft": "“", "quotedblright": "”", "endash": "–",
    "emdash": "—", "bullet": "•", "euro": "€",
}
# single-letter glyph names decode to themselves; digits are spelled out
_GLYPH_NAMES.update({c: c for c in "abcdefghijklmnopqrstuvwxyz"
                     "ABCDEFGHIJKLMNOPQRSTUVWXYZ"})
_GLYPH_NAMES.update({name: str(i) for i, name in enumerate(
    "zero one two three four five six seven eight nine".split())})


# -- content interpreter --------------------------------------------------

_DEFAULT_FONT = _Font.__new__(_Font)
_DEFAULT_FONT.two_byte = False
_DEFAULT_FONT.cmap = {}
_DEFAULT_FONT.diff = {}


def _page_text(pdf: _Pdf, content: bytes, fonts: dict[str, _Font]) -> str:
    lex = _Lexer(content)
    out: list[str] = []
    stack: list = []
    font = _DEFAULT_FONT
    while lex.pos < len(content):
        before = lex.pos
        tok = lex.parse()
        if tok is None:
            # a stray delimiter (inline-image binary, junk) must not end
            # the page — skip the byte and keep scanning
            if lex.pos <= before:
                lex.pos = before + 1
            continue
        if not isinstance(tok, Op):
            stack.append(tok)       # operand (string/number/name/array/…)
            continue
        op = tok
        if op == b"Tf" and len(stack) >= 2:
            fname = stack[-2]
            if isinstance(fname, Name):
                font = fonts.get(str(fname), _DEFAULT_FONT)
        elif op in (b"Tj", b"'") and stack \
                and isinstance(stack[-1], bytes):
            out.append(font.decode(stack[-1]))
        elif op == b'"' and stack and isinstance(stack[-1], bytes):
            out.append(font.decode(stack[-1]))
        elif op == b"TJ" and stack and isinstance(stack[-1], list):
            for item in stack[-1]:
                if isinstance(item, bytes):
                    out.append(font.decode(item))
                elif isinstance(item, (int, float)) and item < -150:
                    out.append(" ")      # large negative kern = word gap
        elif op in (b"Td", b"TD", b"T*", b"ET"):
            out.append("\n")
        if op not in (b"BT",):
            stack.clear()
    text = "".join(out)
    return re.sub(r"[ \t]+", " ", re.sub(r"\n{2,}", "\n", text)).strip()


def _collect_pages(pdf: _Pdf) -> list[dict]:
    return [entry[1] for entry in pdf.objects.values()
            if isinstance(entry[1], dict)
            and pdf.resolve(entry[1].get("Type")) == "Page"]


def _page_fonts(pdf: _Pdf, page: dict) -> dict[str, _Font]:
    res = pdf.resolve(page.get("Resources")) or {}
    fontd = pdf.resolve(res.get("Font")) if isinstance(res, dict) else {}
    fonts: dict[str, _Font] = {}
    if isinstance(fontd, dict):
        for name, ref in fontd.items():
            fd = pdf.resolve(ref)
            if isinstance(fd, dict):
                # Type0 fonts hold ToUnicode at the top; descendant fonts
                # add nothing text-wise
                fonts[name] = _Font(pdf, fd)
    return fonts


def parse_pdf(url: str, content: bytes, charset=None) -> list[Document]:
    """Extract text + metadata from a PDF (pdfParser.java parity point)."""
    if not content.lstrip()[:5].startswith(b"%PDF"):
        raise ParserError("not a pdf")
    pdf = _Pdf(content)

    # encrypted documents: declared degradation (no RC4/AES here).
    # /Encrypt lives in the trailer dict for classic xref-table PDFs and
    # in the XRef stream dict for 1.5+ files — check both.
    encrypted = re.search(rb"trailer\b(?:(?!startxref).){0,2048}?/Encrypt",
                          content, re.DOTALL) is not None
    if not encrypted:
        for entry in pdf.objects.values():
            d = entry[1]
            if isinstance(d, dict) and "Encrypt" in d:
                encrypted = True
                break
    if encrypted:
        return [Document(url=url, mime_type="application/pdf",
                         text="", doctype=DT_PDF)]

    texts: list[str] = []
    for page in _collect_pages(pdf):
        fonts = _page_fonts(pdf, page)
        contents = page.get("Contents")
        streams: list[bytes] = []
        resolved = pdf.resolve(contents)
        if isinstance(resolved, list):
            for ref in resolved:
                s = pdf.stream_of(ref)
                if s:
                    streams.append(s)
        else:
            s = pdf.stream_of(contents)
            if s:
                streams.append(s)
        for s in streams:
            t = _page_text(pdf, s, fonts)
            if t:
                texts.append(t)

    if not texts:
        # degenerate PDFs without a /Page tree: scan every decodable
        # stream that looks like a content stream (BT..ET text blocks)
        for num, (_b, d, raw) in pdf.objects.items():
            if raw is None:
                continue
            data = pdf._decode_stream(d if isinstance(d, dict) else {}, raw)
            if data and b"BT" in data and (b"Tj" in data or b"TJ" in data):
                t = _page_text(pdf, data, {})
                if t:
                    texts.append(t)

    # metadata from /Info
    title = author = subject = keywords = ""
    for entry in pdf.objects.values():
        d = entry[1]
        # outline (bookmark) items also carry /Title but have tree links
        # (/Parent /Next /First) — they must not clobber the /Info dict
        if isinstance(d, dict) and ("Title" in d or "Author" in d) \
                and "Type" not in d and "Subtype" not in d \
                and not ({"Parent", "Next", "First", "Prev", "Dest"} & d.keys()):
            title = _info_str(pdf.resolve(d.get("Title"))) or title
            author = _info_str(pdf.resolve(d.get("Author"))) or author
            subject = _info_str(pdf.resolve(d.get("Subject"))) or subject
            keywords = _info_str(pdf.resolve(d.get("Keywords"))) or keywords

    return [Document(
        url=url, mime_type="application/pdf", title=title, author=author,
        description=subject,
        keywords=[k for k in re.split(r"[,;]\s*", keywords) if k],
        text="\n".join(texts), doctype=DT_PDF)]


def _info_str(v) -> str:
    if isinstance(v, bytes):
        if v.startswith(b"\xfe\xff"):
            return v.decode("utf-16-be", "replace").lstrip("﻿").strip()
        return v.decode("latin-1", "replace").strip()
    return ""
