"""PDF parser — pure-Python text extraction for Flate/plain streams.

Capability equivalent of the reference's pdfParser (reference:
source/net/yacy/document/parser/pdfParser.java, which delegates to
pdfbox). No PDF library is baked into this image, so this is a minimal
but real extractor: it walks PDF objects, inflates FlateDecode content
streams, tokenizes text operators (Tj, TJ, '), unescapes PDF string
literals, and pulls /Title /Author /Subject from the Info dictionary.
Covers the common simple-generator PDFs (the fixture corpus); exotic
encodings (CID fonts, encryption) degrade to empty text rather than
erroring.
"""

from __future__ import annotations

import re
import zlib

from ..document import Document

_STREAM_RE = re.compile(rb"stream\r?\n(.*?)\r?\nendstream", re.DOTALL)
_INFO_FIELD_RE = {
    "title": re.compile(rb"/Title\s*\((.*?)(?<!\\)\)", re.DOTALL),
    "author": re.compile(rb"/Author\s*\((.*?)(?<!\\)\)", re.DOTALL),
    "subject": re.compile(rb"/Subject\s*\((.*?)(?<!\\)\)", re.DOTALL),
}
# text-showing operators inside BT..ET blocks
_TJ_RE = re.compile(rb"\((?:\\.|[^()\\])*\)\s*(?:Tj|')", re.DOTALL)
_TJ_ARRAY_RE = re.compile(rb"\[((?:[^\[\]\\]|\\.)*?)\]\s*TJ", re.DOTALL)
_STR_RE = re.compile(rb"\((?:\\.|[^()\\])*\)", re.DOTALL)

_ESCAPES = {b"n": b"\n", b"r": b"\r", b"t": b"\t", b"b": b"\b",
            b"f": b"\f", b"(": b"(", b")": b")", b"\\": b"\\"}


def _unescape(raw: bytes) -> bytes:
    out = bytearray()
    i = 0
    while i < len(raw):
        c = raw[i:i + 1]
        if c == b"\\" and i + 1 < len(raw):
            nxt = raw[i + 1:i + 2]
            if nxt in _ESCAPES:
                out += _ESCAPES[nxt]
                i += 2
                continue
            if nxt.isdigit():   # octal escape
                j = i + 1
                while j < len(raw) and j < i + 4 and raw[j:j + 1].isdigit():
                    j += 1
                try:
                    out.append(int(raw[i + 1:j], 8) & 0xFF)
                except ValueError:
                    pass
                i = j
                continue
            i += 2
            continue
        out += c
        i += 1
    return bytes(out)


def _decode_pdf_text(raw: bytes) -> str:
    if raw.startswith(b"\xfe\xff"):
        try:
            return raw[2:].decode("utf-16-be", "replace")
        except Exception:
            pass
    return raw.decode("latin-1", "replace")


def _extract_strings(stream: bytes) -> list[str]:
    texts: list[str] = []
    for m in _TJ_RE.finditer(stream):
        s = _STR_RE.match(m.group(0))
        if s:
            texts.append(_decode_pdf_text(_unescape(s.group(0)[1:-1])))
    for m in _TJ_ARRAY_RE.finditer(stream):
        parts = [_decode_pdf_text(_unescape(s.group(0)[1:-1]))
                 for s in _STR_RE.finditer(m.group(1))]
        texts.append("".join(parts))
    return texts


def parse_pdf(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    texts: list[str] = []
    for m in _STREAM_RE.finditer(content):
        data = m.group(1)
        # try inflate; fall back to treating it as a plain content stream
        for candidate in (data,):
            try:
                inflated = zlib.decompress(candidate)
            except zlib.error:
                inflated = candidate
            if b"Tj" in inflated or b"TJ" in inflated:
                texts.extend(_extract_strings(inflated))

    meta = {}
    for key, rx in _INFO_FIELD_RE.items():
        m = rx.search(content)
        if m:
            meta[key] = _decode_pdf_text(_unescape(m.group(1))).strip()

    text = " ".join(t for t in texts if t.strip())
    return [Document(url=url, mime_type="application/pdf",
                     title=meta.get("title", "") or text[:120],
                     author=meta.get("author", ""),
                     description=meta.get("subject", ""),
                     text=text)]
