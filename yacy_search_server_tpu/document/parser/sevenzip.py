"""7z archive reader — pure Python over the lzma module's raw decoders.

Capability equivalent of the reference's sevenzipParser (reference:
source/net/yacy/document/parser/sevenzipParser.java via the bundled
J7Zip java port). The container format ([7zFormat.txt]) is parsed
directly: signature + start header, (possibly LZMA-compressed) metadata
header, pack/unpack stream info, folders with a single coder each, and
file names from the FilesInfo block. Supported coders: Copy, LZMA1,
LZMA2 — which covers archives produced by default 7z/p7zip settings.
Multi-coder chains (BCJ2, delta, AES) raise ParserError (declared
degradation; the reference's java port had similar limits)."""

from __future__ import annotations

import io
import lzma
import struct

from .errors import ParserError

_MAGIC = b"7z\xbc\xaf\x27\x1c"

# hard ceiling on any single folder's declared unpack size; crawled
# archives are untrusted and the declared size is what we allocate
MAX_UNPACK_SIZE = 1 << 28          # 256 MB

# property ids
K_END = 0x00
K_HEADER = 0x01
K_MAIN_STREAMS = 0x04
K_FILES_INFO = 0x05
K_PACK_INFO = 0x06
K_UNPACK_INFO = 0x07
K_SUBSTREAMS = 0x08
K_SIZE = 0x09
K_CRC = 0x0A
K_FOLDER = 0x0B
K_UNPACK_SIZE = 0x0C
K_NUM_UNPACK_STREAM = 0x0D
K_EMPTY_STREAM = 0x0E
K_EMPTY_FILE = 0x0F
K_NAME = 0x11
K_ENCODED_HEADER = 0x17
K_DUMMY = 0x19


class _Reader:
    def __init__(self, data: bytes):
        self.d = data
        self.pos = 0

    def byte(self) -> int:
        b = self.d[self.pos]
        self.pos += 1
        return b

    def bytes(self, n: int) -> bytes:
        out = self.d[self.pos:self.pos + n]
        self.pos += n
        return out

    def number(self) -> int:
        """7z variable-length number."""
        first = self.byte()
        mask = 0x80
        value = 0
        for i in range(8):
            if not (first & mask):
                value |= (first & (mask - 1)) << (8 * i)
                return value
            value |= self.byte() << (8 * i)
            mask >>= 1
        return value

    def bits(self, n: int) -> list[bool]:
        out: list[bool] = []
        b = 0
        mask = 0
        for _ in range(n):
            if mask == 0:
                b = self.byte()
                mask = 0x80
            out.append(bool(b & mask))
            mask >>= 1
        return out

    def bool_vector(self, n: int) -> list[bool]:
        all_defined = self.byte()
        return [True] * n if all_defined else self.bits(n)


class _Folder:
    def __init__(self):
        self.coder_id = b""
        self.props = b""
        self.unpack_sizes: list[int] = []
        self.num_unpack_streams = 1

    @property
    def unpack_size(self) -> int:
        return self.unpack_sizes[-1] if self.unpack_sizes else 0

    def decode(self, packed: bytes) -> bytes:
        cid = self.coder_id
        # the unpack size is attacker-declared archive metadata: a tiny
        # crawled .7z may claim a multi-GB output (decompression bomb) —
        # cap it before allocating anything
        if self.unpack_size > MAX_UNPACK_SIZE:
            raise ParserError(
                f"7z: declared unpack size {self.unpack_size} exceeds "
                f"limit {MAX_UNPACK_SIZE}")
        if cid == b"\x00":                 # Copy
            return packed[:self.unpack_size]
        if cid == b"\x21":                 # LZMA2
            dec = lzma.LZMADecompressor(
                format=lzma.FORMAT_RAW,
                filters=[{"id": lzma.FILTER_LZMA2,
                          "dict_size": _lzma2_dict(self.props)}])
            return dec.decompress(packed, self.unpack_size)
        if cid == b"\x03\x01\x01":         # LZMA1
            if len(self.props) < 5:
                raise ParserError("7z: bad lzma props")
            prop = self.props[0]
            lc, rem = prop % 9, prop // 9
            lp, pb = rem % 5, rem // 5
            dict_size = struct.unpack("<I", self.props[1:5])[0]
            dec = lzma.LZMADecompressor(
                format=lzma.FORMAT_RAW,
                filters=[{"id": lzma.FILTER_LZMA1, "lc": lc, "lp": lp,
                          "pb": pb, "dict_size": max(dict_size, 4096)}])
            return dec.decompress(packed, self.unpack_size)
        raise ParserError(f"7z: unsupported coder {cid.hex()}")


def _lzma2_dict(props: bytes) -> int:
    if not props:
        return 1 << 24
    v = props[0]
    if v > 40:
        return 1 << 26
    if v == 40:
        return 0xFFFFFFFF
    return (2 | (v & 1)) << (v // 2 + 11)


class SevenZip:
    """Parsed archive: .files is a list of (name, data)."""

    def __init__(self, data: bytes):
        if not data.startswith(_MAGIC):
            raise ParserError("not a 7z archive")
        next_off, next_size = struct.unpack_from("<QQ", data, 12)
        header = data[32 + next_off:32 + next_off + next_size]
        if not header:
            raise ParserError("7z: empty header")
        self.data = data
        r = _Reader(header)
        tid = r.byte()
        if tid == K_ENCODED_HEADER:
            header = self._decode_encoded_header(r)
            r = _Reader(header)
            tid = r.byte()
        if tid != K_HEADER:
            raise ParserError("7z: no header")
        self.files: list[tuple[str, bytes]] = []
        self._parse_header(r)

    # -- metadata parsing ----------------------------------------------------

    def _read_streams_info(self, r: _Reader):
        pack_pos = 0
        pack_sizes: list[int] = []
        folders: list[_Folder] = []
        substream_counts: list[int] = []
        substream_sizes: list[int] = []
        while True:
            tid = r.byte()
            if tid == K_END:
                break
            if tid == K_PACK_INFO:
                pack_pos = r.number()
                num_pack = r.number()
                while True:
                    sub = r.byte()
                    if sub == K_END:
                        break
                    if sub == K_SIZE:
                        pack_sizes = [r.number() for _ in range(num_pack)]
                    elif sub == K_CRC:
                        defined = r.bool_vector(num_pack)
                        r.bytes(4 * sum(defined))
                    else:
                        raise ParserError("7z: bad packinfo")
            elif tid == K_UNPACK_INFO:
                if r.byte() != K_FOLDER:
                    raise ParserError("7z: expected folder")
                num_folders = r.number()
                external = r.byte()
                if external:
                    raise ParserError("7z: external folders unsupported")
                for _ in range(num_folders):
                    folders.append(self._read_folder(r))
                if r.byte() != K_UNPACK_SIZE:
                    raise ParserError("7z: expected unpack sizes")
                for f in folders:
                    f.unpack_sizes = [r.number()
                                      for _ in range(f._num_out_streams)]
                while True:
                    sub = r.byte()
                    if sub == K_END:
                        break
                    if sub == K_CRC:
                        defined = r.bool_vector(num_folders)
                        r.bytes(4 * sum(defined))
            elif tid == K_SUBSTREAMS:
                while True:
                    sub = r.byte()
                    if sub == K_END:
                        break
                    if sub == K_NUM_UNPACK_STREAM:
                        substream_counts = [r.number() for _ in folders]
                    elif sub == K_SIZE:
                        for i, f in enumerate(folders):
                            n = (substream_counts[i]
                                 if substream_counts else 1)
                            sizes = [r.number() for _ in range(n - 1)]
                            sizes.append(f.unpack_size - sum(sizes))
                            substream_sizes.extend(sizes)
                    elif sub == K_CRC:
                        total = (sum(substream_counts)
                                 if substream_counts else len(folders))
                        defined = r.bool_vector(total)
                        r.bytes(4 * sum(defined))
            else:
                raise ParserError(f"7z: unexpected id {tid}")
        return pack_pos, pack_sizes, folders, substream_counts, \
            substream_sizes

    def _read_folder(self, r: _Reader) -> _Folder:
        f = _Folder()
        num_coders = r.number()
        if num_coders != 1:
            raise ParserError("7z: multi-coder folders unsupported")
        flags = r.byte()
        id_size = flags & 0x0F
        f.coder_id = r.bytes(id_size)
        f._num_out_streams = 1
        if flags & 0x10:     # complex coder
            raise ParserError("7z: complex coders unsupported")
        if flags & 0x20:     # attributes
            psize = r.number()
            f.props = r.bytes(psize)
        return f

    def _decode_encoded_header(self, r: _Reader) -> bytes:
        (pack_pos, pack_sizes, folders,
         _counts, _sizes) = self._read_streams_info(r)
        if not folders or not pack_sizes:
            raise ParserError("7z: bad encoded header")
        off = 32 + pack_pos
        packed = self.data[off:off + pack_sizes[0]]
        return folders[0].decode(packed)

    def _parse_header(self, r: _Reader) -> None:
        pack_pos = 0
        pack_sizes: list[int] = []
        folders: list[_Folder] = []
        counts: list[int] = []
        sizes: list[int] = []
        names: list[str] = []
        empty_streams: list[bool] = []
        while r.pos < len(r.d):
            tid = r.byte()
            if tid == K_END:
                break
            if tid == K_MAIN_STREAMS:
                (pack_pos, pack_sizes, folders,
                 counts, sizes) = self._read_streams_info(r)
            elif tid == K_FILES_INFO:
                num_files = r.number()
                while True:
                    ptype = r.byte()
                    if ptype == K_END:
                        break
                    psize = r.number()
                    payload = _Reader(r.bytes(psize))
                    if ptype == K_NAME:
                        ext = payload.byte()
                        if not ext:
                            raw = payload.d[payload.pos:]
                            names = [n for n in
                                     raw.decode("utf-16-le", "replace")
                                     .split("\0") if n]
                    elif ptype == K_EMPTY_STREAM:
                        empty_streams = payload.bits(num_files)
            else:
                # skip unknown top-level block
                psize = r.number()
                r.bytes(psize)

        # decode folders into one contiguous unpacked pool
        pool = io.BytesIO()
        off = 32 + pack_pos
        for i, f in enumerate(folders):
            size = pack_sizes[i] if i < len(pack_sizes) else 0
            pool.write(f.decode(self.data[off:off + size]))
            off += size
        blob = pool.getvalue()

        # split into substreams and pair with non-empty-stream names
        if not sizes:
            sizes = [f.unpack_size for f in folders]
        content_names = [n for j, n in enumerate(names)
                         if not (empty_streams and j < len(empty_streams)
                                 and empty_streams[j])]
        pos = 0
        for i, size in enumerate(sizes):
            name = content_names[i] if i < len(content_names) \
                else f"member{i}"
            self.files.append((name, blob[pos:pos + size]))
            pos += size
