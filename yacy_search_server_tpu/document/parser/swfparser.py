"""SWF (Flash) parser — text and link extraction from the tag stream.

Capability equivalent of the reference's swfParser (reference:
source/net/yacy/document/parser/swfParser.java, which delegates to
javaswf's SWF2HTML). Built from the SWF file format spec instead:

- header: ``FWS`` (uncompressed), ``CWS`` (zlib, SWF>=6) or ``ZWS``
  (LZMA, SWF>=13) + version byte + uncompressed length
- a RECT (variable-width bit field) + frame rate/count, then TAGS:
  16-bit code<<6|length headers (length 0x3F = extended 32-bit)
- text sources: DefineEditText (tag 37) carries its initial text
  inline; the ActionScript ConstantPool (action 0x88) and GetURL
  (action 0x83) inside DoAction/DoInitAction/PlaceObject2 clips carry
  string constants and target URLs.

Glyph-indexed DefineText spans are intentionally out of scope (they
need font cmap reconstruction); DefineEditText + constant pools cover
the text Flash sites actually carried.
"""

from __future__ import annotations

import lzma
import struct
import zlib

from ..document import Document
from .errors import ParserError

MAX_DECOMPRESSED = 1 << 26      # 64 MB — crawled archives are untrusted

TAG_DO_ACTION = 12
TAG_DEFINE_EDIT_TEXT = 37
TAG_DO_INIT_ACTION = 59

ACTION_GETURL = 0x83
ACTION_CONSTANT_POOL = 0x88


def _decompress(data: bytes) -> bytes:
    sig = data[:3]
    if sig == b"FWS":
        return data[8:]
    if sig == b"CWS":
        try:
            out = zlib.decompressobj().decompress(data[8:],
                                                  MAX_DECOMPRESSED + 1)
        except zlib.error as e:
            raise ParserError(f"swf: bad zlib body: {e}")
    elif sig == b"ZWS":
        # ZWS carries a 4-byte compressed-size field, then a raw LZMA
        # stream with a 5-byte props header
        if len(data) < 18:
            raise ParserError("swf: truncated ZWS header")
        body = data[17:]
        props = data[12:17]
        lc = props[0] % 9
        rem = props[0] // 9
        lp, pb = rem % 5, rem // 5
        dict_size = struct.unpack("<I", props[1:5])[0]
        try:
            dec = lzma.LZMADecompressor(
                format=lzma.FORMAT_RAW,
                filters=[{"id": lzma.FILTER_LZMA1, "lc": lc, "lp": lp,
                          "pb": pb, "dict_size": max(dict_size, 4096)}])
            out = dec.decompress(body, MAX_DECOMPRESSED + 1)
        except lzma.LZMAError as e:
            raise ParserError(f"swf: bad lzma body: {e}")
    else:
        raise ParserError("not a swf file")
    if len(out) > MAX_DECOMPRESSED:
        raise ParserError("swf: decompressed body exceeds limit")
    return out


def _skip_rect(body: bytes, off: int) -> int:
    if off >= len(body):
        return off
    nbits = body[off] >> 3
    total_bits = 5 + 4 * nbits
    return off + (total_bits + 7) // 8


def _iter_tags(body: bytes, off: int):
    n = len(body)
    while off + 2 <= n:
        code_len = struct.unpack_from("<H", body, off)[0]
        off += 2
        code = code_len >> 6
        length = code_len & 0x3F
        if length == 0x3F:
            if off + 4 > n:
                return
            length = struct.unpack_from("<I", body, off)[0]
            off += 4
        if length > n - off:
            length = n - off
        yield code, body[off:off + length]
        off += length
        if code == 0:           # End tag
            return


def _cstring(buf: bytes, off: int) -> tuple[str, int]:
    end = buf.find(b"\0", off)
    if end < 0:
        end = len(buf)
    return buf[off:end].decode("utf-8", "replace"), end + 1


def _edit_text(payload: bytes) -> str:
    """DefineEditText: flags select which optional fields precede the
    variable name and the optional InitialText."""
    off = 2                     # CharacterID
    off = _skip_rect(payload, off)
    if off + 2 > len(payload):
        return ""
    # the two flag bytes are a BIT STREAM, MSB-first per byte (not a
    # little-endian word): byte0 = HasText|WordWrap|Multiline|Password|
    # ReadOnly|HasTextColor|HasMaxLength|HasFont, byte1 = HasFontClass|
    # AutoSize|HasLayout|NoSelect|Border|WasStatic|HTML|UseOutlines
    b0, b1 = payload[off], payload[off + 1]
    off += 2
    has_text = b0 & 0x80
    has_font = b0 & 0x01
    has_max_length = b0 & 0x02
    has_text_color = b0 & 0x04
    has_font_class = b1 & 0x80
    has_layout = b1 & 0x20
    if has_font:
        off += 2                # FontID
    if has_font_class:
        _, off = _cstring(payload, off)
    if has_font:
        off += 2                # FontHeight
    if has_text_color:
        off += 4                # RGBA
    if has_max_length:
        off += 2
    if has_layout:
        off += 9                # align + margins + indent + leading
    _, off = _cstring(payload, off)     # VariableName
    if has_text and off <= len(payload):
        text, _ = _cstring(payload, off)
        return text
    return ""


def _actions(payload: bytes) -> tuple[list[str], list[str]]:
    """(strings, urls) from an action block (ConstantPool + GetURL)."""
    strings: list[str] = []
    urls: list[str] = []
    off = 0
    n = len(payload)
    while off < n:
        code = payload[off]
        off += 1
        if code == 0:
            break
        length = 0
        if code >= 0x80:
            if off + 2 > n:
                break
            length = struct.unpack_from("<H", payload, off)[0]
            off += 2
        data = payload[off:off + length]
        off += length
        if code == ACTION_CONSTANT_POOL and len(data) >= 2:
            count = struct.unpack_from("<H", data, 0)[0]
            p = 2
            for _ in range(count):
                if p >= len(data):
                    break
                s, p = _cstring(data, p)
                if s:
                    strings.append(s)
        elif code == ACTION_GETURL:
            url, p = _cstring(data, 0)
            if url and not url.lower().startswith("fscommand:"):
                urls.append(url)
    return strings, urls


def parse_swf(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    body = _decompress(content)
    off = _skip_rect(body, 0)
    off += 4                    # frame rate (fixed8.8) + frame count
    texts: list[str] = []
    links: list[str] = []
    for code, payload in _iter_tags(body, off):
        try:
            if code == TAG_DEFINE_EDIT_TEXT:
                t = _edit_text(payload)
                if t:
                    texts.append(t)
            elif code == TAG_DO_ACTION:
                strings, urls = _actions(payload)
                texts.extend(s for s in strings
                             if not s.startswith(("http://", "https://")))
                links.extend(s for s in strings
                             if s.startswith(("http://", "https://")))
                links.extend(urls)
            elif code == TAG_DO_INIT_ACTION and len(payload) > 2:
                strings, urls = _actions(payload[2:])
                texts.extend(s for s in strings
                             if not s.startswith(("http://", "https://")))
                links.extend(s for s in strings
                             if s.startswith(("http://", "https://")))
                links.extend(urls)
        except (struct.error, IndexError):
            continue            # salvage the rest of the tag stream
    from ..document import Anchor
    doc = Document(
        url=url, mime_type="application/x-shockwave-flash",
        title=url.rsplit("/", 1)[-1],
        text="\n".join(texts),
        anchors=[Anchor(u) for u in dict.fromkeys(links)
                 if u.startswith(("http://", "https://"))])
    return [doc]
