"""Media parsers — image metadata, audio tags, torrent files.

Capability equivalents of the reference's media parser set (reference:
source/net/yacy/document/parser/genericImageParser.java — image metadata
via metadata-extractor; audioTagParser.java — ID3/tag parsing via jaudiotagger;
torrentParser.java — bencoded metainfo).  Implemented natively against the
container formats: PNG/GIF/JPEG headers for dimensions plus PNG tEXt and
JPEG EXIF/comment extraction, ID3v1/ID3v2 frames for audio, and a full
bencode decoder for torrents.
"""

from __future__ import annotations

import re
import struct

from ..document import DT_AUDIO, DT_IMAGE, Document
from .errors import ParserError


# -- images ------------------------------------------------------------------

def _png_info(content: bytes) -> tuple[int, int, dict]:
    w, h = struct.unpack(">II", content[16:24])
    texts: dict[str, str] = {}
    off = 8
    while off + 8 <= len(content):
        (length,), ctype = struct.unpack(">I", content[off:off + 4]), \
            content[off + 4:off + 8]
        if ctype == b"tEXt":
            data = content[off + 8:off + 8 + length]
            key, _, val = data.partition(b"\x00")
            texts[key.decode("latin-1", "replace")] = \
                val.decode("latin-1", "replace")
        off += 12 + length
        if ctype == b"IEND":
            break
    return w, h, texts


def _jpeg_info(content: bytes) -> tuple[int, int, dict]:
    w = h = 0
    texts: dict[str, str] = {}
    off = 2
    while off + 4 <= len(content):
        if content[off] != 0xFF:
            off += 1
            continue
        marker = content[off + 1]
        if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
            off += 2
            continue
        if off + 4 > len(content):
            break
        (seglen,) = struct.unpack(">H", content[off + 2:off + 4])
        seg = content[off + 4:off + 2 + seglen]
        if marker in (0xC0, 0xC1, 0xC2, 0xC3):        # SOF
            h, w = struct.unpack(">HH", seg[1:5])
            break
        if marker == 0xFE:                             # comment
            texts["comment"] = seg.decode("latin-1", "replace").strip("\x00")
        off += 2 + seglen
    return w, h, texts


def _gif_info(content: bytes) -> tuple[int, int, dict]:
    w, h = struct.unpack("<HH", content[6:10])
    return w, h, {}


def parse_image(url: str, content: bytes,
                charset: str | None = None) -> list[Document]:
    if content.startswith(b"\x89PNG\r\n\x1a\n"):
        w, h, texts = _png_info(content)
        mime = "image/png"
    elif content.startswith(b"\xff\xd8"):
        w, h, texts = _jpeg_info(content)
        mime = "image/jpeg"
    elif content[:6] in (b"GIF87a", b"GIF89a"):
        w, h, texts = _gif_info(content)
        mime = "image/gif"
    else:
        raise ParserError("unrecognized image container")
    name = url.rsplit("/", 1)[-1]
    parts = [name, f"{w}x{h}"] + [f"{k}: {v}" for k, v in texts.items()]
    return [Document(url=url, mime_type=mime, title=name,
                     text="\n".join(parts), doctype=DT_IMAGE)]


# -- audio (ID3) -------------------------------------------------------------

_ID3V2_TEXT_FRAMES = {
    b"TIT2": "title", b"TPE1": "artist", b"TALB": "album",
    b"TYER": "year", b"TDRC": "year", b"TCON": "genre", b"COMM": "comment",
}


def _id3v2(content: bytes) -> dict:
    if not content.startswith(b"ID3"):
        return {}
    size = ((content[6] & 0x7F) << 21 | (content[7] & 0x7F) << 14
            | (content[8] & 0x7F) << 7 | (content[9] & 0x7F))
    out: dict[str, str] = {}
    off = 10
    end = min(10 + size, len(content))
    while off + 10 <= end:
        fid = content[off:off + 4]
        (flen,) = struct.unpack(">I", content[off + 4:off + 8])
        if flen == 0 or not fid.strip(b"\x00"):
            break
        data = content[off + 10:off + 10 + flen]
        key = _ID3V2_TEXT_FRAMES.get(fid)
        if key and data:
            enc, body = data[0], data[1:]
            try:
                if enc == 1:
                    val = body.decode("utf-16", "replace")
                elif enc == 3:
                    val = body.decode("utf-8", "replace")
                else:
                    val = body.decode("latin-1", "replace")
            except Exception:
                val = ""
            out.setdefault(key, val.strip("\x00").strip())
        off += 10 + flen
    return out


def _id3v1(content: bytes) -> dict:
    tag = content[-128:]
    if not tag.startswith(b"TAG"):
        return {}
    def fld(a, b):
        return tag[a:b].decode("latin-1", "replace").strip("\x00").strip()
    return {k: v for k, v in (
        ("title", fld(3, 33)), ("artist", fld(33, 63)),
        ("album", fld(63, 93)), ("year", fld(93, 97))) if v}


def parse_audio(url: str, content: bytes,
                charset: str | None = None) -> list[Document]:
    tags = _id3v2(content)
    for k, v in _id3v1(content).items():
        tags.setdefault(k, v)
    if not tags:
        raise ParserError("no audio tags found")
    name = url.rsplit("/", 1)[-1]
    title = tags.get("title") or name
    text = "\n".join(f"{k}: {v}" for k, v in tags.items())
    return [Document(url=url, mime_type="audio/mpeg", title=title,
                     author=tags.get("artist", ""), text=text,
                     doctype=DT_AUDIO)]


# -- torrent -----------------------------------------------------------------

def bdecode(data: bytes, off: int = 0):
    """Full bencode decoder (torrentParser.java equivalent)."""
    c = data[off:off + 1]
    if c == b"i":
        end = data.index(b"e", off)
        return int(data[off + 1:end]), end + 1
    if c == b"l":
        out, off = [], off + 1
        while data[off:off + 1] != b"e":
            v, off = bdecode(data, off)
            out.append(v)
        return out, off + 1
    if c == b"d":
        out, off = {}, off + 1
        while data[off:off + 1] != b"e":
            k, off = bdecode(data, off)
            v, off = bdecode(data, off)
            out[k] = v
        return out, off + 1
    if c.isdigit():
        colon = data.index(b":", off)
        n = int(data[off:colon])
        return data[colon + 1:colon + 1 + n], colon + 1 + n
    raise ParserError(f"bad bencode at {off}")


def parse_torrent(url: str, content: bytes,
                  charset: str | None = None) -> list[Document]:
    try:
        meta, _ = bdecode(content)
    except (ValueError, IndexError, ParserError) as e:
        raise ParserError(f"bad torrent: {e}") from e
    if not isinstance(meta, dict):
        raise ParserError("torrent metainfo is not a dict")
    def s(b):
        return b.decode("utf-8", "replace") if isinstance(b, bytes) else str(b)
    info = meta.get(b"info", {})
    name = s(info.get(b"name", b""))
    files = info.get(b"files", [])
    paths = []
    for f in files if isinstance(files, list) else []:
        segs = f.get(b"path", []) if isinstance(f, dict) else []
        paths.append("/".join(s(p) for p in segs))
    words = [name, s(meta.get(b"comment", b""))] + paths
    text = "\n".join(re.sub(r"[._\-]", " ", w) for w in words if w)
    if not text.strip():
        raise ParserError("empty torrent metainfo")
    return [Document(url=url, mime_type="application/x-bittorrent",
                     title=name or "torrent", text=text)]
