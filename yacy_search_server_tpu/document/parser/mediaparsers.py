"""Media parsers — image metadata, audio tags, torrent files.

Capability equivalents of the reference's media parser set (reference:
source/net/yacy/document/parser/genericImageParser.java — image metadata
via metadata-extractor; audioTagParser.java — ID3/tag parsing via jaudiotagger;
torrentParser.java — bencoded metainfo).  Implemented natively against the
container formats: PNG/GIF/JPEG headers for dimensions plus PNG tEXt and
JPEG EXIF/comment extraction, ID3v1/ID3v2 frames for audio, and a full
bencode decoder for torrents.
"""

from __future__ import annotations

import re
import struct

from ..document import DT_AUDIO, DT_IMAGE, Document
from .errors import ParserError


# -- images ------------------------------------------------------------------

def _text8(raw: bytes) -> str:
    """8-bit text decode (shared utf-8 → MacRoman-heuristic → latin-1
    cascade; see textparsers.decode8)."""
    from .textparsers import decode8
    return decode8(raw)


def _png_info(content: bytes) -> tuple[int, int, dict]:
    w, h = struct.unpack(">II", content[16:24])
    texts: dict[str, str] = {}
    off = 8
    while off + 8 <= len(content):
        (length,), ctype = struct.unpack(">I", content[off:off + 4]), \
            content[off + 4:off + 8]
        if ctype == b"tEXt":
            data = content[off + 8:off + 8 + length]
            key, _, val = data.partition(b"\x00")
            texts[key.decode("latin-1", "replace")] = _text8(val)
        off += 12 + length
        if ctype == b"IEND":
            break
    return w, h, texts


def _jpeg_info(content: bytes) -> tuple[int, int, dict]:
    w = h = 0
    texts: dict[str, str] = {}
    off = 2
    while off + 4 <= len(content):
        if content[off] != 0xFF:
            off += 1
            continue
        marker = content[off + 1]
        if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
            off += 2
            continue
        if off + 4 > len(content):
            break
        (seglen,) = struct.unpack(">H", content[off + 2:off + 4])
        seg = content[off + 4:off + 2 + seglen]
        if marker in (0xC0, 0xC1, 0xC2, 0xC3):        # SOF
            h, w = struct.unpack(">HH", seg[1:5])
            break
        if marker == 0xFE:                             # comment
            texts["comment"] = _text8(seg).strip("\x00")
        off += 2 + seglen
    return w, h, texts


def _gif_info(content: bytes) -> tuple[int, int, dict]:
    w, h = struct.unpack("<HH", content[6:10])
    return w, h, {}


# EXIF tag ids worth indexing (genericImageParser.java pulls the same
# set through metadata-extractor)
_EXIF_TAGS = {270: "description", 315: "artist", 306: "datetime",
              271: "make", 272: "model", 305: "software"}


def _exif_info(content: bytes) -> tuple[int, int, dict, float, float]:
    """Dimensions + EXIF text fields + GPS position via PIL (jpeg/tiff)."""
    import io

    from PIL import Image
    texts: dict[str, str] = {}
    lat = lon = 0.0
    with Image.open(io.BytesIO(content)) as im:
        w, h = im.size
        exif = im.getexif()
        for tag, field in _EXIF_TAGS.items():
            v = exif.get(tag)
            if v:
                texts[field] = str(v).strip()
        try:
            gps = exif.get_ifd(0x8825)      # GPS IFD
            if gps and 2 in gps and 4 in gps:
                def dms(v, ref, neg):
                    deg = float(v[0]) + float(v[1]) / 60 + float(v[2]) / 3600
                    return -deg if ref in neg else deg
                lat = dms(gps[2], gps.get(1, "N"), ("S",))
                lon = dms(gps[4], gps.get(3, "E"), ("W",))
        except Exception:
            import logging
            logging.getLogger("parser.exif").debug(
                "malformed EXIF block skipped", exc_info=True)
    return w, h, texts, lat, lon


def parse_image(url: str, content: bytes,
                charset: str | None = None) -> list[Document]:
    lat = lon = 0.0
    if content.startswith(b"\x89PNG\r\n\x1a\n"):
        w, h, texts = _png_info(content)
        mime = "image/png"
    elif content.startswith(b"\xff\xd8"):
        w, h, texts = _jpeg_info(content)
        mime = "image/jpeg"
        try:
            w2, h2, exif, lat, lon = _exif_info(content)
            w, h = w or w2, h or h2
            texts.update(exif)
        except Exception:
            import logging
            logging.getLogger("parser.exif").debug(
                "EXIF segment unreadable in JPEG", exc_info=True)
    elif content[:4] in (b"II*\x00", b"MM\x00*"):      # TIFF
        try:
            w, h, texts, lat, lon = _exif_info(content)
        except Exception as e:
            raise ParserError(f"bad tiff: {e}") from e
        mime = "image/tiff"
    elif content[:6] in (b"GIF87a", b"GIF89a"):
        w, h, texts = _gif_info(content)
        mime = "image/gif"
    else:
        raise ParserError("unrecognized image container")
    name = url.rsplit("/", 1)[-1]
    parts = [name, f"{w}x{h}"] + [f"{k}: {v}" for k, v in texts.items()]
    doc = Document(url=url, mime_type=mime,
                   title=texts.get("description", name) or name,
                   author=texts.get("artist", ""),
                   text="\n".join(parts), doctype=DT_IMAGE,
                   lat=lat, lon=lon)
    return [doc]


# -- audio (ID3) -------------------------------------------------------------

_ID3V2_TEXT_FRAMES = {
    b"TIT2": "title", b"TPE1": "artist", b"TALB": "album",
    b"TYER": "year", b"TDRC": "year", b"TCON": "genre", b"COMM": "comment",
}


def _id3v2(content: bytes) -> dict:
    if not content.startswith(b"ID3"):
        return {}
    size = ((content[6] & 0x7F) << 21 | (content[7] & 0x7F) << 14
            | (content[8] & 0x7F) << 7 | (content[9] & 0x7F))
    out: dict[str, str] = {}
    off = 10
    end = min(10 + size, len(content))
    while off + 10 <= end:
        fid = content[off:off + 4]
        (flen,) = struct.unpack(">I", content[off + 4:off + 8])
        if flen == 0 or not fid.strip(b"\x00"):
            break
        data = content[off + 10:off + 10 + flen]
        key = _ID3V2_TEXT_FRAMES.get(fid)
        if key and data:
            enc, body = data[0], data[1:]
            try:
                if enc == 1:
                    val = body.decode("utf-16", "replace")
                elif enc == 3:
                    val = body.decode("utf-8", "replace")
                else:
                    val = body.decode("latin-1", "replace")
            except Exception:
                val = ""
            out.setdefault(key, val.strip("\x00").strip())
        off += 10 + flen
    return out


def _id3v1(content: bytes) -> dict:
    tag = content[-128:]
    if not tag.startswith(b"TAG"):
        return {}
    def fld(a, b):
        return tag[a:b].decode("latin-1", "replace").strip("\x00").strip()
    return {k: v for k, v in (
        ("title", fld(3, 33)), ("artist", fld(33, 63)),
        ("album", fld(63, 93)), ("year", fld(93, 97))) if v}


_VORBIS_FIELDS = {"title": "title", "artist": "artist", "album": "album",
                  "date": "year", "genre": "genre", "comment": "comment",
                  "description": "comment"}


def _vorbis_comments(block: bytes) -> dict:
    """Vorbis comment structure (shared by Ogg Vorbis and FLAC)."""
    out: dict[str, str] = {}
    try:
        (vlen,) = struct.unpack_from("<I", block, 0)
        pos = 4 + vlen
        (n,) = struct.unpack_from("<I", block, pos)
        pos += 4
        for _ in range(min(n, 64)):
            (clen,) = struct.unpack_from("<I", block, pos)
            pos += 4
            entry = block[pos:pos + clen].decode("utf-8", "replace")
            pos += clen
            k, _, v = entry.partition("=")
            field = _VORBIS_FIELDS.get(k.lower())
            if field and v:
                out.setdefault(field, v.strip())
    except (struct.error, IndexError):
        pass
    return out


def _ogg_tags(content: bytes) -> dict:
    # the comment header packet starts with \x03vorbis (or OpusTags)
    for marker, skip in ((b"\x03vorbis", 7), (b"OpusTags", 8)):
        i = content.find(marker)
        if i >= 0:
            return _vorbis_comments(content[i + skip:])
    return {}


def _flac_tags(content: bytes) -> dict:
    if not content.startswith(b"fLaC"):
        return {}
    pos = 4
    while pos + 4 <= len(content):
        header = content[pos]
        btype, last = header & 0x7F, header & 0x80
        blen = int.from_bytes(content[pos + 1:pos + 4], "big")
        if btype == 4:          # VORBIS_COMMENT
            return _vorbis_comments(content[pos + 4:pos + 4 + blen])
        pos += 4 + blen
        if last:
            break
    return {}


_RIFF_INFO = {b"INAM": "title", b"IART": "artist", b"IPRD": "album",
              b"ICMT": "comment", b"ICRD": "year", b"IGNR": "genre"}


def _riff_tags(content: bytes) -> dict:
    """WAV LIST/INFO chunks (+ an embedded id3 chunk when present)."""
    out: dict[str, str] = {}
    pos = 12
    while pos + 8 <= len(content):
        cid = content[pos:pos + 4]
        (clen,) = struct.unpack_from("<I", content, pos + 4)
        data = content[pos + 8:pos + 8 + clen]
        if cid == b"LIST" and data[:4] == b"INFO":
            ipos = 4
            while ipos + 8 <= len(data):
                fid = data[ipos:ipos + 4]
                (flen,) = struct.unpack_from("<I", data, ipos + 4)
                field = _RIFF_INFO.get(fid)
                if field:
                    out[field] = data[ipos + 8:ipos + 8 + flen].split(
                        b"\0")[0].decode("utf-8", "replace").strip()
                ipos += 8 + flen + (flen & 1)
        elif cid in (b"id3 ", b"ID3 "):
            for k, v in _id3v2(data).items():
                out.setdefault(k, v)
        pos += 8 + clen + (clen & 1)
    return out


_AIFF_TEXT = {b"NAME": "title", b"AUTH": "artist", b"ANNO": "comment"}


def _aiff_tags(content: bytes) -> dict:
    out: dict[str, str] = {}
    pos = 12
    while pos + 8 <= len(content):
        cid = content[pos:pos + 4]
        (clen,) = struct.unpack_from(">I", content, pos + 4)
        data = content[pos + 8:pos + 8 + clen]
        field = _AIFF_TEXT.get(cid)
        if field:
            out[field] = data.decode("utf-8", "replace").strip("\0 ")
        elif cid in (b"ID3 ", b"id3 "):
            for k, v in _id3v2(data).items():
                out.setdefault(k, v)
        pos += 8 + clen + (clen & 1)
    return out


_MP4_ITEMS = {b"\xa9nam": "title", b"\xa9ART": "artist",
              b"\xa9alb": "album", b"\xa9day": "year",
              b"\xa9cmt": "comment", b"\xa9gen": "genre"}


def _mp4_tags(content: bytes) -> dict:
    """MP4/M4A ilst metadata (moov > udta > meta > ilst walk)."""
    out: dict[str, str] = {}

    def walk(data: bytes, path: tuple, depth: int = 0) -> None:
        if depth > 8:
            return
        pos = 0
        while pos + 8 <= len(data):
            (size,) = struct.unpack_from(">I", data, pos)
            btype = data[pos + 4:pos + 8]
            if size < 8:
                break
            body = data[pos + 8:pos + size]
            if btype in (b"moov", b"udta", b"ilst", b"trak"):
                walk(body, path + (btype,), depth + 1)
            elif btype == b"meta":
                walk(body[4:], path + (btype,), depth + 1)  # 4-byte version
            elif btype in _MP4_ITEMS and path and path[-1] == b"ilst":
                # contains a 'data' box: 8B header + 8B type/locale + value
                if body[4:8] == b"data" and len(body) > 16:
                    out[_MP4_ITEMS[btype]] = body[16:].decode(
                        "utf-8", "replace").strip("\0 ")
            pos += size
    walk(content, ())
    return out


def parse_audio(url: str, content: bytes,
                charset: str | None = None) -> list[Document]:
    """Tag extraction across the audio container zoo (reference:
    audioTagParser.java via jaudiotagger — mp3/ogg/flac/wav/aiff/m4a)."""
    mime = "audio/mpeg"
    if content.startswith(b"OggS"):
        tags = _ogg_tags(content)
        mime = "audio/ogg"
    elif content.startswith(b"fLaC"):
        tags = _flac_tags(content)
        mime = "audio/flac"
    elif content.startswith(b"RIFF") and content[8:12] == b"WAVE":
        tags = _riff_tags(content)
        mime = "audio/x-wav"
    elif content.startswith(b"FORM") and content[8:12] in (b"AIFF", b"AIFC"):
        tags = _aiff_tags(content)
        mime = "audio/x-aiff"
    elif content[4:8] == b"ftyp":
        tags = _mp4_tags(content)
        mime = "audio/mp4"
    else:
        tags = _id3v2(content)
        for k, v in _id3v1(content).items():
            tags.setdefault(k, v)
    if not tags:
        raise ParserError("no audio tags found")
    name = url.rsplit("/", 1)[-1]
    title = tags.get("title") or name
    text = "\n".join(f"{k}: {v}" for k, v in tags.items())
    return [Document(url=url, mime_type=mime, title=title,
                     author=tags.get("artist", ""), text=text,
                     doctype=DT_AUDIO)]


# -- torrent -----------------------------------------------------------------

def bdecode(data: bytes, off: int = 0):
    """Full bencode decoder (torrentParser.java equivalent)."""
    c = data[off:off + 1]
    if c == b"i":
        end = data.index(b"e", off)
        return int(data[off + 1:end]), end + 1
    if c == b"l":
        out, off = [], off + 1
        while data[off:off + 1] != b"e":
            v, off = bdecode(data, off)
            out.append(v)
        return out, off + 1
    if c == b"d":
        out, off = {}, off + 1
        while data[off:off + 1] != b"e":
            k, off = bdecode(data, off)
            v, off = bdecode(data, off)
            out[k] = v
        return out, off + 1
    if c.isdigit():
        colon = data.index(b":", off)
        n = int(data[off:colon])
        return data[colon + 1:colon + 1 + n], colon + 1 + n
    raise ParserError(f"bad bencode at {off}")


def parse_torrent(url: str, content: bytes,
                  charset: str | None = None) -> list[Document]:
    try:
        meta, _ = bdecode(content)
    except (ValueError, IndexError, ParserError) as e:
        raise ParserError(f"bad torrent: {e}") from e
    if not isinstance(meta, dict):
        raise ParserError("torrent metainfo is not a dict")
    def s(b):
        return b.decode("utf-8", "replace") if isinstance(b, bytes) else str(b)
    info = meta.get(b"info", {})
    name = s(info.get(b"name", b""))
    files = info.get(b"files", [])
    paths = []
    for f in files if isinstance(files, list) else []:
        segs = f.get(b"path", []) if isinstance(f, dict) else []
        paths.append("/".join(s(p) for p in segs))
    words = [name, s(meta.get(b"comment", b""))] + paths
    text = "\n".join(re.sub(r"[._\-]", " ", w) for w in words if w)
    if not text.strip():
        raise ParserError("empty torrent metainfo")
    return [Document(url=url, mime_type="application/x-bittorrent",
                     title=name or "torrent", text=text)]
