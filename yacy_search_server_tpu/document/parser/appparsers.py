"""Application-format parsers: Android APK, AutoCAD DWG, FreeMind MM,
Commodore SID.

Capability equivalents of the reference's four remaining registry
formats (reference: source/net/yacy/document/parser/apkParser.java —
unzips the package, decodes the BINARY AndroidManifest.xml for package/
version/permissions, indexes entry paths and the resources.arsc string
pool with URL extraction; dwgParser.java — version-gated CAD metadata
text; mmParser.java — SAX walk collecting every node TEXT attribute;
sidAudioParser.java — PSID/RSID header name/author/released fields).

The Android binary-XML (AXML) and resource-table (ARSC) decoders below
are written from the public Android `ResChunk` format: little-endian
chunks of (type u16, header_size u16, size u32); string pools are chunk
type 0x0001 with UTF-16LE or (flag 0x100) UTF-8 payloads; XML start
elements are chunk type 0x0102 carrying string-pool indexes for element
and attribute names.
"""

from __future__ import annotations

import io
import re
import struct
import zipfile
import zlib
from xml.etree import ElementTree

from ..document import DT_APP, DT_AUDIO, Anchor, Document
from .errors import ParserError

# -- Android binary XML (AXML) ------------------------------------------------

_CHUNK_STRING_POOL = 0x0001
_CHUNK_TABLE = 0x0002
_CHUNK_XML = 0x0003
_CHUNK_XML_START_ELEMENT = 0x0102
_UTF8_FLAG = 0x100


def _pool_strings(data: bytes, off: int) -> list[str]:
    """Decode one ResStringPool chunk at `off`; returns its strings."""
    htype, hsize, size = struct.unpack_from("<HHI", data, off)
    if htype != _CHUNK_STRING_POOL:
        return []
    n, _styles, flags, strings_start, _styles_start = struct.unpack_from(
        "<IIIII", data, off + 8)
    utf8 = bool(flags & _UTF8_FLAG)
    offsets = struct.unpack_from(f"<{n}I", data, off + 28)
    base = off + strings_start
    out: list[str] = []
    for so in offsets:
        p = base + so
        try:
            if utf8:
                # two lengths (chars, bytes), each u8 with high-bit ext
                blen = data[p]
                p += 2 if blen & 0x80 else 1
                blen = data[p]
                if blen & 0x80:
                    blen = ((blen & 0x7F) << 8) | data[p + 1]
                    p += 1
                p += 1
                out.append(data[p:p + blen].decode("utf-8", "replace"))
            else:
                clen = struct.unpack_from("<H", data, p)[0]
                p += 2
                if clen & 0x8000:
                    clen = ((clen & 0x7FFF) << 16) \
                        | struct.unpack_from("<H", data, p)[0]
                    p += 2
                out.append(data[p:p + 2 * clen].decode("utf-16-le",
                                                       "replace"))
        except (IndexError, struct.error):
            out.append("")
    return out


def parse_axml(data: bytes) -> tuple[list[tuple[str, dict]], list[str]]:
    """Decode Android binary XML into (elements, pool): elements are
    (tag, {attr: raw-string-value}) in document order; attribute values
    that are not string-typed resolve to '' (the manifest fields the
    indexer needs — package, versionName, permission names — are all
    raw strings)."""
    if len(data) < 8 or struct.unpack_from("<H", data, 0)[0] != _CHUNK_XML:
        raise ParserError("not Android binary XML")
    total = struct.unpack_from("<I", data, 4)[0]
    pool: list[str] = []
    elements: list[tuple[str, dict]] = []
    off = 8

    def s(i: int) -> str:
        return pool[i] if 0 <= i < len(pool) else ""

    while off + 8 <= min(total, len(data)):
        ctype, hsize, csize = struct.unpack_from("<HHI", data, off)
        if csize < 8:
            break
        if ctype == _CHUNK_STRING_POOL and not pool:
            pool = _pool_strings(data, off)
        elif ctype == _CHUNK_XML_START_ELEMENT:
            # lineNumber, comment, ns, name, attrStart, attrSize, count
            _ln, _cm, _ns, name_i = struct.unpack_from("<IIII", data,
                                                       off + 8)
            attr_start, attr_size, n_attr = struct.unpack_from(
                "<HHH", data, off + 24)
            attrs: dict[str, str] = {}
            # attributeStart is relative to the attrExt part, which
            # begins after the 16-byte node header (chunk header +
            # lineNumber + comment)
            p = off + 16 + attr_start
            for _ in range(n_attr):
                _ans, aname, araw = struct.unpack_from("<III", data, p)
                attrs[s(aname)] = s(araw) if araw != 0xFFFFFFFF else ""
                p += attr_size or 20
            elements.append((s(name_i), attrs))
        off += csize
    return elements, pool


def parse_arsc_strings(data: bytes, cap: int = 4096) -> list[str]:
    """Global string pool of a resources.arsc table (the app's compiled
    strings.xml values and asset names)."""
    if len(data) < 12 \
            or struct.unpack_from("<H", data, 0)[0] != _CHUNK_TABLE:
        return []
    hsize = struct.unpack_from("<H", data, 2)[0]
    off = hsize
    while off + 8 <= len(data):
        ctype, _h, csize = struct.unpack_from("<HHI", data, off)
        if ctype == _CHUNK_STRING_POOL:
            return [x for x in _pool_strings(data, off) if x][:cap]
        if csize < 8:
            break
        off += csize
    return []


_URL_RE = re.compile(r"(https?|ftp)://[^\s\"'<>]+")


def parse_apk(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    """Android package: manifest identity + permissions, entry listing,
    resource strings with URL anchors (reference: apkParser.java)."""
    try:
        zf = zipfile.ZipFile(io.BytesIO(content))
    except zipfile.BadZipFile as e:
        raise ParserError(f"not an APK/zip: {e}") from None
    name = url.rsplit("/", 1)[-1]
    parts: list[str] = []
    title = name
    package = version = ""
    permissions: list[str] = []
    try:
        elements, _pool = parse_axml(zf.read("AndroidManifest.xml"))
        for tag, attrs in elements:
            if tag == "manifest":
                package = attrs.get("package", "")
                version = attrs.get("versionName", "")
            elif tag == "uses-permission" and attrs.get("name"):
                permissions.append(attrs["name"])
        title = " ".join(x for x in (name, package, version) if x)
        parts.append(title + ".")
        parts.extend(p + "." for p in permissions)
    except (KeyError, ParserError, zipfile.BadZipFile, zlib.error):
        pass  # no/undecodable/corrupt manifest: still index the rest
    entries = zf.namelist()
    parts.extend(e + "." for e in entries)
    anchors: list[Anchor] = []
    try:
        for s in parse_arsc_strings(zf.read("resources.arsc")):
            parts.append(s + ".")
            for m in _URL_RE.finditer(s):
                anchors.append(Anchor(url=m.group(0)))
    except (KeyError, zipfile.BadZipFile, zlib.error):
        pass
    return [Document(
        url=url, mime_type="application/vnd.android.package-archive",
        title=title, description=package, doctype=DT_APP,
        keywords=permissions, text=" ".join(parts), anchors=anchors)]


# -- AutoCAD DWG --------------------------------------------------------------

_DWG_VERSIONS = {
    b"AC1012": "AutoCAD R13", b"AC1014": "AutoCAD R14",
    b"AC1015": "AutoCAD 2000", b"AC1018": "AutoCAD 2004",
    b"AC1021": "AutoCAD 2007", b"AC1024": "AutoCAD 2010",
    b"AC1027": "AutoCAD 2013", b"AC1032": "AutoCAD 2018",
}
_ASCII_RUN = re.compile(rb"[\x20-\x7e]{6,}")


def parse_dwg(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    """CAD drawing: version identification + printable text-run salvage
    from the property/entity sections — a working superset of the
    reference's (disabled) version-gated property reader
    (reference: dwgParser.java — registers the format, reads the AC10xx
    version, and returns no content)."""
    ver = _DWG_VERSIONS.get(content[:6])
    if ver is None:
        raise ParserError("not a DWG drawing (unknown AC version)")
    texts: list[str] = []
    # ASCII runs (pre-2007 property sections store 8-bit text)
    for m in _ASCII_RUN.finditer(content[:2 << 20]):
        s = m.group(0).decode("ascii").strip()
        if len(s.split()) >= 1 and any(c.isalpha() for c in s):
            texts.append(s)
    # UTF-16LE runs (2007+ sections): printable-low-byte pairs
    # (ASCII + Latin-1 letters, so umlauts survive)
    for m in re.finditer(rb"(?:[\x20-\x7e\xa0-\xff]\x00){6,}",
                         content[:2 << 20]):
        texts.append(m.group(0).decode("utf-16-le").strip())
    seen: set[str] = set()
    uniq = [t for t in texts if not (t in seen or seen.add(t))][:512]
    name = url.rsplit("/", 1)[-1]
    return [Document(
        url=url, mime_type="application/dwg", title=name,
        description=ver, keywords=[ver],
        text=" ".join([ver] + uniq))]


# -- FreeMind mind map --------------------------------------------------------

def parse_mm(url: str, content: bytes,
             charset: str | None = None) -> list[Document]:
    """FreeMind map: every node's TEXT attribute in document order; the
    root node's text is the title (reference: mmParser.java)."""
    try:
        root = ElementTree.fromstring(content)
    except ElementTree.ParseError as e:
        raise ParserError(f"bad FreeMind XML: {e}") from None
    if root.tag != "map":
        raise ParserError("not a FreeMind map (no <map> root)")
    nodes = [n.get("TEXT", "").strip() for n in root.iter("node")]
    nodes = [n for n in nodes if n]
    if not nodes:
        raise ParserError("FreeMind map without node text")
    return [Document(
        url=url, mime_type="application/freemind", title=nodes[0],
        sections=nodes[:64], text=". ".join(nodes) + ".")]


# -- Commodore 64 SID tune ----------------------------------------------------

def parse_sid(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    """PSID/RSID header metadata: tune name, author, release/copyright
    (format: magic at 0, version u16BE at 4, name/author/released as
    32-byte ISO-8859-1 fields at 0x16/0x36/0x56; reference:
    sidAudioParser.java)."""
    if len(content) < 0x76 or content[:4] not in (b"PSID", b"RSID"):
        raise ParserError("not a SID tune")
    version = struct.unpack_from(">H", content, 4)[0]
    if version not in (1, 2, 3, 4):
        raise ParserError(f"unexpected SID version {version}")

    def field(off: int) -> str:
        return content[off:off + 32].split(b"\0", 1)[0] \
            .decode("iso-8859-1").strip()

    name, author, released = field(0x16), field(0x36), field(0x56)
    songs = struct.unpack_from(">H", content, 14)[0]
    text = (f"name: {name} author: {author} publisher: {released} "
            f"songs: {songs} version: {version}")
    return [Document(
        url=url, mime_type="audio/prs.sid",
        title=name or url.rsplit("/", 1)[-1], author=author,
        description=released, text=text, doctype=DT_AUDIO)]
