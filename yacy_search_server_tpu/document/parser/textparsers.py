"""Plain-text family parsers — txt, csv, json, vcf, torrent-ish.

Capability equivalents of the reference's simple parsers (reference:
source/net/yacy/document/parser/txtParser.java, csvParser.java,
vcfParser.java — behavioral: decode, extract title/first line, full text).
"""

from __future__ import annotations

import csv
import io
import json

from ..document import Document
from .errors import ParserError


def decode8(content: bytes) -> str:
    """Charset-less 8-bit text decode: utf-8, then the MacRoman
    heuristic (bytes in 0x80-0x9F are C1 controls in latin-1 but letters
    in MacRoman — classic Mac text like the reference corpus's
    umlaute_mac.* files decodes wrong without this), then latin-1.
    Shared by the text parsers and the media parsers' comment fields."""
    try:
        return content.decode("utf-8")
    except UnicodeDecodeError:
        pass
    if any(0x80 <= b <= 0x9F for b in content[:4096]):
        try:
            return content.decode("mac_roman")
        except UnicodeDecodeError:
            pass
    return content.decode("latin-1", "replace")


def _decode(content: bytes, charset: str | None) -> str:
    if charset:
        try:
            return content.decode(charset)
        except (UnicodeDecodeError, LookupError):
            pass
    return decode8(content)


def parse_text(url: str, content: bytes,
               charset: str | None = None) -> list[Document]:
    text = _decode(content, charset)
    first = text.strip().split("\n", 1)[0][:120]
    return [Document(url=url, mime_type="text/plain", title=first,
                     text=text)]


def parse_csv(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    raw = _decode(content, charset)
    try:
        rows = list(csv.reader(io.StringIO(raw)))
    except csv.Error:
        rows = [line.split(",") for line in raw.splitlines()]
    text = "\n".join(" ".join(cell for cell in row) for row in rows)
    title = " ".join(rows[0])[:120] if rows else ""
    return [Document(url=url, mime_type="text/csv", title=title, text=text)]


def _json_strings(obj, out: list[str]) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.append(str(k))
            _json_strings(v, out)
    elif isinstance(obj, list):
        for v in obj:
            _json_strings(v, out)
    elif isinstance(obj, str):
        out.append(obj)
    elif obj is not None:
        out.append(str(obj))


def parse_json(url: str, content: bytes,
               charset: str | None = None) -> list[Document]:
    try:
        obj = json.loads(_decode(content, charset))
    except json.JSONDecodeError:
        return parse_text(url, content, charset)
    strings: list[str] = []
    _json_strings(obj, strings)
    title = ""
    if isinstance(obj, dict):
        for key in ("title", "name", "id"):
            if isinstance(obj.get(key), str):
                title = obj[key]
                break
    return [Document(url=url, mime_type="application/json", title=title,
                     text=" ".join(strings))]


def parse_vcf(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    raw = _decode(content, charset)
    names, lines = [], []
    for line in raw.splitlines():
        key, _, value = line.partition(":")
        key = key.split(";", 1)[0].upper()
        if key in ("FN", "N"):
            names.append(value.replace(";", " ").strip())
        if key not in ("BEGIN", "END", "VERSION") and value:
            lines.append(value.replace(";", " ").strip())
    return [Document(url=url, mime_type="text/vcard",
                     title=names[0] if names else "", text=" ".join(lines))]


import re as _re

# hex or literal string operand, optionally followed by a widths array,
# then a show-family operator (show/xshow/ashow/widthshow/bshow/bxshow)
_PS_HEX_SHOW_RE = _re.compile(
    rb"<([0-9A-Fa-f\s]+)>\s*(?:\[[-\d\s.]*\]\s*)?"
    rb"(?:x|a|width|b|bx)?show\b", _re.DOTALL)
_PS_LIT_SHOW_RE = _re.compile(
    rb"\(((?:\\.|[^()\\])*)\)\s*(?:\[[-\d\s.]*\]\s*)?"
    rb"(?:x|a|width|b|bx)?show\b", _re.DOTALL)
_PS_TITLE_RE = _re.compile(rb"%%Title:\s*\(?([^)\r\n]*)")


def parse_ps(url: str, content: bytes,
             charset: str | None = None) -> list[Document]:
    """PostScript text extraction (reference: psParser.java — a token
    scanner for show-family operators). Collects literal and hex string
    operands of the show family plus the DSC %%Title comment; glyphs are
    latin-1 in the common generator output."""
    parts: list[str] = []
    for m in _PS_HEX_SHOW_RE.finditer(content):
        hexs = _re.sub(rb"\s", b"", m.group(1))
        if len(hexs) % 2:
            hexs += b"0"
        parts.append(bytes.fromhex(hexs.decode("ascii"))
                     .decode("latin-1", "replace"))
    for m in _PS_LIT_SHOW_RE.finditer(content):
        parts.append(m.group(1).decode("latin-1", "replace"))
    tm = _PS_TITLE_RE.search(content)
    title = tm.group(1).decode("latin-1", "replace").strip() if tm else ""
    text = "\n".join(p.strip() for p in parts if p.strip())
    if not text and not title:
        raise ParserError("ps: no text recovered")
    return [Document(url=url, mime_type="application/postscript",
                     title=title, text=text)]
