"""Plain-text family parsers — txt, csv, json, vcf, torrent-ish.

Capability equivalents of the reference's simple parsers (reference:
source/net/yacy/document/parser/txtParser.java, csvParser.java,
vcfParser.java — behavioral: decode, extract title/first line, full text).
"""

from __future__ import annotations

import csv
import io
import json

from ..document import Document


def _decode(content: bytes, charset: str | None) -> str:
    for cs in (charset, "utf-8", "latin-1"):
        if not cs:
            continue
        try:
            return content.decode(cs)
        except (UnicodeDecodeError, LookupError):
            continue
    return content.decode("utf-8", "replace")


def parse_text(url: str, content: bytes,
               charset: str | None = None) -> list[Document]:
    text = _decode(content, charset)
    first = text.strip().split("\n", 1)[0][:120]
    return [Document(url=url, mime_type="text/plain", title=first,
                     text=text)]


def parse_csv(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    raw = _decode(content, charset)
    try:
        rows = list(csv.reader(io.StringIO(raw)))
    except csv.Error:
        rows = [line.split(",") for line in raw.splitlines()]
    text = "\n".join(" ".join(cell for cell in row) for row in rows)
    title = " ".join(rows[0])[:120] if rows else ""
    return [Document(url=url, mime_type="text/csv", title=title, text=text)]


def _json_strings(obj, out: list[str]) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.append(str(k))
            _json_strings(v, out)
    elif isinstance(obj, list):
        for v in obj:
            _json_strings(v, out)
    elif isinstance(obj, str):
        out.append(obj)
    elif obj is not None:
        out.append(str(obj))


def parse_json(url: str, content: bytes,
               charset: str | None = None) -> list[Document]:
    try:
        obj = json.loads(_decode(content, charset))
    except json.JSONDecodeError:
        return parse_text(url, content, charset)
    strings: list[str] = []
    _json_strings(obj, strings)
    title = ""
    if isinstance(obj, dict):
        for key in ("title", "name", "id"):
            if isinstance(obj.get(key), str):
                title = obj[key]
                break
    return [Document(url=url, mime_type="application/json", title=title,
                     text=" ".join(strings))]


def parse_vcf(url: str, content: bytes,
              charset: str | None = None) -> list[Document]:
    raw = _decode(content, charset)
    names, lines = [], []
    for line in raw.splitlines():
        key, _, value = line.partition(":")
        key = key.split(";", 1)[0].upper()
        if key in ("FN", "N"):
            names.append(value.replace(";", " ").strip())
        if key not in ("BEGIN", "END", "VERSION") and value:
            lines.append(value.replace(";", " ").strip())
    return [Document(url=url, mime_type="text/vcard",
                     title=names[0] if names else "", text=" ".join(lines))]
