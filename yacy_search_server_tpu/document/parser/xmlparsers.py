"""XML-family parsers — generic XML, RSS/Atom feeds, sitemaps.

Capability equivalents of the reference's XML parsers (reference:
source/net/yacy/document/parser/GenericXMLParser.java, rssParser.java
via cora/document/feed, and crawler/retrieval/SitemapImporter.java):
generic XML extracts all character data; rss/atom produce one Document
per item with link anchors; sitemap parsing yields the url list for the
crawler.
"""

from __future__ import annotations

import re
import xml.etree.ElementTree as ET

from ..document import Anchor, Document

_WS_RE = re.compile(r"\s+")


def _localname(tag: str) -> str:
    return tag.rsplit("}", 1)[-1].lower()


def _parse_tree(content: bytes) -> ET.Element | None:
    try:
        return ET.fromstring(content)
    except ET.ParseError:
        return None


def parse_generic_xml(url: str, content: bytes,
                      charset: str | None = None) -> list[Document]:
    root = _parse_tree(content)
    if root is None:
        from .textparsers import parse_text
        return parse_text(url, content, charset)
    texts: list[str] = []
    for el in root.iter():
        if el.text and el.text.strip():
            texts.append(el.text.strip())
        if el.tail and el.tail.strip():
            texts.append(el.tail.strip())
    text = _WS_RE.sub(" ", " ".join(texts))
    return [Document(url=url, mime_type="application/xml",
                     title=text[:120], text=text)]


def is_feed(content: bytes) -> bool:
    head = content[:512].lstrip()
    return (b"<rss" in head or b"<feed" in head or b"<rdf:RDF" in head)


def parse_feed(url: str, content: bytes,
               charset: str | None = None) -> list[Document]:
    """RSS 2.0 / Atom -> one Document per entry (rssParser semantics)."""
    root = _parse_tree(content)
    if root is None:
        return []
    docs: list[Document] = []
    channel_title = ""
    items = []
    for el in root.iter():
        ln = _localname(el.tag)
        if ln in ("item", "entry"):
            items.append(el)
        elif ln == "title" and not items and not channel_title:
            channel_title = (el.text or "").strip()
    for item in items:
        title = link = desc = author = date = ""
        for el in item.iter():
            ln = _localname(el.tag)
            txt = (el.text or "").strip()
            if ln == "title" and not title:
                title = txt
            elif ln == "link" and not link:
                link = txt or el.get("href", "")
            elif ln in ("description", "summary", "content") and not desc:
                desc = re.sub(r"<[^>]+>", " ", txt)
            elif ln in ("author", "creator") and not author:
                author = txt
            elif ln in ("pubdate", "published", "updated", "date") and not date:
                date = txt
        docs.append(Document(
            url=link or url, mime_type="text/html", title=title,
            description=_WS_RE.sub(" ", desc).strip(),
            author=author,
            text=_WS_RE.sub(" ", f"{title} {desc}").strip(),
            anchors=[Anchor(link, text=title)] if link else []))
    if not docs:
        docs = [Document(url=url, mime_type="application/rss+xml",
                         title=channel_title, text=channel_title)]
    return docs


def parse_sitemap(content: bytes) -> tuple[list[str], list[str]]:
    """(page urls, nested sitemap urls) from urlset/sitemapindex."""
    root = _parse_tree(content)
    if root is None:
        return [], []
    pages, nested = [], []
    root_ln = _localname(root.tag)
    for loc in root.iter():
        if _localname(loc.tag) != "loc" or not loc.text:
            continue
        u = loc.text.strip()
        if root_ln == "sitemapindex":
            nested.append(u)
        else:
            pages.append(u)
    return pages, nested
