"""HTML parser — streaming content scraper.

Capability equivalent of the reference's html parser (reference:
source/net/yacy/document/parser/htmlParser.java and
document/parser/html/ContentScraper.java): extract title, headline
sections, meta description/keywords/robots, canonical + base href, anchors
with text, images with alt, visible text with script/style skipped,
charset detection (http header, meta, BOM), html lang, and geo position
from meta tags.
"""

from __future__ import annotations

import re
from html import unescape
from html.parser import HTMLParser
from urllib.parse import urljoin

from ..document import Anchor, Document, Image

_CHARSET_META_RE = re.compile(
    rb"""<meta[^>]+charset\s*=\s*["']?([\w-]+)""", re.IGNORECASE)
_WS_RE = re.compile(r"\s+")

_IGNORE_CONTENT = {"script", "style", "noscript", "template"}
_SECTION_TAGS = {"h1", "h2", "h3", "h4", "h5", "h6"}
# structure-text tags captured for the schema long tail (the reference's
# li_txt/dt_txt/dd_txt/article_txt/bold_txt/italic_txt/underline_txt)
_TAGTEXT_TAGS = {"li": "li", "dt": "dt", "dd": "dd", "article": "article",
                 "b": "bold", "strong": "bold", "i": "italic",
                 "em": "italic", "u": "underline"}
_MEDIA_EXT_AUDIO = {"mp3", "ogg", "oga", "flac", "wav", "m4a", "aac"}
_MEDIA_EXT_VIDEO = {"mp4", "webm", "mkv", "avi", "mov", "mpg", "mpeg", "m4v"}
_MEDIA_EXT_APP = {"apk", "exe", "msi", "dmg", "jar", "deb", "rpm", "zip",
                  "tar", "gz", "7z"}


class ContentScraper(HTMLParser):
    def __init__(self, base_url: str):
        super().__init__(convert_charrefs=True)
        self.base_url = base_url
        self.title_parts: list[str] = []
        self.sections: list[str] = []
        self.headings: dict[int, list[str]] = {}   # level 1..6 -> texts
        self.text_parts: list[str] = []
        self.anchors: list[Anchor] = []
        self.images: list[Image] = []
        self.meta: dict[str, str] = {}
        self.lang = ""
        self.canonical = ""
        self.favicon = ""
        self._base = base_url
        self._in_title = False
        self._section_stack: list[list[str]] = []
        self._ignore_depth = 0
        self._cur_anchor: Anchor | None = None
        self._cur_anchor_text: list[str] = []
        self.embeds: list[str] = []       # audio/video/app media links
        # schema long-tail structure (CollectionSchema li_txt/bold_txt/
        # css_url_sxt/scripts_sxt/iframes_sxt/hreflang/navigation/
        # opengraph/refresh/flash groups)
        self.tag_texts: dict[str, list[str]] = {}
        self._tagtext_stack: list[tuple[str, list[str]]] = []
        self.css: list[str] = []
        self.css_tags: list[str] = []
        self.scripts: list[str] = []
        self.script_count = 0
        self.iframes: list[str] = []
        self.frames: list[str] = []
        self.hreflangs: list[tuple[str, str]] = []   # (lang-cc, url)
        self.navigation: list[tuple[str, str]] = []  # (rel-type, url)
        self.refresh = ""
        self.flash = False
        self.saw_rdfa = False

    # -- tag handling --------------------------------------------------------

    def handle_starttag(self, tag, attrs):
        # valueless attributes (<a href>) parse as value None
        a = {k: (v if v is not None else "") for k, v in attrs}
        # real RDFa signal, recorded by the FIRST pass so the dedicated
        # triple scan only runs when there is something beyond the og:
        # metas already captured in self.meta
        if not self.saw_rdfa and (
                "vocab" in a or "typeof" in a or "about" in a
                or (tag != "meta" and "property" in a)):
            self.saw_rdfa = True
        if tag == "script":
            # counted/collected BEFORE the ignore branch eats the tag
            # (script CONTENT is ignored text; the element itself is a
            # schema signal: scriptscount_i / scripts_sxt)
            self.script_count += 1
            if a.get("src"):
                self.scripts.append(urljoin(self._base, a["src"]))
        if tag in _IGNORE_CONTENT:
            self._ignore_depth += 1
            self.text_parts.append(" ")
            return
        if tag == "html" and a.get("lang"):
            self.lang = a["lang"][:2].lower()
        elif tag == "base" and a.get("href"):
            self._base = urljoin(self.base_url, a["href"])
        elif tag == "title":
            self._in_title = True
        elif tag in _SECTION_TAGS:
            self._section_stack.append((int(tag[1]), []))
        elif tag == "meta":
            name = (a.get("name") or a.get("property") or "").lower()
            if name and a.get("content") is not None:
                self.meta[name] = a["content"]
            equiv = a.get("http-equiv", "").lower()
            if equiv == "content-type":
                self.meta.setdefault("content-type", a.get("content", ""))
            elif equiv == "refresh":
                self.refresh = a.get("content", "")
        elif tag == "link":
            rel = a.get("rel", "").lower()
            href = a.get("href", "")
            if href:
                if "canonical" in rel:
                    self.canonical = urljoin(self._base, href)
                elif "icon" in rel:
                    self.favicon = urljoin(self._base, href)
                elif "stylesheet" in rel:
                    self.css.append(urljoin(self._base, href))
                    # the raw tag text (CollectionSchema css_tag_sxt);
                    # values re-escape so the stored tag stays parseable
                    from html import escape as _esc
                    self.css_tags.append(
                        "<link " + " ".join(
                            f'{k}="{_esc(v, quote=True)}"'
                            for k, v in a.items()) + " />")
                elif "alternate" in rel and a.get("hreflang"):
                    self.hreflangs.append((a["hreflang"].lower(),
                                           urljoin(self._base, href)))
                elif rel in ("next", "prev", "previous", "contents",
                             "index", "top", "up", "first", "last",
                             "glossary", "chapter"):
                    self.navigation.append((rel, urljoin(self._base, href)))
        elif tag in _TAGTEXT_TAGS:
            # implied end tags (html.parser emits none): a new <li>
            # closes an open li; dt/dd close each other (HTML5 rules) —
            # real-world lists rarely close their items explicitly
            if tag == "li":
                self._pop_tagtext("li")
            elif tag in ("dt", "dd"):
                self._pop_tagtext("dt")
                self._pop_tagtext("dd")
            self._tagtext_stack.append((_TAGTEXT_TAGS[tag], []))
        elif tag in ("ul", "ol"):
            self._pop_tagtext("li")
        elif tag == "dl":
            self._pop_tagtext("dt")
            self._pop_tagtext("dd")
        elif tag == "a":
            href = a.get("href", "")
            if href and not href.startswith(("javascript:", "#", "mailto:",
                                            "data:")):
                self._cur_anchor = Anchor(urljoin(self._base, href),
                                          rel=a.get("rel", ""))
                self._cur_anchor_text = []
        elif tag == "img":
            src = a.get("src", "")
            if src and not src.startswith("data:"):
                def _int(v):
                    try:
                        return int(v)
                    except (TypeError, ValueError):
                        return 0
                self.images.append(Image(urljoin(self._base, src),
                                         alt=a.get("alt", ""),
                                         width=_int(a.get("width")),
                                         height=_int(a.get("height"))))
        elif tag in ("audio", "video", "source", "embed", "object"):
            src = a.get("src") or a.get("data") or ""
            if src:
                self.embeds.append(urljoin(self._base, src))
                base_src = src.split("?", 1)[0].split("#", 1)[0].lower()
                if base_src.rsplit(".", 1)[-1] == "swf" \
                        or "flash" in a.get("type", "").lower():
                    self.flash = True
        elif tag in ("frame", "iframe"):
            src = a.get("src", "")
            if src:
                target = urljoin(self._base, src)
                (self.iframes if tag == "iframe"
                 else self.frames).append(target)
                self.anchors.append(Anchor(target, text="", rel="frame"))
        # every tag boundary is a word separator in the extracted text —
        # adjacent text nodes ("indexing<a>deeper</a>") must not concatenate
        self.text_parts.append(" ")

    def handle_endtag(self, tag):
        self.text_parts.append(" ")
        if tag in _IGNORE_CONTENT:
            self._ignore_depth = max(0, self._ignore_depth - 1)
            return
        if tag == "title":
            self._in_title = False
        elif tag in _SECTION_TAGS and self._section_stack:
            level, parts = self._section_stack.pop()
            text = _WS_RE.sub(" ", " ".join(parts)).strip()
            if text:
                self.sections.append(text)
                self.headings.setdefault(level, []).append(text)
        elif tag == "a" and self._cur_anchor is not None:
            self._cur_anchor.text = _WS_RE.sub(
                " ", " ".join(self._cur_anchor_text)).strip()[:500]
            self.anchors.append(self._cur_anchor)
            self._cur_anchor = None
            self._cur_anchor_text = []
        elif tag in _TAGTEXT_TAGS:
            self._pop_tagtext(_TAGTEXT_TAGS[tag])
        elif tag in ("ul", "ol"):        # closes a dangling implied <li>
            self._pop_tagtext("li")
        elif tag == "dl":
            self._pop_tagtext("dt")
            self._pop_tagtext("dd")

    def _pop_tagtext(self, key: str) -> None:
        """Commit the TOP stack entry if it carries `key` (unbalanced end
        tags for other keys are ignored rather than popping the wrong
        entry)."""
        if self._tagtext_stack and self._tagtext_stack[-1][0] == key:
            _k, parts = self._tagtext_stack.pop()
            text = _WS_RE.sub(" ", " ".join(parts)).strip()
            if text:
                self.tag_texts.setdefault(key, []).append(text[:256])

    def handle_data(self, data):
        if self._ignore_depth:
            return
        if self._in_title:
            self.title_parts.append(data)
            return
        if self._section_stack:
            self._section_stack[-1][1].append(data)
        if self._cur_anchor is not None:
            self._cur_anchor_text.append(data)
        for _key, parts in self._tagtext_stack:
            # EVERY open structure element gets the text: an <article>'s
            # words must not vanish into a nested <b>
            parts.append(data)
        self.text_parts.append(data)


def _detect_charset(content: bytes, header_charset: str | None) -> str:
    if header_charset:
        return header_charset
    if content.startswith(b"\xef\xbb\xbf"):
        return "utf-8"
    if content.startswith((b"\xff\xfe", b"\xfe\xff")):
        return "utf-16"
    m = _CHARSET_META_RE.search(content[:4096])
    if m:
        return m.group(1).decode("ascii", "replace").lower()
    return "utf-8"


def parse_html(url: str, content: bytes,
               charset: str | None = None) -> list[Document]:
    cs = _detect_charset(content, charset)
    try:
        html = content.decode(cs, "replace")
    except LookupError:
        html = content.decode("utf-8", "replace")
        cs = "utf-8"
    scraper = ContentScraper(url)
    try:
        scraper.feed(html)
        scraper.close()
    except Exception:
        # salvage whatever was scraped before the failure
        import logging
        logging.getLogger("parser.html").debug(
            "scraper aborted mid-document for %s", url, exc_info=True)

    text = _WS_RE.sub(" ", "".join(scraper.text_parts)).strip()
    title = _WS_RE.sub(" ", "".join(scraper.title_parts)).strip()
    robots = scraper.meta.get("robots", "").lower()
    noindex = "noindex" in robots
    nofollow = "nofollow" in robots
    from ..document import (ROBOTS_NOARCHIVE, ROBOTS_NOFOLLOW,
                            ROBOTS_NOINDEX, ROBOTS_NOSNIPPET)
    robots_flags = ((ROBOTS_NOINDEX if noindex else 0)
                    | (ROBOTS_NOFOLLOW if nofollow else 0)
                    | (ROBOTS_NOARCHIVE if "noarchive" in robots else 0)
                    | (ROBOTS_NOSNIPPET if "nosnippet" in robots else 0))

    audio, video, apps = [], [], []
    for link in scraper.embeds:
        ext = link.rsplit(".", 1)[-1].lower() if "." in link else ""
        if ext in _MEDIA_EXT_AUDIO:
            audio.append(link)
        elif ext in _MEDIA_EXT_VIDEO:
            video.append(link)
        elif ext in _MEDIA_EXT_APP:
            apps.append(link)

    lat = lon = 0.0
    for key in ("geo.position", "icbm"):
        if key in scraper.meta:
            parts = re.split(r"[;,]", scraper.meta[key])
            if len(parts) == 2:
                try:
                    lat, lon = float(parts[0]), float(parts[1])
                except ValueError:
                    pass
            break

    doc = Document(
        url=scraper.canonical or url,
        mime_type="text/html",
        charset=cs,
        title=title or scraper.meta.get("og:title", ""),
        author=scraper.meta.get("author", ""),
        description=scraper.meta.get("description",
                                     scraper.meta.get("og:description", "")),
        keywords=[k.strip() for k in
                  scraper.meta.get("keywords", "").split(",") if k.strip()],
        sections=scraper.sections,
        text="" if noindex else text,
        anchors=[] if nofollow else scraper.anchors,
        images=scraper.images,
        language=scraper.lang,
        lat=lat, lon=lon,
    )
    doc.audio_links = audio
    doc.video_links = video
    doc.app_links = apps
    doc.noindex = noindex
    doc.headings = scraper.headings
    doc.canonical = scraper.canonical
    # doc.url above was rewritten to the canonical; keep the URL the page
    # was actually fetched under so canonical_equal_sku_b can compare them
    doc.fetched_url = url
    doc.robots_flags = robots_flags
    doc.favicon = scraper.favicon
    doc.generator = scraper.meta.get("generator", "")
    doc.publisher = scraper.meta.get("dc.publisher",
                                     scraper.meta.get("og:site_name", ""))
    # schema long-tail structure groups (CollectionSchema li_txt,
    # bold_txt, css_url_sxt, scripts_sxt, iframes_sxt, hreflang_*,
    # navigation_*, opengraph_*, refresh_s, flash_b)
    doc.tag_texts = scraper.tag_texts
    doc.css = scraper.css
    doc.css_tags = scraper.css_tags
    doc.scripts = scraper.scripts
    doc.script_count = scraper.script_count
    doc.iframes = scraper.iframes
    doc.frames = scraper.frames
    doc.hreflangs = scraper.hreflangs
    doc.navigation = scraper.navigation
    doc.refresh = scraper.refresh
    doc.flash = scraper.flash
    doc.opengraph = {k[3:]: v for k, v in scraper.meta.items()
                     if k.startswith("og:")}
    doc.publisher_url = scraper.meta.get("og:url", "")
    # page-technology evaluation (ext_* schema family)
    from ..evaluation import evaluate_page
    doc.evaluation = evaluate_page(html, title)
    # RDFa triples (reference parser/rdfa feeding the lod triple store);
    # the second scan only runs when the first pass saw REAL RDFa (og:
    # meta tags alone are already captured in doc.opengraph)
    if scraper.saw_rdfa:
        from .rdfa import extract_triples
        doc.rdf_triples = extract_triples(html, url)
    return [doc]
