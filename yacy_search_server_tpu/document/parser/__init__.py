"""Parser zoo — MIME/extension-dispatched parsers producing Documents.

Capability equivalent of the reference's TextParser registry (reference:
source/net/yacy/document/TextParser.java:78-95+ registering ~30 parsers,
archive recursion, `parseSource` entry). `parse_source(url, mime, content)`
dispatches on mime then extension, recurses into archives, and returns a
list of normalized Documents (document/document.py).
"""

from .registry import ParserError, parse_source, supported_mime, supports
