"""RDFa extraction — structured triples out of annotated HTML.

Capability equivalent of the reference's rdfaParser family (reference:
source/net/yacy/document/parser/rdfa/ — an RDFa-1.0 transformer feeding
the cora/lod triple store). Implements the RDFa-Lite subset that real
pages carry: ``vocab``/``prefix`` term resolution, ``about``/``resource``
subject chaining, ``typeof`` rdf:type triples, and ``property`` values
from ``content``/``href``/``src`` attributes or the element's text.

``extract_triples(html, base_url)`` returns (subject, predicate, object)
string triples ready for the TripleStore (document/vocabulary.py).
"""

from __future__ import annotations

import re
from html.parser import HTMLParser
from urllib.parse import urljoin

RDF_TYPE = "http://www.w3.org/1999/02/22-rdf-syntax-ns#type"

# common prefixes pages rely on without declaring (RDFa initial context)
DEFAULT_PREFIXES = {
    "dc": "http://purl.org/dc/terms/",
    "foaf": "http://xmlns.com/foaf/0.1/",
    "og": "http://ogp.me/ns#",
    "schema": "http://schema.org/",
    "sioc": "http://rdfs.org/sioc/ns#",
    "skos": "http://www.w3.org/2004/02/skos/core#",
}

_WS_RE = re.compile(r"\s+")

# void elements never get an end tag: their triples come from attributes
# only, and they must not occupy the frame stack
_VOID = {"meta", "link", "br", "img", "input", "hr", "area", "base",
         "col", "embed", "source", "track", "wbr", "param"}
# elements whose close is routinely implied by a sibling (HTML5 rules)
_IMPLIED_SIBLING = {"p": ("p",), "li": ("li",),
                    "dt": ("dt", "dd"), "dd": ("dt", "dd"),
                    "tr": ("tr",), "td": ("td", "th"), "th": ("td", "th"),
                    "option": ("option",)}
# block-level start tags close an open <p> (HTML5 §8.1.2.4)
_P_CLOSERS = {"p", "ul", "ol", "dl", "div", "table", "section", "article",
              "aside", "header", "footer", "blockquote", "pre", "form",
              "nav", "figure", "h1", "h2", "h3", "h4", "h5", "h6"}


class _RdfaScraper(HTMLParser):
    def __init__(self, base_url: str):
        super().__init__(convert_charrefs=True)
        self.base = base_url
        self.triples: list[tuple[str, str, str]] = []
        self.prefixes = dict(DEFAULT_PREFIXES)
        self.vocab = ""
        # (tag, subject, pending-property-or-None, text-parts)
        self._stack: list[list] = []

    # -- term resolution -----------------------------------------------------

    def _resolve(self, term: str) -> str:
        term = term.strip()
        if not term:
            return ""
        if term.startswith(("http://", "https://")):
            return term
        if ":" in term:
            prefix, _, local = term.partition(":")
            ns = self.prefixes.get(prefix.lower())
            return ns + local if ns else term
        return (self.vocab + term) if self.vocab else term

    def _subject(self) -> str:
        for frame in reversed(self._stack):
            if frame[1]:
                return frame[1]
        return self.base

    # -- tag handling --------------------------------------------------------

    def handle_starttag(self, tag, attrs):
        a = {k: (v if v is not None else "") for k, v in attrs}
        # implied sibling closes (html.parser emits no implied end tags:
        # an unpopped frame would swallow pending triples and leak its
        # subject over the rest of the page)
        closes = _IMPLIED_SIBLING.get(tag)
        if closes and self._stack and self._stack[-1][0] in closes:
            self._commit(self._stack.pop())
        if tag in _P_CLOSERS:
            while self._stack and self._stack[-1][0] == "p":
                self._commit(self._stack.pop())
        if a.get("prefix"):
            tokens = a["prefix"].split()
            for i in range(0, len(tokens) - 1, 2):
                self.prefixes[tokens[i].rstrip(":").lower()] = tokens[i + 1]
        if "vocab" in a:
            self.vocab = a["vocab"].strip()

        subject = ""
        if a.get("about"):
            subject = urljoin(self.base, a["about"])
        elif a.get("resource") and not a.get("property"):
            subject = urljoin(self.base, a["resource"])
        elif a.get("typeof") and not a.get("property"):
            # typeof without about mints a subject from the element
            subject = self.base + f"#_auto{len(self.triples)}"

        if a.get("typeof"):
            for t in a["typeof"].split():
                resolved = self._resolve(t)
                if resolved:
                    self.triples.append(
                        (subject or self._subject(), RDF_TYPE, resolved))

        pending = None
        if a.get("property"):
            props = [self._resolve(p) for p in a["property"].split()]
            props = [p for p in props if p]
            subj = subject or self._subject()
            # object from content/href/src wins; else the element text
            obj = a.get("content")
            if obj is None and a.get("href"):
                obj = urljoin(self.base, a["href"])
            if obj is None and a.get("resource"):
                obj = urljoin(self.base, a["resource"])
            if obj is None and a.get("src"):
                obj = urljoin(self.base, a["src"])
            if obj is not None:
                for p in props:
                    self.triples.append((subj, p, obj))
            else:
                pending = (subj, props)
        if tag not in _VOID:
            self._stack.append([tag, subject, pending, []])

    def _commit(self, frame) -> None:
        _tag, _subj, pending, parts = frame
        if pending:
            text = _WS_RE.sub(" ", "".join(parts)).strip()
            if text:
                subj, props = pending
                for p in props:
                    self.triples.append((subj, p, text[:2048]))

    def handle_endtag(self, tag):
        for i in range(len(self._stack) - 1, -1, -1):
            if self._stack[i][0] == tag:
                # frames above the match were implicitly closed
                while len(self._stack) > i:
                    self._commit(self._stack.pop())
                break

    def flush(self) -> None:
        """End of document: commit whatever never saw an end tag."""
        while self._stack:
            self._commit(self._stack.pop())

    def handle_data(self, data):
        for frame in self._stack:
            if frame[2]:
                frame[3].append(data)


def extract_triples(html: str | bytes,
                    base_url: str) -> list[tuple[str, str, str]]:
    if isinstance(html, bytes):
        html = html.decode("utf-8", "replace")
    scraper = _RdfaScraper(base_url)
    try:
        scraper.feed(html)
        scraper.close()
    except Exception:
        # salvage what was collected before the failure
        import logging
        logging.getLogger("parser.rdfa").debug(
            "RDFa scrape aborted mid-document for %s", base_url,
            exc_info=True)
    scraper.flush()
    # dedup, preserving order
    return list(dict.fromkeys(scraper.triples))
