"""Shared parser failure type (reference: net.yacy.document.Parser.Failure)."""


class ParserError(Exception):
    pass
