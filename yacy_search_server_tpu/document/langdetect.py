"""Language identification — stopword profiles + the reference's vote.

Capability equivalent of the reference's language handling (reference:
source/net/yacy/document/language/ (langdetect profiles) and the vote in
search/index/Segment.java:492 — the indexed language is decided between
the parser's metadata language, the statistical detection over the text,
and the URL's TLD hint). Profiles here are high-frequency stopword sets
per language: tiny, dependency-free, and accurate enough for the
whole-document decision the index needs (the reference's n-gram profiles
solve the same problem with more bytes).
"""

from __future__ import annotations

import re

_TOKEN_RE = re.compile(r"[a-zà-ÿа-я]+")

# high-frequency function words per language (lowercase)
_PROFILES: dict[str, frozenset] = {
    "en": frozenset("the of and to in is was for that it with as his on be "
                    "at by are this had not have from".split()),
    "de": frozenset("der die das und ist von den mit für auf des im ein "
                    "eine nicht werden sich dem als auch".split()),
    "fr": frozenset("le la les des et de un une est dans pour que qui sur "
                    "avec pas au aux par plus".split()),
    "es": frozenset("el la los las de y en que es un una por con para del "
                    "se su no más como".split()),
    "it": frozenset("il la le di e che un una per in del con non sono al "
                    "dei più come anche".split()),
    "pt": frozenset("o a os as de e que um uma do da em para com não por "
                    "mais se como foi".split()),
    "nl": frozenset("de het een en van in is dat op te met voor niet zijn "
                    "aan er ook als".split()),
    "ru": frozenset("и в не на что с по как это из у за от так же для "
                    "его к но".split()),
    "sv": frozenset("och att det i en som är av på för med den till inte "
                    "om har de".split()),
    "pl": frozenset("i w na z do się nie jest że to po o jak ale za od "
                    "przez przy".split()),
}

_TLD_LANG = {
    "de": "de", "at": "de", "fr": "fr", "es": "es", "it": "it", "pt": "pt",
    "br": "pt", "nl": "nl", "ru": "ru", "se": "sv", "pl": "pl", "uk": "en",
    "us": "en", "au": "en", "ie": "en", "nz": "en",
}

MIN_TOKENS = 8          # below this the text carries too little signal
MIN_MARGIN = 1.25       # best score must beat the runner-up by this factor


def detect_language(text: str, max_tokens: int = 2000) -> str:
    """Best-profile language code, or '' when unsure."""
    # slice BEFORE lowercasing: .lower() of a multi-MB body would copy it
    tokens = _TOKEN_RE.findall(text[: max_tokens * 12].lower())[:max_tokens]
    if len(tokens) < MIN_TOKENS:
        return ""
    scores = {lang: 0 for lang in _PROFILES}
    for t in tokens:
        for lang, words in _PROFILES.items():
            if t in words:
                scores[lang] += 1
    ranked = sorted(scores.items(), key=lambda kv: -kv[1])
    best, second = ranked[0], ranked[1]
    if best[1] == 0:
        return ""
    if second[1] and best[1] / second[1] < MIN_MARGIN:
        return ""
    return best[0]


def tld_hint(url: str) -> str:
    from ..utils.hashes import safe_host
    host = safe_host(url)
    tld = host.rsplit(".", 1)[-1] if "." in host else ""
    return _TLD_LANG.get(tld, "")


def vote_language(meta_lang: str, text: str, url: str = "") -> str:
    """The Segment.java:492 vote: parser metadata wins when the
    statistical detection agrees or abstains; a confident statistical
    result overrides silent/conflicting metadata; the TLD breaks ties."""
    meta = (meta_lang or "").lower()[:2]
    stat = detect_language(text)
    if meta and (stat == meta or not stat):
        return meta
    if stat:
        if not meta:
            return stat
        # conflict: TLD is the tiebreaker
        hint = tld_hint(url)
        if hint == meta:
            return meta
        return stat
    return tld_hint(url) or meta
