"""Document understanding: parsers -> Document -> Condenser -> postings."""
