"""Gazetteer — place-name geolocalization for documents and queries.

Capability equivalent of the reference's geo library (reference:
source/net/yacy/cora/geo/ — GeonamesLocation/OpenGeoDBLocation load
place-name dumps into in-memory maps; LibraryProvider wires them in, and
document processing derives the lat/lon written into the Solr schema,
feeding location search and the HASLOCATION content flag). Dump format
here: CSV lines "name,lat,lon,population" under DATA/DICTIONARIES/geo/.
Lookups are token-based; the most populous match wins (the reference
ranks candidate locations the same way).
"""

from __future__ import annotations

import os
import re
import threading

_TOKEN_RE = re.compile(r"\w+", re.UNICODE)


class Gazetteer:
    def __init__(self, data_dir: str | None = None):
        # name (lower) -> (lat, lon, population)
        self._places: dict[str, tuple[float, float, int]] = {}
        self._lock = threading.Lock()
        if data_dir and os.path.isdir(data_dir):
            for fn in sorted(os.listdir(data_dir)):
                if fn.endswith((".csv", ".txt")):
                    try:
                        with open(os.path.join(data_dir, fn),
                                  encoding="utf-8") as f:
                            self.load_text(f.read())
                    except OSError:
                        continue

    def load_text(self, text: str) -> int:
        n = 0
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(",")
            if len(parts) < 3:
                continue
            try:
                lat, lon = float(parts[1]), float(parts[2])
                pop = int(parts[3]) if len(parts) > 3 and parts[3] else 0
            except ValueError:
                continue
            self.add(parts[0], lat, lon, pop)
            n += 1
        return n

    def add(self, name: str, lat: float, lon: float,
            population: int = 0) -> None:
        key = name.strip().lower()
        if not key:
            return
        with self._lock:
            old = self._places.get(key)
            # the bigger place wins a name collision
            if old is None or population >= old[2]:
                self._places[key] = (lat, lon, population)

    def find(self, name: str) -> tuple[float, float] | None:
        p = self._places.get(name.strip().lower())
        return (p[0], p[1]) if p else None

    def locate_text(self, text: str,
                    max_tokens: int = 1000) -> tuple[float, float] | None:
        """Best (most populous) place name appearing in the text; bigrams
        are checked so 'new york' style names match."""
        if not self._places:
            return None
        tokens = [t.lower() for t in _TOKEN_RE.findall(text)[:max_tokens]]
        best: tuple[float, float, int] | None = None
        with self._lock:
            for i, tok in enumerate(tokens):
                for cand in ((tok,) if i + 1 >= len(tokens)
                             else (tok + " " + tokens[i + 1], tok)):
                    p = self._places.get(cand)
                    if p is not None and (best is None or p[2] > best[2]):
                        best = p
        return (best[0], best[1]) if best else None

    def size(self) -> int:
        with self._lock:
            return len(self._places)
