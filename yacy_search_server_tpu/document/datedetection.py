"""Date detection — find calendar dates mentioned in document text.

Capability equivalent of the reference's date extraction (reference:
source/net/yacy/document/DateDetection.java — scans content for absolute
dates which fill the dates_in_content_dts / dates_in_content_count_i
schema fields and drive the /date sort and date facets). The reference
builds per-language linear scanners; here a small set of anchored regexes
covers the load-bearing formats (ISO, dotted european, slashed US, and
written month names in english/german/french), normalized to days since
epoch so the ranking kernel can consume them as an int column.
"""

from __future__ import annotations

import datetime as _dt
import re

_MONTHS = {
    # english
    "january": 1, "february": 2, "march": 3, "april": 4, "may": 5,
    "june": 6, "july": 7, "august": 8, "september": 9, "october": 10,
    "november": 11, "december": 12,
    "jan": 1, "feb": 2, "mar": 3, "apr": 4, "jun": 6, "jul": 7, "aug": 8,
    "sep": 9, "sept": 9, "oct": 10, "nov": 11, "dec": 12,
    # german
    "januar": 1, "februar": 2, "märz": 3, "maerz": 3, "mai": 5, "juni": 6,
    "juli": 7, "oktober": 10, "dezember": 12,
    # french
    "janvier": 1, "février": 2, "fevrier": 2, "mars": 3, "avril": 4,
    "juin": 6, "juillet": 7, "août": 8, "aout": 8, "septembre": 9,
    "octobre": 10, "novembre": 11, "décembre": 12, "decembre": 12,
}
_MONTH_RE = "|".join(sorted(_MONTHS, key=len, reverse=True))

# yyyy-mm-dd (ISO)
_ISO = re.compile(r"\b(\d{4})-(\d{2})-(\d{2})\b")
# dd.mm.yyyy (european dotted)
_DOTTED = re.compile(r"\b(\d{1,2})\.(\d{1,2})\.(\d{4})\b")
# mm/dd/yyyy (US slashed)
_SLASHED = re.compile(r"\b(\d{1,2})/(\d{1,2})/(\d{4})\b")
# "March 5, 2024" / "March 5 2024"
_MDY = re.compile(rf"\b({_MONTH_RE})\.?\s+(\d{{1,2}})(?:st|nd|rd|th)?,?\s+(\d{{4}})\b",
                  re.IGNORECASE)
# "5 March 2024" / "5. März 2024"
_DMY = re.compile(rf"\b(\d{{1,2}})(?:st|nd|rd|th)?\.?\s+({_MONTH_RE})\.?\s+(\d{{4}})\b",
                  re.IGNORECASE)

_EPOCH = _dt.date(1970, 1, 1)
# plausibility window (DateDetection restricts to recent years too:
# its kernel covers the current year +/- a few)
_MIN_YEAR, _MAX_YEAR = 1970, 2100


def _mk(year: int, month: int, day: int) -> _dt.date | None:
    if not (_MIN_YEAR <= year <= _MAX_YEAR):
        return None
    try:
        return _dt.date(year, month, day)
    except ValueError:
        return None


def _numeric(m) -> tuple[int, int, int]:
    return int(m.group(1)), int(m.group(2)), int(m.group(3))


# (pattern, match -> (year, month, day) or None)
_SCANNERS = (
    (_ISO, lambda m: _numeric(m)),
    (_DOTTED, lambda m: (int(m.group(3)), int(m.group(2)), int(m.group(1)))),
    (_SLASHED, lambda m: (int(m.group(3)), int(m.group(1)), int(m.group(2)))),
    (_MDY, lambda m: (int(m.group(3)), _MONTHS.get(m.group(1).lower(), 0),
                      int(m.group(2)))),
    (_DMY, lambda m: (int(m.group(3)), _MONTHS.get(m.group(2).lower(), 0),
                      int(m.group(1)))),
)


def dates_in_content(text: str, max_dates: int = 100) -> list[_dt.date]:
    """Distinct dates found in `text`, in per-format first-appearance
    order, capped at `max_dates` (every scanner stops at the cap — a
    date-dump page cannot make the indexing path accumulate unbounded
    matches)."""
    found: dict[_dt.date, None] = {}
    scan = text[:200_000]    # bound the regex work on pathological docs
    for pattern, extract in _SCANNERS:
        if len(found) >= max_dates:
            break
        for m in pattern.finditer(scan):
            ymd = extract(m)
            if ymd and ymd[1]:
                d = _mk(*ymd)
                if d:
                    found.setdefault(d)
                    if len(found) >= max_dates:
                        break
    return list(found)[:max_dates]


def dates_as_days(dates: list[_dt.date]) -> list[int]:
    return [(d - _EPOCH).days for d in dates]


def dates_as_iso(dates: list[_dt.date]) -> list[str]:
    return [d.isoformat() for d in dates]
