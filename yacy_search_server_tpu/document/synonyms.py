"""Synonym library — indexing-time term expansion.

Capability equivalent of the reference's synonym machinery (reference:
source/net/yacy/document/LibraryProvider.java loading synonym
dictionaries from DATA/DICTIONARIES/synonyms/*, and Condenser.java:
applying them so a document containing one member of a synonym group is
also findable under the others). Dictionary format: one comma-separated
group per line ("car,automobile,vehicle"); lookups are symmetric within
a group.
"""

from __future__ import annotations

import os
import threading


class SynonymLibrary:
    def __init__(self, data_dir: str | None = None):
        self._groups: dict[str, set[str]] = {}
        self._lock = threading.Lock()
        self.data_dir = data_dir
        if data_dir and os.path.isdir(data_dir):
            for fn in sorted(os.listdir(data_dir)):
                if fn.endswith((".txt", ".csv")):
                    try:
                        with open(os.path.join(data_dir, fn),
                                  encoding="utf-8") as f:
                            self.load_text(f.read())
                    except OSError:
                        continue

    def load_text(self, text: str) -> int:
        groups = 0
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            words = {w.strip().lower() for w in line.split(",") if w.strip()}
            if len(words) < 2:
                continue
            self.add_group(words)
            groups += 1
        return groups

    def add_group(self, words: set[str]) -> None:
        with self._lock:
            # merge with any group a member already belongs to
            merged = set(words)
            for w in words:
                old = self._groups.get(w)
                if old is not None:
                    merged |= old
            for w in merged:
                self._groups[w] = merged

    def has_entries(self) -> bool:
        with self._lock:
            return bool(self._groups)

    def synonyms_of(self, word: str) -> set[str]:
        """Other members of the word's group ('' set when unknown)."""
        w = word.lower()
        with self._lock:
            group = self._groups.get(w)
            return (group - {w}) if group else set()

    def size(self) -> int:
        with self._lock:
            return len({id(g) for g in self._groups.values()})
