"""Vocabularies, autotagging and a triple store.

Capability equivalents of the reference's linked-data layer (reference:
source/net/yacy/cora/lod/ — an RDF-ish triple store (JenaTripleStore/
TripleStore) and vocabulary model (lod/vocabulary/*); document
autotagging from term vocabularies in document/Tokenizer + LibraryProvider
vocabularies loaded from DATA/DICTIONARIES; ProbabilisticClassifier
bridges bayes-trained context models). A Vocabulary maps literal terms
and synonyms onto tags; `tag_document` yields the vocabulary facets that
the reference writes into vocabulary_* Solr fields.
"""

from __future__ import annotations

import json
import os
import re
import threading

_TOKEN_RE = re.compile(r"\w+", re.UNICODE)


class Vocabulary:
    """term/synonym -> object (tag) mapping, matched against documents."""

    def __init__(self, name: str):
        self.name = name
        self._term2tag: dict[str, str] = {}

    def put(self, tag: str, terms: list[str]) -> None:
        for t in terms:
            t = t.strip().lower()
            if t:
                self._term2tag[t] = tag

    def tags(self) -> set[str]:
        return set(self._term2tag.values())

    def match(self, text: str) -> set[str]:
        found: set[str] = set()
        for tok in _TOKEN_RE.findall(text.lower()):
            tag = self._term2tag.get(tok)
            if tag:
                found.add(tag)
        return found

    def to_dict(self) -> dict:
        inv: dict[str, list[str]] = {}
        for term, tag in self._term2tag.items():
            inv.setdefault(tag, []).append(term)
        return {"name": self.name, "tags": inv}

    @staticmethod
    def from_dict(d: dict) -> "Vocabulary":
        v = Vocabulary(d.get("name", ""))
        for tag, terms in d.get("tags", {}).items():
            v.put(tag, terms)
        return v


class VocabularyLibrary:
    """Named vocabularies persisted under DATA/DICTIONARIES
    (LibraryProvider.vocabularies equivalent)."""

    def __init__(self, data_dir: str | None = None):
        self.data_dir = data_dir
        self._vocs: dict[str, Vocabulary] = {}
        self._lock = threading.Lock()
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            for fn in os.listdir(data_dir):
                if fn.endswith(".vocab.json"):
                    try:
                        with open(os.path.join(data_dir, fn),
                                  encoding="utf-8") as f:
                            v = Vocabulary.from_dict(json.load(f))
                        self._vocs[v.name] = v
                    except (OSError, ValueError):
                        continue

    def put(self, voc: Vocabulary) -> None:
        with self._lock:
            self._vocs[voc.name] = voc
            if self.data_dir:
                path = os.path.join(self.data_dir, voc.name + ".vocab.json")
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(voc.to_dict(), f, ensure_ascii=False)

    def get(self, name: str) -> Vocabulary | None:
        return self._vocs.get(name)

    def names(self) -> list[str]:
        return sorted(self._vocs)

    def tag_document(self, text: str) -> dict[str, set[str]]:
        """vocabulary name -> matched tags (the vocabulary_* facet values)."""
        with self._lock:   # snapshot: indexing races vocabulary admin
            vocs = list(self._vocs.items())
        out: dict[str, set[str]] = {}
        for name, voc in vocs:
            tags = voc.match(text)
            if tags:
                out[name] = tags
        return out


class TripleStore:
    """Minimal (subject, predicate, object) store with pattern queries
    (cora/lod TripleStore equivalent; None = wildcard)."""

    def __init__(self, path: str | None = None):
        self._triples: set[tuple[str, str, str]] = set()
        self._path = path
        self._lock = threading.Lock()
        if path and os.path.exists(path):
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        try:
                            s, p, o = json.loads(line)
                            self._triples.add((s, p, o))
                        except ValueError:
                            continue
            except OSError:
                pass

    def add(self, s: str, p: str, o: str) -> None:
        with self._lock:
            if (s, p, o) in self._triples:
                return
            self._triples.add((s, p, o))
            if self._path:
                try:
                    with open(self._path, "a", encoding="utf-8") as f:
                        f.write(json.dumps([s, p, o], ensure_ascii=False)
                                + "\n")
                except OSError:
                    pass

    def query(self, s: str | None = None, p: str | None = None,
              o: str | None = None) -> list[tuple[str, str, str]]:
        with self._lock:
            return [t for t in self._triples
                    if (s is None or t[0] == s)
                    and (p is None or t[1] == p)
                    and (o is None or t[2] == o)]

    def remove(self, s: str | None = None, p: str | None = None,
               o: str | None = None) -> int:
        with self._lock:
            victims = [t for t in self._triples
                       if (s is None or t[0] == s)
                       and (p is None or t[1] == p)
                       and (o is None or t[2] == o)]
            for t in victims:
                self._triples.discard(t)
            if victims and self._path:
                try:
                    tmp = self._path + ".tmp"
                    with open(tmp, "w", encoding="utf-8") as f:
                        for t in self._triples:
                            f.write(json.dumps(list(t), ensure_ascii=False)
                                    + "\n")
                    os.replace(tmp, self._path)
                except OSError:
                    pass
            return len(victims)

    def __len__(self) -> int:
        with self._lock:
            return len(self._triples)
