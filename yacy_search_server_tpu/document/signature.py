"""Content signatures — exact and fuzzy duplicate-detection hashes.

Capability equivalent of the reference's signature fields (reference:
search/schema/CollectionSchema.java exact_signature_l / fuzzy_signature_l,
computed by EnhancedTextProfileSignature — a Solr TextProfileSignature
variant hashing the most frequent words): 63-bit integers so exact
duplicates (same normalized text) and near-duplicates (same dominant
vocabulary) can be grouped with one int-column compare, which is also how
the uniqueness postprocessing marks *_unique_b flags.
"""

from __future__ import annotations

import hashlib
import re

_WORD_RE = re.compile(r"\w+", re.UNICODE)
_WS_RE = re.compile(r"\s+")


def _h63(data: str) -> int:
    """63-bit positive hash (fits the schema's signed long)."""
    digest = hashlib.md5(data.encode("utf-8", "replace")).digest()
    return int.from_bytes(digest[:8], "big") & 0x7FFF_FFFF_FFFF_FFFF


def exact_signature(text: str) -> int:
    """Hash of the whitespace-normalized, lowercased text — equal iff the
    visible content is byte-equal after trivial formatting."""
    return _h63(_WS_RE.sub(" ", text).strip().lower())


def fuzzy_profile_text(text: str, quant_rate: float = 0.01,
                       min_token_len: int = 2) -> str:
    """The dominant-vocabulary profile string the fuzzy signature hashes
    (stored as CollectionSchema.fuzzy_signature_text_t so operators can
    inspect WHY two documents grouped as near-duplicates)."""
    counts: dict[str, int] = {}
    for w in _WORD_RE.findall(text.lower()):
        if len(w) >= min_token_len:
            counts[w] = counts.get(w, 0) + 1
    if not counts:
        return ""
    max_freq = max(counts.values())
    quant = max(1, round(max_freq * quant_rate)) if max_freq > 1 else 1
    profile = sorted(
        (w for w, c in counts.items() if (c // quant) > 0),
        key=lambda w: (-(counts[w] // quant), w))[:64]
    return " ".join(f"{w}:{counts[w] // quant}" for w in profile)


def fuzzy_signature(text: str, quant_rate: float = 0.01,
                    min_token_len: int = 2) -> int:
    """Hash of the dominant vocabulary: words are counted, counts are
    quantized (TextProfileSignature's QUANT_RATE rounding), and tokens at
    the top quantized frequency form the profile. Layout/boilerplate
    differences that keep the same dominant words collide — which is the
    point."""
    return _h63(fuzzy_profile_text(text, quant_rate, min_token_len))
