"""Normalized parse result — what every parser emits.

Capability equivalent of the reference's Document model (reference:
source/net/yacy/document/Document.java): text, anchors, images, dc_*
metadata, geo position — the single currency between the parser zoo, the
condenser, and the index write path.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# doctype codes carried into the posting rows (reference: Response.docType
# char codes feeding WordReferenceRow's doctype column)
DT_TEXT = 0
DT_HTML = 1
DT_PDF = 2
DT_IMAGE = 3
DT_AUDIO = 4
DT_VIDEO = 5
DT_APP = 6


@dataclass
class Anchor:
    url: str
    text: str = ""
    rel: str = ""


@dataclass
class Image:
    url: str
    alt: str = ""
    width: int = 0
    height: int = 0


@dataclass
class Document:
    url: str
    mime_type: str = "text/plain"
    charset: str = "utf-8"
    title: str = ""
    author: str = ""
    description: str = ""       # dc:description / meta description
    keywords: list[str] = field(default_factory=list)
    sections: list[str] = field(default_factory=list)   # headlines h1..h6
    text: str = ""
    anchors: list[Anchor] = field(default_factory=list)
    images: list[Image] = field(default_factory=list)
    audio_links: list[str] = field(default_factory=list)
    video_links: list[str] = field(default_factory=list)
    app_links: list[str] = field(default_factory=list)
    language: str = ""
    lat: float = 0.0
    lon: float = 0.0
    publish_date_days: int = 0  # days since epoch; 0 = unknown
    doctype: int = 0            # document/parsers/__init__.py doctype codes
    # zone texts per heading level 1..6 (CollectionSchema h1_txt..h6_txt;
    # `sections` above stays the flat all-levels list)
    headings: dict = field(default_factory=dict)
    canonical: str = ""         # <link rel=canonical> target
    robots_flags: int = 0       # meta-robots bitfield (ROBOTS_* below)
    favicon: str = ""
    generator: str = ""         # <meta name=generator> (metagenerator_t)
    publisher: str = ""         # dc:publisher / og:site_name
    # schema long-tail structure groups (html parser; defaults keep
    # non-HTML parsers untouched)
    tag_texts: dict = field(default_factory=dict)  # li/dt/dd/article/...
    css: list = field(default_factory=list)
    scripts: list = field(default_factory=list)
    script_count: int = 0
    iframes: list = field(default_factory=list)
    frames: list = field(default_factory=list)
    hreflangs: list = field(default_factory=list)   # (lang-cc, url)
    navigation: list = field(default_factory=list)  # (rel-type, url)
    refresh: str = ""
    flash: bool = False
    opengraph: dict = field(default_factory=dict)   # og:* sans prefix
    publisher_url: str = ""
    rdf_triples: list = field(default_factory=list)  # (s, p, o)

    def hyperlinks(self) -> list[Anchor]:
        return self.anchors

    def text_length(self) -> int:
        return len(self.text)

    def merge(self, other: "Document") -> None:
        """Fold a sub-document (archive member, multi-doc parse) into this."""
        self.text = (self.text + "\n" + other.text).strip()
        self.anchors.extend(other.anchors)
        self.images.extend(other.images)
        self.sections.extend(other.sections)
        for level, texts in (other.headings or {}).items():
            self.headings.setdefault(level, []).extend(texts)
        if not self.title:
            self.title = other.title


# meta-robots bitfield carried in Document.robots_flags and the robots_i
# schema column (reference: ContentScraper's noindex/nofollow evaluation
# feeding CollectionSchema.robots_i)
ROBOTS_NOINDEX = 1
ROBOTS_NOFOLLOW = 2
ROBOTS_NOARCHIVE = 4
ROBOTS_NOSNIPPET = 8
