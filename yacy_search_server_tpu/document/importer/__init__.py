"""Surrogate importers — bulk document ingestion bypassing the crawler.

Capability equivalents of the reference's importer set (reference:
source/net/yacy/document/importer/WarcImporter.java:59,
MediawikiImporter.java, OAIPMHImporter.java). Each importer yields
normalized Documents that feed the same Segment.store_document write path
the crawler uses.
"""

from .warc import WarcImporter, parse_warc
from .mediawiki import MediawikiImporter, wikitext_to_text
from .oaipmh import OAIPMHHarvester

__all__ = ["WarcImporter", "parse_warc", "MediawikiImporter",
           "wikitext_to_text", "OAIPMHHarvester"]
