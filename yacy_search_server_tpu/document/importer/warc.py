"""WARC importer — ISO 28500 web-archive ingestion.

Capability equivalent of the reference's WarcImporter (reference:
source/net/yacy/document/importer/WarcImporter.java:59 — iterates WARC
response records via jwat-warc, parses each payload through TextParser,
and feeds Switchboard surrogate processing).  This is a native WARC
reader: record framing per the WARC/1.0 spec (header block, Content-Length
body, CRLF CRLF record separator), gzip transparency, response-record
HTTP payload splitting.
"""

from __future__ import annotations

import gzip
import io
from typing import Iterator

from ..document import Document
from ..parser import ParserError, parse_source


def _read_record(stream) -> tuple[dict, bytes] | None:
    """One WARC record: (headers, body) or None at EOF."""
    # skip blank lines between records
    line = stream.readline()
    while line in (b"\r\n", b"\n"):
        line = stream.readline()
    if not line:
        return None
    if not line.startswith(b"WARC/"):
        raise ValueError(f"bad warc version line: {line[:40]!r}")
    headers: dict[str, str] = {}
    while True:
        ln = stream.readline()
        if ln in (b"\r\n", b"\n", b""):
            break
        k, _, v = ln.decode("utf-8", "replace").partition(":")
        headers[k.strip().lower()] = v.strip()
    length = int(headers.get("content-length", "0"))
    body = stream.read(length)
    return headers, body


def _split_http_payload(body: bytes) -> tuple[str, bytes]:
    """HTTP response record -> (content_type, payload)."""
    head, sep, payload = body.partition(b"\r\n\r\n")
    if not sep:
        head, sep, payload = body.partition(b"\n\n")
    ctype = ""
    for ln in head.split(b"\n"):
        if ln.lower().startswith(b"content-type:"):
            ctype = ln.partition(b":")[2].strip().decode(
                "latin-1", "replace")
            break
    return ctype, payload


def parse_warc(data: bytes) -> Iterator[tuple[str, str, bytes]]:
    """Yield (url, mime, payload) for every response record."""
    if data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)
    stream = io.BytesIO(data)
    while True:
        rec = _read_record(stream)
        if rec is None:
            return
        headers, body = rec
        if headers.get("warc-type") != "response":
            continue
        url = headers.get("warc-target-uri", "")
        if not url:
            continue
        ctype = headers.get("content-type", "")
        if ctype.startswith("application/http"):
            mime, payload = _split_http_payload(body)
        else:
            mime, payload = ctype, body
        mime = mime.split(";")[0].strip().lower()
        yield url, mime, payload


class WarcImporter:
    """Parse every response record into Documents and feed a sink."""

    def __init__(self, sink):
        # sink: callable(Document) — normally Segment.store_document
        self.sink = sink
        self.records = 0
        self.indexed = 0
        self.failed = 0

    def import_bytes(self, data: bytes) -> int:
        for url, mime, payload in parse_warc(data):
            self.records += 1
            try:
                docs = parse_source(url, mime or None, payload)
            except ParserError:
                self.failed += 1
                continue
            for doc in docs:
                self.sink(doc)
                self.indexed += 1
        return self.indexed

    def import_file(self, path: str) -> int:
        with open(path, "rb") as f:
            return self.import_bytes(f.read())
