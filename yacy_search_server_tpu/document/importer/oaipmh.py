"""OAI-PMH harvester — Dublin Core record ingestion with resumption.

Capability equivalent of the reference's OAI-PMH importer (reference:
source/net/yacy/document/importer/OAIPMHImporter.java + OAIPMHLoader —
issues ListRecords requests, follows resumptionToken pages, converts each
oai_dc record into a surrogate document).  The fetcher is injectable
(zero-egress testing; production passes the crawler's loader).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from urllib.parse import quote

from ..document import Document

_DC = "{http://purl.org/dc/elements/1.1/}"
_OAI = "{http://www.openarchives.org/OAI/2.0/}"


class OAIPMHHarvester:
    def __init__(self, endpoint: str, fetcher, sink,
                 metadata_prefix: str = "oai_dc", max_pages: int = 64):
        # fetcher: callable(url) -> bytes; sink: callable(Document)
        self.endpoint = endpoint.rstrip("?")
        self.fetcher = fetcher
        self.sink = sink
        self.prefix = metadata_prefix
        self.max_pages = max_pages
        self.harvested = 0

    def _url(self, token: str | None) -> str:
        if token:
            return (f"{self.endpoint}?verb=ListRecords"
                    f"&resumptionToken={quote(token)}")
        return (f"{self.endpoint}?verb=ListRecords"
                f"&metadataPrefix={self.prefix}")

    def harvest(self) -> int:
        token: str | None = None
        for _ in range(self.max_pages):
            data = self.fetcher(self._url(token))
            token = self._ingest_page(data)
            if not token:
                break
        return self.harvested

    def _ingest_page(self, data: bytes) -> str | None:
        root = ET.fromstring(data)
        for rec in root.iter(_OAI + "record"):
            doc = self._record_to_document(rec)
            if doc is not None:
                self.sink(doc)
                self.harvested += 1
        tok = root.find(f".//{_OAI}resumptionToken")
        return tok.text.strip() if tok is not None and tok.text else None

    @staticmethod
    def _record_to_document(rec) -> Document | None:
        def dc(tag) -> list[str]:
            return [el.text.strip() for el in rec.iter(_DC + tag)
                    if el.text and el.text.strip()]
        idents = dc("identifier")
        url = next((i for i in idents if i.startswith("http")), None)
        if url is None:
            header_id = rec.find(f"{_OAI}header/{_OAI}identifier")
            if header_id is None or not header_id.text:
                return None
            url = "oai:" + header_id.text.strip()
        titles, descs = dc("title"), dc("description")
        text = "\n".join(titles + descs + dc("subject"))
        if not text:
            return None
        return Document(url=url, mime_type="text/html",
                        title=titles[0] if titles else "",
                        author=", ".join(dc("creator")),
                        description=descs[0] if descs else "",
                        keywords=dc("subject"), text=text)
