"""MediaWiki XML dump importer.

Capability equivalent of the reference's MediawikiImporter (reference:
source/net/yacy/document/importer/MediawikiImporter.java — streams a
`*-pages-articles.xml(.bz2)` dump, converts wikitext to text, and indexes
each page as a surrogate document).  Streaming via ElementTree.iterparse
so multi-GB dumps never materialize; a native wikitext stripper replaces
the reference's bundled MediawikiToHtml converter.
"""

from __future__ import annotations

import bz2
import gzip
import io
import re
import xml.etree.ElementTree as ET
from typing import Iterator

from ..document import Document

_DROP_BLOCKS = [
    re.compile(r"\{\{[^{}]*\}\}", re.S),        # templates (innermost)
    re.compile(r"<ref[^>/]*/>", re.S),
    re.compile(r"<ref[^>]*>.*?</ref>", re.S),   # references
    re.compile(r"<!--.*?-->", re.S),
]
_FILE_LINK = re.compile(r"\[\[(?:File|Image|Category)[^\[\]]*\]\]", re.I)
_LINK = re.compile(r"\[\[(?:[^|\]]*\|)?([^\]]+)\]\]")
_EXT_LINK = re.compile(r"\[(?:https?:)?//[^\s\]]+\s*([^\]]*)\]")
_MARKUP = re.compile(r"'{2,5}|={2,6}|^[*#:;]+", re.M)
_TAG = re.compile(r"<[^>]+>")


def wikitext_to_text(wt: str) -> str:
    """Wikitext -> plain text (MediawikiImporter's html conversion step)."""
    for _ in range(4):                    # nested templates
        prev = wt
        for pat in _DROP_BLOCKS:
            wt = pat.sub(" ", wt)
        if wt == prev:
            break
    wt = _FILE_LINK.sub(" ", wt)
    wt = _LINK.sub(r"\1", wt)
    wt = _EXT_LINK.sub(r"\1", wt)
    wt = _MARKUP.sub("", wt)
    wt = _TAG.sub(" ", wt)
    wt = re.sub(r"&(nbsp|amp|lt|gt|quot);", " ", wt)
    return re.sub(r"[ \t]+", " ", wt).strip()


def _localname(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


class MediawikiImporter:
    """Stream pages out of a dump into a Document sink."""

    def __init__(self, sink, base_url: str = "http://wiki.local/wiki/",
                 skip_redirects: bool = True):
        self.sink = sink
        self.base_url = base_url.rstrip("/") + "/"
        self.skip_redirects = skip_redirects
        self.pages = 0
        self.indexed = 0

    def import_stream(self, stream) -> int:
        title, text, in_page = "", "", False
        for event, el in ET.iterparse(stream, events=("start", "end")):
            name = _localname(el.tag)
            if event == "start" and name == "page":
                in_page, title, text = True, "", ""
            elif event == "end" and in_page:
                if name == "title":
                    title = el.text or ""
                elif name == "text":
                    text = el.text or ""
                elif name == "page":
                    self.pages += 1
                    self._emit(title, text)
                    in_page = False
                    el.clear()
        return self.indexed

    def _emit(self, title: str, wikitext: str) -> None:
        if not title or not wikitext:
            return
        if self.skip_redirects and wikitext.lstrip()[:9].upper().startswith(
                "#REDIRECT"):
            return
        body = wikitext_to_text(wikitext)
        if not body:
            return
        url = self.base_url + title.replace(" ", "_")
        self.sink(Document(url=url, mime_type="text/html", title=title,
                           text=body))
        self.indexed += 1

    def import_file(self, path: str) -> int:
        if path.endswith(".bz2"):
            with bz2.open(path, "rb") as f:
                return self.import_stream(f)
        if path.endswith(".gz"):
            with gzip.open(path, "rb") as f:
                return self.import_stream(f)
        with open(path, "rb") as f:
            return self.import_stream(f)

    def import_bytes(self, data: bytes) -> int:
        if data[:3] == b"BZh":
            data = bz2.decompress(data)
        elif data[:2] == b"\x1f\x8b":
            data = gzip.decompress(data)
        return self.import_stream(io.BytesIO(data))
