"""Tokenizer + Condenser — document -> per-word posting attributes.

Capability equivalent of the reference's NLP condensing stage (reference:
source/net/yacy/document/Condenser.java:60-183 and Tokenizer.java:43):
tokenize into phrases (sentences) and words, record per-word statistics
(hitcount, first position in text / in phrase / phrase number), set
appearance flags for words occurring in title / author / description /
headlines / url (Tokenizer.java flag semantics, WordReferenceRow.java:104-110),
and doc-level content-category flags (Tokenizer.java:51-56).

Output is designed for the dense write path: `postings_rows()` emits the
int32 feature vector of index/postings.py per word in one shot, so
Segment.store_document turns one document into a [n_words, NF] block append.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field

import numpy as np

from ..utils.bitfield import (
    Bitfield,
    FLAG_APP_DC_CREATOR, FLAG_APP_DC_DESCRIPTION, FLAG_APP_DC_IDENTIFIER,
    FLAG_APP_DC_SUBJECT, FLAG_APP_DC_TITLE, FLAG_APP_EMPHASIZED,
    FLAG_CAT_HASAPP, FLAG_CAT_HASAUDIO, FLAG_CAT_HASIMAGE, FLAG_CAT_HASLOCATION,
    FLAG_CAT_HASVIDEO, FLAG_CAT_INDEXOF,
)
from ..utils.hashes import url_comps, word_hashes
from .document import Document
from ..index import postings as P

_WORD_RE = re.compile(r"\w+", re.UNICODE)
_PHRASE_SPLIT_RE = re.compile(r"[.!?:;\n\r]+")

MAX_WORD_LENGTH = 128


def words_of(text: str) -> list[str]:
    return [w.lower() for w in _WORD_RE.findall(text)
            if 0 < len(w) <= MAX_WORD_LENGTH and not w.isdigit()]


def phrases_of(text: str) -> list[str]:
    return [p for p in (s.strip() for s in _PHRASE_SPLIT_RE.split(text)) if p]


@dataclass
class WordStat:
    count: int = 0
    posintext: int = 0      # first appearance, 1-based word position
    posinphrase: int = 0    # position inside its first phrase
    posofphrase: int = 0    # index of the first phrase containing the word
    flags: Bitfield = field(default_factory=Bitfield)


class Condenser:
    """Single-pass condensation of one Document."""

    def __init__(self, doc: Document, index_text: bool = True,
                 index_media: bool = True, synonyms=None):
        self.doc = doc
        self.words: dict[str, WordStat] = {}
        self.content_flags = Bitfield()
        self.word_count = 0
        self.phrase_count = 0
        self._zone_extra = 0  # zone-only words, counted apart from the body
        self._condense(index_text, index_media)
        if synonyms is not None:
            self._enrich_synonyms(synonyms)

    def _enrich_synonyms(self, synonyms) -> None:
        """Index the document under synonym terms too (reference:
        Condenser applies LibraryProvider synonym dictionaries so one
        group member makes the doc findable under all of them). Synonym
        entries inherit the source word's stats."""
        if not synonyms.has_entries():
            return      # empty library: skip the per-word lock round-trips
        extra: dict[str, WordStat] = {}
        self.synonym_terms: list[str] = []
        for w, st in self.words.items():
            for syn in synonyms.synonyms_of(w):
                if syn not in self.words and syn not in extra:
                    extra[syn] = WordStat(
                        count=st.count, posintext=st.posintext,
                        posinphrase=st.posinphrase,
                        posofphrase=st.posofphrase,
                        flags=Bitfield(st.flags.value))
        self.words.update(extra)
        # the record of indexing-time expansion (schema synonyms_sxt)
        self.synonym_terms = sorted(extra)

    # -- core pass -----------------------------------------------------------

    def _condense(self, index_text: bool, index_media: bool) -> None:
        doc = self.doc

        if index_text:
            phrases = phrases_of(doc.text)
            self.phrase_count = len(phrases)
            pos = 0
            for pnum, phrase in enumerate(phrases):
                for pip, w in enumerate(words_of(phrase)):
                    pos += 1
                    st = self.words.get(w)
                    if st is None:
                        self.words[w] = WordStat(
                            count=1, posintext=pos, posinphrase=pip + 1,
                            posofphrase=pnum)
                    else:
                        st.count += 1
            self.word_count = pos

        # appearance-flagged zones (each word occurrence OR-merges its flag)
        self._flag_zone(doc.title, FLAG_APP_DC_TITLE)
        self._flag_zone(doc.author, FLAG_APP_DC_CREATOR)
        self._flag_zone(doc.description, FLAG_APP_DC_DESCRIPTION)
        for section in doc.sections:
            self._flag_zone(section, FLAG_APP_DC_SUBJECT)
        for kw in doc.keywords:
            self._flag_zone(kw, FLAG_APP_DC_SUBJECT)
        self._flag_zone(re.sub(r"[/._\-?=&]", " ", doc.url), FLAG_APP_DC_IDENTIFIER)
        if index_media:
            for img in doc.images:
                self._flag_zone(img.alt, FLAG_APP_DC_DESCRIPTION)
            for a in doc.anchors:
                self._flag_zone(a.text, FLAG_APP_DC_DESCRIPTION)

        # doc-level category flags, propagated onto every word like the
        # reference's RESULT_FLAGS OR-merge
        cf = self.content_flags
        if "index of" in doc.title.lower() or "index of" in doc.text[:512].lower():
            cf.set(FLAG_CAT_INDEXOF)
        if doc.images:
            cf.set(FLAG_CAT_HASIMAGE)
        if doc.audio_links:
            cf.set(FLAG_CAT_HASAUDIO)
        if doc.video_links:
            cf.set(FLAG_CAT_HASVIDEO)
        if doc.app_links:
            cf.set(FLAG_CAT_HASAPP)
        if doc.lat or doc.lon:
            cf.set(FLAG_CAT_HASLOCATION)
        for st in self.words.values():
            st.flags.or_(cf)

    def _flag_zone(self, text: str, flag: int) -> None:
        if not text:
            return
        for w in words_of(text):
            st = self.words.get(w)
            if st is None:
                # zone-only word (e.g. title word not in body): still indexed,
                # positioned past the body — but it must not inflate
                # word_count, which feeds the wordcount_i / F_WORDS_IN_TEXT
                # body-size signal
                self._zone_extra += 1
                st = WordStat(count=1,
                              posintext=self.word_count + self._zone_extra)
                self.words[w] = st
            st.flags.set(flag)

    # -- dense output --------------------------------------------------------

    def doc_row(self, urlhash_feats: dict | None = None) -> np.ndarray:
        """Neutral doc-level feature row: the catchall-term posting and the
        base every per-word row derives from. Word-specific columns (flags,
        hitcount, positions) stay zero."""
        doc = self.doc
        base = np.zeros(P.NF, dtype=np.int32)
        base[P.F_LASTMOD] = doc.publish_date_days or int(time.time() // 86400)
        base[P.F_WORDS_IN_TITLE] = len(words_of(doc.title))
        base[P.F_WORDS_IN_TEXT] = min(self.word_count, 2**31 - 1)
        base[P.F_PHRASES_IN_TEXT] = self.phrase_count
        base[P.F_DOCTYPE] = doc.doctype
        base[P.F_LANGUAGE] = P.pack_language(doc.language)
        llocal = lother = 0
        from ..utils.hashes import safe_host
        own_host = safe_host(doc.url)
        for a in doc.anchors:
            host = safe_host(a.url)
            if host and host == own_host:
                llocal += 1
            else:
                lother += 1
        base[P.F_LLOCAL] = min(llocal, 255)
        base[P.F_LOTHER] = min(lother, 255)
        base[P.F_URL_LENGTH] = min(len(doc.url), 255)
        base[P.F_URL_COMPS] = url_comps(doc.url)
        if urlhash_feats:
            for k, v in urlhash_feats.items():
                base[k] = v
        return base

    def postings_rows(self, urlhash_feats: dict | None = None,
                      base_row: np.ndarray | None = None
                      ) -> tuple[list[bytes], np.ndarray]:
        """(term hashes, int32 [n_words, NF] feature rows), write-path ready.

        Doc-level columns (url length, link counts, language, ...) are
        broadcast into every row; `urlhash_feats` overrides them. A caller
        that already computed `doc_row()` passes it as `base_row` to skip
        recomputing the per-anchor/url derivations.
        """
        base = self.doc_row(urlhash_feats) if base_row is None else base_row
        rows = np.tile(base, (len(self.words), 1))
        hashes = word_hashes(list(self.words.keys()))
        for i, st in enumerate(self.words.values()):
            rows[i, P.F_FLAGS] = st.flags.value
            rows[i, P.F_HITCOUNT] = min(st.count, 255)
            rows[i, P.F_POSINTEXT] = min(st.posintext, 2**15)
            rows[i, P.F_POSINPHRASE] = min(st.posinphrase, 255)
            rows[i, P.F_POSOFPHRASE] = min(st.posofphrase, 255)
        return hashes, rows
