"""Query model, search orchestration and result ranking (L5 equivalent).

Reference layer: source/net/yacy/search/query/ + search/ranking/ +
search/navigator/ + search/snippet/ (SURVEY.md §1 L5).
"""
