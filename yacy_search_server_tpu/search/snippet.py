"""Snippet extraction — best sentence window for the query words.

Capability equivalent of the reference's snippet machinery (reference:
source/net/yacy/search/snippet/TextSnippet.java and
source/net/yacy/document/SnippetExtractor.java): pick the shortest
sentence combination containing the most query words, trim to a maximum
length around the match, and mark whether all words matched.

``SnippetProducer`` is the live half (VERDICT r2 missing #4): when the
stored ``text_t`` is gone (blanked row, remote result, imported
metadata), the page is fetched through the crawler's LoaderDispatcher
under the query's cacheStrategy — CACHEONLY by default (never hit the
network at query time, the reference's p2p default), IFEXIST for
intranet deployments — parsed, and the snippet extracted from the live
text. Results whose snippet cannot be produced are EVICTED from the
page and, when the fetch proved the URL dead (4xx/5xx, not a transport
error), deleted from the local index — the reference's
``deleteIfSnippetFail`` result-quality mechanism
(SearchEvent.java:1862-1948).
"""

from __future__ import annotations

import re
from concurrent.futures import ThreadPoolExecutor

_SENTENCE_RE = re.compile(r"[^.!?\n\r]+[.!?]?")
MAX_SNIPPET_LENGTH = 220


def extract_snippet(text: str, words: list[str],
                    max_length: int = MAX_SNIPPET_LENGTH) -> tuple[str, bool]:
    """(snippet, all_words_matched) — best-coverage shortest sentence set."""
    if not text or not words:
        return text[:max_length], False
    lw = [w.lower() for w in words]
    best, best_hits, best_len = "", 0, 1 << 30
    for m in _SENTENCE_RE.finditer(text):
        s = m.group().strip()
        if not s:
            continue
        sl = s.lower()
        hits = sum(1 for w in lw if w in sl)
        if hits > best_hits or (hits == best_hits and 0 < hits
                                and len(s) < best_len):
            best, best_hits, best_len = s, hits, len(s)
            if hits == len(lw) and len(s) <= max_length:
                break
    if not best:
        best = text[:max_length]
    if len(best) > max_length:
        # center the window on the first matching word
        pos = min((best.lower().find(w) for w in lw
                   if best.lower().find(w) >= 0), default=0)
        start = max(0, pos - max_length // 3)
        best = ("..." if start else "") + best[start:start + max_length] + "..."
    return best, best_hits == len(lw)


# outcomes of a live snippet attempt
SNIPPET_OK = "ok"            # snippet produced
SNIPPET_UNVERIFIED = "unverified"   # nothing cached / transport error —
#                                     the URL is not proven dead
SNIPPET_DEAD = "dead"        # the fetch proved the URL gone (4xx/5xx)

MAX_SNIPPET_WORKERS = 4

# ONE shared pool for all page renders: per-query ThreadPoolExecutor
# construction + join cost ~2 ms/query on the serving path (profiled in
# r4) — more than the snippet lookups themselves under CACHEONLY
_POOL: ThreadPoolExecutor | None = None


def _pool() -> ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        _POOL = ThreadPoolExecutor(max_workers=MAX_SNIPPET_WORKERS,
                                   thread_name_prefix="snippet")
    return _POOL


class SnippetProducer:
    """Live snippet production through the crawler's loader.

    One per SearchEvent page render; `produce_many` fetches the page's
    missing snippets with a small worker pool (the reference's
    concurrent snippet workers, SearchEvent.java:1862-1930)."""

    def __init__(self, loader, strategy: str = "cacheonly"):
        self.loader = loader
        self.strategy = strategy

    def produce(self, url: str, words: list[str]) -> tuple[str, str]:
        """(snippet, outcome) for one URL under the cacheStrategy."""
        from ..crawler.request import Request
        if self.loader is None:
            return "", SNIPPET_UNVERIFIED
        try:
            resp = self.loader.load(Request(url=url), self.strategy)
        except Exception:
            return "", SNIPPET_UNVERIFIED
        status = resp.status or 0
        if "x-error" in resp.headers:
            # synthetic response (cache miss under CACHEONLY, transport
            # failure): the document was never actually answered for —
            # proves nothing about the URL
            return "", SNIPPET_UNVERIFIED
        if status in (404, 410):
            # the server answered that the document is GONE — the
            # deleteIfSnippetFail signal. Access-denied (401/403 — WAFs
            # routinely 403 crawler-shaped fetches of live pages),
            # transient statuses (429, 5xx), and transport errors prove
            # nothing and must never purge a live document.
            return "", SNIPPET_DEAD
        if status != 200 or not resp.content:
            return "", SNIPPET_UNVERIFIED
        try:
            from ..document.parser.registry import parse_source
            ctype = resp.headers.get("content-type", "text/html")
            docs = parse_source(url, ctype.split(";")[0].strip(),
                                resp.content)
            text = "\n".join(d.text for d in docs if d.text)
        except Exception:
            return "", SNIPPET_UNVERIFIED
        if not text:
            return "", SNIPPET_UNVERIFIED
        snippet, _all = extract_snippet(text, words)
        return snippet, SNIPPET_OK

    def produce_many(self, urls: list[str],
                     words: list[str]) -> list[tuple[str, str]]:
        if len(urls) <= 1:
            return [self.produce(u, words) for u in urls]
        return list(_pool().map(lambda u: self.produce(u, words), urls))
