"""Snippet extraction — best sentence window for the query words.

Capability equivalent of the reference's snippet machinery (reference:
source/net/yacy/search/snippet/TextSnippet.java and
source/net/yacy/document/SnippetExtractor.java): pick the shortest
sentence combination containing the most query words, trim to a maximum
length around the match, and mark whether all words matched. The reference
may fetch the page live (cacheStrategy) — here the condensed text is in the
metadata store (`text_t`), so extraction is always cache-local; a live
re-fetch path can layer on the crawler's loader later.
"""

from __future__ import annotations

import re

_SENTENCE_RE = re.compile(r"[^.!?\n\r]+[.!?]?")
MAX_SNIPPET_LENGTH = 220


def extract_snippet(text: str, words: list[str],
                    max_length: int = MAX_SNIPPET_LENGTH) -> tuple[str, bool]:
    """(snippet, all_words_matched) — best-coverage shortest sentence set."""
    if not text or not words:
        return text[:max_length], False
    lw = [w.lower() for w in words]
    best, best_hits, best_len = "", 0, 1 << 30
    for m in _SENTENCE_RE.finditer(text):
        s = m.group().strip()
        if not s:
            continue
        sl = s.lower()
        hits = sum(1 for w in lw if w in sl)
        if hits > best_hits or (hits == best_hits and 0 < hits
                                and len(s) < best_len):
            best, best_hits, best_len = s, hits, len(s)
            if hits == len(lw) and len(s) <= max_length:
                break
    if not best:
        best = text[:max_length]
    if len(best) > max_length:
        # center the window on the first matching word
        pos = min((best.lower().find(w) for w in lw
                   if best.lower().find(w) >= 0), default=0)
        start = max(0, pos - max_length // 3)
        best = ("..." if start else "") + best[start:start + max_length] + "..."
    return best, best_hits == len(lw)
