"""DidYouMean — spelling/completion suggestions probed against the index.

Capability equivalent of the reference's suggestion generator (reference:
source/net/yacy/data/DidYouMean.java): generate candidate words by the
four edit operations (change/add/delete/transpose letters) plus word
splits, then keep only candidates that actually occur in the local term
index, ranked by posting count.  The reference runs producer/consumer
threads against the IndexCell; here candidate existence is a batched
probe of the RWI (one `count` lookup per candidate — cheap dict/array
lookups, no IO).
"""

from __future__ import annotations

from ..utils.hashes import word2hash

ALPHABET = "abcdefghijklmnopqrstuvwxyz0123456789"


class DidYouMean:
    def __init__(self, segment):
        self.segment = segment

    def _count(self, word: str) -> int:
        return self.segment.rwi.count(word2hash(word))

    def candidates(self, word: str) -> set[str]:
        w = word.lower()
        cand: set[str] = set()
        # ChangingOneLetter / AddingOneLetter / DeletingOneLetter /
        # ReversingTwoConsecutiveLetters (DidYouMean.java producer set)
        for i in range(len(w)):
            for c in ALPHABET:
                cand.add(w[:i] + c + w[i + 1:])
        for i in range(len(w) + 1):
            for c in ALPHABET:
                cand.add(w[:i] + c + w[i:])
        for i in range(len(w)):
            cand.add(w[:i] + w[i + 1:])
        for i in range(len(w) - 1):
            cand.add(w[:i] + w[i + 1] + w[i] + w[i + 2:])
        cand.discard(w)
        cand.discard("")
        return cand

    def suggest(self, word: str, count: int = 10,
                include_exact: bool = True) -> list[str]:
        """Best `count` suggestions by index posting count.  For a
        multi-word query, the last token is completed and the head is
        carried through verbatim (reference: suggest.java completes the
        last token)."""
        w = word.lower().strip()
        if not w:
            return []
        if " " in w:
            head, _, last = w.rpartition(" ")
            return [f"{head} {s}"
                    for s in self.suggest(last, count, include_exact)]
        scored: list[tuple[int, str]] = []
        if include_exact:
            n = self._count(w)
            if n:
                scored.append((n, w))
        for c in self.candidates(w):
            n = self._count(c)
            if n:
                scored.append((n, c))
        # word-split candidates: both halves must exist
        for i in range(1, len(w)):
            a, b = w[:i].strip(), w[i:].strip()
            if not a or not b:
                continue
            na, nb = self._count(a), self._count(b)
            if na and nb:
                scored.append((min(na, nb), f"{a} {b}"))
        scored.sort(key=lambda t: (-t[0], t[1]))
        out, seen = [], set()
        for _, s in scored:
            if s not in seen:
                seen.add(s)
                out.append(s)
            if len(out) >= count:
                break
        return out
