"""Federated external search — OpenSearch endpoints merged into a live event.

Capability equivalent of the reference's federated-search heuristics
(reference: source/net/yacy/cora/federate/FederateSearchManager.java +
opensearch/OpenSearchConnector — configured OpenSearch RSS/Atom URL
templates queried at search time, results injected into the running
SearchEvent as remote entries; wired by Switchboard's heuristic config).
Endpoints are `...{searchTerms}...` URL templates; fetching goes through
the node's loader (cache, politeness, blacklist, zero-egress injection).
"""

from __future__ import annotations

import threading
from urllib.parse import quote

from ..crawler.loader import CacheStrategy
from ..crawler.request import Request
from ..utils.hashes import safe_host, url2hash


def parse_opensearch_results(content: bytes) -> list[dict]:
    """RSS 2.0 / Atom feed -> [{title, link, description}].

    Thin adapter over the parser zoo's feed parser (first-link-wins, HTML
    stripped from summaries) so federated results and feed indexing share
    one set of feed semantics."""
    from ..document.parser.xmlparsers import parse_feed
    rows = []
    for doc in parse_feed("opensearch://result", content):
        if doc.url and doc.url != "opensearch://result":
            rows.append({"title": doc.title, "link": doc.url,
                         "description": doc.description})
    return rows


class FederateSearchManager:
    """Query configured OpenSearch endpoints and feed a live SearchEvent."""

    def __init__(self, loader, endpoints: list[str] | None = None):
        self.loader = loader
        self.endpoints = list(endpoints or [])

    @staticmethod
    def from_config(loader, config) -> "FederateSearchManager":
        raw = config.get("heuristic.opensearch.urls", "")
        eps = [u.strip() for u in raw.split("|") if u.strip()]
        return FederateSearchManager(loader, eps)

    def query_endpoint(self, template: str, querystring: str) -> list[dict]:
        url = template.replace("{searchTerms}", quote(querystring))
        resp = self.loader.load(Request(url), CacheStrategy.IFFRESH)
        if resp.status != 200:
            return []
        return parse_opensearch_results(resp.content)

    def search_into_event(self, event, querystring: str,
                          per_endpoint: int = 10,
                          asynchronous: bool = True) -> int:
        """Fan out to every endpoint; merge results as remote entries.
        Returns endpoints launched (async) or results merged (sync)."""
        if not self.endpoints:
            return 0

        def one(template: str) -> int:
            from .searchevent import ResultEntry
            rows = self.query_endpoint(template, querystring)[:per_endpoint]
            entries = []
            for r in rows:
                try:
                    entries.append(ResultEntry(
                        docid=-1, urlhash=url2hash(r["link"]),
                        score=0, url=r["link"], title=r["title"],
                        snippet=r["description"],
                        host=safe_host(r["link"]),
                        source=f"opensearch:{safe_host(template)}"))
                except Exception:
                    continue
            return event.add_remote_results(entries)

        if asynchronous:
            for t in self.endpoints:
                threading.Thread(target=one, args=(t,), daemon=True,
                                 name="federated-search").start()
            return len(self.endpoints)
        return sum(one(t) for t in self.endpoints)
