"""AccessTracker — per-query log + host access accounting.

Capability equivalent of the reference's search access tracking (reference:
source/net/yacy/search/query/AccessTracker.java:50-172 — a bounded
in-memory list of executed queries with timing/result counts, dumped to a
log file for statistics, plus host-level access counts used for abuse
control on the public search surface; host access also in
server/serverAccessTracker.java).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

MAX_FINISHED = 500          # bounded history (reference minSize/maxSize trim)
DUMP_BATCH = 50             # entries buffered before a dump append


@dataclass
class QueryLogEntry:
    query: str
    timestamp: float
    query_count: int        # include-word count
    result_count: int
    time_ms: float
    offset: int = 0
    client: str = ""

    def dump_line(self) -> str:
        # one line per query: unixtime, client, words, results, millis, query
        return (f"{int(self.timestamp)} {self.client or '-'} "
                f"{self.query_count} {self.result_count} "
                f"{self.time_ms:.1f} {self.query}")


class AccessTracker:
    """Bounded query history with optional file dump + per-host counters."""

    def __init__(self, dump_path: str | None = None):
        self.dump_path = dump_path
        self._finished: deque[QueryLogEntry] = deque(maxlen=MAX_FINISHED)
        self._undumped: list[str] = []
        self._host_access: dict[str, deque[float]] = {}
        self._access_calls = 0
        self._lock = threading.Lock()
        if dump_path:
            os.makedirs(os.path.dirname(dump_path), exist_ok=True)

    # -- query log -----------------------------------------------------------

    def add(self, entry: QueryLogEntry) -> None:
        with self._lock:
            self._finished.append(entry)
            if self.dump_path:
                self._undumped.append(entry.dump_line())
                if len(self._undumped) >= DUMP_BATCH:
                    self._dump_locked()

    def latest(self, n: int = 50) -> list[QueryLogEntry]:
        with self._lock:
            return list(self._finished)[-n:][::-1]

    def size(self) -> int:
        with self._lock:
            return len(self._finished)

    def _dump_locked(self) -> None:
        lines, self._undumped = self._undumped, []
        try:
            with open(self.dump_path, "a", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
        except OSError:
            pass

    def dump(self) -> None:
        with self._lock:
            if self._undumped:
                self._dump_locked()

    # -- host access (abuse control surface) ---------------------------------

    def track_access(self, client_host: str, window_s: float = 600.0) -> int:
        """Record one access from `client_host`; returns accesses within the
        window (callers throttle above a threshold)."""
        now = time.time()
        with self._lock:
            # maxlen bounds a flooding client's memory; the window prune
            # below keeps the COUNT honest for throttling decisions
            times = self._host_access.setdefault(
                client_host, deque(maxlen=20_000))
            times.append(now)
            cutoff = now - window_s
            while times and times[0] < cutoff:
                times.popleft()
            # bound the dict itself: one-off client IPs must not accumulate
            # keys forever on a public node
            self._access_calls += 1
            if self._access_calls % 256 == 0:
                self._prune_hosts_locked(cutoff)
            return len(times)

    def _prune_hosts_locked(self, cutoff: float) -> None:
        dead = []
        for host, times in self._host_access.items():
            while times and times[0] < cutoff:
                times.popleft()
            if not times:
                dead.append(host)
        for host in dead:
            del self._host_access[host]

    def retry_after_s(self, client_host: str, limit: int,
                      window_s: float = 600.0) -> float:
        """Seconds until a retry from this host would PASS the windowed
        limit — the honest Retry-After for a WINDOW denial (ISSUE 9,
        replacing the hard-coded 600).  The retry appends itself before
        the `hits > limit` check, so `over + 1` oldest entries must age
        out, not `over` — an off-by-one here 429s the very client that
        honored the header exactly."""
        now = time.time()
        with self._lock:
            times = self._host_access.get(client_host)
            if not times:
                return 0.0
            over = len(times) - limit
            if over <= 0:
                return 0.0
            i = min(over, len(times) - 1)
            # +1 ms past the boundary: the window prune is STRICT
            # (`times[0] < cutoff`), so at the exact expiry instant the
            # entry still counts — the advertised wait must land
            # strictly after it
            return max(0.0, times[i] + window_s - now + 0.001)

    def access_hosts(self, window_s: float = 600.0) -> list[tuple[str, int]]:
        with self._lock:
            self._prune_hosts_locked(time.time() - window_s)
            return sorted(((h, len(t)) for h, t in self._host_access.items()),
                          key=lambda x: -x[1])
