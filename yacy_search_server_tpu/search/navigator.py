"""Navigators — facet counters over the result candidate set.

Capability equivalent of the reference's navigator plugin registry
(reference: source/net/yacy/search/navigator/ — RestrictedStringNavigator,
HostNavigator, LanguageNavigator, YearNavigator, ...; assembled by
NavigatorPlugins.java and accumulated per result in
SearchEvent.java:1131+). Each navigator is a score map keyed by a facet
value; the UI renders the top entries as refinement links.
"""

from __future__ import annotations

from ..utils.scoremap import ScoreMap

DEFAULT_NAVIGATORS = ("hosts", "language", "filetype", "authors", "year",
                      "dates")


class Navigator:
    """One facet dimension: counts of facet values over seen results."""

    def __init__(self, name: str, field: str):
        self.name = name
        self.field = field
        self.counts = ScoreMap()

    def add(self, value) -> None:
        if value is None:
            return
        v = str(value).strip()
        if v:
            self.counts.inc(v)

    def top(self, n: int = 10) -> list[tuple[str, int]]:
        return self.counts.top(n)

    def __len__(self) -> int:
        return len(self.counts)


def make_navigators(names=DEFAULT_NAVIGATORS) -> dict[str, Navigator]:
    fields = {
        "hosts": "host_s",
        "language": "language_s",
        "filetype": "url_file_ext_s",
        "authors": "author",
        "year": "last_modified_days_i",
        "collections": "collection_sxt",
        # dates mentioned IN the content (reference: DateNavigator over
        # dates_in_content_dts), distinct from the `year` modified-date facet
        "dates": "dates_in_content_dts",
    }
    return {n: Navigator(n, fields[n]) for n in names if n in fields}


def _add_value(nav: Navigator, v) -> None:
    if nav.name == "year" and v:
        import datetime
        v = datetime.date.fromordinal(
            datetime.date(1970, 1, 1).toordinal() + int(v)).year
    if nav.name == "dates" and v:
        from ..index.metadata import split_multi
        for date in split_multi(str(v)):
            nav.add(date)
        return
    nav.add(v)


def accumulate(navigators: dict[str, Navigator], meta) -> None:
    """Count one result document into every active navigator."""
    for nav in navigators.values():
        _add_value(nav, meta.get(nav.field))


def accumulate_batch(navigators: dict[str, Navigator], store,
                     docids) -> None:
    """Count a CANDIDATE SET into every navigator with one batched
    column read per field (per-row LazyRow.get over ~80 oversampled
    candidates x 7 fields was the serving path's top host cost)."""
    from ..index.metadata import INT_FIELDS
    for nav in navigators.values():
        vals = (store.int_values(docids, nav.field)
                if nav.field in INT_FIELDS
                else store.text_values(docids, nav.field))
        for v in vals:
            _add_value(nav, v)
