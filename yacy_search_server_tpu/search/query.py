"""Query model: goal, modifiers, parameters.

Capability equivalent of the reference's query model (reference:
source/net/yacy/search/query/QueryGoal.java — include/exclude word sets
with +/- operators and quoted phrases; QueryModifier.java — in-string
operators site:, filetype:, author:, keyword:, tld:, protocol:, inurl:,
intitle:, daterange:, /language/xx, /date sorting; QueryParams.java —
the full query state handed to the SearchEvent, including the constraint
bitfield and the cache id used to reuse a live event for paging).
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field

from ..ops.ranking import CD_ALL, CD_AUDIO, CD_APP, CD_IMAGE, CD_TEXT, CD_VIDEO, RankingProfile
from ..utils.hashes import word2hash

CONTENTDOM_NAMES = {
    "all": CD_ALL, "text": CD_TEXT, "image": CD_IMAGE,
    "audio": CD_AUDIO, "video": CD_VIDEO, "app": CD_APP,
}

_LANG_MOD = re.compile(r"/language/(\w\w)\b")
_DATE_SORT = re.compile(r"(^|\s)/date(\s|$)")


@dataclass
class QueryModifier:
    """Operators stripped out of the query string (QueryModifier.java)."""

    sitehost: str = ""
    sitehash: str = ""
    filetype: str = ""
    author: str = ""
    keyword: str = ""
    tld: str = ""
    protocol: str = ""
    inurl: str = ""
    intitle: str = ""
    language: str = ""
    date_sort: bool = False
    # daterange:YYYY-MM-DD..YYYY-MM-DD -> inclusive bounds, days since epoch
    from_days: int | None = None
    to_days: int | None = None

    def is_empty(self) -> bool:
        return not (self.sitehost or self.filetype or self.author
                    or self.keyword or self.tld or self.protocol
                    or self.inurl or self.intitle or self.language
                    or self.date_sort or self.from_days is not None
                    or self.to_days is not None)

    def to_string(self) -> str:
        parts = []
        if self.sitehost:
            parts.append(f"site:{self.sitehost}")
        if self.filetype:
            parts.append(f"filetype:{self.filetype}")
        if self.author:
            parts.append(f"author:{self.author}")
        if self.keyword:
            parts.append(f"keyword:{self.keyword}")
        if self.tld:
            parts.append(f"tld:{self.tld}")
        if self.protocol:
            parts.append(f"protocol:{self.protocol}")
        if self.inurl:
            parts.append(f"inurl:{self.inurl}")
        if self.intitle:
            parts.append(f"intitle:{self.intitle}")
        if self.language:
            parts.append(f"/language/{self.language}")
        if self.date_sort:
            parts.append("/date")
        if self.from_days is not None or self.to_days is not None:
            parts.append(f"daterange:{self.from_days}..{self.to_days}")
        return " ".join(parts)


def _strip_prefix_op(q: str, prefix: str) -> tuple[str, str]:
    """Remove `prefix:value` from the query; return (rest, value).

    The prefix must start a token (string start or after whitespace), so
    words merely containing it — `parasite:...`, `website:...` — are not
    mis-parsed as operators.
    """
    i = q.find(prefix)
    while i > 0 and not q[i - 1].isspace():
        i = q.find(prefix, i + 1)
    if i < 0:
        return q, ""
    j = i + len(prefix)
    if j < len(q) and q[j] == "(":
        end = q.find(")", j)
        if end < 0:
            end = len(q)
        value = q[j + 1:end]
        rest = q[:i] + q[end + 1:]
    else:
        end = q.find(" ", j)
        if end < 0:
            end = len(q)
        value = q[j:end]
        rest = q[:i] + q[end:]
    return re.sub(r"\s+", " ", rest).strip(), value.strip()


def parse_modifiers(querystring: str) -> tuple[str, QueryModifier]:
    """Split in-string operators out, returning (bare query, modifier)."""
    q = querystring
    m = QueryModifier()
    q, m.sitehost = _strip_prefix_op(q, "site:")
    if m.sitehost.startswith("www."):
        m.sitehost = m.sitehost[4:]
    q, m.filetype = _strip_prefix_op(q, "filetype:")
    if m.filetype.startswith("."):
        m.filetype = m.filetype[1:]
    m.filetype = m.filetype.lower()
    q, m.author = _strip_prefix_op(q, "author:")
    q, m.keyword = _strip_prefix_op(q, "keyword:")
    q, m.tld = _strip_prefix_op(q, "tld:")
    if m.tld.startswith("."):
        m.tld = m.tld[1:]
    q, m.protocol = _strip_prefix_op(q, "protocol:")
    q, m.inurl = _strip_prefix_op(q, "inurl:")
    q, m.intitle = _strip_prefix_op(q, "intitle:")
    q, dr = _strip_prefix_op(q, "daterange:")
    if dr:
        m.from_days, m.to_days = _parse_daterange(dr)
    lang = _LANG_MOD.search(q)
    if lang:
        m.language = lang.group(1).lower()
        q = _LANG_MOD.sub("", q)
    if _DATE_SORT.search(q):
        m.date_sort = True
        q = _DATE_SORT.sub(" ", q)
    return re.sub(r"\s+", " ", q).strip(), m


@dataclass
class QueryGoal:
    """Include/exclude word sets parsed from the bare query string.

    Reference semantics (QueryGoal.java): words split on whitespace;
    a leading '-' excludes; "quoted phrases" keep their words in the
    include set and remember the phrase for snippet/post filtering;
    include hashes are the search keys for the RWI lookup.
    """

    include_words: list[str] = field(default_factory=list)
    exclude_words: list[str] = field(default_factory=list)
    phrases: list[str] = field(default_factory=list)
    # hash-level queries (P2P search wire carries word HASHES, never the
    # words — the reference's privacy property): when set, these override
    # the hashes derived from the word lists
    _include_hashes_override: list[bytes] | None = None
    _exclude_hashes_override: list[bytes] | None = None

    @staticmethod
    def parse(bare_query: str) -> "QueryGoal":
        g = QueryGoal()
        q = bare_query
        # pull out quoted phrases first
        for phrase in re.findall(r'"([^"]*)"', q):
            phrase = phrase.strip()
            if phrase:
                g.phrases.append(phrase.lower())
                for w in _words(phrase):
                    if w not in g.include_words:
                        g.include_words.append(w)
        q = re.sub(r'"[^"]*"', " ", q)
        for tok in q.split():
            if tok.startswith("-") and len(tok) > 1:
                for w in _words(tok[1:]):
                    if w not in g.exclude_words:
                        g.exclude_words.append(w)
            else:
                for w in _words(tok):
                    if w not in g.include_words and w not in g.exclude_words:
                        g.include_words.append(w)
        return g

    @property
    def include_hashes(self) -> list[bytes]:
        if self._include_hashes_override is not None:
            return self._include_hashes_override
        return [word2hash(w) for w in self.include_words]

    @property
    def exclude_hashes(self) -> list[bytes]:
        if self._exclude_hashes_override is not None:
            return self._exclude_hashes_override
        return [word2hash(w) for w in self.exclude_words]

    def is_catchall(self) -> bool:
        return self.include_words == ["*"] or not self.include_words

    def matches(self, text: str) -> bool:
        """All include words (and phrases) present, no exclude word."""
        t = text.lower()
        for w in self.include_words:
            if w not in t:
                return False
        for w in self.exclude_words:
            if w in t:
                return False
        for p in self.phrases:
            if p not in t:
                return False
        return True


def _words(s: str) -> list[str]:
    return [w.lower() for w in re.findall(r"\w+", s, re.UNICODE) if w]


def _days_since_epoch(datestr: str) -> int | None:
    """'YYYY-MM-DD' or 'YYYYMMDD' -> days since 1970-01-01; None if invalid."""
    import datetime
    s = datestr.strip().replace("-", "")
    if len(s) != 8 or not s.isdigit():
        return None
    try:
        d = datetime.date(int(s[:4]), int(s[4:6]), int(s[6:8]))
    except ValueError:
        return None
    return d.toordinal() - datetime.date(1970, 1, 1).toordinal()


def _parse_daterange(spec: str) -> tuple[int | None, int | None]:
    """'from..to' (either side optional) -> inclusive day bounds."""
    parts = spec.split("..") if ".." in spec else [spec, spec]
    lo = _days_since_epoch(parts[0]) if parts[0] else None
    hi = _days_since_epoch(parts[1]) if len(parts) > 1 and parts[1] else None
    return lo, hi


@dataclass
class QueryParams:
    """Full query state (QueryParams.java:1232 equivalent, load-bearing
    subset): goal + modifier + paging + content domain + ranking profile +
    site/tld constraints; `query_id()` keys the SearchEventCache."""

    goal: QueryGoal
    modifier: QueryModifier
    querystring: str = ""
    item_count: int = 10
    offset: int = 0
    contentdom: int = CD_TEXT
    max_results_rwi: int = 3000
    max_results_node: int = 300
    timeout_ms: int = 3000
    lang: str = "en"
    profile: RankingProfile | None = None
    snippet_fetch: bool = True
    # live snippet cacheStrategy (reference: search.verify config —
    # CACHEONLY never hits the network at query time, the p2p default;
    # IFEXIST is the intranet default) + deleteIfSnippetFail eviction
    snippet_strategy: str = "cacheonly"
    snippet_delete_on_fail: bool = True
    facets: tuple = ("hosts", "language", "filetype", "authors", "year",
                     "dates")
    # domain diversity: max results per host before diversion
    # (doubledom handling, SearchEvent.java:1297-1412)
    max_per_host: int = 6
    # M7 hybrid rerank: blend dense cosine into the sparse first stage
    # (ops/dense.py; new capability beyond the reference)
    hybrid: bool = False
    hybrid_alpha: float = 0.5
    # dense-first retrieval (ISSUE 11): the IVF ANN index generates a
    # dense candidate stream fused with the sparse one — implies
    # hybrid; sheds one ladder rung before the rerank
    dense_first: bool = False
    # optional result URL veto (ContentControl filter; reference consults
    # it in the SearchEvent drain) — callable(url) -> True when blocked
    url_filter: object = None
    # degradation ladder rung this query serves under (ISSUE 9,
    # utils/actuator.LEVEL_*): 0 full, 1 skip live snippets, 2 skip
    # dense rerank, 3 rank-cache/stale-ok only.  Part of query_id so a
    # degraded event never masquerades as (or pages against) a full one
    degrade_level: int = 0

    @staticmethod
    def parse(querystring: str, **kw) -> "QueryParams":
        bare, modifier = parse_modifiers(querystring)
        goal = QueryGoal.parse(bare)
        p = QueryParams(goal=goal, modifier=modifier, querystring=querystring,
                        **kw)
        if modifier.language:
            p.lang = modifier.language
        if p.profile is None:
            p.profile = RankingProfile.for_contentdom(p.contentdom)
        return p

    def query_id(self) -> str:
        """Stable id for event caching — same semantics as the reference's
        QueryParams.id(): identical query state reuses the live event, so
        paging does not re-run the search."""
        key = "|".join((
            ",".join(sorted(
                h.decode("ascii", "replace")
                for h in self.goal.include_hashes)),
            ",".join(sorted(
                h.decode("ascii", "replace")
                for h in self.goal.exclude_hashes)),
            ",".join(sorted(self.include_words())),
            ",".join(sorted(self.goal.exclude_words)),
            ",".join(sorted(self.goal.phrases)),
            self.modifier.to_string(), str(self.contentdom), self.lang,
            self.profile.to_external_string() if self.profile else "",
            (f"h{int(self.hybrid)}a{self.hybrid_alpha}"
             + ("df" if self.dense_first else "")) if self.hybrid else "",
            "cc" if self.url_filter is not None else "",
            f"d{self.degrade_level}" if self.degrade_level else "",
        ))
        return hashlib.md5(key.encode()).hexdigest()  # nosec: cache key only

    def include_words(self) -> list[str]:
        return self.goal.include_words
