"""SearchEvent — scatter-gather search orchestrator, TPU-first.

Capability equivalent of the reference's SearchEvent
(reference: source/net/yacy/search/query/SearchEvent.java:112-2563, the
2,563-line orchestrator) and SearchEventCache.java:42-199. The reference
runs a local Solr thread + a local RWI thread + N remote-peer threads, all
feeding two bounded priority heaps, then drains the heaps through filters,
doubledom diversion and post-ranking per `oneResult` call. Here the local
path is batched:

    term_search (sorted join)  →  constraint masks  →  device cardinal
    + top-K kernel (ops/ranking.score_topk)  →  metadata join  →
    host-diversity drain  →  post-ranking  →  result list

Remote feeders (M5, peers/) later call `add_remote_postings` /
`add_remote_results` on a live event — the heaps survive as host-side
fusion points for asynchronous WAN producers, exactly the straggler
strategy of SURVEY.md §7 ("deadline + late-merge into the cached event").

Filters are applied as masks BEFORE the kernel (the reference interleaves
them into its heap-insert loop, SearchEvent.java:673-836): contentdom
flag constraint, language, site host, tld, filetype, inurl/intitle/author
modifier checks. Host diversity (max N per host, then diversion —
`doubledom`, SearchEvent.java:1297-1412) runs host-side over the oversized
top-K so result *quality* matches, not just speed (SURVEY.md §7 hard part
#1: two-stage top-k with domain-diversity constraints).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..index import postings as P
from ..index.segment import Segment
from ..ops.ranking import (CD_ALL, CD_APP, CD_AUDIO, CD_IMAGE, CD_TEXT,
                           CD_VIDEO, CardinalRanker)
from ..utils.bitfield import (FLAG_CAT_HASAPP, FLAG_CAT_HASAUDIO,
                              FLAG_CAT_HASIMAGE, FLAG_CAT_HASVIDEO)
from ..utils import profiling, tracing
from ..utils.eventtracker import EClass, StageTimer, update as track
from ..utils.hashes import hosthash
from ..utils.topk import WeakPriorityQueue
from .navigator import accumulate, make_navigators
from .query import QueryParams
from .snippet import extract_snippet

# oversampling factor for the device top-k so host-side diversity/filter
# rechecks still fill the page (reference pulls from an unbounded-ish heap)
TOPK_OVERSAMPLE = 8

_CD_FLAG = {CD_IMAGE: FLAG_CAT_HASIMAGE, CD_AUDIO: FLAG_CAT_HASAUDIO,
            CD_VIDEO: FLAG_CAT_HASVIDEO, CD_APP: FLAG_CAT_HASAPP}


def _unconstrained_single_term(q) -> bool:
    """THE predicate for "plain single term, no constraints of any
    kind" — the cacheable query shape.  One implementation shared by
    the device-path eligibility gate and the rung-3 cache-only path: a
    constraint gate added to one but not the other would serve a
    cached UNCONSTRAINED answer for a constrained query (wrong, not
    stale)."""
    m = q.modifier
    inc, exc = q.goal.include_hashes, q.goal.exclude_hashes
    return (len(inc) == 1 and not exc and not m.date_sort
            and not (m.sitehost or m.tld or m.filetype or m.protocol)
            and not m.language
            and _CD_FLAG.get(q.contentdom) is None
            and m.from_days is None and m.to_days is None
            and q.profile.authority <= 12)


@dataclass
class ResultEntry:
    """One search result row (URIMetadataNode-equivalent surface)."""

    docid: int
    urlhash: bytes
    score: int
    url: str = ""
    title: str = ""
    snippet: str = ""
    snippet_done: bool = False  # lazily extracted at page render
    host: str = ""
    filetype: str = ""
    language: str = ""
    size: int = 0
    wordcount: int = 0
    lastmod_days: int = 0
    references: int = 0
    source: str = "local"   # local | peer hash

    def to_json(self) -> dict:
        return {
            "link": self.url, "title": self.title, "description": self.snippet,
            "urlhash": self.urlhash.decode("ascii", "replace"),
            "host": self.host, "size": self.size, "sizename": _sizename(self.size),
            "ranking": int(self.score), "source": self.source,
        }


def _sizename(n: int) -> str:
    for unit in ("bytes", "kB", "MB", "GB"):
        if n < 1024:
            return f"{n} {unit}"
        n //= 1024
    return f"{n} TB"


@dataclass
class ImageResult:
    """One image search result (contentdom=image serving mode — the
    reference builds these from images_urlstub_sxt with source-page
    attribution, SearchEvent.java:2178-2280 / yacysearchitem image
    branch)."""

    image_url: str
    alt: str
    source_url: str          # the page the image appears on
    source_title: str
    source_urlhash: bytes
    host: str
    score: int
    filetype: str = ""
    source: str = "local"


class SearchEvent:
    """One live search: executes locally at construction, accepts remote
    feeder inserts afterwards, serves pages via `one_result`/`results`."""

    def __init__(self, query: QueryParams, segment: Segment, loader=None):
        self.query = query
        self.segment = segment
        # crawler loader for LIVE snippet production (None: cache-local
        # extraction only — embedded/federated events have no crawler)
        self.loader = loader
        self.snippet_evictions = 0
        self._snippet_evicted: set[bytes] = set()
        self.created = time.time()
        self.touched = time.time()
        self._lock = threading.RLock()
        self.navigators = make_navigators(query.facets)
        # host-side fusion heap for asynchronous (remote) producers; local
        # batched results are inserted at construction
        self.result_heap: WeakPriorityQueue[ResultEntry] = WeakPriorityQueue(
            max(query.max_results_node, query.item_count * 10))
        self._seen_urlhashes: set[bytes] = set()
        self._host_counts: dict[bytes, int] = {}
        self._diverted: list[tuple[int, ResultEntry]] = []
        self.local_rwi_considered = 0
        self.local_rwi_evicted = 0
        self.remote_peers_asked = 0
        self.remote_results = 0
        # which peers this event scattered to and which answered — the
        # live state behind the per-event network picture (reference:
        # htroot/SearchEventPicture.java over SearchEvent.primarySearch)
        self.asked_peers: list = []
        self.result_peer_hashes: set[bytes] = set()
        # one-shot latch for query-time heuristics: they fire when the
        # event is created, never again on cache hits/paging (the
        # reference's heuristics are per-search-event)
        self.heuristics_fired = False
        self._pending: list[tuple[int, int]] = []  # lazily-drained ranked
        self._drained = 0                          # local entries drained
        self._ranker = CardinalRanker(query.profile, query.lang)
        # the trace this event was born under: remote feeder threads and
        # late-merging producers parent their spans here (the contextvar
        # does not cross the fan-out thread boundary)
        self.trace_ctx = tracing.current()
        # degradation ladder rung (ISSUE 9, utils/actuator.LEVEL_*):
        # each rung serves a PREFIX of the full pipeline, so degraded
        # answers stay bit-identical in ordering to the corresponding
        # non-degraded stage outputs (tie discipline per stage)
        self.degrade_level = getattr(query, "degrade_level", 0)
        self._run_local()

    def _note_degraded(self, stage: str, n: int = 1) -> None:
        """Every downgraded stage is counted (eventtracker ->
        yacy_stage_events_total) and traced (a zero-length marker span
        when a trace is active)."""
        track(EClass.SEARCH, f"DEGRADED_{stage}", n)
        tracing.emit("search.degraded", 0.0, stage=stage,
                     level=self.degrade_level)

    # -- local batched path --------------------------------------------------

    def _run_local(self) -> None:
        q = self.query
        k_need = max(q.item_count + q.offset, 10) * TOPK_OVERSAMPLE

        # ladder rung 3 (cache-only / stale-ok): answer from the
        # versioned top-k cache with ZERO ranking work; a miss returns
        # an empty page instead of paying device/host ranking — the
        # last line of defense before shedding outright
        if self.degrade_level >= 3:
            got = self._cache_only(k_need)
            if got is not None:
                scores, docids, self.local_rwi_considered = got
                if len(docids):
                    self._fill_results(scores, docids)
            return

        # hybrid-cache plumbing: _device_local may serve a FULL cached
        # hybrid answer (rerank included, zero device work) or hand back
        # the put context for inserting the one computed below
        self._rerank_done = False
        self._hybrid_put = None
        # steady-state path: rank placed device blocks (uploads only the
        # RAM delta); None -> host path (term not resident / query shape
        # needs host-side data)
        placed = self._device_local(k_need)
        if placed is not None:
            scores, docids, self.local_rwi_considered = placed
            if len(docids) == 0:
                return
            if q.hybrid and not self._rerank_done:
                scores, docids = self._second_stage(scores, docids,
                                                    k_need,
                                                    allow_put=True)
            self._fill_results(scores, docids)
            return

        with StageTimer(EClass.SEARCH, "JOIN"):
            joined = self.segment.term_search(
                include_hashes=q.goal.include_hashes or None,
                exclude_hashes=q.goal.exclude_hashes or None)
        self.local_rwi_considered = len(joined)
        if len(joined) == 0:
            return

        with StageTimer(EClass.SEARCH, "PRESORT"):
            mask = self._constraint_mask(joined)
            cand = joined.select(mask)
        if len(cand) == 0:
            return

        # the authority signal is the only hosthash consumer; the per-row
        # python loop must not run for profiles that never read it
        # (ReferenceOrder.java:255 guard — authority only when coeff > 12)
        hosthashes = None
        if q.profile.authority > 12:
            hosthashes = [hosthash(self.segment.metadata.urlhash_of(d))
                          for d in cand.docids.tolist()]
        k = min(len(cand), k_need)
        if q.modifier.date_sort:
            # /date modifier: recency replaces the cardinal as the sort key
            # (reference: QueryModifier /date -> Solr sort last_modified desc)
            lastmod = cand.feats[:, P.F_LASTMOD].astype(np.int64)
            order = np.argsort(-lastmod, kind="stable")[:k]
            scores, docids = lastmod[order], cand.docids[order]
        else:
            with StageTimer(EClass.SEARCH, "NORMALIZING", len(cand)):
                scores, docids = self._ranker.rank(cand, hosthashes, k=k)

        if q.hybrid and len(docids) and not q.modifier.date_sort:
            # host-computed answers never enter the hybrid cache: they
            # are not bit-identical to device-path answers, and a
            # cached one would flap the versioned top-k contract
            scores, docids = self._second_stage(scores, docids, k_need,
                                                allow_put=False)

        self._fill_results(scores, docids)

    def _cache_only(self, k: int):
        """Ladder rung 3 (ISSUE 9): the versioned top-k cache is the
        ONLY serving source — stale-ok, because at this rung an answer
        computed against a slightly older arena epoch beats paying any
        ranking work (and beats shedding).  Only the unconstrained
        single-term shape can answer from the cache (the cache key
        carries no constraints — serving a cached unconstrained answer
        for a constrained query would be wrong, not stale); everything
        else misses and returns empty, counted."""
        q = self.query
        ds = self.segment.devstore
        inc = q.goal.include_hashes
        peek = getattr(ds, "rank_cache_get", None) if ds is not None \
            else None
        if peek is not None and _unconstrained_single_term(q):
            try:
                got = peek(inc[0], q.profile, q.lang, k, stale_ok=True)
            except TypeError:
                # store without the stale_ok surface (mesh store, rank-
                # service client): the strict peek still serves hits
                got = peek(inc[0], q.profile, q.lang, k)
            if got is not None:
                self._note_degraded("CACHE_ONLY_HIT", len(got[1]))
                return got
        self._note_degraded("CACHE_ONLY_MISS")
        return None

    def _fill_results(self, scores, docids) -> None:
        """Queue the ranked candidates and materialize lazily: the page
        drain (results()) joins metadata only for as many entries as the
        page needs plus a post-ranking cushion — materializing the whole
        oversampled top-k per query was the serving path's python
        bottleneck. A cushion beyond the page keeps post-ranking boosts
        competing across the page boundary.

        Facets accumulate over the FULL ranked candidate set here (cheap
        columnar reads), not over materialized entries — the reference's
        facet counts also cover the whole query result, not the page
        (Solr facet counting)."""
        self._pending = list(zip(scores.tolist(), docids.tolist()))
        self._pending.reverse()          # pop() from the end = best-first
        if self.navigators:
            meta = self.segment.metadata
            alive = [int(d) for d in docids.tolist()
                     if not meta.is_deleted(int(d))
                     and int(d) < meta.capacity()]
            from .navigator import accumulate_batch
            accumulate_batch(self.navigators, meta, alive)
        self._drain(self.query.offset + self.query.item_count)

    def _drain(self, need: int) -> None:
        """Materialize pending local candidates until `cushion` of them
        have been drained (counted independently of the heap, which remote
        feeders also fill — remote inserts must not starve better local
        candidates out of materialization)."""
        cushion = need * 2 + 6
        with self._lock:
            if not self._pending:
                return
            with StageTimer(EClass.SEARCH, "RESULTLIST"):
                while self._pending and self._drained < cushion:
                    score, docid = self._pending.pop()
                    made = self._make_entry(int(docid), int(score))
                    if made is None:
                        self.local_rwi_evicted += 1
                        continue
                    self._drained += 1
                    entry, _meta = made
                    self._insert(entry)

    def _device_local(self, k: int):
        """Eligibility gate + dispatch for the device-resident serving path
        (index/devstore.py). Plain single terms rank via the pruned span
        scan; conjunctions — and single terms with exclusions — via the
        device join (sort-merge over docid-sorted side-tables). Query
        shapes needing host-side data still fall back: metadata modifiers
        (site:/tld:/filetype:/protocol), date-sort, and authority-boosted
        profiles."""
        q = self.query
        ds = self.segment.devstore
        if ds is None:
            return None
        inc, exc = q.goal.include_hashes, q.goal.exclude_hashes
        if not inc:
            return None
        m = q.modifier
        from ..index.devstore import NO_FLAG, NO_LANG
        flag = _CD_FLAG.get(q.contentdom)
        lang_filter = (P.pack_language(m.language) if m.language
                       else NO_LANG)
        flag_bit = NO_FLAG if flag is None else flag
        facet_mods = bool(m.sitehost or m.tld or m.filetype or m.protocol)
        # ONE predicate for "plain single term, no constraints of any
        # kind" — the cacheable shape, shared with the rung-3 cache-only
        # path (module-level _unconstrained_single_term). A new routing
        # gate below that constrains results must extend that ONE
        # conjunction, not drift past a hand-copied list.
        unconstrained = _unconstrained_single_term(q)
        # cache-aware eligibility: a repeated hot term answers from the
        # store's versioned top-k result cache with ZERO device work, so
        # none of the cost-based gates below apply to it — in particular
        # the small-candidate host gate (count_upper takes the RWI lock
        # and a cache hit is cheaper than even that host scoring)
        if unconstrained:
            # hybrid queries peek the HYBRID cache first: a hit is the
            # full two-stage answer (sparse rank + dense rerank),
            # bit-identical with zero device work; keyed additionally
            # on (alpha, encoder version, vector version) so it can
            # never survive an encoder swap or a vector write. A miss
            # remembers the put context — the epoch BEFORE the sparse
            # stage runs, so a racing flush leaves the entry born-stale
            # (rung 2 skips the hybrid peek entirely: a cached HYBRID
            # answer would disagree with the rerank-skipped order every
            # computed answer serves while degraded)
            if q.hybrid and self.degrade_level < 2:
                hpeek = getattr(ds, "hybrid_cache_get", None)
                if hpeek is not None:
                    # dense-first answers live under their own key
                    # (candidate stream differs); a dense-first query
                    # that will SHED its probe (rung 1) serves the
                    # plain-hybrid key its computed answer will match
                    df = bool(getattr(q, "dense_first", False)) \
                        and self.degrade_level < 1
                    q0 = time.perf_counter()
                    got = hpeek(inc[0], q.profile, q.lang, k,
                                q.hybrid_alpha, dense_first=df)
                    if got is not None:
                        wall_ms = (time.perf_counter() - q0) * 1000.0
                        track(EClass.SEARCH, "DEVRANK", len(got[1]),
                              wall_ms)
                        tracing.emit("search.devrank", wall_ms,
                                     cache="hybrid_hit")
                        self._rerank_done = True
                        return got
                    # the vector-content version is snapshotted HERE,
                    # with the epoch: a vector write racing the rerank
                    # below must leave the entry unreachable, not filed
                    # under the post-write key as if fresh (the ANN
                    # centroid version likewise, for dense-first)
                    self._hybrid_put = (ds, inc[0], ds.arena_epoch,
                                        ds.hybrid_vector_version(),
                                        ds.ann_centroid_version())
            # the sparse peek still serves hybrid queries' FIRST stage
            # (a hybrid-cache miss can ride a sparse hit into rerank)
            peek = getattr(ds, "rank_cache_get", None)
            if peek is not None:
                q0 = time.perf_counter()
                got = peek(inc[0], q.profile, q.lang, k)
                if got is not None:
                    # the stage still lands in BOTH observability
                    # surfaces (attributable, with zero device work
                    # behind it); hit-only so misses don't double-count
                    # the real DEVRANK stage below
                    wall_ms = (time.perf_counter() - q0) * 1000.0
                    track(EClass.SEARCH, "DEVRANK", len(got[1]), wall_ms)
                    tracing.emit("search.devrank", wall_ms, cache="hit")
                    return got
        # tiny candidate sets: the host path scores them in microseconds
        # (ops/ranking.SMALL_RANK_N numpy twin); a device dispatch — and
        # through a remote tunnel, a full round trip — would dominate.
        # A conjunction's join size is bounded by its RAREST term.
        from ..ops.ranking import SMALL_RANK_N
        # store-overridable threshold: a mesh dryrun (or a locally
        # attached device with a ~0 dispatch floor) may lower it
        thresh = getattr(ds, "small_rank_n", None)
        if thresh is None:
            thresh = SMALL_RANK_N
        if min(self.segment.rwi.count_upper(th)
               for th in inc) <= thresh:
            return None
        if m.date_sort:
            return None
        # metadata-constrained modifiers (site:/tld:/filetype:/protocol)
        # serve on device for SINGLE-term queries via a cached facet
        # docid bitmap (VERDICT r3 #5 widening); conjunctions with them
        # keep the host join
        if facet_mods and (len(inc) != 1 or exc
                           or not getattr(ds, "supports_filter_bitmap",
                                          False)):
            return None
        if q.profile.authority > 12:
            return None
        if len(inc) == 1 and not exc:
            if facet_mods:
                # residency pre-check: building+uploading a bitmap for a
                # term the store will decline anyway is dead work (and
                # would trigger a pointless background prewarm)
                spans = ds.spans_for(inc[0])
                if spans is None or len(spans) > ds.MAX_SPANS:
                    return None
            # the kwarg only goes to stores that declared support (the
            # facet_mods gate above guarantees allow is None otherwise)
            extra = ({"allow_bitmap": self._facet_filter_bitmap(ds, m)}
                     if facet_mods else {})
            with StageTimer(EClass.SEARCH, "DEVRANK"):
                return ds.rank_term(
                    inc[0], q.profile, q.lang, k=k,
                    lang_filter=lang_filter, flag_bit=flag_bit,
                    from_days=m.from_days, to_days=m.to_days, **extra)
        with StageTimer(EClass.SEARCH, "DEVJOIN"):
            return ds.rank_join(
                inc, exc, q.profile, q.lang, k=k,
                lang_filter=lang_filter, flag_bit=flag_bit,
                from_days=m.from_days, to_days=m.to_days)

    def _facet_filter_bitmap(self, ds, m):
        """Device filter bitmap for the active metadata modifiers —
        SAME membership semantics as the host path's _modifier_mask
        (site: exact host or subdomain; tld: suffix; filetype/protocol:
        equality), cached on device per (modifier combo, facet version,
        capacity)."""
        meta = self.segment.metadata
        parts = []
        if m.sitehost:
            parts.append(("site", m.sitehost.lower()))
        if m.tld:
            parts.append(("tld", m.tld.lower()))
        if m.filetype:
            parts.append(("ft", m.filetype.lower()))
        if m.protocol:
            parts.append(("proto", m.protocol.lower()))
        key = (tuple(parts), getattr(meta, "facet_version", 0),
               meta.capacity())

        def docids_fn():
            allowed = None
            for kind, val in parts:
                if kind == "site":
                    suffix = "." + val
                    got = meta.facet_docids(
                        "host_s",
                        lambda h: h == val or h.endswith(suffix))
                elif kind == "tld":
                    suffix = "." + val
                    got = meta.facet_docids(
                        "host_s", lambda h: h.endswith(suffix))
                elif kind == "ft":
                    got = meta.facet_docids("url_file_ext_s", val)
                else:
                    got = meta.facet_docids("url_protocol_s", val)
                allowed = got if allowed is None else \
                    np.intersect1d(allowed, got, assume_unique=False)
            return allowed if allowed is not None else np.empty(0, np.int64)

        return ds.filter_bitmap(key, docids_fn)

    def _second_stage(self, scores, docids, k_need: int,
                      allow_put: bool):
        """The hybrid second stage behind the degradation ladder
        (ISSUE 11): dense-first candidate generation + fusion (sheds at
        rung 1 — ONE rung before the rerank, utils/actuator
        .LEVEL_NO_DENSE_FIRST), the dense rerank (sheds at rung 2), or
        the sparse order as-is. Every rung's output keeps the pinned
        (score DESC, docid ASC) tie discipline, so degraded answers are
        bit-identical to the corresponding non-degraded stage prefix.
        With `allow_put`, files the computed answer in the hybrid top-k
        cache under the context _device_local snapshotted (device-path
        answers only — host-computed orders are not bit-identical)."""
        q = self.query
        if self.degrade_level >= 2:
            # ladder rung 2: skip the whole dense stage — the sparse
            # stage's pinned order serves as-is
            self._note_degraded("RERANK", len(docids))
            return scores, docids
        df_served = False
        if q.dense_first:
            if self.degrade_level >= 1:
                # dense-first sheds one rung BEFORE the rerank: the
                # candidate-generation probe is the more expensive
                # stage, and shedding it still leaves a full hybrid
                # (sparse + rerank) answer
                self._note_degraded("DENSEFIRST", len(docids))
            else:
                with StageTimer(EClass.SEARCH, "DENSEFIRST",
                                len(docids)):
                    got = self._dense_first(scores, docids, k_need)
                if got is not None:
                    scores, docids = got
                    df_served = True
                # None: no ANN index — the plain rerank below serves
                # (counted ann_fallbacks by the store)
        if not df_served:
            with StageTimer(EClass.SEARCH, "DENSERERANK", len(docids)):
                scores, docids = self._dense_rerank(scores, docids)
        if allow_put and self._hybrid_put is not None:
            ds, th, epoch0, dv0, cv0 = self._hybrid_put
            ds.hybrid_cache_put(
                th, q.profile, q.lang, k_need, q.hybrid_alpha,
                epoch0, scores, docids, self.local_rwi_considered,
                dv0=dv0, dense_first=df_served, cv0=cv0)
        return scores, docids

    def _dense_first(self, scores, docids, k: int):
        """Dense-first candidate generation (ISSUE 11): the IVF ANN
        index turns the query vector into a candidate stream that is
        fused with the sparse candidates in ONE cardinal score domain
        (sparse + fixed-scale dense boost) under the pinned (score
        DESC, docid ASC) tie discipline — a document sparse retrieval
        missed can now be recovered by the dense path. Steady state
        rides the devstore's batched `ann` kernel family
        (dense_first_topk); an event without a devstore probes the
        segment's index host-side. Returns None when no ANN index is
        attached (the caller keeps the plain rerank)."""
        q = self.query
        qtext = " ".join(q.include_words())
        qvec = self.segment.encoder.encode(qtext)
        sparse = np.asarray(scores, dtype=np.int64).astype(np.int32)
        dd = np.asarray(docids).astype(np.int32)
        ds = self.segment.devstore
        fn = getattr(ds, "dense_first_topk", None) \
            if ds is not None else None
        if fn is not None:
            got = fn(qvec, sparse, dd, q.hybrid_alpha, k)
            if got is not None:
                s, d = got
                return np.asarray(s, dtype=np.int64), np.asarray(d)
        ann = getattr(self.segment, "ann", None)
        if ann is not None and getattr(ann, "built", False):
            s, d = ann.search_host(qvec, dd, sparse, q.hybrid_alpha, k)
            return np.asarray(s, dtype=np.int64), np.asarray(d)
        return None

    def _dense_rerank(self, scores, docids):
        """M7 second stage: add dense cosine similarity into the sparse
        cardinal scores on device. One score domain throughout — the
        boost has a FIXED scale, so fusion with remote results never
        depends on the local batch's score range.

        Steady state rides the devstore's batched forward-index kernel
        (rerank_boost): candidates gather their doc vectors ON DEVICE
        and concurrent hybrid queries coalesce into one MXU dispatch
        through the pipelined batcher — the per-query get_block gather
        + solo dense_boost_topk hop only survives as the fallback for
        stores without a device path (mesh store, over-budget forward
        index). Both paths order ties by (score DESC, docid ASC) — the
        pinned discipline that keeps solo/batched/packed/cached rerank
        answers identical (arxiv 1807.05798)."""
        q = self.query
        qtext = " ".join(self.query.include_words())
        qvec = self.segment.encoder.encode(qtext)
        docids = np.asarray(docids)
        sparse = np.asarray(scores, dtype=np.int64)
        ds = self.segment.devstore
        rb = getattr(ds, "rerank_boost", None) if ds is not None else None
        if rb is not None:
            got = rb(qvec, sparse.astype(np.int32),
                     docids.astype(np.int32), q.hybrid_alpha)
            if got is not None:
                s, d = got
                return np.asarray(s, dtype=np.int64), np.asarray(d)
        # device lost (ISSUE 10c): the legacy path below still runs a
        # device kernel — on a REAL dead device it would crash the
        # query.  Serve the sparse order instead (the ladder's rung-2
        # prefix: deterministic, tie discipline already applied) and
        # count it as a degraded rerank
        if ds is not None and getattr(ds, "device_lost", False):
            self._note_degraded("RERANK", len(docids))
            return sparse, docids
        # host-gather legacy path (no device store / no device-resident
        # forward index): per-query block upload + solo kernel
        import jax.numpy as jnp

        from ..ops.dense import dense_boost_topk

        doc_vecs = self.segment.dense.get_block(docids)
        k = int(len(docids))
        final, order = dense_boost_topk(
            jnp.asarray(qvec), jnp.asarray(doc_vecs),
            jnp.asarray(sparse.astype(np.int32)),
            jnp.ones(k, dtype=bool), jnp.float32(q.hybrid_alpha), k)
        final = np.asarray(final, dtype=np.int64)
        dd = docids[np.asarray(order)]
        # re-assert the tie discipline (lax.top_k orders ties by input
        # position, i.e. sparse rank): score DESC, then docid ASC
        tie = np.lexsort((dd, -final))
        return final[tie], dd[tie]

    def _constraint_mask(self, plist) -> np.ndarray:
        """Vector filters replacing the reference's per-row checks in
        addRWIs (flags/contentdom/language constraints) and the metadata
        recheck in pullOneFilteredFromRWI (site/tld/filetype)."""
        q = self.query
        n = len(plist)
        mask = np.ones(n, dtype=bool)
        # contentdom flag constraint
        flag = _CD_FLAG.get(q.contentdom)
        if flag is not None:
            mask &= (plist.feats[:, P.F_FLAGS] >> flag) & 1 == 1
        # language modifier is a hard filter (reference: language handled
        # both as filter for /language/ modifier and as ranking preference)
        if q.modifier.language:
            mask &= plist.feats[:, P.F_LANGUAGE] == P.pack_language(
                q.modifier.language)
        # daterange: inclusive bounds on last-modified days
        if q.modifier.from_days is not None:
            mask &= plist.feats[:, P.F_LASTMOD] >= q.modifier.from_days
        if q.modifier.to_days is not None:
            mask &= plist.feats[:, P.F_LASTMOD] <= q.modifier.to_days
        # metadata constraints via the facet inverted indexes: each
        # modifier resolves to a sorted docid set by iterating DISTINCT
        # field values (hosts/extensions/protocols — thousands at most),
        # then one vectorized isin over the candidates. Replaces the
        # per-candidate-row python loop that dominated 100k-row masks
        # (VERDICT r1 weak #5).
        meta = self.segment.metadata
        m = q.modifier
        if m.sitehost:
            want = m.sitehost.lower()
            suffix = "." + want
            allowed = meta.facet_docids(
                "host_s", lambda h: h == want or h.endswith(suffix))
            mask &= np.isin(plist.docids, allowed, assume_unique=False)
        if m.tld:
            suffix = "." + m.tld.lower()
            allowed = meta.facet_docids(
                "host_s", lambda h: h.endswith(suffix))
            mask &= np.isin(plist.docids, allowed, assume_unique=False)
        if m.filetype:
            allowed = meta.facet_docids("url_file_ext_s",
                                        m.filetype.lower())
            mask &= np.isin(plist.docids, allowed, assume_unique=False)
        if m.protocol:
            allowed = meta.facet_docids("url_protocol_s",
                                        m.protocol.lower())
            mask &= np.isin(plist.docids, allowed, assume_unique=False)
        return mask

    def _make_entry(self, docid: int, score: int):
        """Metadata join + modifier recheck; returns (ResultEntry, row)
        or None when evicted. Uses the lazy column-backed row — this runs
        once per oversampled candidate, the serving drain's hot loop."""
        q = self.query
        m = self.segment.metadata.row(docid)
        if m is None:
            return None
        url = m.get("sku", "")
        title = m.get("title", "") or url
        if q.url_filter is not None and q.url_filter(url):
            return None
        if q.modifier.inurl and q.modifier.inurl.lower() not in url.lower():
            return None
        if q.modifier.intitle and q.modifier.intitle.lower() not in title.lower():
            return None
        if q.modifier.author:
            if q.modifier.author.lower() not in (m.get("author") or "").lower():
                return None
        # metadata-facet recheck (site:/tld:/filetype:/protocol): the
        # device path filters by a facet BITMAP that may be up to
        # FILTER_TTL_S stale under active indexing (devstore
        # .filter_bitmap) — a stale false positive dies here, so staleness
        # only ever DELAYS inclusion (the reference's soft-commit lag)
        mod = q.modifier
        if mod.sitehost or mod.tld or mod.filetype or mod.protocol:
            host = (m.get("host_s") or "").lower()
            if mod.sitehost:
                want = mod.sitehost.lower()
                if host != want and not host.endswith("." + want):
                    return None
            if mod.tld and not host.endswith("." + mod.tld.lower()):
                return None
            if mod.filetype and (m.get("url_file_ext_s") or "").lower() \
                    != mod.filetype.lower():
                return None
            if mod.protocol and not url.lower().startswith(
                    mod.protocol.lower() + ":"):
                return None
        if q.modifier.keyword:
            if q.modifier.keyword.lower() not in (m.get("keywords") or "").lower():
                return None
        # quoted phrases must literally appear (QueryGoal phrase recheck)
        if q.goal.phrases:
            text = m.get("text_t", "")
            tl = text.lower()
            for ph in q.goal.phrases:
                if ph not in tl and ph not in title.lower():
                    return None
        # snippet extraction is deferred to page render (results()):
        # only the ~10 returned entries need one, not the whole
        # oversampled top-k — the drain loop is the serving hot path
        return ResultEntry(
            docid=docid, urlhash=self.segment.metadata.urlhash_of(docid),
            score=score, url=url, title=title, snippet="",
            host=m.get("host_s", ""), filetype=m.get("url_file_ext_s", ""),
            language=m.get("language_s", ""), size=m.get("size_i", 0),
            wordcount=m.get("wordcount_i", 0),
            lastmod_days=m.get("last_modified_days_i", 0),
            references=m.get("references_i", 0)), m

    # -- fusion (local batch now, remote feeders in M5) ----------------------

    def _insert(self, entry: ResultEntry) -> bool:
        """Dedup + host-diversity + post-ranking + heap insert. Facet
        accumulation happens upstream over the whole candidate set
        (_fill_results), not per inserted entry."""
        q = self.query
        if q.url_filter is not None and entry.url and q.url_filter(entry.url):
            return False
        # remote entries never went through _constraint_mask: recheck the
        # daterange bounds on the metadata they carry (local entries were
        # already filtered; their recheck is a no-op)
        if q.modifier.from_days is not None \
                and entry.lastmod_days < q.modifier.from_days:
            return False
        if q.modifier.to_days is not None \
                and entry.lastmod_days > q.modifier.to_days:
            return False
        if q.modifier.date_sort:
            # one sort key for every producer: recency (remote cardinal
            # scores are on an incomparable scale)
            entry.score = entry.lastmod_days
        with self._lock:
            if entry.urlhash in self._seen_urlhashes:
                return False
            self._seen_urlhashes.add(entry.urlhash)
            hh = hosthash(entry.urlhash)
            cnt = self._host_counts.get(hh, 0)
            if cnt >= q.max_per_host:
                # doubledom diversion: parked, re-merged if page underfills
                self._diverted.append((entry.score, entry))
                return False
            self._host_counts[hh] = cnt + 1
            score = self._post_ranking(entry)
            entry.score = score
            self.result_heap.put(entry, score)
            return True

    def _post_ranking(self, entry: ResultEntry) -> int:
        """Post-sort boosts (reference: SearchEvent.postRanking,
        SearchEvent.java:1963-2021): query appearing in title/url and
        citation references raise the pre-sorted score."""
        q, score = self.query, entry.score
        if q.modifier.date_sort:
            return score  # recency IS the sort key; boosts would distort it
        prof = q.profile
        tl = entry.title.lower()
        ul = entry.url.lower()
        for w in q.goal.include_words:
            if w in tl:
                score += 128 << prof.descrcompintoplist
            if w in ul:
                score += 128 << prof.urlcompintoplist
        if entry.references > 0:
            score += min(entry.references, 255) << prof.citation
        return score

    def add_remote_results(self, entries: list[ResultEntry]) -> int:
        """Feeder entry point for remote peers (M5): merge asynchronously
        into the live event (the reference's addNodes path)."""
        added = 0
        src0 = entries[0].source if entries else ""
        with tracing.span_in(self.trace_ctx, "search.fusion_remote",
                             n=len(entries), peer=src0):
            added = self._add_remote_locked(entries)
        with self._lock:
            self.remote_results += added
            self.touched = time.time()
        return added

    def _add_remote_locked(self, entries: list[ResultEntry]) -> int:
        added = 0
        for e in entries:
            src = getattr(e, "source", None)
            if src and src != "local":
                try:
                    self.result_peer_hashes.add(
                        src.encode("ascii") if isinstance(src, str) else src)
                except UnicodeEncodeError:
                    pass  # non-hash source label: nothing to mark
            if self._insert(e):
                added += 1
        return added

    # -- consumption ---------------------------------------------------------

    def results_available(self) -> int:
        """Heap entries that are actually SERVABLE (snippet-evicted slots
        stay in the heap but never render — paging links must not point
        at pages made only of them)."""
        return max(0, self.result_heap.size_available()
                   - len(self._snippet_evicted))

    def results(self, offset: int | None = None,
                count: int | None = None,
                with_snippets: bool | None = None) -> list[ResultEntry]:
        """One page of results, best-first (oneResult loop equivalent).
        `with_snippets` overrides the query's snippet_fetch for THIS call
        (shared QueryParams on a cached event must never be mutated)."""
        self.touched = time.time()
        q = self.query
        offset = q.offset if offset is None else offset
        count = q.item_count if count is None else count
        if with_snippets is None:
            with_snippets = q.snippet_fetch
        need = offset + count
        self._drain(need)
        with self._lock:
            avail = self.results_available()
            if avail < need and self._diverted and not self._pending:
                # page underfills: merge back diverted same-host entries
                # (the reference re-admits doubledom-parked results when the
                # drained stacks run dry, SearchEvent.java:1376-1412)
                self._diverted.sort(key=lambda t: -t[0])
                refill = need - avail
                for score, entry in self._diverted[:refill]:
                    self.result_heap.put(entry, score)
                del self._diverted[:refill]
        got = self._page_entries(offset, count)
        if with_snippets:
            # snippet production may EVICT entries (deleteIfSnippetFail);
            # backfill from the heap until the page fills or runs dry —
            # and RE-DRAIN: evictions consumed materialization cushion,
            # so _pending may still hold live candidates
            while True:
                evicted = self._produce_snippets(got)
                if not evicted:
                    break
                with self._lock:
                    self._drained = max(0, self._drained - evicted)
                self._drain(need)
                refill = self._page_entries(offset, count)
                if [e.urlhash for e in refill] == [e.urlhash for e in got]:
                    break
                got = refill
        return got

    def _page_entries(self, offset: int, count: int) -> list[ResultEntry]:
        """One page from the heap, skipping snippet-evicted entries (their
        heap slots stay; offsets count LIVE entries only)."""
        got: list[ResultEntry] = []
        live = 0
        i = 0
        while len(got) < count:
            el = self.result_heap.element(i, timeout_s=0)
            i += 1
            if el is None:
                break
            e = el.payload
            if e.urlhash in self._snippet_evicted:
                continue
            live += 1
            if live > offset:
                got.append(e)
        return got

    def _produce_snippets(self, entries: list[ResultEntry]) -> int:
        """Fill missing snippets; returns how many entries were evicted
        (reference: concurrent snippet workers + deleteIfSnippetFail,
        SearchEvent.java:1862-1948)."""
        with tracing.span("search.snippets", n=len(entries)):
            return self._produce_snippets_inner(entries)

    def _produce_snippets_inner(self, entries: list[ResultEntry]) -> int:
        from .snippet import (SNIPPET_DEAD, SNIPPET_OK, SnippetProducer)
        q = self.query
        words = q.goal.include_words
        live_jobs: list[ResultEntry] = []
        for e in entries:
            if e.snippet_done or e.snippet:
                continue
            if e.source == "local":
                text = self.segment.metadata.text_value(e.docid, "text_t")
                if text:
                    e.snippet, _ = extract_snippet(text, words)
                    e.snippet_done = True
                    continue
            # stored text gone (blanked row / imported metadata) or a
            # remote result without a peer snippet: live path
            live_jobs.append(e)
        # ladder rung 1 (ISSUE 9): skip LIVE snippet fetches — cache-
        # local extraction above already served what it could; the
        # network fetches (the expensive, latency-tailed part) are the
        # first thing the ladder sheds.  No eviction either: under
        # degradation a missing snippet proves nothing.
        if live_jobs and self.loader is not None \
                and self.degrade_level >= 1:
            self._note_degraded("SNIPPETS", len(live_jobs))
            for e in live_jobs:
                e.snippet_done = True
            return 0
        if not live_jobs or self.loader is None:
            for e in live_jobs:
                e.snippet_done = True
            return 0
        producer = SnippetProducer(self.loader, q.snippet_strategy)
        outcomes = producer.produce_many([e.url for e in live_jobs], words)
        evicted = 0
        # eviction applies only when verification was REQUESTED: under
        # cacheonly a missing cache entry proves nothing (the reference
        # keeps unverified results in its cacheonly default too)
        verifying = q.snippet_strategy != "cacheonly"
        for e, (snippet, outcome) in zip(live_jobs, outcomes):
            e.snippet_done = True
            if outcome == SNIPPET_OK:
                e.snippet = snippet
                continue
            if not (verifying and q.snippet_delete_on_fail):
                continue
            with self._lock:
                self._snippet_evicted.add(e.urlhash)
                self.snippet_evictions += 1
            evicted += 1
            if outcome == SNIPPET_DEAD and e.source == "local":
                # the fetch proved the document gone: purge it from the
                # local index (the reference's deleteIfSnippetFail index
                # hygiene; transport errors never purge)
                try:
                    self.segment.remove_document(e.urlhash)
                except Exception:
                    import logging
                    logging.getLogger("search.snippets").warning(
                        "dead-document purge failed for %r; the index "
                        "still claims a URL the snippet fetch proved gone",
                        e.urlhash, exc_info=True)
        return evicted

    def one_result(self, item: int) -> ResultEntry | None:
        page = self.results(offset=item, count=1)
        return page[0] if page else None

    def image_results(self, offset: int | None = None,
                      count: int | None = None) -> list["ImageResult"]:
        """One page of IMAGE results (contentdom=image serving mode).

        Ranked page documents — already constrained to HASIMAGE carriers
        by the contentdom flag filter — expand into one entry per image
        from their indexed ``images_urlstub_sxt``/``images_alt_sxt``
        arrays, deduplicated by image URL across source pages (the first,
        best-ranked, page wins attribution), paged over the expansion.
        Remote entries carry no local metadata row and contribute no
        images (the reference fetches their image fields from the peer's
        metadata lines; our remote ResultEntry surface has no image
        arrays yet). Match: reference SearchEvent.java:2178-2280."""
        from ..index.metadata import split_multi_positional
        from ..utils.hashes import url_file_ext
        q = self.query
        offset = q.offset if offset is None else offset
        count = q.item_count if count is None else count
        need = offset + count
        meta = self.segment.metadata
        out: list[ImageResult] = []
        seen: set[str] = set()
        doc_off = 0
        chunk = max(count, 10)
        # deterministic expansion from rank 0 every call: dedup must
        # see the same prefix regardless of the requested page.
        # with_snippets=False: image mode never shows page snippets, so
        # the carrier scan must not pay a text_t read per document.
        while len(out) < need:
            docs = self.results(offset=doc_off, count=chunk,
                                with_snippets=False)
            if not docs:
                break
            for r in docs:
                if r.source != "local":
                    continue
                stubs = split_multi_positional(
                    meta.text_value(r.docid, "images_urlstub_sxt"))
                if not any(stubs):
                    continue
                protos = split_multi_positional(
                    meta.text_value(r.docid, "images_protocol_sxt"))
                # legacy rows (indexed before the positional arrays)
                # have no protocol column and their alt array dropped
                # empty slots — alignment is unrecoverable, so alts are
                # omitted rather than misattributed (re-crawl restores)
                alts = (split_multi_positional(
                    meta.text_value(r.docid, "images_alt_sxt"))
                    if any(protos) else [])
                for j, stub in enumerate(stubs):
                    key = stub.lower()
                    if not stub or key in seen:
                        continue
                    seen.add(key)
                    proto = (protos[j] if j < len(protos)
                             and protos[j] else "http")
                    image_url = f"{proto}://{stub}"
                    out.append(ImageResult(
                        image_url=image_url,
                        alt=alts[j] if j < len(alts) else "",
                        source_url=r.url, source_title=r.title,
                        source_urlhash=r.urlhash, host=r.host,
                        score=r.score,
                        filetype=url_file_ext(image_url),
                        source=r.source))
            doc_off += len(docs)
            if len(docs) < chunk:
                break
        return out[offset:need]

    def facet(self, name: str, n: int = 10) -> list[tuple[str, int]]:
        nav = self.navigators.get(name)
        return nav.top(n) if nav else []


class SearchEventCache:
    """query-id → live SearchEvent, so paging reuses the executed search
    (reference: SearchEventCache.java:42-199, incl. memory-pressure
    cleanup — here a simple TTL + max-size policy)."""

    def __init__(self, max_events: int = 100, ttl_s: float = 600.0):
        self.max_events = max_events
        self.ttl_s = ttl_s
        self._events: dict[str, SearchEvent] = {}
        self._lock = profiling.ObservedLock("search_cache")
        # most recent event id — the default subject of the search-event
        # picture (reference: SearchEventCache.lastEventID)
        self.last_event_id: str | None = None

    def get_event(self, query: QueryParams, segment: Segment,
                  loader=None) -> SearchEvent:
        qid = query.query_id()
        with self._lock:
            ev = self._events.get(qid)
            if ev is not None:
                ev.touched = time.time()
                return ev
        ev = SearchEvent(query, segment, loader=loader)
        with self._lock:
            self.cleanup_locked()
            self._events[qid] = ev
            self.last_event_id = qid
        return ev

    def event_by_id(self, qid: str) -> "SearchEvent | None":
        """Look up a LIVE event by its query id — the progressive
        per-item delivery surface (reference: htroot/yacysearchitem.java
        reads the cached event while feeders still run)."""
        with self._lock:
            ev = self._events.get(qid)
            if ev is not None:
                ev.touched = time.time()
            return ev

    def cleanup_locked(self) -> None:
        now = time.time()
        dead = [k for k, e in self._events.items()
                if now - e.touched > self.ttl_s]
        for k in dead:
            del self._events[k]
        while len(self._events) >= self.max_events:
            oldest = min(self._events, key=lambda k: self._events[k].touched)
            del self._events[oldest]

    def clear(self) -> None:
        """Drop every cached event (filter-set changes invalidate results
        computed under the old filter)."""
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)
