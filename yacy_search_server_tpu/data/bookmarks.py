"""Bookmarks — tagged, dated, public/private URL records.

Capability equivalent of the reference's bookmark database (reference:
source/net/yacy/data/BookmarksDB.java — bookmark records keyed by URL
hash with tag sets, public flag and date folders, plus tag and date
indexes; the ymark successor keeps the same shape). Tag queries drive
the bookmark UI and the ContentControl filter source (data/contentcontrol).
"""

from __future__ import annotations

import time

from ..utils.hashes import url2hash
from .tables import Tables


class BookmarksDB:
    TABLE = "bookmarks"

    def __init__(self, tables: Tables):
        self.tables = tables

    def add(self, url: str, title: str = "", description: str = "",
            tags: list[str] | None = None, public: bool = False,
            owner: str = "admin") -> str:
        pk = url2hash(url).decode("ascii", "replace")
        self.tables.insert(self.TABLE, {
            "url": url, "title": title or url, "description": description,
            "tags": sorted({t.strip().lower() for t in (tags or [])
                            if t.strip()}),
            "public": bool(public), "owner": owner, "date": time.time()},
            pk=pk)
        return pk

    def get(self, url_or_pk: str) -> dict | None:
        row = self.tables.get(self.TABLE, url_or_pk)
        if row is None and "://" in url_or_pk:
            row = self.tables.get(
                self.TABLE, url2hash(url_or_pk).decode("ascii", "replace"))
        return row

    def remove(self, url_or_pk: str) -> bool:
        row = self.get(url_or_pk)
        return bool(row) and self.tables.delete(self.TABLE, row["_pk"])

    def all(self, public_only: bool = False) -> list[dict]:
        rows = self.tables.rows(self.TABLE)
        if public_only:
            rows = [r for r in rows if r.get("public")]
        return sorted(rows, key=lambda r: -r.get("date", 0))

    def by_tag(self, tag: str, public_only: bool = False) -> list[dict]:
        t = tag.strip().lower()
        return [r for r in self.all(public_only) if t in r.get("tags", [])]

    def tags(self) -> list[tuple[str, int]]:
        counts: dict[str, int] = {}
        for r in self.tables.rows(self.TABLE):
            for t in r.get("tags", []):
                counts[t] = counts.get(t, 0) + 1
        return sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
