"""URL blacklists — named pattern lists filtering crawl/DHT/search/proxy.

Capability equivalent of the reference's blacklist engine (reference:
source/net/yacy/repository/Blacklist.java + data/ListManager.java):
entries are `host/path` patterns where the host part may carry `*`
wildcards and the path part is a regex; each named list can be activated
for any of the blacklist *types* (crawler, dht, search, news, proxy,
surftips).  A URL is denied for a type when any active list for that type
contains a matching pattern.  Lists persist as one `<name>.black` text
file per list, entries one per line — the reference's on-disk format.
"""

from __future__ import annotations

import os
import re
import threading
from urllib.parse import urlsplit

TYPES = ("crawler", "dht", "search", "news", "proxy", "surftips")


def _host_pattern_to_regex(host: str) -> re.Pattern:
    # host wildcards: `*.example.org`, `example.*` (Blacklist.java hostpath
    # matching); translate * -> [^/]* on the escaped host
    esc = re.escape(host.lower()).replace(r"\*", r"[^/]*")
    return re.compile(rf"^{esc}$")


class _Entry:
    __slots__ = ("raw", "host_re", "path_re")

    def __init__(self, raw: str):
        self.raw = raw
        host, _, path = raw.partition("/")
        self.host_re = _host_pattern_to_regex(host)
        if not path or path == "*":
            path = ".*"
        try:
            self.path_re = re.compile(path)
        except re.error:
            self.path_re = re.compile(re.escape(path))

    def matches(self, host: str, path: str) -> bool:
        return bool(self.host_re.match(host)
                    and self.path_re.fullmatch(path.lstrip("/")))


class Blacklist:
    def __init__(self, data_dir: str | None = None):
        self.data_dir = data_dir
        self._lists: dict[str, list[_Entry]] = {}
        # list name -> set of types it is active for
        self._active: dict[str, set[str]] = {}
        # crawler busy-threads match while HTTP admin threads mutate
        self._lock = threading.RLock()
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._load()

    # -- persistence ---------------------------------------------------------

    def _list_path(self, name: str) -> str:
        return os.path.join(self.data_dir, f"{name}.black")

    # lint: unlocked-ok(construction-time: only __init__ calls this,
    # before the blacklist is shared with any other thread)
    def _load(self) -> None:
        for fn in os.listdir(self.data_dir):
            if not fn.endswith(".black"):
                continue
            name = fn[:-6]
            with open(os.path.join(self.data_dir, fn), encoding="utf-8") as f:
                entries = [ln.strip() for ln in f if ln.strip()
                           and not ln.startswith("#")]
            self._lists[name] = [_Entry(e) for e in entries]
            self._active[name] = set(TYPES)
        actp = os.path.join(self.data_dir, "active.conf")
        if os.path.isfile(actp):
            with open(actp, encoding="utf-8") as f:
                self._active = {}
                for ln in f:
                    if "=" in ln:
                        name, types = ln.strip().split("=", 1)
                        self._active[name] = set(
                            t for t in types.split(",") if t in TYPES)

    def _save_list(self, name: str) -> None:
        if not self.data_dir:
            return
        # snapshot under the (reentrant) lock — callers already hold it,
        # but the explicit take keeps the read guarded on every path
        with self._lock:
            entries = list(self._lists.get(name, []))
            active = {n: sorted(types)
                      for n, types in sorted(self._active.items())}
        with open(self._list_path(name), "w", encoding="utf-8") as f:
            for e in entries:
                f.write(e.raw + "\n")
        with open(os.path.join(self.data_dir, "active.conf"), "w",
                  encoding="utf-8") as f:
            for n, types in active.items():
                f.write(f"{n}={','.join(types)}\n")

    # -- management ----------------------------------------------------------

    def add(self, list_name: str, pattern: str,
            types: set[str] | None = None) -> None:
        with self._lock:
            entries = self._lists.setdefault(list_name, [])
            if any(e.raw == pattern for e in entries):
                return
            entries.append(_Entry(pattern))
            self._active.setdefault(list_name, set(types or TYPES))
            self._save_list(list_name)

    def remove(self, list_name: str, pattern: str) -> None:
        with self._lock:
            entries = self._lists.get(list_name, [])
            self._lists[list_name] = [e for e in entries if e.raw != pattern]
            self._save_list(list_name)

    def set_active(self, list_name: str, types: set[str]) -> None:
        with self._lock:
            self._active[list_name] = set(t for t in types if t in TYPES)
            self._save_list(list_name)

    def list_names(self) -> list[str]:
        with self._lock:
            return sorted(self._lists)

    def entries(self, list_name: str) -> list[str]:
        with self._lock:
            return [e.raw for e in self._lists.get(list_name, [])]

    # -- matching ------------------------------------------------------------

    def is_listed(self, btype: str, url: str) -> bool:
        try:
            parts = urlsplit(url if "://" in url else "http://" + url)
        except ValueError:
            return False
        host = (parts.hostname or "").lower()
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        with self._lock:
            for name, entries in self._lists.items():
                if btype not in self._active.get(name, ()):
                    continue
                for e in entries:
                    if e.matches(host, path):
                        return True
        return False

    def crawler_reason(self, url: str) -> str | None:
        """CrawlStacker-compatible callable: reason string or None."""
        return "url in crawler blacklist" if self.is_listed("crawler", url) else None
