"""ContentControl — bookmark-driven URL filtering.

Capability equivalent of the reference's content-control subsystem
(reference: source/net/yacy/contentcontrol/ — ContentControlFilterUpdateThread
compiles bookmarks carrying the control tag into an in-memory URL filter
consulted by the search result drain; SMWListSyncThread pulls external
lists into the same bookmark folder). Here the source is the local
BookmarksDB: bookmarks tagged with the control tag become block entries,
recompiled by a busy thread when the bookmark set changes.
"""

from __future__ import annotations

import threading

from ..utils.hashes import safe_host

DEFAULT_CONTROL_TAG = "contentcontrol"


class ContentControl:
    def __init__(self, bookmarks, control_tag: str = DEFAULT_CONTROL_TAG):
        self.bookmarks = bookmarks
        self.control_tag = control_tag
        self.enabled = False
        self._hosts: set[str] = set()
        self._urls: set[str] = set()
        self._stamp: int = -1
        self._lock = threading.Lock()

    def update_filter_job(self) -> bool:
        """Recompile the filter when the bookmark set changed (the
        reference's ContentControlFilterUpdateThread busy job)."""
        rows = self.bookmarks.by_tag(self.control_tag)
        stamp = hash(tuple(sorted(r.get("url", "") for r in rows)))
        with self._lock:
            if stamp == self._stamp:
                return False
            hosts: set[str] = set()
            urls: set[str] = set()
            for r in rows:
                url = r.get("url", "")
                if not url:
                    continue
                if url.endswith("/*") or url.endswith("/"):
                    host = safe_host(url)
                    if host:
                        hosts.add(host)
                else:
                    urls.add(url)
                    host = safe_host(url)
                    # a bare host bookmark blocks the whole host
                    if host and url.rstrip("/").endswith(host):
                        hosts.add(host)
            self._hosts = hosts
            self._urls = urls
            self._stamp = stamp
            return True

    def excluded(self, url: str) -> bool:
        """Is this result URL blocked by the active filter?"""
        if not self.enabled:
            return False
        with self._lock:
            if url in self._urls:
                return True
            host = safe_host(url)
            return bool(host) and host in self._hosts

    def size(self) -> int:
        with self._lock:
            return len(self._hosts) + len(self._urls)
