"""Data boards and operator-facing stores — blacklists, work tables,
bookmarks, wiki, blog, messages, user accounts.

Capability equivalents of the reference's `source/net/yacy/data/` package
and `source/net/yacy/repository/Blacklist.java`.
"""
