"""Tables — generic named row tables, the application data substrate.

Capability equivalent of the reference's Tables machinery (reference:
source/net/yacy/kelondro/blob/Tables.java — named tables of string-keyed
rows over BEncodedHeap files, used by the API-call recorder, bookmarks
and every other small application store; BEncodedHeap.java row codec).
Here each table is an append-only JSONL journal compacted at load: the
row dict IS the record, `_pk` is the primary key, and updates/deletes are
journal entries that later lines supersede — the same LSM-lite shape as
the RWI runs, sized for thousands of rows, not millions.
"""

from __future__ import annotations

import json
import os
import threading


class Tables:
    """Named tables of dict rows with stable string pks."""

    def __init__(self, data_dir: str | None = None):
        self.data_dir = data_dir
        self._tables: dict[str, dict[str, dict]] = {}
        self._seq: dict[str, int] = {}
        self._lock = threading.RLock()
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            for fn in os.listdir(data_dir):
                if fn.endswith(".jsonl"):
                    self._load(fn[:-6])

    # -- io -------------------------------------------------------------------

    def _path(self, table: str) -> str | None:
        if not self.data_dir:
            return None
        return os.path.join(self.data_dir, table + ".jsonl")

    # lint: unlocked-ok(construction-time: only __init__ calls this,
    # before the table store is shared with any other thread)
    def _load(self, table: str) -> None:
        path = self._path(table)
        rows: dict[str, dict] = {}
        seq = 0
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    try:
                        d = json.loads(line)
                    except ValueError:
                        continue
                    pk = d.get("_pk")
                    if not pk:
                        continue
                    if d.get("_del"):
                        rows.pop(pk, None)
                    else:
                        rows[pk] = d
                    if pk.isdigit():
                        seq = max(seq, int(pk) + 1)
        except OSError:
            return
        self._tables[table] = rows
        self._seq[table] = seq
        self._compact(table)

    def _compact(self, table: str) -> None:
        path = self._path(table)
        if not path:
            return
        # snapshot under the (reentrant) lock, write outside it
        with self._lock:
            rows = list(self._tables.get(table, {}).values())
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for row in rows:
                    f.write(json.dumps(row) + "\n")
            os.replace(tmp, path)
        except OSError:
            pass

    def _append(self, table: str, row: dict) -> None:
        path = self._path(table)
        if not path:
            return
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(row) + "\n")
        except OSError:
            pass

    # -- CRUD -----------------------------------------------------------------

    def insert(self, table: str, row: dict, pk: str | None = None) -> str:
        with self._lock:
            t = self._tables.setdefault(table, {})
            if pk is None:
                pk = str(self._seq.get(table, 0))
                self._seq[table] = int(pk) + 1
            stored = {**row, "_pk": pk}
            t[pk] = stored
            self._append(table, stored)
            return pk

    def update(self, table: str, pk: str, row: dict) -> bool:
        with self._lock:
            t = self._tables.get(table)
            if t is None or pk not in t:
                return False
            stored = {**t[pk], **row, "_pk": pk}
            t[pk] = stored
            self._append(table, stored)
            return True

    def get(self, table: str, pk: str) -> dict | None:
        with self._lock:
            row = self._tables.get(table, {}).get(pk)
            return dict(row) if row else None

    def delete(self, table: str, pk: str) -> bool:
        with self._lock:
            t = self._tables.get(table)
            if t is None or t.pop(pk, None) is None:
                return False
            self._append(table, {"_pk": pk, "_del": 1})
            return True

    def rows(self, table: str) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._tables.get(table, {}).values()]

    def drop_table(self, table: str) -> bool:
        """Remove a whole table and its journal file (partition
        retirement — SplitTable's by-age table discard)."""
        with self._lock:
            if self._tables.pop(table, None) is None:
                return False
            self._seq.pop(table, None)
            if self.data_dir:
                path = os.path.join(self.data_dir, table + ".jsonl")
                try:
                    os.remove(path)
                except OSError:
                    pass
            return True

    def select(self, table: str, **match) -> list[dict]:
        """Rows whose columns equal every given value."""
        with self._lock:
            return [dict(r) for r in self._tables.get(table, {}).values()
                    if all(r.get(k) == v for k, v in match.items())]

    def size(self, table: str) -> int:
        with self._lock:
            return len(self._tables.get(table, {}))

    def tables(self) -> list[str]:
        with self._lock:
            return sorted(self._tables)

    def clear(self, table: str) -> None:
        with self._lock:
            self._tables[table] = {}
            self._compact(table)


class PartitionedTable:
    """Date-partitioned table set behind one table-like API.

    Capability equivalent of the reference's SplitTable (reference:
    source/net/yacy/kelondro/table/SplitTable.java:61 — a set of
    per-date-suffix Tables presented as one Index so writes land in the
    current partition while reads fan over all of them, and whole
    partitions can be dropped by age instead of row-by-row deletes).
    Here partitions are months ("%Y%m"); rows are stamped with their
    partition so updates/deletes route directly."""

    def __init__(self, tables: Tables, base_name: str):
        self.tables = tables
        self.base = base_name

    def _partition(self, when_s: float | None = None) -> str:
        import time as _time
        return _time.strftime("%Y%m", _time.gmtime(when_s))

    def _table(self, partition: str) -> str:
        return f"{self.base}.{partition}"

    def partitions(self) -> list[str]:
        with self.tables._lock:
            prefix = self.base + "."
            return sorted(t[len(prefix):] for t in self.tables._tables
                          if t.startswith(prefix))

    def insert(self, row: dict, pk: str | None = None,
               when_s: float | None = None) -> str:
        part = self._partition(when_s)
        pk = self.tables.insert(self._table(part), row, pk=pk)
        return f"{part}/{pk}"

    @staticmethod
    def _split_pk(full_pk: str) -> tuple[str, str]:
        part, _, pk = full_pk.partition("/")
        return part, pk

    def get(self, full_pk: str) -> dict | None:
        part, pk = self._split_pk(full_pk)
        return self.tables.get(self._table(part), pk)

    def update(self, full_pk: str, row: dict) -> bool:
        part, pk = self._split_pk(full_pk)
        return self.tables.update(self._table(part), pk, row)

    def delete(self, full_pk: str) -> bool:
        part, pk = self._split_pk(full_pk)
        return self.tables.delete(self._table(part), pk)

    def rows(self) -> list[dict]:
        """All rows across partitions, oldest partition first."""
        out: list[dict] = []
        for part in self.partitions():
            out.extend(self.tables.rows(self._table(part)))
        return out

    def drop_partitions_older_than(self, keep_months: int) -> int:
        """Whole-partition retirement — the point of date splitting
        (SplitTable discards table files by age)."""
        import time as _time
        cutoff = _time.strftime(
            "%Y%m", _time.gmtime(_time.time() - keep_months * 30 * 86400))
        dropped = 0
        for part in self.partitions():
            if part < cutoff:
                if self.tables.drop_table(self._table(part)):
                    dropped += 1
        return dropped
