"""Data boards — wiki, blog, peer messages.

Capability equivalents of the reference's community-data subsystems
(reference: source/net/yacy/data/wiki/WikiBoard.java + WikiCode.java
markup renderer, data/BlogBoard.java, data/MessageBoard.java — each a
MapHeap of dated, authored records; wiki keeps a version history in a
separate bkp store). All three sit on the generic Tables substrate here.
"""

from __future__ import annotations

import html
import re
import time

from .tables import Tables


# -- WikiCode markup (full markup engine; reference WikiCode.java) ------------

_RE_H = [(re.compile(rf"^({'=' * n})\s*(.+?)\s*{'=' * n}\s*$"), n)
         for n in (6, 5, 4, 3, 2, 1)]
_RE_BOLD_ITALIC = re.compile(r"'''''(.+?)'''''")
_RE_BOLD = re.compile(r"'''(.+?)'''")
_RE_ITALIC = re.compile(r"''(.+?)''")
_RE_STRIKE = re.compile(r"&lt;s&gt;(.*?)&lt;/s&gt;", re.S)
_RE_UNDERLINE = re.compile(r"&lt;u&gt;(.*?)&lt;/u&gt;", re.S)
_RE_LINK_EXT = re.compile(r"\[((?:https?|ftp)://[^\s\]]+)(?:\s+([^\]]+))?\]")
_RE_LINK_WIKI = re.compile(r"\[\[([^\]|]+)(?:\|([^\]]*))?\]\]")
_RE_METADATA = re.compile(r"\{\{[^{}]*\}\}")
_RE_ANCHOR_STRIP = re.compile(r"[^a-zA-Z0-9_]")

# table cell/row properties the renderer lets through (everything else a
# page author writes is dropped — the reference allowlists the same way)
_TABLE_PROPS = frozenset(
    ("rowspan", "colspan", "vspace", "hspace", "cellspacing", "cellpadding",
     "border", "align", "valign", "bgcolor", "width", "height"))
_ALIGN_VALUES = frozenset(("left", "right", "center", "justify", "top",
                           "middle", "bottom"))


def _attr(v: str) -> str:
    """Attribute-position neutralization: the surrounding text is escaped
    with quote=False, so values must not be able to close the quote."""
    return v.replace('"', "%22").replace("'", "%27")


def _table_props(spec: str) -> str:
    """Filter `key="value"`/`key=value` table properties through the
    allowlist; align/valign values are further value-checked."""
    keep = []
    for m in re.finditer(r"([a-zA-Z]+)\s*=\s*\"?([^\s\"]+)\"?", spec):
        key, val = m.group(1).lower(), m.group(2)
        if key not in _TABLE_PROPS:
            continue
        if key in ("align", "valign") and val.lower() not in _ALIGN_VALUES:
            continue
        keep.append(f'{key}="{_attr(val)}"')
    return (" " + " ".join(keep)) if keep else ""


def _media_link(target: str, label: str | None) -> str | None:
    """[[Image:...]] / [[Youtube:...]] / [[Vimeo:...]] embeds."""
    low = target.lower()
    if low.startswith("image:"):
        src = target[6:].strip()
        align, caption = "", label
        if label in ("left", "right", "center"):
            align, caption = f' align="{label}"', None
        alt = caption or src.rsplit("/", 1)[-1]
        return f'<img src="{_attr(src)}" alt="{_attr(alt)}"{align}/>'
    if low.startswith("youtube:"):
        vid = _attr(target[8:].strip())
        return (f'<iframe width="425" height="350" frameborder="0" '
                f'src="//www.youtube.com/embed/{vid}"></iframe>')
    if low.startswith("vimeo:"):
        vid = _attr(target[6:].strip())
        return (f'<iframe width="425" height="350" frameborder="0" '
                f'src="//player.vimeo.com/video/{vid}"></iframe>')
    return None


def _inline(line: str) -> str:
    """Span-level markup inside one line (input already HTML-escaped)."""
    line = _RE_METADATA.sub("", line)        # {{template}} metadata: drop
    line = _RE_BOLD_ITALIC.sub(r"<b><i>\1</i></b>", line)
    line = _RE_BOLD.sub(r"<b>\1</b>", line)
    line = _RE_ITALIC.sub(r"<i>\1</i>", line)
    line = _RE_STRIKE.sub(r'<span class="strike">\1</span>', line)
    line = _RE_UNDERLINE.sub(r'<span class="underline">\1</span>', line)

    def wiki_link(m):
        target = m.group(1).strip()
        media = _media_link(target, m.group(2))
        if media is not None:
            return media
        label = m.group(2) or target
        return (f'<a href="Wiki.html?page={_attr(target)}">{label}</a>')

    line = _RE_LINK_WIKI.sub(wiki_link, line)
    line = _RE_LINK_EXT.sub(
        lambda m: f'<a href="{_attr(m.group(1))}" class="extern">'
                  f'{m.group(2) or m.group(1)}</a>', line)
    return line


def _anchor(title: str) -> str:
    return _RE_ANCHOR_STRIP.sub("", title.strip().replace(" ", "_"))


class _WikiRenderer:
    """Line-oriented WikiCode renderer with the reference's block model:
    nested */# lists, ;:-definition lists, :-indent blockquotes, leading-
    space preformat, {| |} tables, <pre> verbatim blocks, = headings =
    with anchors and a generated table of contents."""

    def __init__(self):
        self.out: list[str] = []
        self.list_stack: list[str] = []     # open "ul"/"ol" nesting
        self.quote_depth = 0
        self.in_dl = False
        self.in_pre_block = False           # <pre>..</pre> verbatim
        self.in_space_pre = False           # leading-space preformat
        self.in_table = False
        self.in_row = False
        self.headings: list[tuple[int, str, str]] = []  # level, title, anchor

    # -- block-state closers --------------------------------------------------

    def _close_lists(self, depth: int = 0) -> None:
        while len(self.list_stack) > depth:
            self.out.append(f"</{self.list_stack.pop()}>")

    def _close_quote(self, depth: int = 0) -> None:
        while self.quote_depth > depth:
            self.out.append("</blockquote>")
            self.quote_depth -= 1

    def _close_dl(self) -> None:
        if self.in_dl:
            self.out.append("</dl>")
            self.in_dl = False

    def _close_space_pre(self) -> None:
        if self.in_space_pre:
            self.out.append("</pre>")
            self.in_space_pre = False

    def _close_row(self) -> None:
        if self.in_row:
            self.out.append("</tr>")
            self.in_row = False

    def _close_blocks(self) -> None:
        self._close_lists()
        self._close_quote()
        self._close_dl()
        self._close_space_pre()

    # -- table ----------------------------------------------------------------

    def _table_line(self, line: str) -> None:
        if line.startswith("{|"):
            self.in_table = True
            self.out.append(f"<table{_table_props(line[2:])}>")
            return
        if line.startswith("|}"):
            self._close_row()
            self.out.append("</table>")
            self.in_table = False
            return
        if line.startswith("|-"):
            self._close_row()
            self.out.append(f"<tr{_table_props(line[2:])}>")
            self.in_row = True
            return
        if not line.startswith(("|", "!")):
            # plain content inside {| ... |}: render inline, not as a
            # cell (a bare line must not lose its first character)
            if line.strip():
                self.out.append(_inline(line))
            return
        tag = "th" if line.startswith("!") else "td"
        body = line[1:]
        sep = "!!" if tag == "th" else "||"
        if not self.in_row:
            self.out.append("<tr>")
            self.in_row = True
        for cell in body.split(sep):
            # optional `props | content` prefix inside the cell
            props = ""
            if "|" in cell:
                head, rest = cell.split("|", 1)
                if head and "=" in head and "[" not in head:
                    got = _table_props(head)
                    if got:
                        props, cell = got, rest
            self.out.append(f"<{tag}{props}>{_inline(cell.strip())}</{tag}>")

    # -- main loop ------------------------------------------------------------

    def feed(self, raw: str) -> None:
        line = html.escape(raw.rstrip(), quote=False)

        # verbatim <pre> blocks (escaped form after html.escape)
        if self.in_pre_block:
            if line.strip() == "&lt;/pre&gt;":
                self.out.append("</pre>")
                self.in_pre_block = False
            else:
                self.out.append(line)
            return
        if line.strip() == "&lt;pre&gt;":
            self._close_blocks()
            self.out.append("<pre>")
            self.in_pre_block = True
            return

        if self.in_table:
            self._table_line(line)
            return
        if line.startswith("{|"):
            self._close_blocks()
            self._table_line(line)
            return

        if line.strip() == "----":
            self._close_blocks()
            self.out.append("<hr/>")
            return

        for rex, n in _RE_H:
            m = rex.match(line)
            if m:
                self._close_blocks()
                title = _inline(m.group(2))
                anchor = _anchor(re.sub(r"<[^>]+>", "", title))
                self.headings.append((n, title, anchor))
                self.out.append(
                    f'<h{n}><a name="{anchor}"></a>{title}</h{n}>')
                return

        # nested * / # lists: prefix run of list glyphs sets the depth
        m = re.match(r"([*#]+)\s*(.*)$", line)
        if m:
            glyphs, body = m.group(1), m.group(2)
            self._close_quote()
            self._close_dl()
            self._close_space_pre()
            want = ["ul" if g == "*" else "ol" for g in glyphs]
            # unwind where the nesting diverges, then open the rest
            keep = 0
            while (keep < len(self.list_stack) and keep < len(want)
                   and self.list_stack[keep] == want[keep]):
                keep += 1
            self._close_lists(keep)
            for tag in want[keep:]:
                self.out.append(f"<{tag}>")
                self.list_stack.append(tag)
            self.out.append(f"<li>{_inline(body)}</li>")
            return

        # definition list: ;term:definition  (or continuation ":def")
        if line.startswith(";"):
            self._close_lists()
            self._close_quote()
            if not self.in_dl:
                self.out.append("<dl>")
                self.in_dl = True
            body = line[1:]
            if ":" in body:
                term, desc = body.split(":", 1)
                self.out.append(f"<dt>{_inline(term.strip())}</dt>"
                                f"<dd>{_inline(desc.strip())}</dd>")
            else:
                self.out.append(f"<dt>{_inline(body.strip())}</dt>")
            return
        if self.in_dl and line.startswith(":"):
            self.out.append(f"<dd>{_inline(line[1:].strip())}</dd>")
            return

        # ':' indentation → nested blockquotes
        m = re.match(r"(:+)\s*(.*)$", line)
        if m:
            depth, body = len(m.group(1)), m.group(2)
            self._close_lists()
            self._close_dl()
            while self.quote_depth < depth:
                self.out.append("<blockquote>")
                self.quote_depth += 1
            self._close_quote(depth)
            self.out.append(_inline(body) + "<br/>")
            return

        # leading space → preformatted code
        if raw.startswith(" ") and raw.strip():
            self._close_lists()
            self._close_quote()
            self._close_dl()
            if not self.in_space_pre:
                self.out.append("<pre>")
                self.in_space_pre = True
            self.out.append(line[1:])
            return

        self._close_blocks()
        if not line.strip():
            self.out.append("<p/>")
        else:
            self.out.append(_inline(line) + "<br/>")

    def toc(self) -> str:
        """The reference inserts a WikiTOCBox when a page carries more
        than one heading."""
        if len(self.headings) < 2:
            return ""
        rows = ['<div class="WikiTOCBox"><b>Contents</b><br/>']
        top = min(n for n, _, _ in self.headings)
        for n, title, anchor in self.headings:
            indent = "&nbsp;" * (4 * (n - top))
            rows.append(f'{indent}<a href="#{anchor}" class="WikiTOC">'
                        f"{title}</a><br/>")
        rows.append("</div>")
        return "\n".join(rows)

    def html(self) -> str:
        if self.in_pre_block or self.in_space_pre:
            self.out.append("</pre>")
            self.in_pre_block = self.in_space_pre = False
        if self.in_table:
            self._close_row()
            self.out.append("</table>")
            self.in_table = False
        self._close_blocks()
        body = "\n".join(self.out)
        toc = self.toc()
        return (toc + "\n" + body) if toc else body


def wikicode_to_html(text: str) -> str:
    """Render full WikiCode: =headings= (1-6) with anchors + TOC,
    '''''bold-italic'''''/'''bold'''/''italic'', <s>/<u> spans, nested
    */# lists, ;:-definition lists, :-indent blockquotes, leading-space
    and <pre> preformat, {| ... |} tables with attribute allowlist,
    [[page]] / [[page|label]] / [[Image:...]] / [[Youtube:..]] /
    [[Vimeo:..]], [url label] external links, {{metadata}} removal,
    ---- rules, blank-line paragraphs (reference:
    source/net/yacy/data/wiki/WikiCode.java)."""
    r = _WikiRenderer()
    for raw in text.splitlines():
        r.feed(raw)
    return r.html()


class WikiBoard:
    """Named pages with full version history (WikiBoard + bkp semantics)."""

    TABLE = "wiki"
    TABLE_BKP = "wiki_bkp"

    def __init__(self, tables: Tables):
        self.tables = tables

    def put(self, page: str, content: str, author: str = "anonymous") -> None:
        key = page.strip().lower()
        old = self.tables.get(self.TABLE, key)
        if old is not None:
            self.tables.insert(self.TABLE_BKP, old, pk=None)
        self.tables.insert(self.TABLE, {
            "page": page.strip(), "content": content, "author": author,
            "date": time.time()}, pk=key)

    def get(self, page: str) -> dict | None:
        return self.tables.get(self.TABLE, page.strip().lower())

    def render(self, page: str) -> str:
        row = self.get(page)
        return wikicode_to_html(row["content"]) if row else ""

    def pages(self) -> list[str]:
        return sorted(r["page"] for r in self.tables.rows(self.TABLE))

    def history(self, page: str) -> list[dict]:
        key = page.strip().lower()
        return sorted((r for r in self.tables.rows(self.TABLE_BKP)
                       if r.get("page", "").strip().lower() == key),
                      key=lambda r: r.get("date", 0))


class BlogBoard:
    """Dated entries, newest first (BlogBoard semantics)."""

    TABLE = "blog"

    def __init__(self, tables: Tables):
        self.tables = tables

    def add(self, subject: str, content: str, author: str = "anonymous",
            wikicode: bool = True) -> str:
        return self.tables.insert(self.TABLE, {
            "subject": subject, "content": content, "author": author,
            "date": time.time(), "wikicode": bool(wikicode), "comments": []})

    def entries(self, n: int = 20) -> list[dict]:
        rows = sorted(self.tables.rows(self.TABLE),
                      key=lambda r: -r.get("date", 0))
        return rows[:n]

    def get(self, pk: str) -> dict | None:
        return self.tables.get(self.TABLE, pk)

    def render(self, pk: str) -> str:
        row = self.get(pk)
        if row is None:
            return ""
        if row.get("wikicode"):
            return wikicode_to_html(row["content"])
        return html.escape(row["content"]).replace("\n", "<br/>")

    def comment(self, pk: str, author: str, content: str) -> bool:
        row = self.get(pk)
        if row is None:
            return False
        row.setdefault("comments", []).append(
            {"author": author, "content": content, "date": time.time()})
        return self.tables.update(self.TABLE, pk, row)

    def delete(self, pk: str) -> bool:
        return self.tables.delete(self.TABLE, pk)


class MessageBoard:
    """Peer-to-peer messages (MessageBoard semantics; the wire delivery is
    the yacy/message RPC — this is the mailbox)."""

    TABLE = "messages"

    def __init__(self, tables: Tables):
        self.tables = tables

    def send(self, to: str, from_: str, subject: str, content: str) -> str:
        return self.tables.insert(self.TABLE, {
            "to": to, "from": from_, "subject": subject, "content": content,
            "date": time.time(), "read": False})

    def inbox(self, user: str, unread_only: bool = False) -> list[dict]:
        rows = [r for r in self.tables.rows(self.TABLE) if r.get("to") == user
                and (not unread_only or not r.get("read"))]
        return sorted(rows, key=lambda r: -r.get("date", 0))

    def mark_read(self, pk: str) -> bool:
        row = self.tables.get(self.TABLE, pk)
        if row is None:
            return False
        row["read"] = True
        return self.tables.update(self.TABLE, pk, row)

    def delete(self, pk: str) -> bool:
        return self.tables.delete(self.TABLE, pk)
