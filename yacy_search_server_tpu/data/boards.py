"""Data boards — wiki, blog, peer messages.

Capability equivalents of the reference's community-data subsystems
(reference: source/net/yacy/data/wiki/WikiBoard.java + WikiCode.java
markup renderer, data/BlogBoard.java, data/MessageBoard.java — each a
MapHeap of dated, authored records; wiki keeps a version history in a
separate bkp store). All three sit on the generic Tables substrate here.
"""

from __future__ import annotations

import html
import re
import time

from .tables import Tables


# -- WikiCode markup (subset of reference WikiCode.java) ----------------------

_RE_H = [(re.compile(rf"^{'=' * n}\s*(.+?)\s*{'=' * n}\s*$"), f"h{8 - n}")
         for n in (6, 5, 4, 3, 2)]
_RE_BOLD = re.compile(r"'''(.+?)'''")
_RE_ITALIC = re.compile(r"''(.+?)''")
_RE_LINK_EXT = re.compile(r"\[(https?://[^\s\]]+)(?:\s+([^\]]+))?\]")
_RE_LINK_WIKI = re.compile(r"\[\[([^\]|]+)(?:\|([^\]]+))?\]\]")


def wikicode_to_html(text: str) -> str:
    """Render the load-bearing WikiCode subset: == headings ==, '''bold''',
    ''italic'', [[page]] / [[page|label]], [url label], * / # lists,
    ---- rules, blank-line paragraphs."""
    out: list[str] = []
    in_list: str | None = None

    def close_list():
        nonlocal in_list
        if in_list:
            out.append(f"</{in_list}>")
            in_list = None

    def _attr(v: str) -> str:
        # tags are escaped below with quote=False; attribute values must
        # still neutralize quotes so hrefs cannot break out
        return v.replace('"', "%22").replace("'", "%27")

    for raw in text.splitlines():
        line = html.escape(raw.rstrip(), quote=False)
        line = _RE_BOLD.sub(r"<b>\1</b>", line)
        line = _RE_ITALIC.sub(r"<i>\1</i>", line)
        line = _RE_LINK_WIKI.sub(
            lambda m: f'<a href="Wiki.html?page={_attr(m.group(1).strip())}">'
                      f'{m.group(2) or m.group(1)}</a>', line)
        line = _RE_LINK_EXT.sub(
            lambda m: f'<a href="{_attr(m.group(1))}">'
                      f'{m.group(2) or m.group(1)}</a>',
            line)
        if line.strip() == "----":
            close_list()
            out.append("<hr/>")
            continue
        matched_h = False
        for rex, tag in _RE_H:
            m = rex.match(line)
            if m:
                close_list()
                out.append(f"<{tag}>{m.group(1)}</{tag}>")
                matched_h = True
                break
        if matched_h:
            continue
        if line.startswith(("* ", "# ")):
            want = "ul" if line[0] == "*" else "ol"
            if in_list != want:
                close_list()
                out.append(f"<{want}>")
                in_list = want
            out.append(f"<li>{line[2:]}</li>")
            continue
        close_list()
        if not line.strip():
            out.append("<p/>")
        else:
            out.append(line + "<br/>")
    close_list()
    return "\n".join(out)


class WikiBoard:
    """Named pages with full version history (WikiBoard + bkp semantics)."""

    TABLE = "wiki"
    TABLE_BKP = "wiki_bkp"

    def __init__(self, tables: Tables):
        self.tables = tables

    def put(self, page: str, content: str, author: str = "anonymous") -> None:
        key = page.strip().lower()
        old = self.tables.get(self.TABLE, key)
        if old is not None:
            self.tables.insert(self.TABLE_BKP, old, pk=None)
        self.tables.insert(self.TABLE, {
            "page": page.strip(), "content": content, "author": author,
            "date": time.time()}, pk=key)

    def get(self, page: str) -> dict | None:
        return self.tables.get(self.TABLE, page.strip().lower())

    def render(self, page: str) -> str:
        row = self.get(page)
        return wikicode_to_html(row["content"]) if row else ""

    def pages(self) -> list[str]:
        return sorted(r["page"] for r in self.tables.rows(self.TABLE))

    def history(self, page: str) -> list[dict]:
        key = page.strip().lower()
        return sorted((r for r in self.tables.rows(self.TABLE_BKP)
                       if r.get("page", "").strip().lower() == key),
                      key=lambda r: r.get("date", 0))


class BlogBoard:
    """Dated entries, newest first (BlogBoard semantics)."""

    TABLE = "blog"

    def __init__(self, tables: Tables):
        self.tables = tables

    def add(self, subject: str, content: str, author: str = "anonymous",
            wikicode: bool = True) -> str:
        return self.tables.insert(self.TABLE, {
            "subject": subject, "content": content, "author": author,
            "date": time.time(), "wikicode": bool(wikicode), "comments": []})

    def entries(self, n: int = 20) -> list[dict]:
        rows = sorted(self.tables.rows(self.TABLE),
                      key=lambda r: -r.get("date", 0))
        return rows[:n]

    def get(self, pk: str) -> dict | None:
        return self.tables.get(self.TABLE, pk)

    def render(self, pk: str) -> str:
        row = self.get(pk)
        if row is None:
            return ""
        if row.get("wikicode"):
            return wikicode_to_html(row["content"])
        return html.escape(row["content"]).replace("\n", "<br/>")

    def comment(self, pk: str, author: str, content: str) -> bool:
        row = self.get(pk)
        if row is None:
            return False
        row.setdefault("comments", []).append(
            {"author": author, "content": content, "date": time.time()})
        return self.tables.update(self.TABLE, pk, row)

    def delete(self, pk: str) -> bool:
        return self.tables.delete(self.TABLE, pk)


class MessageBoard:
    """Peer-to-peer messages (MessageBoard semantics; the wire delivery is
    the yacy/message RPC — this is the mailbox)."""

    TABLE = "messages"

    def __init__(self, tables: Tables):
        self.tables = tables

    def send(self, to: str, from_: str, subject: str, content: str) -> str:
        return self.tables.insert(self.TABLE, {
            "to": to, "from": from_, "subject": subject, "content": content,
            "date": time.time(), "read": False})

    def inbox(self, user: str, unread_only: bool = False) -> list[dict]:
        rows = [r for r in self.tables.rows(self.TABLE) if r.get("to") == user
                and (not unread_only or not r.get("read"))]
        return sorted(rows, key=lambda r: -r.get("date", 0))

    def mark_read(self, pk: str) -> bool:
        row = self.tables.get(self.TABLE, pk)
        if row is None:
            return False
        row["read"] = True
        return self.tables.update(self.TABLE, pk, row)

    def delete(self, pk: str) -> bool:
        return self.tables.delete(self.TABLE, pk)
