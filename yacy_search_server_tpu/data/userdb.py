"""User database — accounts with hashed credentials and right flags.

Capability equivalent of the reference's user administration (reference:
source/net/yacy/data/UserDB.java — user entries with MD5(user:pw)
credential hashes and per-right flags consumed by the servlet security
layer; http/YaCyLegacyCredential.java hash form). The admin account
itself lives in config (adminAccountBase64MD5) exactly like the
reference; this DB is for additional named users.
"""

from __future__ import annotations

import hashlib
import time

from .tables import Tables

# right flags (UserDB.AccessRight subset)
RIGHT_ADMIN = "admin"
RIGHT_DOWNLOAD = "download"
RIGHT_UPLOAD = "upload"
RIGHT_PROXY = "proxy"
RIGHT_BLOG = "blog"
RIGHT_WIKI = "wiki"
RIGHT_BOOKMARK = "bookmark"
ALL_RIGHTS = (RIGHT_ADMIN, RIGHT_DOWNLOAD, RIGHT_UPLOAD, RIGHT_PROXY,
              RIGHT_BLOG, RIGHT_WIKI, RIGHT_BOOKMARK)


def credential_hash(user: str, password: str) -> str:
    """MD5(user:pw) hex — the reference's legacy credential form
    (YaCyLegacyCredential)."""
    return hashlib.md5(f"{user}:{password}".encode("utf-8")).hexdigest()  # nosec


class UserDB:
    TABLE = "users"

    def __init__(self, tables: Tables):
        self.tables = tables

    def create(self, user: str, password: str,
               rights: list[str] | None = None) -> bool:
        if not user or self.tables.get(self.TABLE, user) is not None:
            return False
        self.tables.insert(self.TABLE, {
            "name": user, "credential": credential_hash(user, password),
            "rights": [r for r in (rights or []) if r in ALL_RIGHTS],
            "created": time.time(), "last_access": 0.0}, pk=user)
        return True

    def authenticate(self, user: str, password: str) -> bool:
        row = self.tables.get(self.TABLE, user)
        if row is None or row["credential"] != credential_hash(user, password):
            return False
        row["last_access"] = time.time()
        self.tables.update(self.TABLE, user, row)
        return True

    def has_right(self, user: str, right: str) -> bool:
        row = self.tables.get(self.TABLE, user)
        return bool(row) and (right in row.get("rights", [])
                              or RIGHT_ADMIN in row.get("rights", []))

    def grant(self, user: str, right: str) -> bool:
        row = self.tables.get(self.TABLE, user)
        if row is None or right not in ALL_RIGHTS:
            return False
        if right not in row["rights"]:
            row["rights"].append(right)
        return self.tables.update(self.TABLE, user, row)

    def revoke(self, user: str, right: str) -> bool:
        row = self.tables.get(self.TABLE, user)
        if row is None or right not in row.get("rights", []):
            return False
        row["rights"].remove(right)
        return self.tables.update(self.TABLE, user, row)

    def set_password(self, user: str, password: str) -> bool:
        row = self.tables.get(self.TABLE, user)
        if row is None:
            return False
        row["credential"] = credential_hash(user, password)
        return self.tables.update(self.TABLE, user, row)

    def delete(self, user: str) -> bool:
        return self.tables.delete(self.TABLE, user)

    def users(self) -> list[dict]:
        return sorted(self.tables.rows(self.TABLE),
                      key=lambda r: r.get("name", ""))
