"""WorkTables — recorded API calls with repeat schedules.

Capability equivalent of the reference's action recorder + scheduler
(reference: source/net/yacy/data/WorkTables.java:66-232 — every admin
action is written into the `api` table with its servlet path, comment and
optional repeat schedule; the scheduler busy thread re-executes due rows
via a self-HTTP call, Switchboard.java:1131-1151 schedulerJob). Replaying
through the HTTP surface (not an internal function call) is load-bearing:
the recorded URL IS the action, surviving restarts and code changes.
"""

from __future__ import annotations

import time

from .tables import Tables

TABLE_API = "api"

# schedule units in seconds (WorkTables scheme: minutes/hours/days)
_UNITS = {"minutes": 60, "hours": 3600, "days": 86400}


class WorkTables:
    def __init__(self, tables: Tables):
        self.tables = tables

    # -- recording ------------------------------------------------------------

    def record_api_call(self, path: str, servlet_name: str, comment: str,
                        repeat_count: int = 0,
                        repeat_unit: str = "days") -> str:
        """Record one executed admin action; `path` is the full local URL
        path incl. query (the replayable action).

        Re-recording the same URL UPDATES the existing row (bumping its
        exec bookkeeping) instead of inserting — scheduled replays re-enter
        the recording servlet, and must not grow the table (the reference
        dedups recorded actions by URL the same way)."""
        now = time.time()
        existing = self.tables.select(TABLE_API, url=path)
        if existing:
            row = existing[0]
            row["date_last_exec"] = now
            row["exec_count"] = int(row.get("exec_count", 0)) + 1
            if repeat_count:        # replay URLs carry no schedule params;
                row["repeat_count"] = int(repeat_count)   # keep the stored one
                row["repeat_unit"] = (repeat_unit if repeat_unit in _UNITS
                                      else "days")
            row["date_next_exec"] = self._next_exec(row)
            self.tables.update(TABLE_API, row["_pk"], row)
            return row["_pk"]
        row = {
            "url": path, "type": servlet_name, "comment": comment,
            "date_recording": now, "date_last_exec": now,
            "exec_count": 1,
            "repeat_count": int(repeat_count),
            "repeat_unit": repeat_unit if repeat_unit in _UNITS else "days",
        }
        row["date_next_exec"] = self._next_exec(row)
        return self.tables.insert(TABLE_API, row)

    def set_schedule(self, pk: str, repeat_count: int,
                     repeat_unit: str = "days") -> bool:
        row = self.tables.get(TABLE_API, pk)
        if row is None:
            return False
        row["repeat_count"] = int(repeat_count)
        row["repeat_unit"] = repeat_unit if repeat_unit in _UNITS else "days"
        row["date_next_exec"] = self._next_exec(row)
        return self.tables.update(TABLE_API, pk, row)

    @staticmethod
    def _next_exec(row: dict) -> float:
        n = int(row.get("repeat_count", 0))
        if n <= 0:
            return 0.0
        unit_s = _UNITS.get(row.get("repeat_unit", "days"), 86400)
        return float(row.get("date_last_exec", time.time())) + n * unit_s

    # -- scheduler ------------------------------------------------------------

    def due_rows(self, now: float | None = None) -> list[dict]:
        now = time.time() if now is None else now
        return [r for r in self.tables.rows(TABLE_API)
                if r.get("date_next_exec", 0) and r["date_next_exec"] <= now]

    def scheduler_job(self, execute, now: float | None = None) -> bool:
        """Re-execute every due recorded call through `execute(path) ->
        bool` (the self-HTTP GET); update bookkeeping. Returns True if
        anything ran (BusyThread contract)."""
        ran = False
        now = time.time() if now is None else now
        for row in self.due_rows(now):
            ok = False
            try:
                ok = bool(execute(row["url"]))
            except Exception:
                ok = False
            row["date_last_exec"] = now
            row["exec_count"] = int(row.get("exec_count", 0)) + 1
            row["last_exec_ok"] = ok
            row["date_next_exec"] = self._next_exec(row)
            self.tables.update(TABLE_API, row["_pk"], row)
            ran = True
        return ran

    def clear(self) -> None:
        """Drop every recorded/scheduled API call (bin/clearapi.sh)."""
        self.tables.clear("api")

    def calls(self) -> list[dict]:
        return sorted(self.tables.rows(TABLE_API),
                      key=lambda r: -r.get("date_recording", 0))
