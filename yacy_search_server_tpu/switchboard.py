"""Switchboard — the application kernel owning every subsystem.

Capability equivalent of the reference's Switchboard (reference:
source/net/yacy/search/Switchboard.java:— the singleton that owns
sb.index / sb.crawler / sb.crawlQueues / sb.crawlStacker / sb.loader and
the 4-stage concurrent indexing pipeline, Switchboard.java:1033-1101),
minus the P2P subsystems that the peers/ layer wires in (M5).

The indexing pipeline keeps the reference's exact 4-stage shape with
per-stage WorkflowProcessors and backpressure:

    parseDocument -> condenseDocument -> webStructureAnalysis
        -> storeDocumentIndex (serialized)

(stage semantics: Switchboard.parseDocument:2400, condenseDocument,
webStructureAnalysis, storeDocumentIndex:2126). Stage 4 is the only
writer into the Segment, matching the reference's 2-worker serialized
store stage.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from .crawler.cache import HTCache
from .crawler.frontier import NoticedURL, StackType
from .crawler.latency import Latency
from .crawler.loader import CacheStrategy, LoaderDispatcher
from .crawler.profile import CrawlProfile, default_profiles
from .crawler.queues import CrawlQueues
from .crawler.request import Request, Response
from .crawler.robots import RobotsTxt
from .crawler.stacker import CrawlStacker
from .data.blacklist import Blacklist
from .document.condenser import Condenser
from .document.document import Document
from .document.parser import ParserError, parse_source
from .index.segment import Segment
from .search.searchevent import SearchEvent, SearchEventCache
from .search.query import QueryParams
from .utils import tracing
from .utils.config import Config
from .utils.eventtracker import EClass, StageTimer
from .utils.workflow import BusyThread, ThreadRegistry, WorkflowProcessor
from .webstructure import WebStructureGraph


@dataclass
class IndexingEntry:
    """The work item flowing through the 4 pipeline stages
    (Switchboard.IndexingQueueEntry equivalent)."""
    response: Response
    profile: CrawlProfile
    documents: list[Document] = field(default_factory=list)
    condensers: list[Condenser] = field(default_factory=list)
    # per-document pipeline trace handle (utils/tracing.begin): stages
    # run on decoupled worker threads, so the context travels on the
    # work item, not the contextvar
    trace: object = None
    # crawl-to-searchable SLO stamp (ISSUE 13a): pipeline-entry time,
    # carried by value for the same decoupled-thread reason
    ingest_stamp: float = 0.0


class Switchboard:
    def __init__(self, data_dir: str | None = None,
                 config: Config | None = None,
                 transport=None, pipeline_workers: int = 2):
        self.config = config or Config()
        self.data_dir = data_dir
        # tracing is on by default (the <2% overhead contract is pinned
        # by bench.py --trace-overhead). The flag is process-global
        # (co-hosted loopback nodes share one spine), so only an
        # EXPLICIT config setting touches it — a default-config
        # switchboard must not clobber another node's choice or an
        # operator's runtime set_enabled()
        if "tracing.enabled" in set(self.config.keys()):
            tracing.set_enabled(
                self.config.get_bool("tracing.enabled", True))
        sub = (lambda s: os.path.join(data_dir, s)) if data_dir else (
            lambda s: None)
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)

        # core subsystems (Switchboard ctor parity)
        self.index = Segment(sub("INDEX"))
        # device-resident serving is the product default: eligible queries
        # rank placed postings blocks instead of re-uploading candidates
        # (VERDICT r1 weak #1); config-gated for hosts without a device
        if self.config.get_bool("index.device.serving", True):
            try:
                budget = self.config.get_int(
                    "index.device.budgetBytes", 2 << 30)
                # a node with >1 chip serves from ALL of them: the mesh
                # store partitions the arena over ('term','doc') axes
                # (VERDICT r2 #1). index.device.mesh: auto|on|off;
                # index.device.meshTermAxis sizes the term axis.
                mesh_mode = self.config.get("index.device.mesh", "auto")
                import jax as _jax
                n_dev = len(_jax.devices())
                use_mesh = (mesh_mode == "on"
                            or (mesh_mode == "auto" and n_dev > 1))
                if use_mesh:
                    n_term = self.config.get_int(
                        "index.device.meshTermAxis", 1)
                    if n_dev % max(n_term, 1):
                        # a config typo must be LOUD, not a silent
                        # fall-through to host serving
                        raise ValueError(
                            f"index.device.meshTermAxis={n_term} does not"
                            f" divide the {n_dev} available devices")
                    self.index.enable_mesh_serving(
                        n_term=n_term, budget_bytes=budget)
                else:
                    self.index.enable_device_serving(
                        budget_bytes=budget,
                        # compressed residency + tier ladder: bit-packed
                        # blocks with fused on-device decode; corpus
                        # size becomes a tiering decision instead of an
                        # HBM ceiling (off by default — the capacity
                        # bench and parity tests drive it)
                        packed_residency=self.config.get_bool(
                            "index.device.packedResidency", False),
                        warm_budget_bytes=self.config.get_int(
                            "index.device.warmBudgetBytes", 1 << 30))
                if self.config.get_bool("index.device.batching", True):
                    self.index.devstore.enable_batching(
                        max_batch=self.config.get_int(
                            "index.device.batchSize", 16),
                        dispatchers=self.config.get_int(
                            # dispatcher threads sit blocked in the
                            # device round trip; 8 saturates the tunnel
                            # (16 measured no better at 10M/64thr)
                            "index.device.dispatchers", 8),
                        # batch exact stream scans (the r5 modifier
                        # mix's solo dispatches) too — off by default
                        # until the mix protocol commits the win
                        scan_batching=self.config.get_bool(
                            "index.device.scanBatching", False),
                        # pipelined dispatch: issue async, fetch in the
                        # completer pool (one round trip per wave);
                        # completerDepth bounds in-flight waves per
                        # dispatcher
                        pipeline=self.config.get_bool(
                            "index.device.pipeline", True),
                        completer_depth=self.config.get_int(
                            "index.device.completerDepth", 2),
                        # batch hybrid dense reranks through the same
                        # pipeline (on by default — the last solo
                        # kernel; bench --rerank-overhead pins the
                        # gate); off = solo dispatches of the same
                        # packed kernel, the parity-test A/B switch
                        rerank_batching=self.config.get_bool(
                            "index.device.rerankBatching", True))
                # dense-first serving knobs (ISSUE 11): probe width and
                # per-query lane budget ride the store; the forward
                # index's device budget replaces the old hard-coded
                # 1 GiB class constant
                ds = self.index.devstore
                if hasattr(ds, "ann_nprobe"):   # mesh store: no ANN yet
                    ds.ann_nprobe = self.config.get_int(
                        "index.ann.nprobe", ds.ann_nprobe)
                    ds.ann_probe_lanes = self.config.get_int(
                        "index.ann.probeLanes", ds.ann_probe_lanes)
            except ValueError:
                raise
            except Exception:  # no usable jax backend: host path serves
                self.index.devstore = None
                self.index.rwi.listener = None
        self.index.dense.device_budget_bytes = self.config.get_int(
            "index.dense.deviceBudgetBytes",
            self.index.dense.device_budget_bytes)
        self.latency = Latency()
        self.htcache = HTCache(sub("HTCACHE"))
        self.loader = LoaderDispatcher(self.htcache, self.latency,
                                       transport=transport)
        self.robots = RobotsTxt(
            fetcher=lambda url: self._robots_fetch(url))
        self.profiles: dict[str, CrawlProfile] = {}
        for p in default_profiles().values():
            self.profiles[p.handle] = p
        # user profiles survive restarts (the reference keeps them in a
        # MapHeap; CrawlSwitchboard reload) — the frontier's queued
        # requests reference profile handles that must still resolve.
        # Defaults are excluded from the file BY HANDLE (a user profile
        # may legitimately reuse a default's name).
        self._default_handles = set(self.profiles)
        self._profiles_lock = threading.Lock()
        self._profiles_path = sub("CRAWL_PROFILES.jsonl") if data_dir else None
        self._load_profiles()
        self.noticed = NoticedURL(self.latency, sub("CRAWL"))
        self.blacklist = Blacklist(sub("BLACKLISTS"))
        self.crawl_stacker = CrawlStacker(
            self.noticed, self.profiles, segment=self.index,
            robots=self.robots, blacklist=self.blacklist.crawler_reason)
        self.crawl_queues = CrawlQueues(
            self.noticed, self.loader, self.profiles, robots=self.robots,
            indexer=self.to_indexer, data_dir=sub("CRAWL"))
        self.web_structure = WebStructureGraph(sub("WEBSTRUCTURE"))
        self.search_cache = SearchEventCache()
        from .search.accesstracker import AccessTracker
        self.access_tracker = AccessTracker(
            os.path.join(data_dir, "LOG", "queries.log") if data_dir else None)
        self._heuristic_fired: dict[str, float] = {}
        # application data substrate: generic tables + the stores above them
        # (reference: sb.tables / WorkTables / boards / BookmarksDB / UserDB)
        from .data.boards import BlogBoard, MessageBoard, WikiBoard
        from .data.bookmarks import BookmarksDB
        from .data.tables import Tables
        from .data.userdb import UserDB
        from .data.worktables import WorkTables
        self.tables = Tables(sub("TABLES"))
        self.work_tables = WorkTables(self.tables)
        self.wiki = WikiBoard(self.tables)
        self.blog = BlogBoard(self.tables)
        self.messages = MessageBoard(self.tables)
        self.bookmarks = BookmarksDB(self.tables)
        self.userdb = UserDB(self.tables)
        # recently searched terms/viewed items for the UI session
        # (reference: Switchboard.trail served by api/trail_p.java)
        from collections import deque
        self.trail: deque = deque(maxlen=100)
        from .data.contentcontrol import ContentControl
        from .document.vocabulary import TripleStore, VocabularyLibrary
        self.vocabularies = VocabularyLibrary(sub("DICTIONARIES"))
        self.index.vocabularies = self.vocabularies
        from .document.synonyms import SynonymLibrary
        syn_dir = os.path.join(data_dir, "DICTIONARIES", "synonyms") \
            if data_dir else None
        self.synonyms = SynonymLibrary(syn_dir)
        self.index.synonyms = self.synonyms
        from .document.geolocalization import Gazetteer
        self.gazetteer = Gazetteer(
            os.path.join(data_dir, "DICTIONARIES", "geo")
            if data_dir else None)
        self.index.gazetteer = self.gazetteer if self.gazetteer.size() else None
        from .crawler.snapshots import Snapshots
        self.snapshots = Snapshots(sub("SNAPSHOTS"))
        self.triplestore = TripleStore(
            os.path.join(data_dir, "triplestore.jsonl") if data_dir else None)
        self.content_control = ContentControl(self.bookmarks)
        self.content_control.enabled = self.config.get_bool(
            "contentcontrol.enabled", False)
        # self-HTTP executor for the scheduler; the HTTP server sets this
        # when it binds (the reference re-executes recorded API calls
        # through its own HTTP port, WorkTables.execAPICall)
        self.api_executor = None
        self.threads = ThreadRegistry()

        self.indexed_count = 0
        self._pipeline_seq = 0   # pipeline trace sampling counter
        self.started = time.time()
        self._closed = False
        # set by signal handlers or the Steering servlet; the launcher's
        # waitForShutdown blocks on it (yacy.java:393)
        self.shutdown_event = threading.Event()

        # the 4-stage pipeline; stage 4 single-worker = serialized IO
        self._store_proc = WorkflowProcessor(
            "storeDocumentIndex", self._stage_store, workers=1,
            queue_size=200)
        self._structure_proc = WorkflowProcessor(
            "webStructureAnalysis", self._stage_structure, workers=1,
            queue_size=200, next_stage=self._store_proc)
        self._condense_proc = WorkflowProcessor(
            "condenseDocument", self._stage_condense,
            workers=pipeline_workers, queue_size=200,
            next_stage=self._structure_proc)
        self._parse_proc = WorkflowProcessor(
            "parseDocument", self._stage_parse, workers=pipeline_workers,
            queue_size=200, next_stage=self._condense_proc)

        # fleet observability (ISSUE 5): the digest renderer + per-peer
        # digest table.  Constructed on EVERY switchboard (the fleet
        # health rules and /metrics yacy_fleet_* families reference it
        # unconditionally); the peer stack wires identity + gossip in
        # (peers/node.py)
        from .utils.fleet import FleetTable
        self.fleet = FleetTable(self)

        # node health engine (ISSUE 4): rules + SLO burn rates + flight
        # recorder over the same series /metrics exports.  Constructed
        # here (cheap: no evaluation), driven by the 15_health busy
        # thread — or directly by tests/Performance_Health_p
        from .utils.health import HealthEngine
        self.health = HealthEngine(
            self, incidents_dir=sub("HEALTH") if data_dir else None)

        # tail-attribution engine (ISSUE 15): process-global like the
        # histogram registry it gates on; configured here so tail.* is
        # read once per switchboard like every performance knob
        from .utils import tailattr
        tailattr.configure(self.config)

        # whitebox profiler (ISSUE 20): the always-on sampler thread +
        # lock-wait observatory knobs.  configure() starts the process-
        # global sampler (idempotent — one daemon thread per process,
        # shared by every switchboard like the histogram registry)
        from .utils import profiling
        profiling.configure(self.config)

        # actuator layer (ISSUE 9): the rules above only OBSERVE — this
        # closes the loop.  Admission token buckets, the serving
        # degradation ladder, batcher auto-tuning and the remote-search
        # peer guard, all ticked by the health engine right after rule
        # evaluation (one cadence for sensing and actuation)
        from .utils.actuator import ActuatorEngine
        self.actuators = ActuatorEngine(self)

        # streaming-ingest write path (ISSUE 13): the merge/promotion
        # scheduler the `merge_scheduler` actuator drives — compactions
        # and tier promotions defer while the serving SLO burns, catch
        # up when the node is healthy again.  The devstore consults it
        # on every promotion submit; the cleanup job's merge path routes
        # through it.
        from .ingest.scheduler import MergeScheduler
        self.ingest_scheduler = MergeScheduler(self)
        if self.index.devstore is not None:
            self.index.devstore.ingest_scheduler = self.ingest_scheduler
            # device-side index build (ISSUE 13b): bit-pack fresh runs
            # as ONE vmapped dispatch per row bucket instead of the
            # host per-term loop (bit-identical; parity-pinned).  Off
            # by default on host-only backends — the win is moving the
            # pack onto an accelerator, not re-buying it on the CPU.
            self.index.devstore.ingest_device_build = \
                self.config.get_bool("ingest.deviceBuild", False)

        # data-store migrations: rows written by an older release are
        # upgraded in place once, tracked by the STORE_VERSION marker in
        # the data dir (reference: migration.java version-gated rewrites,
        # yacy.java:285)
        if data_dir:
            from .migration import migrate_data
            from .yacy import VERSION
            migrate_data(self.index, data_dir, VERSION)

    # -- crawl control -------------------------------------------------------

    def _robots_fetch(self, url: str):
        resp = self.loader.load(Request(url), CacheStrategy.IFFRESH)
        return resp.content if resp.status == 200 else None

    # lint: unlocked-ok(construction-time: only __init__ calls this,
    # before the switchboard is shared with any other thread)
    def _load_profiles(self) -> None:
        import json
        if not self._profiles_path or not os.path.exists(self._profiles_path):
            return
        try:
            with open(self._profiles_path, encoding="utf-8") as f:
                for line in f:
                    try:
                        p = CrawlProfile.from_dict(json.loads(line))
                        self.profiles[p.handle] = p
                    except (ValueError, TypeError, KeyError):
                        continue
        except OSError:
            pass

    def _save_profiles(self) -> None:
        import json
        if not self._profiles_path:
            return
        # the WHOLE save runs under the lock: concurrent saves would
        # otherwise race on the shared .tmp file and a stale snapshot
        # could os.replace a newer one (the file is tiny; serializing is
        # cheap)
        with self._profiles_lock:
            rows = [p.to_dict() for p in self.profiles.values()
                    if p.handle not in self._default_handles]
            tmp = self._profiles_path + ".tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    for row in rows:
                        f.write(json.dumps(row) + "\n")
                os.replace(tmp, self._profiles_path)
            except OSError:
                pass

    def add_profile(self, profile: CrawlProfile) -> CrawlProfile:
        with self._profiles_lock:
            self.profiles[profile.handle] = profile
        self._save_profiles()
        return profile

    def start_crawl(self, start_url: str, depth: int = 0,
                    name: str | None = None, **profile_kwargs) -> CrawlProfile:
        """Create a crawl profile and stack the start url
        (Crawler_p servlet semantics)."""
        profile = CrawlProfile(name or start_url, start_url=start_url,
                               depth=depth, **profile_kwargs)
        self.add_profile(profile)
        req = Request(url=start_url, profile_handle=profile.handle, depth=0)
        reason = self.crawl_stacker.stack(req)
        if reason:
            # rejected start never crawls: do not leak its profile
            with self._profiles_lock:
                self.profiles.pop(profile.handle, None)
            self._save_profiles()
            raise ValueError(f"start url rejected: {reason}")
        return profile

    def start_sitemap_crawl(self, sitemap_url: str,
                            name: str | None = None,
                            **profile_kwargs) -> int:
        """Stack every location of a sitemap (recursing through indexes);
        returns urls stacked (Crawler_p sitemap start semantics)."""
        from .crawler.sitemap import SitemapImporter
        profile = CrawlProfile(name or f"sitemap:{sitemap_url}",
                               start_url=sitemap_url, depth=0,
                               **profile_kwargs)
        self.add_profile(profile)
        importer = SitemapImporter(self.loader, self.crawl_stacker,
                                   profile.handle)
        stacked = importer.import_sitemap(sitemap_url)
        if stacked == 0:
            with self._profiles_lock:
                self.profiles.pop(profile.handle, None)
            self._save_profiles()    # the pop must reach the file too
        return stacked

    def run_postprocessing(self) -> int:
        """Citation-rank postprocessing: host BlockRank power iteration ->
        cr_host_norm_d columns (reference: CollectionConfiguration
        postprocessing + BlockRank)."""
        from .ops.blockrank import postprocess_segment
        return postprocess_segment(self.index, self.web_structure)

    def crawl_until_idle(self, timeout_s: float = 60.0) -> int:
        """Drive the crawl synchronously until frontier + pipeline drain
        (test/CLI surface; the busy-thread mode is deploy_threads).

        Loops drain+flush because link discovery happens inside the async
        parse stage: the frontier refills after the first drain empties."""
        t_end = time.time() + timeout_s
        total = 0
        while time.time() < t_end:
            n = self.crawl_queues.drain(
                StackType.LOCAL, timeout_s=max(0.1, t_end - time.time()))
            self.flush_pipeline()
            total += n
            if n == 0 and self.noticed.size(StackType.LOCAL) == 0:
                break
        return total

    # -- indexing pipeline ---------------------------------------------------

    def to_indexer(self, response: Response, profile: CrawlProfile) -> None:
        """Pipeline entry (Switchboard.toIndexer). Admitted entries get
        a trace: the 4 stages run on decoupled worker threads, so the
        handle rides the entry and every stage's StageTimer span lands
        under it (utils/tracing.PipelineTrace). SAMPLED (1 in
        tracing.pipelineSampleEvery, first document always) — an active
        crawl tracing every document would flood the bounded trace
        ring and evict the search traces within seconds."""
        reason = response.indexable()
        if reason is not None:
            self.crawl_queues.error_cache.push(
                response.request.urlhash(), response.url, reason)
            return
        entry = IndexingEntry(response, profile)
        # crawl-to-searchable SLO (ISSUE 13a): the clock starts HERE,
        # where the crawler hands the document to the pipeline — every
        # stage wall, the store, the flush and the device pack all land
        # inside this one latency
        from .ingest import slo as ingest_slo
        entry.ingest_stamp = ingest_slo.TRACKER.stamp()
        every = self.config.get_int("tracing.pipelineSampleEvery", 16)
        seq = self._pipeline_seq
        self._pipeline_seq = seq + 1
        if every > 0 and seq % every == 0:
            entry.trace = tracing.begin("pipeline.index", url=response.url)
        self._parse_proc.enqueue(entry)

    @staticmethod
    def _trace_ctx(entry: IndexingEntry):
        return entry.trace.ctx if entry.trace is not None else None

    def _end_trace(self, entry: IndexingEntry, **attrs) -> None:
        if entry.trace is not None:
            entry.trace.end(**attrs)

    def _stage_parse(self, entry: IndexingEntry):
        with tracing.attached(self._trace_ctx(entry)), \
                StageTimer(EClass.INDEX, "parseDocument", 1):
            resp = entry.response
            try:
                entry.documents = parse_source(
                    resp.url, resp.mime_type(), resp.content,
                    resp.charset())
            except ParserError as e:
                self.crawl_queues.error_cache.push(
                    resp.request.urlhash(), resp.url, f"parser: {e}")
                self._end_trace(entry, outcome="parser_error")
                return None
            # discovered hyperlinks -> stacker (depth+1), the crawl loop
            if entry.profile.depth > resp.request.depth:
                for doc in entry.documents:
                    self.crawl_stacker.enqueue_entries(
                        doc.anchors, resp.request.urlhash(),
                        entry.profile.handle, resp.request.depth + 1)
            return entry

    def _stage_condense(self, entry: IndexingEntry):
        with tracing.attached(self._trace_ctx(entry)), \
                StageTimer(EClass.INDEX, "condenseDocument", 1):
            entry.documents = [d for d in entry.documents
                               if not getattr(d, "noindex", False)
                               and entry.profile.index_allowed(d.url)]
            entry.condensers = [
                Condenser(d, index_text=entry.profile.index_text,
                          index_media=entry.profile.index_media)
                for d in entry.documents]
            return entry

    def _stage_structure(self, entry: IndexingEntry):
        with tracing.attached(self._trace_ctx(entry)), \
                StageTimer(EClass.INDEX, "webStructureAnalysis", 1):
            for doc in entry.documents:
                self.web_structure.add_document(doc.url, [
                    a.url for a in doc.anchors])
            return entry

    def _stage_store(self, entry: IndexingEntry):
        with tracing.attached(self._trace_ctx(entry)), \
                StageTimer(EClass.INDEX, "storeDocumentIndex", 1):
            req = entry.response.request
            # snapshot the loaded rendition when the profile asks for it
            # (Transactions.store on the indexing path)
            if 0 <= req.depth <= entry.profile.snapshot_depth:
                try:
                    self.snapshots.store(entry.response.url,
                                         entry.response.content,
                                         depth=req.depth)
                except OSError:
                    pass
            for doc in entry.documents:
                self.index.store_document(
                    doc, crawldepth=req.depth,
                    collection=entry.profile.collections[0],
                    referrer_urlhash=req.referrer_hash or None,
                    responsetime_ms=int(
                        entry.response.fetch_time_s * 1000),
                    httpstatus=entry.response.status,
                    ingest_stamp=entry.ingest_stamp or None)
                # RDFa annotations land in the lod triple store
                # (reference: parser/rdfa -> cora/lod)
                for s_, p_, o_ in getattr(doc, "rdf_triples", []):
                    self.triplestore.add(s_, p_, o_)
                self.indexed_count += 1
            self._end_trace(entry, documents=len(entry.documents))
            return None

    def flush_pipeline(self, timeout_s: float = 30.0) -> None:
        """Wait until all four stages are drained. Joining the stages in
        order is sufficient: a stage enqueues downstream before marking its
        own item done, so join(parse) implies every parse result reached
        condense, and so on."""
        for p in (self._parse_proc, self._condense_proc,
                  self._structure_proc, self._store_proc):
            p.join()

    # -- search --------------------------------------------------------------

    def search(self, query_string: str, count: int = 10,
               offset: int = 0, hybrid: bool = False,
               client: str = "", contentdom: str = "",
               use_cache: bool = True,
               dense_first: bool = False) -> SearchEvent:
        # root trace for direct callers (node.search, benchmarks, the
        # federation connectors); under a servlet's trace this degrades
        # to a child span — one request stays one trace
        with tracing.trace("switchboard.search", q=query_string[:64],
                           count=count, offset=offset):
            return self._search_traced(query_string, count, offset,
                                       hybrid, client, contentdom,
                                       use_cache, dense_first)

    def _search_traced(self, query_string: str, count: int,
                       offset: int, hybrid: bool, client: str,
                       contentdom: str, use_cache: bool,
                       dense_first: bool = False) -> SearchEvent:
        q = QueryParams.parse(query_string)
        q.item_count = count
        q.offset = offset
        # dense-first IS a hybrid mode (the fused list blends the dense
        # boost into the sparse cardinal domain)
        q.hybrid = hybrid or dense_first
        q.dense_first = dense_first
        if contentdom:
            # contentdom selects the media type AND its ranking preset
            # (reference: yacysearch.java contentdom parameter)
            from .search.query import CONTENTDOM_NAMES
            cd = CONTENTDOM_NAMES.get(contentdom.lower())
            if cd is not None and cd != q.contentdom:
                q.contentdom = cd
                from .ops.ranking import RankingProfile
                q.profile = RankingProfile.for_contentdom(cd)
        # operator-tuned coefficients (Ranking_p editor) override the
        # default TEXT profile only — image/audio/video content domains
        # keep their cat*-boosted presets (reference: RankingProfile
        # serialized into config keys, RankingProfile.java:155+, with
        # per-contentdom presets at :92-124)
        ext = self.config.get("rankingProfile.default", "")
        if ext:
            from .ops.ranking import CD_ALL, CD_TEXT, RankingProfile
            if q.contentdom in (CD_ALL, CD_TEXT):
                try:
                    q.profile = RankingProfile.from_external_string(ext)
                except (ValueError, KeyError):
                    pass
        if self.content_control.enabled:
            q.url_filter = self.content_control.excluded
        # live snippet verification policy (reference: search.verify
        # config; cacheonly is the p2p default, ifexist the intranet one)
        q.snippet_strategy = self.config.get(
            "search.verify",
            "ifexist" if self.config.get(
                "network.unit.name", "") == "intranet" else "cacheonly")
        q.snippet_delete_on_fail = self.config.get_bool(
            "search.verify.delete", True)
        # degradation ladder (ISSUE 9): the actuator's current rung
        # rides the query explicitly — every downstream stage decision
        # (snippets, rerank, cache-only) reads THIS value, and the
        # per-level histogram in the headline artifact counts it
        act = getattr(self, "actuators", None)
        if act is not None:
            q.degrade_level = act.effective_level()
            act.note_query(q.degrade_level)
        t0 = time.time()
        if use_cache:
            event = self.search_cache.get_event(q, self.index,
                                                loader=self.loader)
        else:
            # cache bypass (benchmarks / debugging): a fresh event per
            # call — paging over it is the caller's problem
            event = SearchEvent(q, self.index, loader=self.loader)
        if query_string and (not self.trail
                             or self.trail[-1] != query_string):
            self.trail.append(query_string)
        from .search.accesstracker import QueryLogEntry
        self.access_tracker.add(QueryLogEntry(
            query=query_string, timestamp=t0,
            query_count=len(q.goal.include_words),
            result_count=event.result_heap.size_available(),
            time_ms=(time.time() - t0) * 1000.0,
            offset=offset, client=client))
        # site heuristic (reference: Switchboard.heuristicSite:4209): a
        # site:-restricted query that finds little triggers a shallow crawl
        # of that site so the next query round can answer from the index
        if not event.heuristics_fired:
            # one-shot per event: paging / cache hits never re-fire
            event.heuristics_fired = True
            if q.modifier.sitehost and self.config.get_bool(
                    "heuristic.site", False) \
                    and event.result_heap.size_available() < count:
                self.heuristic_site(q.modifier.sitehost)
            # opensearch heuristic: external endpoints late-merge into the
            # live event (FederateSearchManager; results appear on paging)
            if self.config.get_bool("heuristic.opensearch", False) \
                    and q.goal.include_words:
                from .search.federated import FederateSearchManager
                FederateSearchManager.from_config(
                    self.loader, self.config).search_into_event(
                        event, " ".join(q.goal.include_words))
        return event

    # heuristic re-fire cooldown per host (the reference's heuristics are
    # one-shot per search event; a cached event pages without re-searching)
    HEURISTIC_COOLDOWN_S = 600.0

    def heuristic_site(self, host: str) -> bool:
        """Stack a shallow heuristic crawl of `host` in the background
        (fire-and-forget; robots.txt fetch must not stall the search
        request that triggered it). Per-host cooldown stops underfilled
        repeat queries from re-firing."""
        now = time.time()
        last = self._heuristic_fired.get(host, 0.0)
        if now - last < self.HEURISTIC_COOLDOWN_S:
            return False
        self._heuristic_fired[host] = now

        def _fire():
            try:
                self.start_crawl(f"http://{host}/", depth=1,
                                 name=f"heuristic:{host}")
            except ValueError:
                pass
        threading.Thread(target=_fire, name=f"heuristic-{host}",
                         daemon=True).start()
        return True

    # -- surrogate import (Switchboard.java:1153-1174 busy thread) -----------

    @property
    def surrogates_in(self) -> str | None:
        if not self.data_dir:
            return None
        p = os.path.join(self.data_dir, "SURROGATES", "in")
        os.makedirs(p, exist_ok=True)
        return p

    def surrogate_process_job(self) -> bool:
        """Import one pending surrogate file (WARC or MediaWiki dump) from
        DATA/SURROGATES/in, then move it to ../out. Returns True if a file
        was processed (BusyThread contract)."""
        indir = self.surrogates_in
        if indir is None:
            return False
        candidates = sorted(
            f for f in os.listdir(indir)
            if f.endswith((".warc", ".warc.gz", ".xml", ".xml.bz2",
                           ".xml.gz")))
        if not candidates:
            return False
        from .document.importer import MediawikiImporter, WarcImporter
        name = candidates[0]
        path = os.path.join(indir, name)
        sink = lambda doc: (self.index.store_document(doc),
                            setattr(self, "indexed_count",
                                    self.indexed_count + 1))
        try:
            if ".warc" in name:
                WarcImporter(sink).import_file(path)
            else:
                MediawikiImporter(sink).import_file(path)
        finally:
            outdir = os.path.join(self.data_dir, "SURROGATES", "out")
            os.makedirs(outdir, exist_ok=True)
            os.replace(path, os.path.join(outdir, name))
        return True

    # -- busy threads (deployThread parity) ---------------------------------

    def deploy_threads(self) -> None:
        self.threads.deploy(BusyThread(
            "50_localcrawl",
            lambda: self.crawl_queues.core_crawl_job(StackType.LOCAL),
            idle_sleep_s=1.0, busy_sleep_s=0.05))
        self.threads.deploy(BusyThread(
            "30_cleanup", self._cleanup_job,
            idle_sleep_s=30.0, busy_sleep_s=30.0))
        self.threads.deploy(BusyThread(
            "70_surrogates", self.surrogate_process_job,
            idle_sleep_s=10.0, busy_sleep_s=0.1))
        self.threads.deploy(BusyThread(
            "20_scheduler", self.scheduler_job,
            idle_sleep_s=60.0, busy_sleep_s=10.0))
        if self.config.get_bool("health.enabled", True):
            tick_s = self.config.get_float("health.tickS", 5.0)
            self.threads.deploy(BusyThread(
                # busy pacing while unhealthy: an unhealthy node
                # re-evaluates (and recovers its rules) at twice the
                # healthy cadence
                "15_health", self.health.tick_job,
                idle_sleep_s=tick_s, busy_sleep_s=max(1.0, tick_s / 2)))
        self.threads.deploy(BusyThread(
            "25_contentcontrol", self._content_control_job,
            idle_sleep_s=30.0, busy_sleep_s=5.0))

        if self.config.get_bool("recrawl.enabled", False):
            from .crawler.recrawl import RecrawlJob
            stale_days = self.config.get_int("recrawl.staleAgeDays", 30)
            prof = CrawlProfile(
                "recrawl", recrawl_if_older_s=stale_days * 86400,
                store_ht_cache=False)
            self.add_profile(prof)
            self._recrawl = RecrawlJob(self.index, self.crawl_stacker,
                                       prof.handle,
                                       stale_age_days=stale_days)
            self.threads.deploy(BusyThread(
                "60_recrawl", self._recrawl.job,
                idle_sleep_s=120.0, busy_sleep_s=5.0))

    def _content_control_job(self) -> bool:
        changed = self.content_control.update_filter_job()
        if changed:
            # cached events were computed under the old filter set
            self.search_cache.clear()
        return changed

    def scheduler_job(self) -> bool:
        """Re-execute due recorded API calls via self-HTTP
        (Switchboard.schedulerJob, Switchboard.java:1131-1151)."""
        if self.api_executor is None:
            return False
        return self.work_tables.scheduler_job(self.api_executor)

    def _cleanup_job(self) -> bool:
        self.search_cache.cleanup_locked()
        # a device-join fallback flagged a multi-span hot term: merge the
        # runs so conjunctions return to the device path (VERDICT r2 weak
        # #2 — "schedule run merges so hot terms stay single-span").
        # Single-span needs a FULL merge (max_runs=1), which rewrites the
        # whole run set — so it is rate-limited and deferred while a
        # flush is pending (steady ingestion must not thrash compaction).
        ds = self.index.devstore
        if ds is not None and getattr(ds, "merge_wanted", False) \
                and not self.index.rwi.needs_flush():
            now = time.monotonic()
            last = getattr(self, "_last_join_merge", 0.0)
            if now - last >= self.config.get_int(
                    "index.joinMergeIntervalS", 600):
                self._last_join_merge = now
                ds.merge_wanted = False
                try:
                    # routed through the merge scheduler (ISSUE 13c):
                    # while the serving SLO burns the compaction is
                    # DEFERRED (counted) and the catch-up runs it when
                    # the merge_scheduler actuator sees recovery
                    self.ingest_scheduler.request_merge(max_runs=1)
                except Exception:
                    import logging
                    logging.getLogger("switchboard.jobs").warning(
                        "background RWI run merge failed", exc_info=True)
                return True
        return False

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.threads.terminate_all()
        self.crawl_queues.close()
        self.flush_pipeline()
        for p in (self._parse_proc, self._condense_proc,
                  self._structure_proc, self._store_proc):
            p.shutdown()
        self.noticed.close()
        self.web_structure.close()
        self.access_tracker.dump()
        self.index.close()
