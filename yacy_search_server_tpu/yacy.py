"""Entry point + lifecycle: startup, lock file, CLI verbs, shutdown.

Capability equivalent of the reference's launcher (reference:
source/net/yacy/yacy.java — main:699, startup:149-408 creating the DATA
dir, the `yacy.running` lock file with PID:197-207, the Switchboard:210,
migration:285, the HTTP server:298-301, a JVM shutdown hook:380 and
sb.waitForShutdown:393; CLI verbs -start/-shutdown/-version:503-509,
where -shutdown POSTs to the running instance's Steering servlet).

Usage:
    python -m yacy_search_server_tpu.yacy [-start] [--data DIR] [--port N]
    python -m yacy_search_server_tpu.yacy -shutdown [--port N]
    python -m yacy_search_server_tpu.yacy -version
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time

VERSION = "0.4.0"
REVISION = 0        # build counter within a version (release comparison)

DEFAULT_PORT = 8090


# -- lock file (yacy.running semantics) ---------------------------------------

def acquire_lock(data_dir: str) -> str:
    """Create DATA/yacy.running with our PID; detect unclean shutdown
    (yacy.java:197-207 write, :672 stale-lock detection)."""
    os.makedirs(data_dir, exist_ok=True)
    lock = os.path.join(data_dir, "yacy.running")
    if os.path.exists(lock):
        try:
            old_pid = int(open(lock, encoding="ascii").read().strip() or 0)
        except (OSError, ValueError):
            old_pid = 0
        if old_pid and _pid_alive(old_pid):
            raise RuntimeError(
                f"another instance (pid {old_pid}) holds {lock}")
        print(f"warning: stale lock {lock} (unclean shutdown?), removing",
              file=sys.stderr)
        os.remove(lock)
    with open(lock, "w", encoding="ascii") as f:
        f.write(str(os.getpid()))
    return lock


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def release_lock(lock: str) -> None:
    try:
        os.remove(lock)
    except OSError:
        pass


# -- startup ------------------------------------------------------------------

def startup(data_dir: str, port: int = DEFAULT_PORT, host: str = "127.0.0.1",
            peer_name: str | None = None, p2p: bool = True):
    """Build the full node: config, migration, switchboard/peer stack,
    HTTP server, busy threads. Returns (node_or_sb, http_server, lock)."""
    from .migration import migrate
    from .utils.config import Config

    lock = acquire_lock(data_dir)
    # async bounded logging first: everything after this logs through
    # the single-writer queue (ConcurrentLog shape, yacy.java:176-188)
    from .utils.logging import setup as setup_logging
    setup_logging(data_dir)
    settings = os.path.join(data_dir, "SETTINGS", "yacy.conf")
    config = Config(settings_path=settings)
    migrate(config, VERSION)

    port = config.get_int("port", port)
    peer_name = peer_name or config.get("peerName", f"peer-{os.getpid()}")

    def _upnp_map(sb_like) -> None:
        # best-effort router port mapping on startup (reference:
        # UPnP.addPortMappings on startup/port change, utils/upnp/
        # UPnP.java) — real SSDP/SOAP, config-gated, never fatal
        if not config.get_bool("upnp.enabled", False):
            return
        try:
            from .peers.operation import UPnP
            from .peers.upnp import SSDPDriver
            upnp = UPnP(driver=SSDPDriver())
            if upnp.add_port_mapping(port):
                sb_like.upnp = upnp
        except Exception:
            import logging
            logging.getLogger("yacy.upnp").debug(
                "UPnP port mapping unavailable", exc_info=True)

    if p2p:
        from .peers.node import P2PNode
        from .peers.transport import HttpTransport
        node = P2PNode(peer_name, HttpTransport(), data_dir=data_dir,
                       port=port)
        node.sb.config = config
        http = node.serve_http(host=host, port=port)
        node.deploy_threads()
        _upnp_map(node.sb)
        return node, http, lock
    from .server.httpd import YaCyHttpServer
    from .switchboard import Switchboard
    sb = Switchboard(data_dir=data_dir, config=config)
    http = YaCyHttpServer(sb, port=port, host=host).start()
    sb.deploy_threads()
    _upnp_map(sb)
    return sb, http, lock


def wait_for_shutdown(sb) -> None:
    """Block until the shutdown event fires (signal or Steering servlet);
    the reference's sb.waitForShutdown."""
    ev = sb.shutdown_event

    def _sig(signum, frame):
        ev.set()

    for s in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(s, _sig)
        except ValueError:
            pass    # not the main thread (tests)
    while not ev.is_set():
        ev.wait(1.0)


# -- CLI verbs ----------------------------------------------------------------

def shutdown_running(port: int = DEFAULT_PORT,
                     host: str = "127.0.0.1") -> bool:
    """Ask a running instance to stop (yacy.java:503-509 POSTs to the
    Steering servlet)."""
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/Steering_p.json?shutdown=1",
                timeout=10) as r:
            return r.status == 200
    except OSError:
        return False


def peel_verb(argv: list[str]) -> tuple[str, list[str]]:
    """The reference's verbs are dash-prefixed (-start/-gui/-shutdown/
    -version), which argparse would read as options — peel first."""
    if argv and argv[0].lstrip("-") in ("start", "gui", "shutdown",
                                        "version"):
        return "-" + argv[0].lstrip("-"), argv[1:]
    return "-start", argv


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    verb, argv = peel_verb(argv)
    ap = argparse.ArgumentParser(prog="yacy-tpu", add_help=True)
    ap.add_argument("--data", default="DATA")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--name", default=None, help="peer name")
    ap.add_argument("--no-p2p", action="store_true")
    args = ap.parse_args(argv)
    args.verb = verb

    if args.verb == "-version":
        print(VERSION)
        return 0
    if args.verb == "-shutdown":
        ok = shutdown_running(args.port, args.host)
        print("shutdown requested" if ok else "no running instance found")
        return 0 if ok else 1

    node, http, lock = startup(args.data, port=args.port, host=args.host,
                               peer_name=args.name, p2p=not args.no_p2p)
    sb = getattr(node, "sb", node)
    print(f"serving on {http.base_url} (data: {args.data})")
    try:
        if args.verb == "-gui":
            # reference -gui: tray + browser popup beside the server
            # (gui/Tray.java); headless boxes degrade to the popup only
            from .gui import run_gui
            seed = getattr(node, "seed", None)
            run_gui(http.base_url, sb.shutdown_event,
                    peer_name=getattr(seed, "name", ""))
        wait_for_shutdown(sb)
    finally:
        print("shutting down ...")
        upnp = getattr(sb, "upnp", None)
        if upnp is not None:          # release router mappings (UPnP.java)
            try:
                upnp.delete_port_mappings()
            except Exception:
                import logging
                logging.getLogger("yacy.upnp").debug(
                    "UPnP unmap failed at shutdown", exc_info=True)
        node.close()
        http.close()
        release_lock(lock)
        from .utils.logging import shutdown as logging_shutdown
        logging_shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
