"""UPnP IGD port mapping — real SSDP discovery + SOAP control.

The reference maps its ports on the router via the weupnp library
(reference: source/net/yacy/utils/upnp/UPnP.java — discovery of an
InternetGatewayDevice and AddPortMapping/DeletePortMapping on startup
and port change). This is the same protocol implemented directly:

1. **SSDP discovery**: UDP M-SEARCH to 239.255.255.250:1900 for
   ``urn:schemas-upnp-org:device:InternetGatewayDevice:1``; responses
   carry a LOCATION header pointing at the device description.
2. **Device description**: fetch the XML, walk its service list for a
   WANIPConnection/WANPPPConnection service and take its controlURL.
3. **SOAP control**: POST AddPortMapping / DeletePortMapping /
   GetExternalIPAddress envelopes to the controlURL.

Both IO edges are injectable (`socket_factory`, `http`) so the protocol
logic is testable against a simulated gateway in this zero-egress image;
the defaults do real network IO when deployed.
"""

from __future__ import annotations

import re
import socket as _socketlib
from urllib.parse import urljoin, urlsplit

SSDP_ADDR = "239.255.255.250"
SSDP_PORT = 1900
IGD_SEARCH_TARGETS = (
    "urn:schemas-upnp-org:device:InternetGatewayDevice:1",
    "urn:schemas-upnp-org:service:WANIPConnection:1",
)
WAN_SERVICE_TYPES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)

_LOCATION_RE = re.compile(r"^location:\s*(\S+)\s*$",
                          re.IGNORECASE | re.MULTILINE)


class Gateway:
    """One discovered IGD: where to send SOAP control requests."""

    __slots__ = ("location", "control_url", "service_type")

    def __init__(self, location: str, control_url: str, service_type: str):
        self.location = location
        self.control_url = control_url
        self.service_type = service_type


def _default_http(url: str, data: bytes | None = None,
                  headers: dict | None = None, timeout: float = 5.0) -> bytes:
    import urllib.request
    req = urllib.request.Request(url, data=data, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:  # nosec - LAN
        return r.read()


class SSDPDriver:
    """The UPnP.java/weupnp flow as a driver for peers.operation.UPnP.

    `socket_factory()` must return a UDP socket object supporting
    sendto/recvfrom/settimeout/close; `http(url, data, headers)` returns
    response bytes. Tests inject both; production uses the defaults."""

    def __init__(self, socket_factory=None, http=None,
                 timeout_s: float = 3.0):
        self._socket_factory = socket_factory or self._real_socket
        self.http = http or _default_http
        self.timeout_s = timeout_s
        self._gateway: Gateway | None = None

    @staticmethod
    def _real_socket():
        s = _socketlib.socket(_socketlib.AF_INET, _socketlib.SOCK_DGRAM)
        s.setsockopt(_socketlib.IPPROTO_IP, _socketlib.IP_MULTICAST_TTL, 2)
        return s

    # -- step 1: SSDP M-SEARCH ----------------------------------------------

    def _msearch(self) -> list[str]:
        """Collect LOCATION urls from M-SEARCH responses."""
        locations: list[str] = []
        sock = self._socket_factory()
        try:
            sock.settimeout(self.timeout_s)
            for st in IGD_SEARCH_TARGETS:
                msg = ("M-SEARCH * HTTP/1.1\r\n"
                       f"HOST: {SSDP_ADDR}:{SSDP_PORT}\r\n"
                       'MAN: "ssdp:discover"\r\n'
                       "MX: 2\r\n"
                       f"ST: {st}\r\n\r\n").encode("ascii")
                sock.sendto(msg, (SSDP_ADDR, SSDP_PORT))
            while True:
                try:
                    data, _addr = sock.recvfrom(2048)
                except (TimeoutError, OSError):
                    break
                m = _LOCATION_RE.search(data.decode("utf-8", "replace"))
                if m and m.group(1) not in locations:
                    locations.append(m.group(1))
        finally:
            try:
                sock.close()
            except OSError:
                pass
        return locations

    # -- step 2: device description -----------------------------------------

    def _parse_description(self, location: str) -> Gateway | None:
        try:
            xml = self.http(location).decode("utf-8", "replace")
        except Exception:
            return None
        # walk <service> blocks for a WAN*Connection control URL
        for svc in re.finditer(r"<service>(.*?)</service>", xml, re.S):
            block = svc.group(1)
            st = _tag(block, "serviceType")
            if st not in WAN_SERVICE_TYPES:
                continue
            control = _tag(block, "controlURL")
            if not control:
                continue
            base = _tag(xml, "URLBase") or location
            return Gateway(location, urljoin(base, control), st)
        return None

    # -- driver protocol (peers.operation.UPnP) ------------------------------

    def discover(self) -> Gateway | None:
        if self._gateway is not None:
            return self._gateway
        for location in self._msearch():
            gw = self._parse_description(location)
            if gw is not None:
                self._gateway = gw
                return gw
        return None

    def _soap(self, gw: Gateway, action: str, args: dict[str, str]) -> str:
        arg_xml = "".join(f"<{k}>{v}</{k}>" for k, v in args.items())
        envelope = (
            '<?xml version="1.0"?>'
            '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/"'
            ' s:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
            f'<s:Body><u:{action} xmlns:u="{gw.service_type}">{arg_xml}'
            f"</u:{action}></s:Body></s:Envelope>").encode("utf-8")
        headers = {
            "Content-Type": 'text/xml; charset="utf-8"',
            "SOAPAction": f'"{gw.service_type}#{action}"',
        }
        return self.http(gw.control_url, envelope,
                         headers).decode("utf-8", "replace")

    def _local_ip(self, gw: Gateway) -> str:
        """The LAN address the router should forward to: the local end
        of a UDP 'connection' toward the gateway."""
        host = urlsplit(gw.location).hostname or "192.168.0.1"
        s = _socketlib.socket(_socketlib.AF_INET, _socketlib.SOCK_DGRAM)
        try:
            s.connect((host, 1900))
            return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"
        finally:
            s.close()

    def add_port_mapping(self, gw: Gateway, port: int, proto: str,
                         desc: str) -> bool:
        try:
            resp = self._soap(gw, "AddPortMapping", {
                "NewRemoteHost": "",
                "NewExternalPort": str(port),
                "NewProtocol": proto,
                "NewInternalPort": str(port),
                "NewInternalClient": self._local_ip(gw),
                "NewEnabled": "1",
                "NewPortMappingDescription": desc,
                "NewLeaseDuration": "0",
            })
        except Exception:
            return False
        return "AddPortMappingResponse" in resp and "Fault" not in resp

    def delete_port_mapping(self, gw: Gateway, port: int,
                            proto: str) -> bool:
        try:
            resp = self._soap(gw, "DeletePortMapping", {
                "NewRemoteHost": "",
                "NewExternalPort": str(port),
                "NewProtocol": proto,
            })
        except Exception:
            return False
        return "DeletePortMappingResponse" in resp and "Fault" not in resp

    def external_ip(self, gw: Gateway) -> str | None:
        try:
            resp = self._soap(gw, "GetExternalIPAddress", {})
        except Exception:
            return None
        return _tag(resp, "NewExternalIPAddress") or None


def _tag(xml: str, name: str) -> str:
    m = re.search(rf"<{name}>\s*(.*?)\s*</{name}>", xml, re.S)
    return m.group(1) if m else ""
