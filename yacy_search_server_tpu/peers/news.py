"""News gossip — the network's event channel, piggybacked on peer pings.

Capability equivalent of the reference's news system (reference:
source/net/yacy/peers/NewsDB.java — persistent news records with id =
originator+created+category, attribute maps, distribution counters — and
NewsPool.java:598 — incoming/processed/outgoing/published queues with
per-category expiry, fed and drained by the hello exchange). Categories
carry decentralized announcements: crawl starts, profile updates,
bookmark/wiki/blog changes, votes. Peers relay a bounded sample of fresh
records with every hello, so news floods the network without any broker.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

# category names follow the reference (NewsPool.java constants)
CAT_CRAWL_START = "crwlstrt"
CAT_CRAWL_STOP = "crwlstop"
CAT_PROFILE_UPDATE = "prfleupd"
CAT_BOOKMARK_ADD = "bkmrkadd"
CAT_WIKI_UPDATE = "wiki_upd"
CAT_BLOG_ADD = "blog_add"
CAT_VOTE_ADD = "stippadd"

MAX_NEWS_PER_HELLO = 8          # gossip batch bound per exchange
MAX_INCOMING = 1000             # pool bound (NewsPool maxsize semantics)
NEWS_TTL_S = 3 * 24 * 3600.0    # records expire (per-category in reference)
MAX_RELAY_SENDS = 32            # stop re-gossiping a record after N sends


class NewsRecord:
    """One announcement: identity is (originator, created, category)."""

    def __init__(self, category: str, originator: str, attributes: dict,
                 created: float | None = None, record_id: str | None = None):
        self.category = category
        self.originator = originator          # peer hash (ascii)
        self.created = created if created is not None else time.time()
        self.attributes = dict(attributes)
        self.id = record_id or self._make_id()
        self.distributed = 0                  # times gossiped onward by us

    def _make_id(self) -> str:
        key = f"{self.originator}|{self.created:.3f}|{self.category}"
        return hashlib.md5(key.encode("utf-8")).hexdigest()[:24]  # nosec

    def age_s(self) -> float:
        return time.time() - self.created

    def to_dict(self) -> dict:
        return {"id": self.id, "cat": self.category, "orig": self.originator,
                "created": self.created, "attr": self.attributes}

    @staticmethod
    def from_dict(d: dict) -> "NewsRecord":
        return NewsRecord(d["cat"], d["orig"], d.get("attr", {}),
                          created=float(d["created"]), record_id=d["id"])


class NewsPool:
    """Incoming/processed news queues + my own outgoing records."""

    def __init__(self, data_dir: str | None = None):
        self._incoming: dict[str, NewsRecord] = {}
        self._processed: set[str] = set()
        self._processed_order: list[str] = []   # FIFO eviction order
        self._mine: dict[str, NewsRecord] = {}
        self._lock = threading.Lock()
        self._path = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._path = os.path.join(data_dir, "news.jsonl")
            self._load()

    # -- publish (my own announcements) --------------------------------------

    def publish(self, category: str, originator: str,
                attributes: dict) -> NewsRecord:
        rec = NewsRecord(category, originator, attributes)
        with self._lock:
            self._mine[rec.id] = rec
            self._append(rec, "mine")
        return rec

    # -- gossip exchange ------------------------------------------------------

    def outgoing_batch(self, n: int = MAX_NEWS_PER_HELLO) -> list[dict]:
        """Fresh records to attach to a hello: my own first, then relayed
        incoming ones that have not been re-sent too often."""
        with self._lock:
            self._expire_locked()
            out: list[NewsRecord] = []
            mine = sorted(self._mine.values(), key=lambda r: -r.created)
            out.extend(r for r in mine if r.distributed < MAX_RELAY_SENDS)
            relay = sorted((r for r in self._incoming.values()
                            if r.distributed < MAX_RELAY_SENDS),
                           key=lambda r: -r.created)
            out.extend(relay)
            out = out[:n]
            for r in out:
                r.distributed += 1
            return [r.to_dict() for r in out]

    def ingest_batch(self, records: list[dict], my_hash: str) -> int:
        """Merge gossip received with a hello; my own records bounce off."""
        added = 0
        with self._lock:
            for d in records:
                try:
                    rec = NewsRecord.from_dict(d)
                except (KeyError, TypeError, ValueError):
                    continue
                if rec.originator == my_hash or rec.id in self._processed \
                        or rec.id in self._incoming or rec.id in self._mine:
                    continue
                if rec.age_s() > NEWS_TTL_S:
                    continue
                if len(self._incoming) >= MAX_INCOMING:
                    oldest = min(self._incoming.values(),
                                 key=lambda r: r.created)
                    del self._incoming[oldest.id]
                self._incoming[rec.id] = rec
                self._append(rec, "in")
                added += 1
        return added

    # -- consumption ----------------------------------------------------------

    def incoming(self, category: str | None = None) -> list[NewsRecord]:
        with self._lock:
            recs = [r for r in self._incoming.values()
                    if category is None or r.category == category]
            return sorted(recs, key=lambda r: -r.created)

    MAX_PROCESSED_IDS = 4096   # TTL bounds replays; ids older than that
                               # can be forgotten safely

    def mark_processed(self, record_id: str) -> None:
        with self._lock:
            if self._incoming.pop(record_id, None) is not None:
                self._remember_processed_locked(record_id)
                if self._path:
                    try:
                        with open(self._path, "a", encoding="utf-8") as f:
                            f.write(json.dumps({"k": "proc",
                                                "id": record_id}) + "\n")
                    except OSError:
                        pass

    def _remember_processed_locked(self, record_id: str) -> None:
        if record_id in self._processed:
            return
        self._processed.add(record_id)
        self._processed_order.append(record_id)
        # FIFO eviction: forget the OLDEST ids, never the one just added —
        # a still-circulating record must stay deduplicated until its TTL
        while len(self._processed_order) > self.MAX_PROCESSED_IDS:
            self._processed.discard(self._processed_order.pop(0))

    def size(self) -> tuple[int, int, int]:
        with self._lock:
            return len(self._incoming), len(self._processed), len(self._mine)

    # -- internals ------------------------------------------------------------

    def _expire_locked(self) -> None:
        for pool in (self._incoming, self._mine):
            dead = [rid for rid, r in pool.items() if r.age_s() > NEWS_TTL_S]
            for rid in dead:
                del pool[rid]

    def _append(self, rec: NewsRecord, kind: str) -> None:
        if not self._path:
            return
        try:
            with open(self._path, "a", encoding="utf-8") as f:
                f.write(json.dumps({"k": kind, **rec.to_dict()}) + "\n")
        except OSError:
            pass

    # lint: unlocked-ok(construction-time: only __init__ calls this,
    # before the pool is shared with any other thread)
    def _load(self) -> None:
        if not self._path or not os.path.exists(self._path):
            return
        try:
            with open(self._path, "r", encoding="utf-8") as f:
                for line in f:
                    try:
                        d = json.loads(line)
                    except ValueError:
                        continue
                    if d.get("k") == "proc":
                        rid = d.get("id", "")
                        self._remember_processed_locked(rid)
                        self._incoming.pop(rid, None)
                        continue
                    try:
                        rec = NewsRecord.from_dict(d)
                    except (KeyError, ValueError):
                        continue
                    if rec.age_s() > NEWS_TTL_S or rec.id in self._processed:
                        continue
                    pool = self._mine if d.get("k") == "mine" else self._incoming
                    pool[rec.id] = rec
        except OSError:
            pass
        self._compact()

    # lint: unlocked-ok(construction-time: only _load calls this,
    # still inside __init__ before the pool is shared)
    def _compact(self) -> None:
        """Rewrite the append-only journal with only live state — expired,
        superseded and processed-and-forgotten lines drop out, bounding the
        file across restarts."""
        if not self._path:
            return
        tmp = self._path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in self._mine.values():
                    f.write(json.dumps({"k": "mine", **rec.to_dict()}) + "\n")
                for rec in self._incoming.values():
                    f.write(json.dumps({"k": "in", **rec.to_dict()}) + "\n")
                for rid in self._processed:
                    f.write(json.dumps({"k": "proc", "id": rid}) + "\n")
            os.replace(tmp, self._path)
        except OSError:
            pass
