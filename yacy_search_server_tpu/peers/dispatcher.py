"""DHT index distribution — continuous re-sharding of the RWI to the net.

Capability equivalent of the reference's send pipeline (reference:
source/net/yacy/peers/Dispatcher.java:53-381 —
selectContainersEnqueueToBuffer:296 pulls containers OUT of the local
index (ownership moves), splitContainer:234 splits each container by the
vertical partition of each posting's URL hash, dequeueContainer:339 forms
per-target Transmission.Chunks — and Transmission.java:77-276 with
re-enqueue on failure).

TPU-first difference: splitContainer is one bulk numpy projection over
the whole container (Distribution.vertical_partitions_bulk) instead of a
per-entry loop.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..index.metadata import DOUBLE_FIELDS, INT_FIELDS, TEXT_FIELDS
from ..index.postings import PostingsList
from ..parallel.distribution import Distribution
from ..utils import histogram
from .dht import select_distribution_targets
from .protocol import Protocol
from .seed import Seed, SeedDB

# metadata columns shipped with transferURL — the URIMetadata surface,
# deliberately excluding the full text body (the reference ships metadata
# rows, not documents; snippets are re-fetched from the source URL)
TRANSFER_TEXT_FIELDS = tuple(f for f in TEXT_FIELDS if f != "text_t")


def merge_cells(a: tuple[PostingsList, list[bytes]],
                b: tuple[PostingsList, list[bytes]]
                ) -> tuple[PostingsList, list[bytes]]:
    """Concatenate two (postings, urlhashes) cells (single definition of
    the merge invariant — buffer and per-target chunks both use it)."""
    ap, au = a
    bp, bu = b
    return (PostingsList(np.concatenate([ap.docids, bp.docids]),
                         np.concatenate([ap.feats, bp.feats])),
            au + bu)


class Transmission:
    """One per-target batch: containers + referenced URL metadata
    (Transmission.Chunk equivalent)."""

    def __init__(self, target: Seed,
                 containers: dict[bytes, tuple[PostingsList, list[bytes]]],
                 metadata_rows: dict[bytes, dict]):
        self.target = target
        self.containers = containers
        self.metadata_rows = metadata_rows

    def posting_count(self) -> int:
        return sum(len(p) for p, _ in self.containers.values())

    def transmit(self, protocol: Protocol) -> tuple[bool, float]:
        """-> (ok, pause_s): the receiver's backpressure hint
        (transferRWI 'pause' reply field)."""
        t0 = time.perf_counter()
        ok, reply = protocol.transfer_index(
            self.target, self.containers, self.metadata_rows)
        wall_ms = (time.perf_counter() - t0) * 1000.0
        # DHT transfer wall -> windowed histogram (ISSUE 4): transfers
        # run on node background loops, so this site records directly
        # rather than through the span bridge
        histogram.observe("dht.transfer", wall_ms)
        # fleet digests piggyback on the transferRWI chunks inside
        # transfer_index (Protocol._call); the observed wall feeds the
        # per-peer RTT column of the fleet table (ISSUE 5)
        if ok and getattr(protocol, "fleet", None) is not None:
            protocol.fleet.note_rtt(self.target.hash, wall_ms)
        try:
            pause = float(reply.get("pause", 0) or 0)
        except (TypeError, ValueError):
            pause = 0.0
        return ok, pause


class Dispatcher:
    """Buffer of (termhash, partition) -> postings awaiting transmission."""

    def __init__(self, segment, seeddb: SeedDB, dist: Distribution,
                 protocol: Protocol, redundancy: int = 3):
        self.segment = segment
        self.seeddb = seeddb
        self.dist = dist
        self.protocol = protocol
        self.redundancy = redundancy
        # (termhash, partition) -> (PostingsList, urlhashes)
        self._buffer: dict[tuple[bytes, int],
                           tuple[PostingsList, list[bytes]]] = {}
        # per-target backpressure: peer hash -> resume timestamp (the
        # receiver's 'pause' hints, honored like the reference's sender)
        self._paused_until: dict[bytes, float] = {}
        self._lock = threading.Lock()
        self.transferred_postings = 0
        self.failed_transmissions = 0

    # -- select & split (ownership moves out of the index) -------------------

    def select_containers_to_buffer(self, start_pos: int, limit_pos: int,
                                    max_containers: int = 32,
                                    max_refs: int = 2000) -> int:
        """Pull containers in a ring segment out of the local RWI
        (delete-on-select: Dispatcher.java:296), split them by vertical
        partition, and buffer the pieces. Returns postings buffered."""
        terms = self.segment.rwi.terms_in_ring_segment(start_pos, limit_pos)
        total = 0
        meta = self.segment.metadata
        for th in terms[:max_containers]:
            if total >= max_refs:
                break
            plist = self.segment.rwi.remove_term(th)
            if len(plist) == 0:
                continue
            uhs = [meta.urlhash_of(int(d)) for d in plist.docids]
            self._buffer_split(th, plist, uhs)
            total += len(plist)
        return total

    def _buffer_split(self, th: bytes, plist: PostingsList,
                      uhs: list[bytes]) -> None:
        """Split a container by each posting's vertical partition and merge
        the pieces into the buffer (splitContainer:234, one bulk numpy
        projection). The single entry point for buffering — failure
        re-enqueues go through the same split so every cell holds only
        postings of ITS partition (the DHT placement invariant)."""
        uh_arr = np.frombuffer(b"".join(uhs),
                               dtype=np.uint8).reshape(len(uhs), 12)
        parts = self.dist.vertical_partitions_bulk(uh_arr)
        with self._lock:
            for part in np.unique(parts):
                sel = parts == int(part)
                piece = PostingsList(plist.docids[sel], plist.feats[sel])
                piece_uhs = [u for u, m in zip(uhs, sel) if m]
                self._merge_into_buffer((th, int(part)), piece, piece_uhs)

    def _merge_into_buffer(self, key, piece: PostingsList,
                           uhs: list[bytes]) -> None:
        old = self._buffer.get(key)
        if old is None:
            self._buffer[key] = (piece, uhs)
        else:
            self._buffer[key] = merge_cells(old, (piece, uhs))

    def buffer_size(self) -> int:
        with self._lock:
            return len(self._buffer)

    # -- dequeue & transmit --------------------------------------------------

    def _metadata_row(self, uh: bytes) -> dict:
        docid = self.segment.metadata.docid(uh)
        if docid is None:
            return {}
        m = self.segment.metadata.get(docid)
        if m is None:
            return {}
        row = {}
        for f in TRANSFER_TEXT_FIELDS:
            v = m.get(f, "")
            if v:
                row[f] = v
        for f in INT_FIELDS + DOUBLE_FIELDS:
            v = m.get(f, 0)
            if v:
                row[f] = v
        return row

    def dequeue_transmissions(self, max_chunks: int = 8) -> list[Transmission]:
        """Form per-target chunks for up to max_chunks buffered cells
        (dequeueContainer:339): each (term, partition) cell goes to its
        `redundancy` DHT owners."""
        with self._lock:
            keys = list(self._buffer.keys())[:max_chunks]
            cells = [(k, self._buffer.pop(k)) for k in keys]
        per_target: dict[bytes, Transmission] = {}
        unsendable = []
        now = time.time()
        with self._lock:
            self._paused_until = {h: t for h, t in
                                  self._paused_until.items() if t > now}
            paused = set(self._paused_until)
        for (th, part), (plist, uhs) in cells:
            owners = select_distribution_targets(
                self.seeddb, self.dist, th, part, self.redundancy)
            # honor receiver backpressure: paused owners get their replica
            # later — the cell is RE-BUFFERED whenever any owner is
            # skipped, so redundancy is never silently degraded (re-sent
            # postings dedup by docid on the receive side)
            targets = [t for t in owners if t.hash not in paused]
            if not targets or len(targets) < len(owners):
                unsendable.append(((th, part), (plist, uhs)))
            if not targets:
                continue
            rows = {uh: self._metadata_row(uh) for uh in set(uhs)}
            for t in targets:
                tx = per_target.get(t.hash)
                if tx is None:
                    tx = per_target[t.hash] = Transmission(t, {}, {})
                # replicas ship the same container to multiple targets; a
                # target owning several partitions of one term gets the
                # pieces MERGED (keying by term alone must not drop any)
                old = tx.containers.get(th)
                tx.containers[th] = (plist, uhs) if old is None \
                    else merge_cells(old, (plist, uhs))
                tx.metadata_rows.update(rows)
        if unsendable:
            with self._lock:
                for key, (plist, uhs) in unsendable:
                    self._merge_into_buffer(key, plist, uhs)
        return list(per_target.values())

    def transmit_all(self, transmissions: list[Transmission]) -> int:
        """Send chunks; failed chunks re-enqueue their containers
        (Transmission.java failure path). Returns postings delivered."""
        sent = 0
        for tx in transmissions:
            ok, pause_s = tx.transmit(self.protocol)
            if pause_s > 0:
                with self._lock:
                    self._paused_until[tx.target.hash] = \
                        time.time() + pause_s
            if ok:
                sent += tx.posting_count()
            else:
                self.failed_transmissions += 1
                for th, (plist, uhs) in tx.containers.items():
                    # a per-target container may span several vertical
                    # partitions: re-split so each piece re-enters the
                    # buffer under its own (term, partition) cell
                    self._buffer_split(th, plist, uhs)
        self.transferred_postings += sent
        return sent

    # -- lifecycle -----------------------------------------------------------

    def restore_buffer_to_index(self) -> int:
        """Shutdown path: postings still buffered go back into the local
        index so ownership is never lost."""
        with self._lock:
            cells = list(self._buffer.items())
            self._buffer.clear()
        n = 0
        for (th, _part), (plist, _uhs) in cells:
            self.segment.rwi.add_many(th, plist)
            n += len(plist)
        return n
