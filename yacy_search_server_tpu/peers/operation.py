"""Node operation helpers: NAT port mapping + release update discovery.

Capability equivalents of the reference's operational plumbing
(reference: source/net/yacy/utils/upnp/UPnP.java — router port mapping
via weupnp on startup/port change; peers/operation/yacyRelease.java —
signed release discovery from configured update locations with an
auto-update policy, and yacyUpdateLocation.java). Both are gated
best-effort subsystems here: UPnP uses an injectable SSDP/SOAP driver
(this image has zero egress, so the default driver reports unavailable
without network IO), and release discovery parses a release table from
an update location via an injectable fetcher.
"""

from __future__ import annotations

import re

from .. import yacy as _launcher


class UPnP:
    """Router port mapping, best-effort (UPnP.java semantics)."""

    def __init__(self, driver=None):
        # driver: object with discover() -> gateway|None,
        #         add_port_mapping(gw, port, proto, desc) -> bool,
        #         delete_port_mapping(gw, port, proto) -> bool
        self.driver = driver
        self.mapped_ports: set[int] = set()

    def available(self) -> bool:
        return self.driver is not None and self.driver.discover() is not None

    def add_port_mapping(self, port: int) -> bool:
        if self.driver is None:
            return False
        gw = self.driver.discover()
        if gw is None:
            return False
        ok = self.driver.add_port_mapping(gw, port, "TCP", "yacy-tpu")
        if ok:
            self.mapped_ports.add(port)
        return ok

    def delete_port_mappings(self) -> None:
        if self.driver is None:
            return
        gw = self.driver.discover()
        if gw is None:
            return
        for port in list(self.mapped_ports):
            if self.driver.delete_port_mapping(gw, port, "TCP"):
                self.mapped_ports.discard(port)


_RELEASE_RE = re.compile(
    r"yacy_tpu_v(?P<version>\d+(?:\.\d+)*)[-_](?P<rev>\d+)\.(?:tar\.gz|whl)")


class Release:
    def __init__(self, version: str, rev: int, url: str):
        self.version = version
        self.rev = rev
        self.url = url

    def version_tuple(self) -> tuple[int, ...]:
        return tuple(int(p) for p in self.version.split("."))

    def __repr__(self):
        return f"Release({self.version}-{self.rev})"


class ReleaseManager:
    """Update-location scan + newer-release decision (yacyRelease.java).

    `fetcher(url) -> str|None` supplies the release index page; with no
    fetcher (zero-egress deployments) every check reports 'no update'."""

    def __init__(self, update_locations: list[str] | None = None,
                 fetcher=None):
        self.update_locations = update_locations or []
        self.fetcher = fetcher

    def scan(self) -> list[Release]:
        releases: list[Release] = []
        if self.fetcher is None:
            return releases
        for loc in self.update_locations:
            try:
                page = self.fetcher(loc)
            except Exception:
                continue
            if not page:
                continue
            for m in _RELEASE_RE.finditer(page):
                releases.append(Release(
                    m.group("version"), int(m.group("rev")),
                    loc.rstrip("/") + "/" + m.group(0)))
        releases.sort(key=lambda r: (r.version_tuple(), r.rev))
        return releases

    def newer_than_current(self) -> Release | None:
        cur = (tuple(int(p) for p in _launcher.VERSION.split(".")),
               _launcher.REVISION)
        candidates = [r for r in self.scan()
                      if (r.version_tuple(), r.rev) > cur]
        return candidates[-1] if candidates else None


# -- signed releases ----------------------------------------------------
# The reference verifies releases against the project's public key
# before auto-deploying (yacyRelease.checkFingerprint — SHA1withRSA over
# the tarball, .sig files beside the release). Here the signature scheme
# is Ed25519 (smaller keys, no parameter pitfalls): <release>.sig holds
# the raw 64-byte signature over the release bytes, and the operator
# pins the 32-byte public key (hex) in config `update.publicKey`.


def verify_release(data: bytes, signature: bytes,
                   public_key_hex: str) -> bool:
    """True iff `signature` is a valid Ed25519 signature of `data` under
    the pinned public key. Any malformed input verifies False — an
    update path must fail closed."""
    try:
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives.asymmetric.ed25519 import \
            Ed25519PublicKey
    except ImportError:
        return False
    try:
        key = Ed25519PublicKey.from_public_bytes(
            bytes.fromhex(public_key_hex))
        key.verify(signature, data)
        return True
    except (ValueError, TypeError, InvalidSignature):
        # TypeError: non-bytes input (e.g. a text-mode fetcher) — still
        # fail closed, never propagate out of the update check
        return False


class SignedReleaseDownloader:
    """Fetch + verify + stage a release (yacyRelease download/deploy).

    `fetch_bytes(url) -> bytes` supplies the artifact and its .sig; a
    verified release lands in `stage_dir` for the operator (or a deploy
    hook) to install — the node never self-restarts here, matching the
    'deploy script' half of the reference being an external step."""

    def __init__(self, public_key_hex: str, fetch_bytes,
                 stage_dir: str | None = None):
        self.public_key_hex = public_key_hex
        self.fetch_bytes = fetch_bytes
        self.stage_dir = stage_dir

    def download(self, release: Release) -> str | None:
        """Returns the staged file path, or None when the signature (or
        the fetch) fails. Nothing unverified ever touches the disk
        outside a temp file."""
        import os
        import tempfile
        if not self.public_key_hex:
            return None     # no pinned key: refuse, never trust-on-fetch
        try:
            data = self.fetch_bytes(release.url)
            sig = self.fetch_bytes(release.url + ".sig")
        except Exception:
            return None
        if not isinstance(data, bytes) or not isinstance(sig, bytes):
            return None     # a text-mode fetcher cannot carry a signature
        if not data or not sig or not verify_release(
                data, sig, self.public_key_hex):
            return None
        stage = self.stage_dir or tempfile.mkdtemp(prefix="yacy-release-")
        os.makedirs(stage, exist_ok=True)
        path = os.path.join(stage, release.url.rsplit("/", 1)[-1])
        tmp = path + ".part"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return path
