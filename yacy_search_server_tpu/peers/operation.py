"""Node operation helpers: NAT port mapping + release update discovery.

Capability equivalents of the reference's operational plumbing
(reference: source/net/yacy/utils/upnp/UPnP.java — router port mapping
via weupnp on startup/port change; peers/operation/yacyRelease.java —
signed release discovery from configured update locations with an
auto-update policy, and yacyUpdateLocation.java). Both are gated
best-effort subsystems here: UPnP uses an injectable SSDP/SOAP driver
(this image has zero egress, so the default driver reports unavailable
without network IO), and release discovery parses a release table from
an update location via an injectable fetcher.
"""

from __future__ import annotations

import re

from .. import yacy as _launcher


class UPnP:
    """Router port mapping, best-effort (UPnP.java semantics)."""

    def __init__(self, driver=None):
        # driver: object with discover() -> gateway|None,
        #         add_port_mapping(gw, port, proto, desc) -> bool,
        #         delete_port_mapping(gw, port, proto) -> bool
        self.driver = driver
        self.mapped_ports: set[int] = set()

    def available(self) -> bool:
        return self.driver is not None and self.driver.discover() is not None

    def add_port_mapping(self, port: int) -> bool:
        if self.driver is None:
            return False
        gw = self.driver.discover()
        if gw is None:
            return False
        ok = self.driver.add_port_mapping(gw, port, "TCP", "yacy-tpu")
        if ok:
            self.mapped_ports.add(port)
        return ok

    def delete_port_mappings(self) -> None:
        if self.driver is None:
            return
        gw = self.driver.discover()
        if gw is None:
            return
        for port in list(self.mapped_ports):
            if self.driver.delete_port_mapping(gw, port, "TCP"):
                self.mapped_ports.discard(port)


_RELEASE_RE = re.compile(
    r"yacy_tpu_v(?P<version>\d+(?:\.\d+)*)[-_](?P<rev>\d+)\.(?:tar\.gz|whl)")


class Release:
    def __init__(self, version: str, rev: int, url: str):
        self.version = version
        self.rev = rev
        self.url = url

    def version_tuple(self) -> tuple[int, ...]:
        return tuple(int(p) for p in self.version.split("."))

    def __repr__(self):
        return f"Release({self.version}-{self.rev})"


class ReleaseManager:
    """Update-location scan + newer-release decision (yacyRelease.java).

    `fetcher(url) -> str|None` supplies the release index page; with no
    fetcher (zero-egress deployments) every check reports 'no update'."""

    def __init__(self, update_locations: list[str] | None = None,
                 fetcher=None):
        self.update_locations = update_locations or []
        self.fetcher = fetcher

    def scan(self) -> list[Release]:
        releases: list[Release] = []
        if self.fetcher is None:
            return releases
        for loc in self.update_locations:
            try:
                page = self.fetcher(loc)
            except Exception:
                continue
            if not page:
                continue
            for m in _RELEASE_RE.finditer(page):
                releases.append(Release(
                    m.group("version"), int(m.group("rev")),
                    loc.rstrip("/") + "/" + m.group(0)))
        releases.sort(key=lambda r: (r.version_tuple(), r.rev))
        return releases

    def newer_than_current(self) -> Release | None:
        cur = (tuple(int(p) for p in _launcher.VERSION.split(".")),
               _launcher.REVISION)
        candidates = [r for r in self.scan()
                      if (r.version_tuple(), r.rev) > cur]
        return candidates[-1] if candidates else None
