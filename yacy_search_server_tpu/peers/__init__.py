"""P2P network layer: peer identity, DHT selection, shard transfer,
membership gossip and remote scatter-gather search.

Capability equivalent of the reference's peers/ package (reference:
source/net/yacy/peers/ — Seed.java, SeedDB.java, DHTSelection.java,
Dispatcher.java, Transmission.java, Protocol.java, Network.java,
RemoteSearch.java) re-designed around an injectable Transport so the whole
network runs in-process for tests (the multi-peer harness the reference
lacks, SURVEY.md §4) and over HTTP for real WAN federation (server/).
"""

from .seed import Seed, SeedDB, PeerType
from .transport import LoopbackNetwork, PeerUnreachable, Transport

__all__ = ["Seed", "SeedDB", "PeerType", "LoopbackNetwork",
           "PeerUnreachable", "Transport"]
