"""Client side of every peer RPC + the wire codecs.

Capability equivalent of the reference's Protocol.java (reference:
source/net/yacy/peers/Protocol.java — hello:190, queryRWICount:375,
search:883-1025, transferIndex:1680) with the key=value multipart wire
format replaced by JSON-able tables delivered through an injectable
Transport. Postings travel keyed by URL HASH (as the reference's
serialized WordReferenceRows are), not by peer-local docid — docids are
a node-local notion.

Every call returns (ok, reply_table); a transport failure demotes the
peer in the caller's SeedDB (the reference's PeerActions.peerDeparture
on failed RPCs).
"""

from __future__ import annotations

import time

import numpy as np

from ..index.postings import NF, PostingsList
from ..utils import fleet as fleetdigest
from ..utils import tracing
from .seed import Seed, SeedDB
from .transport import PeerUnreachable, Transport

# server-side cap on postings per transferRWI call
# (reference: htroot/yacy/transferRWI.java:195)
MAX_RWI_ENTRIES_PER_CALL = 1000


# -- wire codecs -------------------------------------------------------------

def encode_postings(plist: PostingsList, urlhashes: list[bytes]) -> dict:
    """PostingsList + per-row urlhashes -> wire table."""
    return {
        "uh": [h.decode("ascii") for h in urlhashes],
        "feats": plist.feats.tolist(),
    }


def decode_postings(table: dict) -> tuple[list[bytes], np.ndarray]:
    uh = [h.encode("ascii") for h in table.get("uh", [])]
    feats = np.asarray(table.get("feats", []), dtype=np.int32)
    if feats.size == 0:
        feats = feats.reshape(0, NF)
    return uh, feats


class Protocol:
    """Stateless client methods bound to (my seeddb, transport)."""

    def __init__(self, seeddb: SeedDB, transport: Transport, news=None,
                 fleet=None):
        self.seeddb = seeddb
        self.transport = transport
        self.news = news            # NewsPool | None (peers/news.py)
        self.fleet = fleet          # FleetTable | None (utils/fleet.py)

    # -- plumbing ------------------------------------------------------------

    def _call(self, target: Seed, endpoint: str, payload: dict
              ) -> tuple[bool, dict]:
        # env-gated failpoint (utils/faultinject): a blackholed peer is
        # unreachable — fail after the configured delay, exactly like a
        # dead network path, so peer-avoidance tests drive the real
        # skip/timeout machinery deterministically
        from ..utils import faultinject
        if faultinject.blackholed(target.hash):
            delay = faultinject.blackhole_delay_s(target.hash)
            if delay > 0.0:
                import time as _time
                _time.sleep(delay)
            self.seeddb.disconnected(target.hash)
            return False, {}
        # trace propagation: the active trace id rides every outgoing
        # RPC in-band (tracing.PAYLOAD_KEY); HttpTransport promotes it
        # to the X-YaCy-Trace header on the real wire, and the remote
        # PeerServer roots its spans under it — one trace network-wide
        tid = tracing.current_trace_id()
        if tid is not None and tracing.PAYLOAD_KEY not in payload:
            payload = {**payload, tracing.PAYLOAD_KEY: tid}
        # fleet gossip piggyback (ISSUE 5): the metric digest rides the
        # SAME exchanges the DHT already pays for — hello pings, remote
        # searches, transferRWI chunks — per-peer rate-limited inside
        # outgoing_digest so chunked transfers don't re-send it
        dig = None
        if self.fleet is not None and \
                fleetdigest.PAYLOAD_KEY not in payload:
            dig = self.fleet.outgoing_digest(target.hash)
            if dig is not None:
                payload = {**payload, fleetdigest.PAYLOAD_KEY: dig}
        try:
            reply = self.transport.rpc(target.hash, endpoint, payload)
        except PeerUnreachable:
            # a digest attached to a failed call never arrived: release
            # the per-peer rate-limit slot so the next successful call
            # re-sends instead of leaving the peer stale for an interval
            if dig is not None:
                self.fleet.send_failed(target.hash)
            self.seeddb.disconnected(target.hash)
            return False, {}
        except Exception:
            # a crashing remote handler (HTTP 500 equivalent) is a failed
            # call, not a sender crash: callers rely on the False return to
            # re-enqueue in-flight index transfers instead of losing them
            if dig is not None:
                self.fleet.send_failed(target.hash)
            self.seeddb.disconnected(target.hash)
            return False, {}
        self.seeddb.connected(target)
        if self.fleet is not None and isinstance(reply, dict):
            d = reply.pop(fleetdigest.PAYLOAD_KEY, None)
            if d is not None:
                self.fleet.ingest(d)
        return True, reply

    # -- membership ----------------------------------------------------------

    def hello(self, target: Seed) -> tuple[bool, dict]:
        """Publish my seed; harvest the target's seed view
        (Protocol.java:190; Network.publishMySeed)."""
        my = self.seeddb.my_seed
        gossip = [s.dna() for s in self.seeddb.active_seeds()[:16]]
        payload = {"seed": my.dna(), "seeds": gossip}
        if self.news is not None:
            # news rides the ping (reference: hello exchange carries the
            # news queues, NewsPool feed/drain in PeerActions)
            payload["news"] = self.news.outgoing_batch()
        ok, reply = self._call(target, "hello", payload)
        if not ok:
            return False, {}
        if "seed" in reply:
            self.seeddb.connected(Seed.from_dna(reply["seed"]))
        for dna in reply.get("seeds", []):
            try:
                self.seeddb.hearsay(Seed.from_dna(dna))
            except (KeyError, ValueError):
                continue
        if self.news is not None and reply.get("news"):
            self.news.ingest_batch(reply["news"],
                                   my.hash.decode("ascii", "replace"))
        return True, reply

    def seedlist(self, target: Seed) -> list[Seed]:
        """Bootstrap: fetch the peer directory of a (principal) peer."""
        ok, reply = self._call(target, "seedlist", {})
        if not ok:
            return []
        seeds = []
        for dna in reply.get("seeds", []):
            try:
                s = Seed.from_dna(dna)
            except (KeyError, ValueError):
                continue
            self.seeddb.hearsay(s)
            seeds.append(s)
        return seeds

    # -- statistics ----------------------------------------------------------

    def query_rwi_count(self, target: Seed, wordhash: bytes) -> int:
        """How many postings does the peer hold for this term
        (Protocol.queryRWICount)."""
        ok, reply = self._call(
            target, "query", {"object": "rwicount",
                              "env": wordhash.decode("ascii")})
        return int(reply.get("response", -1)) if ok else -1

    # -- search --------------------------------------------------------------

    def search(self, target: Seed, wordhashes: list[bytes],
               exclude_hashes: list[bytes] | None = None,
               count: int = 10, timeout_ms: int = 3000,
               lang: str = "", contentdom: int = 0,
               with_abstracts: bool = False,
               urls: list[bytes] | None = None) -> tuple[bool, dict]:
        """Remote search RPC (Protocol.search / htroot/yacy/search.java):
        the peer runs a local search and returns result rows + optional
        per-word url-hash abstracts for the secondary join round.
        `urls` is the SECONDARY search shape (Protocol
        .secondaryRemoteSearch): restrict the peer's answer to these
        url hashes — the caller already knows, from the abstract join,
        that they complete a cross-peer conjunction."""
        payload = {
            "query": [h.decode("ascii") for h in wordhashes],
            "exclude": [h.decode("ascii") for h in (exclude_hashes or [])],
            "count": count, "time": timeout_ms, "lang": lang,
            "contentdom": contentdom,
            "abstracts": "words" if with_abstracts else "",
        }
        if urls:
            payload["urls"] = [u.decode("ascii") for u in urls]
        return self._call(target, "search", payload)

    # -- index transfer ------------------------------------------------------

    def transfer_index(self, target: Seed,
                       containers: dict[bytes, tuple[PostingsList, list[bytes]]],
                       metadata_rows: dict[bytes, dict]
                       ) -> tuple[bool, dict]:
        """transferRWI then transferURL for reported-unknown URLs
        (Protocol.transferIndex:1680 two-RPC shape).

        containers: termhash -> (postings, per-row urlhashes)
        metadata_rows: urlhash -> metadata field table

        Large transmissions are CHUNKED into successive transferRWI calls
        of <=MAX_RWI_ENTRIES_PER_CALL postings each — postings here have
        already been removed from the sender's index (delete-on-select),
        so silently truncating would lose index data network-wide. Any
        failed chunk fails the whole transmission; the caller re-enqueues
        (the receive side dedups re-sent postings by docid).
        """
        # flatten into per-call batches of whole-or-split containers
        batches: list[list[dict]] = [[]]
        n = 0
        for th, (plist, uhs) in containers.items():
            off = 0
            while off < len(plist):
                take = min(len(plist) - off, MAX_RWI_ENTRIES_PER_CALL - n)
                batches[-1].append({
                    "term": th.decode("ascii"),
                    "postings": encode_postings(
                        PostingsList(plist.docids[off:off + take],
                                     plist.feats[off:off + take]),
                        uhs[off:off + take]),
                })
                off += take
                n += take
                if n >= MAX_RWI_ENTRIES_PER_CALL:
                    batches.append([])
                    n = 0
        unknown: list[bytes] = []
        reply: dict = {}
        for entries in batches:
            if not entries:
                continue
            # wire-entry stamp (ISSUE 15 satellite / ROADMAP 3b first
            # slice): the receiver anchors its crawl-to-searchable SLO
            # stamps at this send time, so peer-pushed postings land in
            # the ingest tiers + burn rule.  Wall-clock seconds because
            # monotonic stamps do not cross hosts; absent-stamp peers
            # are tolerated (the receiver anchors at its wire entry).
            ok, reply = self._call(target, "transferRWI",
                                   {"entries": entries,
                                    "stamp": round(time.time(), 3)})
            if not ok:
                return False, {}
            if reply.get("result") not in ("ok", None):
                # receiver refused ("not granted"/"busy"): nothing was
                # stored — treat as failure so the caller re-enqueues
                # (delete-on-select postings must never be dropped)
                return False, reply
            unknown.extend(u.encode("ascii")
                           for u in reply.get("unknownURL", []))
        if unknown:
            rows = {u.decode("ascii"): metadata_rows[u]
                    for u in set(unknown) if u in metadata_rows}
            ok2, reply2 = self._call(target, "transferURL", {"rows": rows})
            if not ok2:
                return False, {}
            reply = {**reply, **reply2}
        return True, reply

    # -- multi-process mesh runtime (ISSUE 12) -------------------------------

    def mesh_rpc(self, target: Seed, endpoint: str,
                 payload: dict) -> tuple[bool, dict]:
        """One mesh-runtime RPC (meshstep/meshcommit/meshinfo/...):
        plain `_call` plumbing, so the fleet digest and the active trace
        id ride the same exchange — the scatter that keeps the SPMD
        fleet in lockstep IS the gossip the mesh view feeds on."""
        assert endpoint.startswith("mesh"), endpoint
        return self._call(target, endpoint, payload)

    def fetch_trace(self, target: Seed, trace_id: str) -> tuple[bool, dict]:
        """Cross-peer trace assembly (ISSUE 5): pull the peer's retained
        segment of a trace out of its ring by trace id (server side:
        PeerServer.do_tracefetch).  The reply carries the answering
        peer's hash so merged spans stay attributable."""
        return self._call(target, "tracefetch", {"trace": trace_id})

    def fetch_profile(self, target: Seed,
                      n: int = 12) -> tuple[bool, dict]:
        """Whitebox straggler forensics (ISSUE 20d): pull the peer's
        in-process profile snapshot — folded stacks, lock table, last
        deep capture — over the same wire the mesh already pays for
        (server side: PeerServer.do_profsnap)."""
        return self._call(target, "profsnap", {"n": n})

    def idx(self, target: Seed) -> dict:
        """Peer index statistics (htroot/yacy/idx.java server side).
        Returns {} for unreachable peers AND for peers answering with an
        error shape (older versions without the handler)."""
        ok, reply = self._call(target, "idx", {})
        return reply if ok and "urls" in reply else {}

    def fetch_blacklist(self, target: Seed) -> list[str]:
        """Pull a peer's shared url blacklist (htroot/yacy/list.java,
        col=black) for cooperative filtering."""
        ok, reply = self._call(target, "list", {"col": "black"})
        return list(reply.get("list", [])) if ok else []

    # -- messages + profile ---------------------------------------------------

    def message(self, target: Seed, subject: str, content: str) -> bool:
        """Deliver a peer-to-peer message into the target's mailbox
        (reference: htroot/yacy/message.java; Protocol message call).
        The sender identity is my seed hash/name."""
        my = self.seeddb.my_seed
        ok, reply = self._call(target, "message", {
            "from": my.hash.decode("ascii", "replace"),
            "fromname": my.name, "subject": subject, "content": content})
        return ok and reply.get("result") == "ok"

    def profile(self, target: Seed) -> dict:
        """Fetch a peer's operator profile (htroot/yacy/profile.java;
        Protocol.getProfile:1992)."""
        ok, reply = self._call(target, "profile", {})
        return reply.get("profile", {}) if ok else {}

    # -- remote crawl delegation ---------------------------------------------

    def pull_crawl_urls(self, target: Seed, count: int = 10) -> list[dict]:
        """Pull crawl work from a peer publishing remote-crawl URLs
        (htroot/yacy/urls.java server side)."""
        ok, reply = self._call(target, "urls", {"count": count})
        return reply.get("requests", []) if ok else []

    def crawl_receipt(self, target: Seed, urlhash: bytes, result: str,
                      reason: str = "") -> bool:
        """Report a delegated crawl's outcome back to the delegating peer
        (htroot/yacy/crawlReceipt.java)."""
        ok, _ = self._call(target, "crawlReceipt",
                           {"urlhash": urlhash.decode("ascii"),
                            "result": result, "reason": reason})
        return ok
