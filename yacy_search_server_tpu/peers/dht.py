"""DHT target selection — which peers hold/receive a term's postings.

Capability equivalent of the reference's DHTSelection (reference:
source/net/yacy/peers/DHTSelection.java:57-438 —
selectDHTSearchTargets:141 picks `redundancy` peers per query word whose
ring position covers the word, per vertical partition;
selectDHTDistributionTargets:182 is the write-side counterpart). A peer
"covers" a position by proximity on the closed base64-cardinal ring
(Distribution.java:87-93 ring distance, forward direction).
"""

from __future__ import annotations

from ..parallel.distribution import Distribution, horizontal_dht_distance
from .seed import Seed, SeedDB


def _closest(seeds: list[Seed], position: int, n: int) -> list[Seed]:
    """The n peers closest at-or-after `position` on the ring."""
    return sorted(
        seeds, key=lambda s: horizontal_dht_distance(position,
                                                     s.ring_position()))[:n]


def select_distribution_targets(seeddb: SeedDB, dist: Distribution,
                                wordhash: bytes, partition: int,
                                redundancy: int,
                                include_self: bool = False) -> list[Seed]:
    """Write side: peers that should RECEIVE (wordhash, partition) postings.

    Only active senior peers accepting DHT-in are eligible
    (DHTSelection.java:182 skips non-active / robinson peers).
    """
    pos = dist.vertical_dht_position(wordhash, partition)
    pool = [s for s in seeddb.active_seeds() if s.accepts_dht_in()]
    if include_self:
        pool = pool + [seeddb.my_seed]
    return _closest(pool, pos, redundancy)

def select_search_targets(seeddb: SeedDB, dist: Distribution,
                          wordhashes: list[bytes], redundancy: int,
                          max_peers: int = 64) -> list[Seed]:
    """Read side: the union of peers covering any (word, partition) cell.

    A query for word W must reach peers of ALL vertical partitions at W's
    horizontal position (SURVEY.md §5: the "partitions" parameter of
    Protocol.search), each cell with `redundancy` replicas.
    """
    chosen: dict[bytes, Seed] = {}
    pool = [s for s in seeddb.active_seeds() if s.is_senior()]
    if not pool:
        return []
    for wh in wordhashes:
        for part in range(dist.vertical_partitions()):
            pos = dist.vertical_dht_position(wh, part)
            for s in _closest(pool, pos, redundancy):
                chosen[s.hash] = s
            if len(chosen) >= max_peers:
                return list(chosen.values())
    return list(chosen.values())


def my_responsibility(seeddb: SeedDB, dist: Distribution, wordhash: bytes,
                      partition: int, redundancy: int) -> bool:
    """Is MY peer one of the `redundancy` owners of (wordhash, partition)?
    Used to decide whether to keep postings locally vs hand them off."""
    targets = select_distribution_targets(seeddb, dist, wordhash, partition,
                                          redundancy, include_self=True)
    return any(t.hash == seeddb.my_seed.hash for t in targets)
