"""Java-wire compatibility codec — speak the reference's P2P formats.

The declared-optional stretch of SURVEY §7: a node should be able to
federate with a LIVE YaCy peer, whose wire is NOT our JSON transport but
(reference file:line):

- **request**: HTTP POST multipart/form-data whose parts are key=value
  strings, with `basicRequestParts` identification fields and the
  salted-magic-sim authentication digest
  (source/net/yacy/peers/Protocol.java:2149+, authentifyRequest:2109);
- **response**: a `key=value` line table (FileUtils.table,
  Protocol.java:971 result parsing);
- **seed DNA**: the peer record serialized as `{k=v,k=v,}` (MapTools
  .map2string, kelondro/util/MapTools.java:71) wrapped in
  `crypt.simpleEncode` — `"b|" + base64(content)` or `"z|" +
  base64(gzip(content))`, shorter wins (utils/crypt.java:74,
  Seed.genSeedStr:1389, genRemoteSeed:1247).

Our Base64Order is already bit-compatible with the reference's enhanced
coder (utils/base64order.py — DHT math depends on it), so the encodings
here round-trip against real YaCy output byte-for-byte.

``JavaWireClient`` implements the hello RPC (Protocol.java:190) over an
injectable HTTP POST callable; ``java_hello_response`` renders the
server side of hello in the Java table format so a real peer can greet
this node (htroot/yacy/hello.java). Index-transfer RPCs reuse the same
codec primitives (transferRWI posts the same part format with
line-serialized posting rows).
"""

from __future__ import annotations

import gzip as _gzip
import hashlib
import json as _json
import secrets
import time

from ..utils import fleet as fleetdigest
from ..utils import tracing
from ..utils.base64order import enhanced_coder
from .seed import Seed

# multipart part name carrying the trace id on the Java wire (the
# HTTP-header equivalent of tracing.TRACE_HEADER; a real YaCy peer
# ignores unknown parts, and our inbound handlers do the same — the
# tolerate-and-ignore contract, test_javawire)
TRACE_PART = "xtrace"

# multipart part carrying the fleet metric digest (ISSUE 5): the Java
# wire's rendition of the in-band `_digest` payload key.  Same
# tolerate-and-ignore contract — a real YaCy peer drops the unknown
# part, and a malformed part decodes to None and is ignored.
DIGEST_PART = "xdigest"


def encode_digest_part(digest: dict) -> str:
    """Digest dict -> the `xdigest` part value (compact JSON, the one
    encoding utils/fleet shares across transports)."""
    return fleetdigest.encode_digest(digest)


def decode_digest_part(part: str):
    """Tolerant decode of an `xdigest` part; None on malformed input
    (the receiving hello handler ignores it like any unknown part)."""
    try:
        obj = _json.loads(part)
    except (TypeError, ValueError):
        return None
    return obj if isinstance(obj, dict) else None

# ---------------------------------------------------------------------------
# crypt.simpleEncode / simpleDecode
# ---------------------------------------------------------------------------


def simple_encode(content: str, method: str = "auto") -> str:
    """reference utils/crypt.java:74 — 'b' base64, 'z' gzip+base64,
    'p' plain; 'auto' = shorter of b/z (Seed.genSeedStr:1389)."""
    if method == "p":
        return "p|" + content
    b = "b|" + enhanced_coder.encode(
        content.encode("utf-8")).decode("ascii")
    if method == "b":
        return b
    z = "z|" + enhanced_coder.encode(
        _gzip.compress(content.encode("utf-8"))).decode("ascii")
    if method == "z":
        return z
    return b if len(b) < len(z) else z


def simple_decode(encoded: str) -> str | None:
    if not encoded or len(encoded) < 3:
        return None
    if encoded[1] != "|":
        return encoded          # not encoded (crypt.simpleDecode:88)
    kind, payload = encoded[0], encoded[2:]
    try:
        if kind == "b":
            return enhanced_coder.decode(payload).decode("utf-8")
        if kind == "z":
            return _gzip.decompress(
                enhanced_coder.decode(payload)).decode("utf-8")
        if kind == "p":
            return payload
    except Exception:
        return None
    return None


# ---------------------------------------------------------------------------
# MapTools map2string / string2map
# ---------------------------------------------------------------------------


def map2string(m: dict[str, str], braces: bool = True) -> str:
    """kelondro/util/MapTools.java:71 — ``{k=v,k=v,}`` (note the
    trailing separator the reference emits)."""
    body = "".join(f"{k}={v}," for k, v in m.items() if v is not None)
    return "{" + body + "}" if braces else body


def string2map(s: str) -> dict[str, str]:
    """MapTools.java:54 — tolerant parse of map2string output."""
    if s is None:
        return {}
    if (p := s.find("{")) >= 0:
        s = s[p + 1:].strip()
    if (p := s.rfind("}")) >= 0:
        s = s[:p].strip()
    out: dict[str, str] = {}
    for token in s.split(","):
        token = token.strip()
        p = token.find("=")
        if p > 0:
            out[token[:p].strip()] = token[p + 1:].strip()
    return out


# ---------------------------------------------------------------------------
# Seed DNA (Seed.toString / genSeedStr / genRemoteSeed)
# ---------------------------------------------------------------------------

# our Seed field <-> reference DNA key (Seed.java constants)
_FLAG_TRUE, _FLAG_FALSE = "true", "false"


def seed_to_dna(seed: Seed) -> dict[str, str]:
    return {
        "Hash": seed.hash.decode("ascii", "replace"),
        "Name": seed.name or "anonymous",
        "IP": seed.ip,
        "Port": str(seed.port),
        "PeerType": seed.peer_type,
        "Version": str(seed.version),
        "UTC": "+0000",
        "LCount": str(seed.link_count),
        "ICount": str(seed.word_count),
        "RCount": "0",
        "Uptime": str(int(seed.uptime_s // 60)),
        "CRWCnt": "0",
        "CRTCnt": "0",
        "dct": str(int(time.time() * 1000)),
        "Flags": ("".join((
            "s" if seed.flags_accept_remote_crawl else "-",
            "s" if seed.flags_accept_remote_index else "-"))),
    }


def encode_seed(seed: Seed) -> str:
    """Seed.genSeedStr:1389 — DNA map as `{k=v,...}` in simpleEncode."""
    return simple_encode(map2string(seed_to_dna(seed)))


def decode_seed(seed_str: str) -> Seed:
    """Seed.genRemoteSeed:1247 — decode + DNA map parse; raises
    ValueError on malformed input (the reference throws IOException)."""
    decoded = simple_decode(seed_str)
    if not decoded:
        raise ValueError("seed string does not decode")
    dna = string2map(decoded)
    h = dna.pop("Hash", None)
    if not h or len(h) != 12:
        raise ValueError(f"bad seed hash: {h!r}")
    s = Seed(h.encode("ascii"), name=dna.get("Name", ""),
             ip=dna.get("IP", ""),
             port=int(dna.get("Port", "8090") or 8090),
             peer_type=dna.get("PeerType", "senior"))
    try:
        s.link_count = int(dna.get("LCount", "0") or 0)
        s.word_count = int(dna.get("ICount", "0") or 0)
    except ValueError:
        pass
    flags = dna.get("Flags", "")
    s.flags_accept_remote_crawl = flags[:1] == "s"
    s.flags_accept_remote_index = flags[1:2] == "s"
    return s


# ---------------------------------------------------------------------------
# key=value response tables (FileUtils.table)
# ---------------------------------------------------------------------------


def table_decode(content: bytes | str) -> dict[str, str]:
    if isinstance(content, bytes):
        content = content.decode("utf-8", "replace")
    out: dict[str, str] = {}
    for line in content.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        p = line.find("=")
        if p > 0:
            out[line[:p]] = line[p + 1:]
    return out


def table_encode(m: dict[str, object]) -> bytes:
    return "".join(f"{k}={v}\n" for k, v in m.items()).encode("utf-8")


# ---------------------------------------------------------------------------
# multipart/form-data requests + salted-magic authentication
# ---------------------------------------------------------------------------


def random_salt() -> str:
    """crypt.randomSalt shape: 8 base64-alphabet chars."""
    alphabet = bytes(enhanced_coder.alpha).decode("ascii")
    return "".join(secrets.choice(alphabet) for _ in range(8))


def magic_md5(salt: str, iam: str, magic: str) -> str:
    """salted-magic-sim digest (Protocol.authentifyRequest:2131)."""
    return hashlib.md5(f"{salt}{iam}{magic}".encode("utf-8")).hexdigest()


def basic_request_parts(my_hash: str, target_hash: str | None, salt: str,
                        network_name: str = "freeworld",
                        network_magic: str = "") -> dict[str, str]:
    """Protocol.basicRequestParts:2149 — identification + auth fields."""
    parts = {"iam": my_hash}
    if target_hash:
        parts["youare"] = target_hash
    parts["mytime"] = time.strftime("%Y%m%d%H%M%S", time.gmtime())
    parts["myUTC"] = str(int(time.time() * 1000))
    parts["netid"] = network_name
    parts["key"] = salt
    if network_magic:
        parts["magicmd5"] = magic_md5(salt, my_hash, network_magic)
    # distributed tracing rides the Java wire too: every outgoing call
    # built on basicRequestParts (hello, search, transferRWI) carries
    # the active trace id as an extra part; receivers that don't know
    # it ignore it like any unknown part
    tid = tracing.current_trace_id()
    if tid is not None:
        parts[TRACE_PART] = tid
    return parts


def multipart_encode(parts: dict[str, str]) -> tuple[bytes, str]:
    """multipart/form-data body + content-type for the part map (the
    reference posts UTF8.StringBody parts via Apache HttpClient)."""
    boundary = "----YaCyTPU" + secrets.token_hex(12)
    chunks: list[bytes] = []
    for name, value in parts.items():
        chunks.append(
            (f"--{boundary}\r\n"
             f'Content-Disposition: form-data; name="{name}"\r\n\r\n'
             f"{value}\r\n").encode("utf-8"))
    chunks.append(f"--{boundary}--\r\n".encode("ascii"))
    return b"".join(chunks), f"multipart/form-data; boundary={boundary}"


def multipart_decode(body: bytes, content_type: str) -> dict[str, str]:
    """Parse a multipart/form-data body into a part map (the server side
    of the Java wire; tolerant of both \\r\\n and \\n)."""
    marker = "boundary="
    p = content_type.find(marker)
    if p < 0:
        return {}
    boundary = content_type[p + len(marker):].split(";")[0].strip()
    # RFC 2046 allows a QUOTED boundary; several HTTP stacks emit it
    boundary = boundary.strip('"')
    out: dict[str, str] = {}
    for segment in body.split(b"--" + boundary.encode("ascii")):
        seg = segment.strip(b"\r\n")
        if not seg or seg == b"--":
            continue
        head, _, payload = seg.partition(b"\r\n\r\n")
        if not payload:
            head, _, payload = seg.partition(b"\n\n")
        name = None
        for line in head.decode("utf-8", "replace").splitlines():
            if "form-data" in line and "name=" in line:
                name = line.split("name=", 1)[1].strip().strip('";')
                name = name.split('"')[0]
        if name:
            out[name] = payload.decode("utf-8", "replace").rstrip("\r\n")
    return out


# ---------------------------------------------------------------------------
# hello RPC, both directions (Protocol.hello:190 / htroot/yacy/hello.java)
# ---------------------------------------------------------------------------


class JavaWireClient:
    """Client half of the Java wire. `http_post(url, body, content_type)
    -> bytes` is injectable — tests run a simulated Java peer, a real
    deployment passes a urllib-based poster."""

    def __init__(self, my_seed: Seed, http_post,
                 network_name: str = "freeworld",
                 network_magic: str = "", digest_provider=None):
        self.my_seed = my_seed
        self.http_post = http_post
        self.network_name = network_name
        self.network_magic = network_magic
        # callable(target_hash | None) -> digest dict | None (normally
        # FleetTable.outgoing_digest, so the Java wire honors the SAME
        # per-peer rate limit as the JSON transports): when set, hellos
        # carry the fleet digest as the xdigest part
        self.digest_provider = digest_provider

    def hello(self, target_host: str, target_port: int,
              target_hash: str | None = None):
        """POST /yacy/hello.html in the Java part format; returns
        (other_peer_seed, extra_seeds, response_table) or None."""
        salt = random_salt()
        parts = basic_request_parts(
            self.my_seed.hash.decode("ascii"), target_hash, salt,
            self.network_name, self.network_magic)
        parts["count"] = "20"
        parts["magic"] = "0"
        parts["seed"] = encode_seed(self.my_seed)
        if self.digest_provider is not None:
            d = self.digest_provider(target_hash)
            if d:
                parts[DIGEST_PART] = encode_digest_part(d)
        body, ctype = multipart_encode(parts)
        url = f"http://{target_host}:{target_port}/yacy/hello.html"
        try:
            raw = self.http_post(url, body, ctype)
        except Exception:
            return None
        if not raw:
            return None
        table = table_decode(raw)
        # seed0 IS the responder; seed1..N are gossip — they must not
        # stand in for each other when one fails to decode
        other: Seed | None = None
        if (s0 := table.get("seed0")) is not None:
            try:
                other = decode_seed(s0)
            except ValueError:
                other = None
        extra: list[Seed] = []
        i = 1
        while (s := table.get(f"seed{i}")) is not None:
            try:
                extra.append(decode_seed(s))
            except ValueError:
                pass
            i += 1
        if other is not None and target_hash \
                and other.hash.decode("ascii") != target_hash:
            return None         # consistency check (Protocol.java:248)
        return other, extra, table


def java_hello_response(my_seed: Seed, extra_seeds: list[Seed],
                        client_ip: str, client_seed: Seed | None) -> bytes:
    """Server half of hello in the Java table format
    (htroot/yacy/hello.java): seed0 = this node, seedN = a gossip batch,
    yourip/yourtype tell the caller how it looks from here."""
    table: dict[str, object] = {
        "message": "ok",
        "mytime": time.strftime("%Y%m%d%H%M%S", time.gmtime()),
        "seed0": encode_seed(my_seed),
        "yourip": client_ip,
        "yourtype": (client_seed.peer_type if client_seed else "junior"),
    }
    for i, s in enumerate(extra_seeds[:20], start=1):
        table[f"seed{i}"] = encode_seed(s)
    return table_encode(table)
