"""P2PNode — a Switchboard plus the full peer stack, one per network node.

The composition the reference builds inside Switchboard's constructor
(reference: source/net/yacy/search/Switchboard.java:668 Dispatcher wiring,
:1218-1230 peer ping deploy, :4133-4207 dhtTransferJob with its guard
rails) — factored out so N nodes can live in one process over a
LoopbackNetwork (the simulated multi-peer harness) or over HTTP (server/).
"""

from __future__ import annotations

import random
import time

from ..parallel.distribution import LONG_MAX, Distribution
from ..search.searchevent import SearchEvent
from ..switchboard import Switchboard
from .dispatcher import Dispatcher
from .network import Network
from .news import CAT_CRAWL_START, NewsPool
from .protocol import Protocol
from .remotesearch import RemoteSearch
from .seed import PeerType, Seed, SeedDB, make_seed_hash
from .server import PeerServer
from .transport import Transport

# freeworld defaults (reference: defaults/yacy.network.freeworld.unit)
DEFAULT_PARTITION_EXPONENT = 4     # 2^4 = 16 vertical partitions
DEFAULT_REDUNDANCY = 3             # dhtredundancy.senior
# dhtTransferJob guards (Switchboard.java:4147-4160)
MIN_PEERS_FOR_DHT = 1


class P2PNode:
    """One peer: switchboard + seed identity + protocol client/server +
    DHT dispatcher + membership gossip + remote search."""

    def __init__(self, name: str, p2p_transport: Transport,
                 data_dir: str | None = None,
                 crawl_transport=None,
                 port: int = 8090,
                 partition_exponent: int = DEFAULT_PARTITION_EXPONENT,
                 redundancy: int = DEFAULT_REDUNDANCY,
                 peer_type: str = PeerType.SENIOR,
                 accept_remote_index: bool = True,
                 accept_remote_crawl: bool = False,
                 cluster_peers: list[str] | None = None):
        self.sb = Switchboard(data_dir=data_dir, transport=crawl_transport)
        self.seed = Seed(make_seed_hash(name, "127.0.0.1", port), name=name,
                         port=port, peer_type=peer_type)
        self.seed.flags_accept_remote_index = accept_remote_index
        self.seed.flags_accept_remote_crawl = accept_remote_crawl
        self.seeddb = SeedDB(self.seed, data_dir)
        self.sb.seeddb = self.seeddb     # status/graphics servlets read it
        # servlet-level P2P access: yacysearch's resource=global fan-out
        # and /metrics' DHT counters reach the peer stack through the
        # switchboard (httpd's *.yacy rewrite already expects sb.node)
        self.sb.node = self
        self.dist = Distribution(partition_exponent)
        self.redundancy = redundancy
        self.news = NewsPool(data_dir)
        self.sb.news = self.news     # feed servlet reads the pool from sb
        # fleet observability (ISSUE 5): the switchboard's fleet table
        # learns this node's identity and rides every protocol exchange
        self.sb.fleet.my_hash = self.seed.hash.decode("ascii", "replace")
        self.protocol = Protocol(self.seeddb, p2p_transport,
                                 news=self.news, fleet=self.sb.fleet)
        self.server = PeerServer(self.sb, self.seeddb,
                                 accept_remote_index=accept_remote_index,
                                 accept_remote_crawl=accept_remote_crawl,
                                 news=self.news)
        p2p_transport.register(self.seed.hash, self.server.handle)
        self._transport = p2p_transport
        self.dispatcher = Dispatcher(self.sb.index, self.seeddb, self.dist,
                                     self.protocol, redundancy)
        self.network = Network(self.seeddb, self.protocol)
        # active network definition; ctor args override its DHT geometry
        from ..utils.config import NetworkUnit
        self.network_unit = NetworkUnit("freeworld", {
            "network.unit.dht.partitionExponent": str(partition_exponent),
            "network.unit.dhtredundancy.senior": str(redundancy)})
        self.cluster_peers = list(cluster_peers or [])
        self._rng = random.Random(self.seed.ring_position())

    # -- network definition ---------------------------------------------------

    def switch_network(self, unit_name: str, overrides=None) -> None:
        """Re-wire DHT + crawl behavior to another network definition at
        runtime (reference: Switchboard.switchNetwork selected by
        `network.unit.definition`): partition exponent, redundancy and
        remote-search budgets come from the unit; buffered outbound
        postings return to the local index first (their vertical split
        depends on the partition count)."""
        from ..utils.config import NETWORK_UNITS, NetworkUnit
        if unit_name not in NETWORK_UNITS:
            # a typo must not silently rewire the node onto the PUBLIC net
            raise ValueError(f"unknown network unit: {unit_name!r} "
                             f"(have: {sorted(NETWORK_UNITS)})")
        unit = NetworkUnit(unit_name, overrides)
        self.dispatcher.restore_buffer_to_index()
        self.dist = Distribution(unit.partition_exponent)
        self.redundancy = unit.redundancy_senior
        self.dispatcher = Dispatcher(self.sb.index, self.seeddb, self.dist,
                                     self.protocol, self.redundancy)
        self.network_unit = unit
        self.sb.config.set("network.unit.definition", unit.name)

    # -- membership ----------------------------------------------------------

    def bootstrap(self, seeds: list[Seed]) -> None:
        self.network.bootstrap = [s for s in seeds
                                  if s.hash != self.seed.hash]
        # over HTTP, bootstrap seeds carry the initial address book (the
        # reference's seed-list files carry IP:port the same way)
        if hasattr(self._transport, "set_address"):
            for s in self.network.bootstrap:
                self._transport.set_address(
                    s.hash, f"http://{s.ip}:{s.port}")

    def ping(self) -> int:
        return self.network.peer_ping()

    # -- DHT distribution (the dhtTransferJob busy thread) -------------------

    def dht_transfer_job(self, max_containers: int = 32,
                         max_refs: int = 2000,
                         segment_fraction: float = 1 / 64) -> bool:
        """One transfer cycle over a random ring segment; returns True if
        anything was shipped (BusyThread contract). Guards mirror
        Switchboard.dhtShallTransfer: enough peers, something to send,
        buffer not overfull."""
        if len(self.seeddb.active) < MIN_PEERS_FOR_DHT:
            return False
        if self.sb.index.rwi_size() == 0 and self.dispatcher.buffer_size() == 0:
            return False
        if self.dispatcher.buffer_size() < self.dist.vertical_partitions():
            start = self._rng.randrange(LONG_MAX)
            span = max(1, int(LONG_MAX * segment_fraction))
            limit = (start + span) % LONG_MAX
            self.dispatcher.select_containers_to_buffer(
                start, limit, max_containers, max_refs)
        txs = self.dispatcher.dequeue_transmissions()
        if not txs:
            return False
        return self.dispatcher.transmit_all(txs) > 0

    def distribute_all(self, rounds: int = 512) -> int:
        """Drive transfer to completion (test/CLI surface): sweep the whole
        ring deterministically, then flush the buffer."""
        total = 0
        parts = 16
        for i in range(parts):
            start = i * (LONG_MAX // parts)
            limit = (i + 1) * (LONG_MAX // parts) - 1
            self.dispatcher.select_containers_to_buffer(
                start, limit, max_containers=10**6, max_refs=10**9)
        for _ in range(rounds):
            txs = self.dispatcher.dequeue_transmissions(max_chunks=64)
            if not txs:
                break
            total += self.dispatcher.transmit_all(txs)
            if self.dispatcher.buffer_size() == 0:
                break
        return total

    # -- crawl (news-announcing wrapper + remote crawl delegation) -----------

    def start_crawl(self, start_url: str, depth: int = 0, **kw):
        """Start a crawl and announce it on the news channel
        (reference: Switchboard publishes a crwlstrt record on crawl start)."""
        profile = self.sb.start_crawl(start_url, depth=depth, **kw)
        self.news.publish(CAT_CRAWL_START,
                          self.seed.hash.decode("ascii", "replace"),
                          {"startURL": start_url, "intention":
                           kw.get("name", ""), "generalDepth": str(depth)})
        return profile

    def remote_crawl_loader_job(self, max_urls: int = 10) -> bool:
        """Pull delegated crawl work from a peer that publishes it, load
        the pages into MY index, and report receipts back (reference:
        CrawlQueues.remoteCrawlLoaderJob:444 + crawlReceipt round-trip).
        Returns True if any URL was processed (BusyThread contract)."""
        providers = [s for s in self.seeddb.active_seeds()
                     if s.flags_accept_remote_crawl]
        if not providers:
            return False
        provider = self._rng.choice(providers)
        requests = self.protocol.pull_crawl_urls(provider, count=max_urls)
        worked = False
        from ..crawler.loader import CacheStrategy
        from ..crawler.request import Request
        for rd in requests:
            try:
                req = Request.from_dict(rd)
            except (KeyError, ValueError):
                continue
            try:
                resp = self.sb.loader.load(req, CacheStrategy.IFFRESH)
            except Exception:
                self.protocol.crawl_receipt(provider, req.urlhash(),
                                            "exception", "load failed")
                continue
            if resp.status == 200:
                # the delegator's profile handle never resolves here (handles
                # hash node-local creation state); fall back to the dedicated
                # "remote" default profile, not an arbitrary one
                profile = self.sb.profiles.get(req.profile_handle) or \
                    next((p for p in self.sb.profiles.values()
                          if p.name == "remote"),
                         next(iter(self.sb.profiles.values())))
                self.sb.to_indexer(resp, profile)
                self.protocol.crawl_receipt(provider, req.urlhash(), "fill")
                worked = True
            else:
                self.protocol.crawl_receipt(provider, req.urlhash(),
                                            "reject", f"status {resp.status}")
        return worked

    # -- search --------------------------------------------------------------

    def search(self, query_string: str, count: int = 10,
               remote: bool = True, timeout_s: float | None = None,
               secondary: bool = True) -> SearchEvent:
        """Local batched search + remote scatter-gather into one event
        (the yacysearch entry: local threads + primaryRemoteSearches).
        The per-peer budget defaults to the active network unit's
        remotesearch.maxtime/maxcount.

        Cluster mode (reference: cluster.peers.yacydomain allowlist ->
        Searchdom.CLUSTER): when `cluster_peers` is set, the scatter goes to
        exactly that fixed peer set instead of DHT-selected targets."""
        event = self.sb.search(query_string, count=count)
        if remote:
            self.scatter(event, count, timeout_s=timeout_s,
                         secondary=secondary)
        return event

    def scatter(self, event: SearchEvent, count: int,
                timeout_s: float | None = None,
                secondary: bool = True) -> int:
        """Remote scatter-gather into a live event — THE fan-out used by
        both node.search and the servlet's resource=global path, so
        cluster mode (the cluster_peers allowlist) and the secondary
        abstract-join round apply no matter which surface asked.
        Returns the number of peers asked."""
        if not self.seeddb.active:
            return 0
        # a CACHED event carries the trace of the request that created
        # it (possibly long finished): this scatter belongs to the
        # request driving it NOW, so its fan-out spans re-parent here
        from ..utils import tracing
        cur = tracing.current()
        if cur is not None:
            event.trace_ctx = cur
        if timeout_s is None:
            timeout_s = self.network_unit.remotesearch_maxtime_ms / 1000.0
        per_peer = max(count, self.network_unit.remotesearch_maxcount)
        # fleet-aware avoidance (ISSUE 9): the remote_peer_guard
        # actuator maintains the avoided-peer set from gossiped digests
        act = getattr(self.sb, "actuators", None)
        avoid = set(act.avoided_peers()) if act is not None else None
        rs = RemoteSearch(event, self.seeddb, self.dist, self.protocol,
                          redundancy=self.redundancy,
                          per_peer_count=per_peer, timeout_s=timeout_s,
                          avoid_hashes=avoid)
        if self.cluster_peers:
            allowed = {n.lower() for n in self.cluster_peers}
            targets = [s for s in self.seeddb.active_seeds()
                       if s.name.lower() in allowed]
            asked = rs.start_fixed(targets)
        else:
            asked = rs.start()
        rs.join()
        if secondary and rs.secondary_search():
            rs.join(timeout_s / 2)
        return asked

    # -- cross-peer trace assembly (ISSUE 5) ----------------------------------

    def assemble_trace(self, trace_id: str, max_peers: int = 16,
                       timeout_s: float = 5.0) -> int:
        """Fetch the remote segments of `trace_id` from active peers and
        merge them into the local ring (Performance_Trace_p's assemble
        affordance): the originator of a resource=global search renders
        the FULL distributed waterfall instead of an opaque fan-out gap.
        Fetches run CONCURRENTLY against a deadline (the RemoteSearch
        fan-out discipline) so one slow/dead peer costs one timeout, not
        a serial sum across the whole page load.  The peers the traced
        search ACTUALLY asked come first (their hashes ride the
        `peers.remotesearch` span attrs), so a large mesh never
        exhausts `max_peers` on uninvolved nodes; remaining slots fall
        back to active peers (remote segments can exist on peers whose
        fan-out span was lost).  Returns the number of spans merged (0
        when every peer's segment was already present — the idempotence
        contract)."""
        import threading

        from ..utils import tracing
        merged = [0]
        lock = threading.Lock()

        def fetch(seed):
            ok, reply = self.protocol.fetch_trace(seed, trace_id)
            if not ok:
                return
            spans = reply.get("spans")
            src = reply.get("peer") or seed.hash.decode("ascii", "replace")
            if spans:
                n = tracing.merge_remote_spans(trace_id, spans, src)
                with lock:
                    merged[0] += n

        targets: list = []
        seen: set = set()
        rec = tracing.get_trace(trace_id)
        if rec is not None:
            for s in rec.spans:
                ph = s.attrs.get("peer_hash")
                if not isinstance(ph, str):
                    continue
                seed = self.seeddb.get(ph.encode("ascii", "replace"))
                if seed is not None and seed.hash not in seen:
                    seen.add(seed.hash)
                    targets.append(seed)
        for seed in self.seeddb.active_seeds():
            if len(targets) >= max_peers:
                break
            if seed.hash not in seen:
                seen.add(seed.hash)
                targets.append(seed)

        threads = []
        for seed in targets[:max_peers]:
            th = threading.Thread(target=fetch, args=(seed,),
                                  name=f"tracefetch-{seed.name}",
                                  daemon=True)
            th.start()
            threads.append(th)
        t_end = time.monotonic() + timeout_s
        for th in threads:
            left = t_end - time.monotonic()
            if left <= 0:
                break
            th.join(left)
        return merged[0]

    # -- HTTP face (DCN deployment) ------------------------------------------

    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """Expose this node's UI/API + /yacy/* wire endpoints over a real
        socket and advertise the bound address in the seed DNA (the
        reference's Jetty startup + Seed IP/port publication). When the
        node's transport is an HttpTransport without a resolver, wire the
        SeedDB in as the address book — gossiped seeds become reachable."""
        from ..server.httpd import YaCyHttpServer
        from .transport import HttpTransport

        self.http = YaCyHttpServer(self.sb, port=port, host=host,
                                   peer_server=self.server).start()
        self.seed.ip = host
        self.seed.port = self.http.port
        if isinstance(self._transport, HttpTransport) \
                and self._transport.resolver is None:
            def resolve(peer_hash: bytes) -> str | None:
                s = self.seeddb.get(peer_hash)
                return f"http://{s.ip}:{s.port}" if s else None
            self._transport.resolver = resolve
        return self.http

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if getattr(self, "http", None) is not None:
            self.http.close()
            self.http = None
        self.dispatcher.restore_buffer_to_index()
        self._transport.unregister(self.seed.hash)
        self.seeddb.close()
        self.sb.close()

    def deploy_threads(self) -> None:
        """Busy threads incl. the P2P jobs (deployThread parity)."""
        from ..utils.workflow import BusyThread
        self.sb.deploy_threads()
        self.sb.threads.deploy(BusyThread(
            "30_peerping", lambda: self.ping() > 0,
            idle_sleep_s=30.0, busy_sleep_s=30.0))
        self.sb.threads.deploy(BusyThread(
            "70_dht_distribution", self.dht_transfer_job,
            idle_sleep_s=15.0, busy_sleep_s=1.0))
        self.sb.threads.deploy(BusyThread(
            "62_remotetriggeredcrawl", self.remote_crawl_loader_job,
            idle_sleep_s=10.0, busy_sleep_s=1.0))
