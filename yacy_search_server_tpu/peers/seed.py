"""Peer identity (Seed) and the peer directory (SeedDB).

Capability equivalent of the reference's peer DNA and seed database
(reference: source/net/yacy/peers/Seed.java:139-237 — hash, IPs, port,
flags, counts, PeerType junior/senior/principal — and SeedDB.java — three
tables active/passive/potential plus mySeed). A seed serializes to a flat
string map ("DNA") for the hello/seedlist gossip wire format.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..utils.base64order import enhanced_coder
from ..utils.hashes import word2hash


class PeerType:
    JUNIOR = "junior"        # not reachable from the WAN, no DHT-in
    SENIOR = "senior"        # reachable, full DHT citizen
    PRINCIPAL = "principal"  # senior + publishes seed lists


def make_seed_hash(name: str, ip: str, port: int) -> bytes:
    """Deterministic 12-char base64 peer hash (the reference draws a random
    hash once and persists it; determinism here makes tests reproducible)."""
    return word2hash(f"{name}|{ip}|{port}")


class Seed:
    """One peer's DNA. Field names follow the reference's Seed properties."""

    def __init__(self, hash_b: bytes, name: str = "", ip: str = "127.0.0.1",
                 port: int = 8090, peer_type: str = PeerType.SENIOR,
                 version: str = "0.1"):
        self.hash = hash_b                  # 12 ascii bytes, base64 alphabet
        self.name = name
        self.ip = ip
        self.port = port
        self.peer_type = peer_type
        self.version = version
        self.flags_accept_remote_crawl = False
        self.flags_accept_remote_index = True   # "dhtIn"
        self.link_count = 0                 # URLs in the local index
        self.word_count = 0                 # RWI terms in the local index
        self.uptime_s = 0.0
        self.last_seen = time.time()
        self.birth = time.time()
        self.connects = 0

    # -- ring placement ------------------------------------------------------

    def ring_position(self) -> int:
        """Cardinal position of this peer on the DHT ring."""
        return enhanced_coder.cardinal(self.hash)

    def is_senior(self) -> bool:
        return self.peer_type in (PeerType.SENIOR, PeerType.PRINCIPAL)

    def accepts_dht_in(self) -> bool:
        return self.is_senior() and self.flags_accept_remote_index

    # -- DNA wire format -----------------------------------------------------

    def dna(self) -> dict:
        return {
            "Hash": self.hash.decode("ascii"),
            "Name": self.name,
            "IP": self.ip,
            "Port": str(self.port),
            "PeerType": self.peer_type,
            "Version": self.version,
            "CRWCnt": "1" if self.flags_accept_remote_crawl else "0",
            "DhtIn": "1" if self.flags_accept_remote_index else "0",
            "LCount": str(self.link_count),
            "ICount": str(self.word_count),
            "Uptime": str(int(self.uptime_s)),
            "LastSeen": str(self.last_seen),
        }

    @staticmethod
    def from_dna(d: dict) -> "Seed":
        s = Seed(d["Hash"].encode("ascii"), name=d.get("Name", ""),
                 ip=d.get("IP", "127.0.0.1"), port=int(d.get("Port", 8090)),
                 peer_type=d.get("PeerType", PeerType.SENIOR),
                 version=d.get("Version", "0"))
        s.flags_accept_remote_crawl = d.get("CRWCnt") == "1"
        s.flags_accept_remote_index = d.get("DhtIn", "1") == "1"
        s.link_count = int(d.get("LCount", 0))
        s.word_count = int(d.get("ICount", 0))
        s.uptime_s = float(d.get("Uptime", 0))
        s.last_seen = float(d.get("LastSeen", time.time()))
        return s

    def touch(self) -> None:
        self.last_seen = time.time()

    def __repr__(self) -> str:
        return (f"Seed({self.hash.decode('ascii')}, {self.name!r}, "
                f"{self.peer_type})")


class SeedDB:
    """active / passive / potential peer tables + my own seed.

    State transitions mirror the reference's PeerActions: a peer we talked
    to goes active; one that stops answering demotes to passive; hearsay
    seeds (learned via gossip, never contacted) start potential.
    """

    def __init__(self, my_seed: Seed, data_dir: str | None = None):
        self.my_seed = my_seed
        self.active: dict[bytes, Seed] = {}
        self.passive: dict[bytes, Seed] = {}
        self.potential: dict[bytes, Seed] = {}
        self._lock = threading.RLock()
        self._path = os.path.join(data_dir, "seeds.jsonl") if data_dir else None
        if self._path and os.path.exists(self._path):
            self._load()

    # -- ingestion (PeerActions.peerArrival semantics) -----------------------

    def connected(self, seed: Seed) -> None:
        """We exchanged an RPC with this peer: it is active."""
        if seed.hash == self.my_seed.hash:
            return
        with self._lock:
            seed.touch()
            seed.connects += 1
            self.passive.pop(seed.hash, None)
            self.potential.pop(seed.hash, None)
            self.active[seed.hash] = seed

    def hearsay(self, seed: Seed) -> None:
        """Seed learned from gossip: potential until we talk to it."""
        if seed.hash == self.my_seed.hash:
            return
        with self._lock:
            if seed.hash in self.active or seed.hash in self.passive:
                return
            self.potential[seed.hash] = seed

    def disconnected(self, peer_hash: bytes) -> None:
        """Peer failed to answer: demote active -> passive."""
        with self._lock:
            s = self.active.pop(peer_hash, None)
            if s is not None:
                self.passive[s.hash] = s

    # -- lookup --------------------------------------------------------------

    def get(self, peer_hash: bytes) -> Seed | None:
        with self._lock:
            return (self.active.get(peer_hash)
                    or self.passive.get(peer_hash)
                    or self.potential.get(peer_hash))

    def active_seeds(self) -> list[Seed]:
        with self._lock:
            return list(self.active.values())

    def passive_seeds(self) -> list[Seed]:
        with self._lock:
            return list(self.passive.values())

    def all_seeds(self) -> list[Seed]:
        with self._lock:
            return (list(self.active.values()) + list(self.passive.values())
                    + list(self.potential.values()))

    def sizes(self) -> dict[str, int]:
        with self._lock:
            return {"active": len(self.active), "passive": len(self.passive),
                    "potential": len(self.potential)}

    # -- persistence ---------------------------------------------------------

    def save(self) -> None:
        if not self._path:
            return
        with self._lock, open(self._path, "w", encoding="utf-8") as f:
            for table, seeds in (("active", self.active),
                                 ("passive", self.passive),
                                 ("potential", self.potential)):
                for s in seeds.values():
                    f.write(json.dumps({"t": table, "dna": s.dna()}) + "\n")

    # lint: unlocked-ok(construction-time: only __init__ calls this,
    # before the seed DB is shared with any other thread)
    def _load(self) -> None:
        with open(self._path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                    s = Seed.from_dna(rec["dna"])
                except (json.JSONDecodeError, KeyError, ValueError):
                    continue
                # all reloaded seeds start passive: liveness is re-proven by
                # the ping cycle after restart
                table = self.passive if rec.get("t") != "potential" \
                    else self.potential
                table[s.hash] = s

    def close(self) -> None:
        self.save()
