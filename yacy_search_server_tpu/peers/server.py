"""Server side of every peer RPC — the htroot/yacy/* servlet equivalents.

Capability equivalent of the reference's P2P wire endpoints (reference:
htroot/yacy/hello.java, search.java:223-430, transferRWI.java:61-287,
transferURL.java, query.java, urls.java, crawlReceipt.java,
seedlist.java). One PeerServer instance is bound to a node's subsystems
and registered with the Transport; the same handlers back the HTTP wire
endpoints in server/ so loopback tests exercise the production logic.
"""

from __future__ import annotations

import time

from ..index.metadata import (DOUBLE_FIELDS, INT_FIELDS, TEXT_FIELDS,
                              DocumentMetadata)
from ..index.postings import NF
from ..utils import fleet as fleetdigest
from ..utils import tracing
from .protocol import MAX_RWI_ENTRIES_PER_CALL, decode_postings
from .seed import Seed, SeedDB

# shed transferRWI load when the RWI RAM buffer is this full
# (reference: transferRWI.java:121 checks the word cache flush threshold)
RWI_BUFFER_SHED_FACTOR = 2.0


class PeerServer:
    """Dispatches endpoint name -> handler against one node's subsystems."""

    def __init__(self, switchboard, seeddb: SeedDB,
                 accept_remote_index: bool = True,
                 accept_remote_crawl: bool = False,
                 blacklist=None, news=None):
        self.sb = switchboard
        self.seeddb = seeddb
        self.accept_remote_index = accept_remote_index
        self.accept_remote_crawl = accept_remote_crawl
        self.blacklist = blacklist     # callable(url) -> bool (denied)
        self.news = news               # NewsPool | None
        self.received_rwi_count = 0
        self.received_url_count = 0

    # -- dispatch ------------------------------------------------------------

    def handle(self, endpoint: str, payload: dict) -> dict:
        fn = getattr(self, "do_" + endpoint, None)
        if fn is None:
            return {"error": f"unknown endpoint {endpoint}"}
        # distributed tracing: an inbound trace id (in-band from the
        # loopback/JSON wire, X-YaCy-Trace via server/httpd.py) roots
        # THIS peer's spans under the ORIGINATOR's trace — the remote
        # segment of one network-wide trace. The span carries this
        # node's identity so cross-peer assembly can attribute it.
        tid = payload.pop(tracing.PAYLOAD_KEY, None) \
            if isinstance(payload, dict) else None
        # fleet gossip (ISSUE 5): an inbound digest lands in the fleet
        # table, and a digest-bearing caller gets ours back on the same
        # reply (mutual exchange — old peers that never send a digest
        # never receive one, the version-skew contract)
        dig = payload.pop(fleetdigest.PAYLOAD_KEY, None) \
            if isinstance(payload, dict) else None
        fl = getattr(self.sb, "fleet", None)
        if dig is not None and fl is not None:
            fl.ingest(dig)
        if tid is not None and tracing.enabled():
            me = self.seeddb.my_seed
            with tracing.remote_trace(
                    str(tid), f"peer.{endpoint}",
                    peer=me.hash.decode("ascii", "replace"),
                    peer_name=me.name):
                reply = fn(payload)
        else:
            reply = fn(payload)
        if fl is not None and isinstance(dig, dict) \
                and isinstance(reply, dict):
            caller = dig.get("peer")
            if isinstance(caller, str) and caller:
                rd = fl.outgoing_digest(caller)
                if rd is not None:
                    reply = {**reply, fleetdigest.PAYLOAD_KEY: rd}
        return reply

    # -- membership ----------------------------------------------------------

    def do_hello(self, payload: dict) -> dict:
        """Ingest the caller's seed (it reached us, so it is alive) plus its
        gossip; answer with my seed + a gossip batch (hello.java)."""
        try:
            caller = Seed.from_dna(payload["seed"])
            self.seeddb.connected(caller)
        except (KeyError, ValueError):
            pass
        for dna in payload.get("seeds", []):
            try:
                self.seeddb.hearsay(Seed.from_dna(dna))
            except (KeyError, ValueError):
                continue
        me = self.seeddb.my_seed
        me.link_count = self.sb.index.doc_count()
        me.word_count = self.sb.index.rwi_size()
        reply = {"seed": me.dna(),
                 "seeds": [s.dna() for s in self.seeddb.active_seeds()[:16]]}
        if self.news is not None:
            if payload.get("news"):
                self.news.ingest_batch(payload["news"],
                                       me.hash.decode("ascii", "replace"))
            reply["news"] = self.news.outgoing_batch()
        return reply

    def do_seedlist(self, payload: dict) -> dict:
        return {"seeds": [s.dna() for s in self.seeddb.all_seeds()[:256]]}

    # -- statistics ----------------------------------------------------------

    def do_query(self, payload: dict) -> dict:
        if payload.get("object") == "rwicount":
            wh = payload.get("env", "").encode("ascii")
            return {"response": self.sb.index.rwi.count(wh)}
        if payload.get("object") == "lurlcount":
            return {"response": self.sb.index.doc_count()}
        return {"response": -1}

    # -- search (the remote side of scatter-gather) --------------------------

    def _resolve_urls(self, want_urls: list[bytes],
                      include: list[bytes]) -> list[dict]:
        """Secondary-search answer: verify each requested url hash
        against THIS peer's postings for the requested words and return
        its metadata row. Ranking is the ASKER's job (it fuses into its
        own event heap); membership is ours — a url whose docid appears
        in every requested word's postings here is exactly the
        contribution the abstract join predicted."""
        import numpy as np
        meta = self.sb.index.metadata
        plists = {wh: self.sb.index.rwi.get(wh) for wh in include}
        links = []
        for uh in want_urls:
            docid = meta.docid(uh)
            if docid is None:
                continue
            score = 0
            ok = True
            for wh, plist in plists.items():
                pos = np.flatnonzero(plist.docids == docid) \
                    if len(plist) else []
                if len(pos) == 0:
                    ok = False
                    break
                from ..index import postings as iP
                score += int(plist.feats[int(pos[0]), iP.F_HITCOUNT])
            if not ok:
                continue
            row = meta.row(docid)
            if row is None:
                continue
            links.append({
                "urlhash": uh.decode("ascii", "replace"),
                "url": row.get("sku", ""),
                "title": row.get("title", "") or row.get("sku", ""),
                "host": row.get("host_s", ""), "score": score,
                "filetype": row.get("url_file_ext_s", ""),
                "language": row.get("language_s", ""),
                "size": row.get("size_i", 0),
                "wordcount": row.get("wordcount_i", 0),
                "lastmod_days": row.get("last_modified_days_i", 0),
                "references": row.get("references_i", 0),
                "snippet": "",
            })
        return links

    def do_search(self, payload: dict) -> dict:
        """Run a local search on behalf of a remote peer
        (htroot/yacy/search.java:330 creates its own SearchEvent)."""
        from ..search.query import QueryParams
        from ..search.searchevent import SearchEvent

        include = [h.encode("ascii") for h in payload.get("query", [])]
        exclude = [h.encode("ascii") for h in payload.get("exclude", [])]
        count = min(int(payload.get("count", 10)), 100)
        q = QueryParams.parse("")          # hash-level query: no words
        q.goal.include_words = []
        q.item_count = count
        q.snippet_fetch = False
        # patch hash-level search keys in (the wire carries hashes, never
        # the words themselves — privacy property of the reference wire)
        q.goal._include_hashes_override = include
        q.goal._exclude_hashes_override = exclude
        # secondary-search constraint: only these url hashes may answer
        # (the asking peer's abstract join proved they complete a
        # cross-peer conjunction — search.java's urls parameter).
        # Capped: an unbounded list must not bypass the per-RPC work
        # clamp (the reference caps its abstracts at 512 hashes)
        want_urls = [u.encode("ascii")
                     for u in payload.get("urls", [])[:64]] or None
        if want_urls is not None:
            # resolve DIRECTLY against the index: a ranked-search fetch
            # would silently drop a join-gap url that ranks below its
            # cutoff — the exact document this request exists to recover
            links = self._resolve_urls(want_urls, include)
        else:
            ev = SearchEvent(q, self.sb.index)
            links = []
            for e in ev.results(offset=0, count=count):
                links.append({
                    "urlhash": e.urlhash.decode("ascii", "replace"),
                    "url": e.url, "title": e.title, "host": e.host,
                    "score": int(e.score), "filetype": e.filetype,
                    "language": e.language, "size": e.size,
                    "wordcount": e.wordcount,
                    "lastmod_days": e.lastmod_days,
                    "references": e.references, "snippet": e.snippet,
                })
        reply = {"joincount": (ev.local_rwi_considered
                               if want_urls is None else len(links)),
                 "links": links}
        if payload.get("abstracts") == "words":
            # per-word url-hash abstracts for the secondary join round
            # (search.java:398-427 serializes compressed abstracts)
            abstracts = {}
            for wh in include:
                plist = self.sb.index.rwi.get(wh)
                uhs = [self.sb.index.metadata.urlhash_of(int(d)).decode(
                    "ascii", "replace") for d in plist.docids[:512]]
                abstracts[wh.decode("ascii")] = uhs
            reply["abstracts"] = abstracts
        return reply

    # -- multi-process mesh runtime (ISSUE 12) -------------------------------
    # The SPMD fleet's control plane rides the SAME wire servlets as
    # every other peer RPC (/yacy/meshstep.html etc. over HTTP): the
    # coordinator's scatter, the go/no-go commit, health/introspection
    # and — in test fleets only — fault arming.  Handlers delegate to
    # the process's MeshMember (parallel/distributed.py); a node that
    # is not a mesh member answers with an error table, never a crash.

    def _mesh_member(self):
        return getattr(self.sb, "mesh_member", None)

    def do_meshstep(self, payload: dict) -> dict:
        m = self._mesh_member()
        if m is None:
            return {"error": "not a mesh member"}
        return m.enqueue_step(payload)

    def do_meshcommit(self, payload: dict) -> dict:
        m = self._mesh_member()
        if m is None:
            return {"error": "not a mesh member"}
        return m.commit_step(payload.get("seq", -1),
                             bool(payload.get("go", False)))

    def do_meshinfo(self, payload: dict) -> dict:
        m = self._mesh_member()
        if m is None:
            return {"error": "not a mesh member"}
        # tick_health (ISSUE 15): the caller drives one health-engine
        # evaluation on this member — mesh runtimes run no busy
        # threads, and the tail-forensics acceptance needs the burn
        # rules + flight recorder evaluated against live histograms
        return m.info(
            tick_health=bool(payload.get("tick_health")),
            prime_tail_gate=bool(payload.get("prime_tail_gate")))

    def do_meshsearch(self, payload: dict) -> dict:
        """External query entry on the coordinator: scatter → collective
        (or committed host fallback) → fused ranking + the pid of every
        process that took part (the CI hygiene gate asserts the set
        spans ≥ 2 OS processes)."""
        m = self._mesh_member()
        if m is None:
            return {"error": "not a mesh member"}
        if m.process_id != 0:
            return {"error": "not the coordinator"}
        term = payload.get("term", "")
        if not term:
            from ..utils.hashes import word2hash
            term = word2hash(str(payload.get("word", ""))).hex()
        from ..ops.ranking import RankingProfile
        # validate BEFORE the scatter: a malformed term/profile must be
        # one rejected request, not a step every member chokes on
        try:
            th = bytes.fromhex(term)
            prof = payload.get("profile") or \
                RankingProfile().to_external_string()
            RankingProfile.from_external_string(prof)
            # per-RPC work clamp (the reference caps every wire request)
            k = min(max(int(payload.get("k", 10)), 1), 100)
        except Exception as e:
            return {"error": f"bad mesh query: {e!r}"}
        if len(th) != 12:
            return {"error": f"term hash must be 12 bytes, got {len(th)}"}
        return m.serve_query(term, prof,
                             lang=str(payload.get("lang", "en")), k=k)

    def do_meshfault(self, payload: dict) -> dict:
        """Arm a faultinject point INSIDE this mesh member (the chaos
        harness's reach into one OS process of the fleet — how the
        device-loss survival test fails exactly ONE member's transfers).
        Gated on the YACY_MESH_TESTING env of the MEMBER process: a
        production fleet never exposes fault arming on the wire."""
        import os as _os

        from ..utils import faultinject
        if self._mesh_member() is None or \
                not _os.environ.get("YACY_MESH_TESTING"):
            return {"error": "fault arming not enabled"}
        # wire enumeration (ISSUE 19): the registry + what is armed NOW
        # + the timestamped arm/clear/expire history — the game-day
        # conductor and verdict engine read ONE source of truth instead
        # of keeping parallel bookkeeping of what they armed where
        if payload.get("list"):
            m = self._mesh_member()
            return {"result": "ok", "pid": _os.getpid(),
                    "member": m.process_id,
                    "faultpoints": sorted(
                        faultinject.REGISTERED_FAULTPOINTS),
                    "crashpoints": list(faultinject.CRASHPOINTS),
                    "armed": faultinject.snapshot(),
                    "schedule": faultinject.schedule(
                        int(payload.get("n", 0) or 0))}
        point = str(payload.get("point", ""))
        try:
            if payload.get("clear"):
                faultinject.clear(point or None)
            else:
                faultinject.set_fault(point, payload.get("value"))
        except KeyError as e:
            return {"error": str(e)}
        return {"result": "ok", "pid": _os.getpid()}

    # -- cross-peer trace assembly (ISSUE 5) ---------------------------------

    def do_tracefetch(self, payload: dict) -> dict:
        """Serve this node's retained segment of a trace out of the
        local ring by trace id, so the ORIGINATOR of a distributed
        search can assemble the full waterfall instead of rendering an
        opaque resource=global gap (client: Protocol.fetch_trace,
        merge: tracing.merge_remote_spans via P2PNode.assemble_trace)."""
        tid = str(payload.get("trace", ""))
        me = self.seeddb.my_seed
        out = {"trace_id": tid,
               "peer": me.hash.decode("ascii", "replace"),
               "root": "", "spans": [], "truncated": 0}
        if not tracing.valid_trace_id(tid):
            return out
        seg = tracing.trace_segment(tid)
        if seg is not None:
            out["root"] = seg["root"]
            out["spans"] = seg["spans"]
            out["truncated"] = seg["truncated"]
        return out

    # -- whitebox profile fetch (ISSUE 20d) ----------------------------------

    def do_profsnap(self, payload: dict) -> dict:
        """Serve this process's whitebox profile snapshot — top folded
        stacks, per-lock wait/hold table, last deep capture — so a
        coordinator convicting this member as a straggler can attach
        the member's OWN evidence to the conviction incident (client:
        Protocol.fetch_profile, hook: MeshMember._on_convicted)."""
        from ..utils import profiling
        me = self.seeddb.my_seed
        try:
            n = max(1, min(32, int(payload.get("n", 12))))
        except (TypeError, ValueError):
            n = 12
        return {"peer": me.hash.decode("ascii", "replace"),
                "name": me.name,
                "profile": profiling.snapshot(n)}

    # -- index transfer (receive) --------------------------------------------

    def do_transferRWI(self, payload: dict) -> dict:
        """Admission + store postings; reply lists unknown URLs and may ask
        the sender to pause (transferRWI.java:61-287 semantics: granted
        flag, load shedding, blacklist, storeRWI, unknownURL, pause)."""
        if not self.accept_remote_index:
            return {"result": "not granted", "unknownURL": [], "pause": 60}
        # ingest SLO stamp at WIRE ENTRY (ISSUE 15 satellite / ROADMAP
        # 3b first slice): peer-pushed postings land in the
        # ingest.searchable/.flushed/.device tiers + the burn rule like
        # locally-crawled documents.  The sender's wall-clock `stamp`
        # (riding the existing payload) back-dates the entry by the
        # wire+queue delay, clamped against clock skew; absent-stamp
        # peers anchor at this node's wire entry — tolerated, never
        # rejected.
        from ..ingest import slo as ingest_slo
        t_entry = ingest_slo.TRACKER.stamp()
        try:
            sent = float(payload.get("stamp", 0.0))
        except (TypeError, ValueError):
            sent = 0.0
        if sent > 0.0:
            t_entry -= max(0.0, min(time.time() - sent, 600.0))
        rwi = self.sb.index.rwi
        if rwi.ram_postings_count > \
                rwi.max_ram_postings * RWI_BUFFER_SHED_FACTOR:
            return {"result": "busy", "unknownURL": [], "pause": 60}

        meta = self.sb.index.metadata
        unknown: set[bytes] = set()
        received = 0
        # bounded-buffer backpressure (ISSUE 13): a DHT writer is held
        # to the same hard cap as the local indexer, but a peer handler
        # thread is not a crawler thread — it waits only briefly
        # (counted into the ingest.backpressure SLO) and then SHEDS
        # with the protocol's own busy/pause reply; the sender retries
        # after `pause`.  A full-wall wait here would pin the peer
        # server's handler threads (search scatter, digests) behind a
        # slow flush.  One admitted call's overflow is bounded by
        # MAX_RWI_ENTRIES_PER_CALL.
        if rwi.ram_postings_count >= rwi.hard_max_ram_postings():
            rwi.wait_capacity(timeout_s=2.0)
            if rwi.ram_postings_count >= rwi.hard_max_ram_postings():
                return {"result": "busy", "unknownURL": [], "pause": 60}
        entries = payload.get("entries", [])[:MAX_RWI_ENTRIES_PER_CALL]
        stamped_docs: set[bytes] = set()
        for entry in entries:
            th = entry.get("term", "").encode("ascii")
            if len(th) != 12:
                continue
            uhs, feats = decode_postings(entry.get("postings", {}))
            if feats.shape[1] != NF:
                continue
            for i, uh in enumerate(uhs):
                if received >= MAX_RWI_ENTRIES_PER_CALL:
                    break
                docid = meta.docid(uh)
                if docid is None or meta.is_deleted(docid):
                    # stub row reserves the docid; transferURL fills it in
                    docid = meta.put(DocumentMetadata(uh))
                    unknown.add(uh)
                elif not (meta.text_value(docid, "sku")):
                    unknown.add(uh)   # stub from an earlier call, still bare
                rwi.add(th, docid, feats[i])
                received += 1
                stamped_docs.add(uh)
        # one SLO stamp per received DOCUMENT (not posting): the doc is
        # searchable from the RAM buffer now, and its stamp rides the
        # pending set into the flush/device tiers like a crawled doc's
        for _uh in stamped_docs:
            ingest_slo.TRACKER.note_stored(rwi, t_entry)
        self.received_rwi_count += received
        # single-flight (ISSUE 13): a transfer racing the indexer's
        # flush skips instead of stacking a duplicate one
        rwi.maybe_flush()
        return {"result": "ok", "received": received,
                "unknownURL": [u.decode("ascii") for u in unknown],
                "pause": 0}

    def do_transferURL(self, payload: dict) -> dict:
        """Receive URL metadata for previously-unknown urlhashes
        (transferURL.java). Fills stub rows IN PLACE so postings stored
        against the stub docid stay valid."""
        meta = self.sb.index.metadata
        stored = 0
        for uh_s, fields in payload.get("rows", {}).items():
            uh = uh_s.encode("ascii")
            if self.blacklist is not None and \
                    self.blacklist(fields.get("sku", "")):
                continue
            clean = {k: v for k, v in fields.items()
                     if k in TEXT_FIELDS or k in INT_FIELDS
                     or k in DOUBLE_FIELDS}
            docid = meta.docid(uh)
            if docid is None or meta.is_deleted(docid):
                meta.put(DocumentMetadata(uh, **clean))
            else:
                meta.set_fields(docid, **clean)
            stored += 1
        self.received_url_count += stored
        return {"result": "ok", "stored": stored}

    def do_idx(self, payload: dict) -> dict:
        """Index statistics for peer-to-peer capacity planning
        (htroot/yacy/idx.java — urls/words counts per peer)."""
        return {"urls": self.sb.index.doc_count(),
                "words": self.sb.index.rwi_size(),
                "rwi_runs": self.sb.index.rwi.run_count()}

    def do_list(self, payload: dict) -> dict:
        """Share blacklist entries with peers (htroot/yacy/list.java —
        col=black returns the url blacklist for cooperative filtering).
        Only the lists NAMED in `blacklist.share.lists` leave the node
        (per-list consent, the reference's shared-list selection): a
        private list next to a shared one must never leak."""
        if payload.get("col") != "black":
            return {"list": []}
        bl = getattr(self.sb, "blacklist", None)
        shared_names = {n.strip() for n in self.sb.config.get(
            "blacklist.share.lists", "").split(",") if n.strip()}
        if bl is None or not shared_names:
            return {"list": []}
        out: list[str] = []
        for name in sorted(shared_names):   # entries([]) for unknown names
            out.extend(bl.entries(name))
        cap = 10_000
        return {"list": out[:cap], "truncated": len(out) > cap}

    # -- messages + profile ---------------------------------------------------

    MAX_MESSAGE_SIZE = 32_768
    MAX_MAILBOX_SIZE = 1000

    def do_message(self, payload: dict) -> dict:
        """Accept a peer message into the local mailbox (message.java).
        Addressed to the operator ('admin'), sender recorded as
        'name (hash)' so replies can route. Gated: the operator can turn
        messaging off, and a full mailbox refuses further inserts
        (message.java checks acceptance + advertised size first)."""
        if not self.sb.config.get_bool("messages.accept", True):
            return {"result": "rejected", "reason": "not accepted"}
        subject = str(payload.get("subject", ""))[:256]
        content = str(payload.get("content", ""))[:self.MAX_MESSAGE_SIZE]
        sender = f"{payload.get('fromname', '?')} ({payload.get('from', '')})"
        if not content:
            return {"result": "rejected", "reason": "empty"}
        if len(self.sb.messages.inbox("admin")) >= self.MAX_MAILBOX_SIZE:
            return {"result": "rejected", "reason": "mailbox full"}
        self.sb.messages.send("admin", sender, subject, content)
        return {"result": "ok"}

    def do_profile(self, payload: dict) -> dict:
        """Operator profile (profile.java) — config-backed key/value set."""
        cfg = self.sb.config
        return {"profile": {
            "name": cfg.get("promoteSearchPageGreeting", ""),
            "nickname": self.seeddb.my_seed.name,
            "homepage": cfg.get("profile.homepage", ""),
            "email": cfg.get("profile.email", ""),
            "comment": cfg.get("profile.comment", ""),
        }}

    # -- remote crawl delegation ---------------------------------------------

    def do_urls(self, payload: dict) -> dict:
        """Publish crawl work from the GLOBAL stack to a pulling peer
        (htroot/yacy/urls.java). Only nodes that opted into remote-crawl
        delegation hand out work — otherwise any peer could drain the
        GLOBAL stack of a node that never consented."""
        if not self.accept_remote_crawl:
            return {"requests": []}
        from ..crawler.frontier import StackType
        count = min(int(payload.get("count", 10)), 100)
        out = []
        for _ in range(count):
            req, _sleep = self.sb.noticed.pop(StackType.GLOBAL)
            if req is None:
                break
            out.append(req.to_dict())
        return {"requests": out}

    def do_crawlReceipt(self, payload: dict) -> dict:
        urlhash = payload.get("urlhash", "").encode("ascii")
        result = payload.get("result", "")
        if result != "fill" and urlhash:
            self.sb.crawl_queues.error_cache.push(
                urlhash, "", f"remote crawl: {payload.get('reason', result)}")
        return {"result": "ok", "delay": 10}
