"""Remote scatter-gather search — the WAN fan-out feeding a live event.

Capability equivalent of the reference's remote search (reference:
source/net/yacy/peers/RemoteSearch.java:59-468 primaryRemoteSearches —
one thread per DHT-selected peer, results merged asynchronously into the
caller's SearchEvent — and SecondarySearchSuperviser.java:198 — the
index-abstract-driven second round that closes multi-word join gaps).

Stragglers: threads run as daemons against a deadline; answers landing
after the deadline still merge into the live (cached) event — the
reference's "deadline + late-merge" paging behavior (SURVEY.md §7).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

from ..parallel.distribution import Distribution
from ..search.searchevent import ResultEntry, SearchEvent
from ..utils import tracing
from ..utils.fleet import peer_key
from .dht import select_search_targets
from .protocol import Protocol
from .seed import Seed, SeedDB


def _entries_from_links(links: list[dict], source: str) -> list[ResultEntry]:
    out = []
    for row in links:
        try:
            out.append(ResultEntry(
                docid=-1,
                urlhash=row["urlhash"].encode("ascii"),
                score=int(row.get("score", 0)),
                url=row.get("url", ""), title=row.get("title", ""),
                snippet=row.get("snippet", ""), host=row.get("host", ""),
                filetype=row.get("filetype", ""),
                language=row.get("language", ""),
                size=int(row.get("size", 0)),
                wordcount=int(row.get("wordcount", 0)),
                lastmod_days=int(row.get("lastmod_days", 0)),
                references=int(row.get("references", 0)),
                source=source))
        except (KeyError, ValueError):
            continue
    return out


class RemoteSearch:
    """Fan-out controller for one SearchEvent."""

    # adaptive per-peer timeout envelope (ISSUE 9 satellite): a derived
    # timeout is p95 x headroom, clamped into [floor, static] — the
    # static timeout_s stays both the digest-less fallback and the hard
    # ceiling (a sick peer must never get MORE budget than before)
    TIMEOUT_FLOOR_S = 0.5
    TIMEOUT_HEADROOM = 3.0

    def __init__(self, event: SearchEvent, seeddb: SeedDB,
                 dist: Distribution, protocol: Protocol,
                 redundancy: int = 3, per_peer_count: int = 10,
                 timeout_s: float = 3.0,
                 avoid_hashes: set | None = None):
        self.event = event
        self.seeddb = seeddb
        self.dist = dist
        self.protocol = protocol
        self.redundancy = redundancy
        self.per_peer_count = per_peer_count
        self.timeout_s = timeout_s
        # peers the actuator layer (utils/actuator.remote_peer_guard)
        # marked sick: digest-reported critical / wedged kernel /
        # outlier p95 — skipped by the scatter, counted per skip
        self.avoid_hashes: set[str] = set(avoid_hashes or ())
        self.peers_skipped_sick = 0
        self._threads: list[threading.Thread] = []
        # per-word abstracts harvested for the secondary round:
        # wordhash -> {urlhash -> set of peer hashes that hold it}
        self._abstracts: dict[bytes, dict[bytes, set[bytes]]] = \
            defaultdict(lambda: defaultdict(set))
        self._abs_lock = threading.Lock()
        # peers already asked in a secondary round (checkedPeers —
        # repeat rounds must not re-ask)
        self._checked_secondary: set[bytes] = set()

    # -- primary round -------------------------------------------------------

    def start(self, with_abstracts: bool | None = None,
              extra_peers: int = 8) -> int:
        """Launch one search thread per selected peer; returns peer count
        (RemoteSearch.primaryRemoteSearches:172).

        Beyond the DHT RWI targets, up to `extra_peers` further senior
        peers get a metadata search — the reference's per-peer Solr
        searches (RemoteSearch.java:282,388) that catch content living
        only in a peer's local index (robinson peers, not-yet-distributed
        postings)."""
        include = self.event.query.goal.include_hashes
        if not include:
            return 0
        targets = select_search_targets(
            self.seeddb, self.dist, include, self.redundancy)
        # avoided DHT holders are replaced, not just dropped: the extras
        # budget grows by the number of sick targets (and never offers
        # an avoided peer), so redundancy survives a sick holder set
        # instead of silently shrinking toward zero
        sick = sum(1 for t in targets
                   if peer_key(t.hash) in self.avoid_hashes)
        have = {t.hash for t in targets}
        extras = sorted(
            (s for s in self.seeddb.active_seeds()
             if s.is_senior() and s.hash not in have
             and peer_key(s.hash) not in self.avoid_hashes),
            key=lambda s: s.hash)[:extra_peers + sick]
        return self.start_fixed(targets + extras, with_abstracts)

    def start_fixed(self, targets: list[Seed],
                    with_abstracts: bool | None = None) -> int:
        """Scatter to an explicit peer set — the shared spawn loop, and the
        cluster-mode entry (reference: QueryParams.Searchdom.CLUSTER over
        the cluster allowlist)."""
        include = self.event.query.goal.include_hashes
        if not include or not targets:
            return 0
        if with_abstracts is None:
            with_abstracts = len(include) > 1
        # fleet-aware peer avoidance (ISSUE 9): peers whose gossiped
        # digests report critical health / a wedged kernel / an outlier
        # serving p95 are skipped — one sick peer must not drag every
        # global query for the full static timeout.  Every skip is
        # counted and attributable (/metrics yacy_remotesearch_peers).
        live = []
        for t in targets:
            if peer_key(t.hash) in self.avoid_hashes:
                self.peers_skipped_sick += 1
                continue
            live.append(t)
        fl = self.protocol.fleet
        if fl is not None:
            if self.peers_skipped_sick:
                fl.note_remote("skipped_sick", self.peers_skipped_sick)
            fl.note_remote("asked", len(live))
        for t in live:
            th = threading.Thread(
                target=self._one_peer, args=(t, with_abstracts),
                name=f"remotesearch-{t.name}", daemon=True)
            th.start()
            self._threads.append(th)
        self.event.remote_peers_asked += len(live)
        self.event.asked_peers.extend(live)
        return len(live)

    def _peer_timeout_s(self, target: Seed) -> float:
        """Per-peer adaptive timeout from the digest-reported RPC-wall
        p95 (with a sane floor/ceiling); the static `timeout_s` serves
        digest-less peers unchanged (ISSUE 9 satellite — was a fixed
        3.0 s for every peer regardless of its observed behavior)."""
        fl = self.protocol.fleet
        if fl is None:
            return self.timeout_s
        p95_ms = fl.peer_rpc_p95_ms(target.hash)
        if p95_ms is None:
            return self.timeout_s
        t = min(max(self.TIMEOUT_HEADROOM * p95_ms / 1000.0,
                    self.TIMEOUT_FLOOR_S), self.timeout_s)
        if t < self.timeout_s:
            # only a budget that actually DIFFERS counts as an adaptive
            # decision (a slow peer clamped back to the static ceiling
            # received nothing different)
            fl.note_remote("adaptive_timeout")
        return t

    def _one_peer(self, target: Seed, with_abstracts: bool,
                  wordhashes: list[bytes] | None = None,
                  urls: list[bytes] | None = None) -> None:
        # fan-out threads start with an empty context: parent this
        # peer's leg under the trace the event was born in, so the
        # scatter (and the wire-propagated remote segment) stays one
        # trace (utils/tracing — the span spine)
        # peer_hash on the span: the cross-peer assembly reads it back
        # to fetch trace segments from exactly the peers this search
        # actually asked (node.assemble_trace)
        with tracing.span_in(self.event.trace_ctx, "peers.remotesearch",
                             peer=target.name,
                             peer_hash=target.hash.decode("ascii",
                                                          "replace"),
                             secondary=urls is not None) as sp:
            q = self.event.query
            include = wordhashes or q.goal.include_hashes
            t0 = time.perf_counter()
            ok, reply = self.protocol.search(
                target, include, q.goal.exclude_hashes,
                count=self.per_peer_count,
                timeout_ms=int(self._peer_timeout_s(target) * 1000),
                lang=q.lang, contentdom=q.contentdom,
                with_abstracts=with_abstracts, urls=urls)
            # the fleet peer table shows each peer's last observed RPC
            # wall next to its gossiped digest (Network_Health_p)
            if ok and self.protocol.fleet is not None:
                self.protocol.fleet.note_rtt(
                    target.hash, (time.perf_counter() - t0) * 1000.0)
            sp.set(ok=ok, links=len(reply.get("links", [])) if ok else 0)
            if not ok:
                return
            entries = _entries_from_links(
                reply.get("links", []), source=target.hash.decode("ascii"))
            self.event.add_remote_results(entries)
        if with_abstracts:
            with self._abs_lock:
                for wh_s, uhs in reply.get("abstracts", {}).items():
                    wh = wh_s.encode("ascii")
                    for uh_s in uhs:
                        self._abstracts[wh][uh_s.encode("ascii")].add(
                            target.hash)

    def join(self, timeout_s: float | None = None) -> None:
        """Wait for the fan-out up to the deadline; stragglers keep running
        as daemons and late-merge into the live event."""
        deadline = timeout_s if timeout_s is not None else self.timeout_s
        import time
        t_end = time.monotonic() + deadline
        for th in self._threads:
            left = t_end - time.monotonic()
            if left <= 0:
                break
            th.join(left)

    # -- secondary round (abstract-driven join completion) -------------------

    def secondary_search(self, max_peers: int = 8) -> int:
        """Close multi-word join gaps with TARGETED per-peer requests: a
        URL listed in the abstracts of every query word — but by
        DIFFERENT peers — is a conjunctive hit no single peer could
        produce on its own. For each such peer, ask again with (a) only
        the words that peer's abstracts actually hold for its URLs and
        (b) the URL set itself as a constraint, so the peer answers
        exactly the join-gap documents (the reference's per-peer
        abstractJoin → wordsFromPeer → secondaryRemoteSearch protocol,
        SecondarySearchSuperviser.java:130-197; repeat rounds skip
        already-checked peers)."""
        include = self.event.query.goal.include_hashes
        if len(include) < 2:
            return 0
        with self._abs_lock:
            abstracts = {wh: dict(m) for wh, m in self._abstracts.items()}
        if len(abstracts) < len(include):
            return 0
        # abstract JOIN: urls present for EVERY word somewhere in the
        # network, with the combined holder set per url
        common: set[bytes] | None = None
        for wh in include:
            urls = set(abstracts.get(wh, {}).keys())
            common = urls if common is None else (common & urls)
        if not common:
            return 0
        my_hash = getattr(getattr(self.seeddb, "my_seed", None), "hash",
                          None)
        # per-PEER url targets: a peer is asked only about urls whose
        # join spans peers (a single-holder url needs no second round)
        peer_urls: dict[bytes, set[bytes]] = {}
        for uh in common:
            holders: set[bytes] = set()
            for wh in include:
                holders |= abstracts[wh].get(uh, set())
            if len(holders) <= 1:
                continue
            for ph in holders:
                if ph != my_hash:
                    peer_urls.setdefault(ph, set()).add(uh)
        started = 0
        for ph, urls in peer_urls.items():
            if started >= max_peers:
                break               # budget counts peers actually ASKED:
            #                         ineligible holders must not consume
            #                         slots, or repeat rounds starve
            if ph in self._checked_secondary:
                continue            # never ask a peer twice
            # the sick-peer guard covers the secondary round too: a
            # digest-flagged peer listed as an abstract holder would
            # otherwise drag the join round for its full timeout
            if peer_key(ph) in self.avoid_hashes:
                self.peers_skipped_sick += 1
                if self.protocol.fleet is not None:
                    self.protocol.fleet.note_remote("skipped_sick")
                continue
            seed = self.seeddb.get(ph)
            if seed is None:
                continue
            # the words THIS peer can contribute for its target urls
            words = [wh for wh in include
                     if any(ph in abstracts[wh].get(uh, ())
                            for uh in urls)]
            if not words:
                continue
            self._checked_secondary.add(ph)
            th = threading.Thread(
                target=self._one_peer,
                args=(seed, False, words, sorted(urls)),
                name=f"secondary-{seed.name}", daemon=True)
            th.start()
            self._threads.append(th)
            self.event.asked_peers.append(seed)
            started += 1
        self.event.remote_peers_asked += started
        return started
