"""Membership gossip — the peer-ping cycle.

Capability equivalent of the reference's Network busy thread (reference:
source/net/yacy/peers/Network.java:188-360 publishMySeed — hello to
bootstrap/known peers, merge returned seed views, promote/demote peer
states) plus seed-list bootstrap.
"""

from __future__ import annotations

import random

from .protocol import Protocol
from .seed import Seed, SeedDB


class Network:
    """One node's view of the P2P network + the ping job."""

    def __init__(self, seeddb: SeedDB, protocol: Protocol,
                 bootstrap: list[Seed] | None = None):
        self.seeddb = seeddb
        self.protocol = protocol
        self.bootstrap = bootstrap or []
        self.ping_rounds = 0

    def peer_ping(self, fanout: int = 4) -> int:
        """One ping cycle: hello a sample of (bootstrap | active |
        potential) peers; potential peers that answer promote to active,
        active peers that fail demote to passive (handled inside
        Protocol._call / SeedDB). Returns peers reached."""
        candidates: list[Seed] = []
        if not self.seeddb.active:
            candidates.extend(self.bootstrap)
        active = self.seeddb.active_seeds()
        random.shuffle(active)
        candidates.extend(active[:fanout])
        potential = list(self.seeddb.potential.values())
        random.shuffle(potential)
        candidates.extend(potential[:fanout])
        # passive peers get a retry chance occasionally (the reference
        # re-pings passive seeds at a lower rate)
        passive = list(self.seeddb.passive.values())
        if passive and self.ping_rounds % 4 == 0:
            candidates.append(random.choice(passive))

        reached = 0
        seen: set[bytes] = set()
        for target in candidates:
            if target.hash in seen or target.hash == self.seeddb.my_seed.hash:
                continue
            seen.add(target.hash)
            ok, _ = self.protocol.hello(target)
            if ok:
                reached += 1
        self.ping_rounds += 1
        # fleet digests ride the hellos above (Protocol._call piggyback);
        # the ping cycle is also the fleet table's staleness driver — a
        # peer that stopped answering ages out of the merged mesh view
        # on the same cadence it ages out of the seed directory
        if self.protocol.fleet is not None:
            self.protocol.fleet.evict_stale()
        return reached

    def bootstrap_from_seedlist(self, source: Seed) -> int:
        """Initial join: pull a peer directory from a principal peer."""
        seeds = self.protocol.seedlist(source)
        return len(seeds)
