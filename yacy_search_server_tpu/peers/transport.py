"""Transport abstraction + in-process loopback network.

The reference's transport is multipart HTTP POST between peers (reference:
source/net/yacy/peers/Protocol.java client side, htroot/yacy/* server
side). Here the transport is injectable: `LoopbackNetwork` delivers the
same logical RPCs in-process — the simulated multi-peer harness the
reference never had (SURVEY.md §4: "no multi-node/distributed tests and no
fake network backend") — while server/ speaks HTTP for real deployments.

Failure injection (dead peers, latency) is built in because the P2P layer
must behave under partial failure: DHT redundancy, transfer re-enqueue and
search-deadline semantics are all tested through this class.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Protocol as TProtocol


class PeerUnreachable(Exception):
    pass


class Transport(TProtocol):
    def rpc(self, target_hash: bytes, endpoint: str, payload: dict) -> dict:
        """Deliver one RPC to the peer `target_hash`; returns the reply
        table. Raises PeerUnreachable when the peer cannot be reached."""
        ...


class LoopbackNetwork:
    """In-process P2P network: peer hash -> server handler registry."""

    def __init__(self):
        self._nodes: dict[bytes, Callable[[str, dict], dict]] = {}
        self._dead: set[bytes] = set()
        self._latency_s: dict[bytes, float] = {}
        self._lock = threading.Lock()
        self.rpc_log: list[tuple[bytes, str]] = []   # (target, endpoint)

    def register(self, peer_hash: bytes,
                 handler: Callable[[str, dict], dict]) -> None:
        with self._lock:
            self._nodes[peer_hash] = handler

    def unregister(self, peer_hash: bytes) -> None:
        with self._lock:
            self._nodes.pop(peer_hash, None)

    # -- failure injection ---------------------------------------------------

    def kill(self, peer_hash: bytes) -> None:
        with self._lock:
            self._dead.add(peer_hash)

    def revive(self, peer_hash: bytes) -> None:
        with self._lock:
            self._dead.discard(peer_hash)

    def set_latency(self, peer_hash: bytes, seconds: float) -> None:
        with self._lock:
            self._latency_s[peer_hash] = seconds

    # -- delivery ------------------------------------------------------------

    def rpc(self, target_hash: bytes, endpoint: str, payload: dict) -> dict:
        with self._lock:
            handler = self._nodes.get(target_hash)
            dead = target_hash in self._dead
            delay = self._latency_s.get(target_hash, 0.0)
            self.rpc_log.append((target_hash, endpoint))
        if dead or handler is None:
            raise PeerUnreachable(target_hash.decode("ascii", "replace"))
        if delay:
            time.sleep(delay)
        return handler(endpoint, payload)


class HttpTransport:
    """Real-socket transport: JSON POST to the target's /yacy/<endpoint>
    wire servlet — the DCN leg of the communication backend (reference:
    Protocol.java posts multipart forms to <peer>/yacy/<endpoint>.html;
    here the body is one JSON table, same logical message set).

    Address resolution: explicit address book first (bootstrap), then the
    `resolver` callable (normally backed by the node's SeedDB, whose seed
    DNA gossips IP:port exactly as the reference's does). A handler
    registered locally short-circuits in-process — rpc-to-self never
    touches a socket.
    """

    def __init__(self, resolver: Callable[[bytes], str | None] | None = None,
                 timeout_s: float = 10.0):
        self._local: dict[bytes, Callable[[str, dict], dict]] = {}
        self._addresses: dict[bytes, str] = {}
        self.resolver = resolver
        self.timeout_s = timeout_s
        self._lock = threading.Lock()

    def register(self, peer_hash: bytes,
                 handler: Callable[[str, dict], dict]) -> None:
        with self._lock:
            self._local[peer_hash] = handler

    def unregister(self, peer_hash: bytes) -> None:
        with self._lock:
            self._local.pop(peer_hash, None)

    def set_address(self, peer_hash: bytes, base_url: str) -> None:
        with self._lock:
            self._addresses[peer_hash] = base_url.rstrip("/")

    def _resolve(self, peer_hash: bytes) -> str | None:
        with self._lock:
            addr = self._addresses.get(peer_hash)
        if addr:
            return addr
        return self.resolver(peer_hash) if self.resolver else None

    def rpc(self, target_hash: bytes, endpoint: str, payload: dict) -> dict:
        import json as _json
        import urllib.request
        with self._lock:
            handler = self._local.get(target_hash)
        if handler is not None:
            return handler(endpoint, payload)
        base = self._resolve(target_hash)
        if not base:
            raise PeerUnreachable(target_hash.decode("ascii", "replace"))
        # the trace id travels as a real HTTP header on the wire (the
        # server side parses X-YaCy-Trace back into the payload); keep
        # the JSON body free of transport concerns
        from ..utils import tracing
        headers = {"Content-Type": "application/json"}
        tid = payload.get(tracing.PAYLOAD_KEY)
        if tid is not None:
            payload = {k: v for k, v in payload.items()
                       if k != tracing.PAYLOAD_KEY}
            if tracing.valid_trace_id(tid):
                headers[tracing.TRACE_HEADER] = tid
        body = _json.dumps(payload).encode("utf-8")
        req = urllib.request.Request(
            f"{base}/yacy/{endpoint}.html", data=body, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                reply = _json.loads(r.read().decode("utf-8"))
        except Exception as e:
            raise PeerUnreachable(f"{target_hash!r}: {e}") from e
        return reply
