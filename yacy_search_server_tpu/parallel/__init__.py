"""Distribution & device-mesh layer: DHT math, meshes, sharded execution."""
