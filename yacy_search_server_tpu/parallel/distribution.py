"""DHT partition math — horizontal (term) ring x vertical (doc) partitions.

Bit-compatible re-implementation of the reference's partition model
(reference: source/net/yacy/cora/federate/yacy/Distribution.java:35-93):

- horizontal position: base64 cardinal of the word hash -> [0, 2^63)
- ring distance: closed-at-the-end cardinal distance
- vertical partitions: 2^e sub-shards selected by the *url* hash, so one
  url's postings land on the same vertical position for every word.

TPU-first additions: bulk numpy projections for whole postings batches
(used when routing an index-transfer buffer) and the mapping of the
vertical axis onto a device-mesh axis (parallel/mesh.py) — the 16 vertical
partitions of the freeworld network become 16-way data parallelism at
query time.
"""

from __future__ import annotations

import numpy as np

from ..utils.base64order import enhanced_coder

LONG_MAX = (1 << 63) - 1


def horizontal_dht_position(word_hash: bytes) -> int:
    """Word hash -> cardinal ring position in [0, 2^63)."""
    return enhanced_coder.cardinal(word_hash)


def horizontal_dht_distance(from_pos: int, to_pos: int) -> int:
    """Closed-ring distance from `from_pos` forward to `to_pos`."""
    if to_pos >= from_pos:
        return to_pos - from_pos
    return (LONG_MAX - from_pos) + to_pos + 1


def horizontal_positions_bulk(word_hashes: np.ndarray) -> np.ndarray:
    """uint8 [n, 12] hash array -> int64 [n] ring positions."""
    return enhanced_coder.cardinal_array(word_hashes)


class Distribution:
    """Vertical (doc-hash) partitioning on top of the horizontal ring."""

    def __init__(self, vertical_partition_exponent: int):
        self.vertical_partition_exponent = vertical_partition_exponent
        self.partition_count = 1 << vertical_partition_exponent
        self.shift_length = 63 - vertical_partition_exponent
        self.partition_size = 1 << self.shift_length
        self.partition_mask = self.partition_size - 1

    def vertical_partitions(self) -> int:
        return self.partition_count

    def vertical_dht_partition(self, url_hash: bytes) -> int:
        """Which of the 2^e vertical partitions this url belongs to."""
        return int(enhanced_coder.cardinal(url_hash) >> self.shift_length)

    def vertical_dht_position(self, word_hash: bytes, vertical_partition: int) -> int:
        """Ring position of (word, partition): word position folded into the
        partition's segment of the ring."""
        h = horizontal_dht_position(word_hash)
        return (h & self.partition_mask) | (vertical_partition << self.shift_length)

    def vertical_partitions_bulk(self, url_hashes: np.ndarray) -> np.ndarray:
        """uint8 [n, 12] url-hash array -> int32 [n] partition ids.

        This is the routing primitive of the DHT dispatcher: one call
        splits a whole postings container by target partition
        (replacing the reference's per-entry splitContainer loop,
        peers/Dispatcher.java:234).
        """
        pos = enhanced_coder.cardinal_array(url_hashes)
        return (pos >> self.shift_length).astype(np.int32)
