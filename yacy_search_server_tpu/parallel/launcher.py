"""Mesh fleet launcher + supervision harness (ISSUE 12).

One command brings a multi-process SPMD mesh up from nothing::

    python -m yacy_search_server_tpu.parallel.launcher --procs 3

The launcher finds free ports, spawns one child interpreter per mesh
process (``python -m yacy_search_server_tpu.parallel.distributed`` with
the ``YACY_MESH_*`` env contract — XLA flags land in the environment
BEFORE the child's jax initializes, which is the only reliable way to
size the per-process CPU device pool), waits for every member's HTTP
face to answer, and supervises:

* **watchdog/reaper** — children run in their own process group; ANY
  failure path (exception during bring-up, test error, supervisor
  exit) kills the whole group with TERM→KILL escalation, and an atexit
  hook backstops even that.  Children additionally watch their parent
  pid and exit on reparenting, so an orphaned fleet cannot outlive a
  SIGKILLed supervisor.
* **liveness** — `poll()` reaps exited children and reports who died;
  `kill_member()` is the chaos-harness surface for the survival tests.

The fleet object is also the client: `search()` POSTs to the
coordinator's ``/yacy/meshsearch.html`` wire servlet (the same JSON
wire every peer RPC uses), `info()`/`fault()` hit the members directly.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

from . import distributed as D

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _free_ports(n: int) -> list[int]:
    """Bind-then-release n distinct ephemeral ports (the standard
    small-race pattern; children bind immediately after spawn)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _post(url: str, payload: dict, timeout_s: float = 30.0,
          headers: dict | None = None) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return json.loads(r.read().decode("utf-8"))


def _post_ex(url: str, payload: dict, timeout_s: float = 30.0,
             headers: dict | None = None) -> tuple[int, dict]:
    """Status-capturing POST: an admission 429 (or any HTTP error) is a
    RESULT the game-day workload records, not an exception — the
    availability gate is 'degraded + counted, never 500'."""
    try:
        return 200, _post(url, payload, timeout_s, headers)
    except urllib.error.HTTPError as e:
        try:
            body = e.read().decode("utf-8", "replace")
        except OSError:
            body = ""
        return e.code, {"error": body[:200]}


class MeshFleet:
    """Supervisor + client for one multi-process mesh."""

    def __init__(self, procs: int = 2, local_devices: int = 2,
                 ndocs: int = 512, seed: int = 3, n_term: int = 1,
                 run_dir: str | None = None, testing: bool = True,
                 bringup_timeout_s: float = 120.0,
                 config: dict | None = None):
        assert procs >= 2, "a multi-process mesh needs >= 2 processes"
        self.procs = procs
        self.local_devices = local_devices
        self.children: list[subprocess.Popen] = []
        self.run_dir = run_dir
        self._closed = False
        coord_port, *self.http_ports = _free_ports(procs + 1)
        self.logs: list[str] = []
        env_common = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count="
                         f"{local_devices}",
            "PYTHONPATH": _REPO_ROOT + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
            D.ENV_COORDINATOR: f"127.0.0.1:{coord_port}",
            D.ENV_NPROCS: str(procs),
            D.ENV_LOCAL_DEVICES: str(local_devices),
            D.ENV_HTTP_PORTS: ",".join(str(p) for p in self.http_ports),
            D.ENV_NDOCS: str(ndocs),
            D.ENV_SEED: str(seed),
            D.ENV_NTERM: str(n_term),
        }
        if testing:
            env_common[D.ENV_TESTING] = "1"
        if config:
            # construction-time knobs for every member's Switchboard
            # (incident cooldown, admission burst, conviction windows —
            # things the engines read once; see Config.__init__)
            env_common["YACY_CONFIG_OVERRIDES"] = ",".join(
                f"{k}={v}" for k, v in sorted(config.items()))
        atexit.register(self.close)
        try:
            for i in range(procs):
                env = dict(env_common)
                env[D.ENV_PROC_ID] = str(i)
                if run_dir:
                    mdir = os.path.join(run_dir, f"member{i}")
                    # fresh slate: a reused run dir would load last
                    # run's persisted index UNDER the deterministic
                    # corpus ingest — duplicate postings, divergent
                    # rankings (the SPMD corpus contract is per-run)
                    import shutil
                    shutil.rmtree(os.path.join(mdir, "DATA"),
                                  ignore_errors=True)
                    os.makedirs(mdir, exist_ok=True)
                    env[D.ENV_DATA_DIR] = os.path.join(mdir, "DATA")
                    logf = open(os.path.join(mdir, "member.log"), "wb")
                    self.logs.append(logf.name)
                else:
                    logf = subprocess.DEVNULL
                try:
                    self.children.append(subprocess.Popen(
                        [sys.executable, "-m",
                         "yacy_search_server_tpu.parallel.distributed"],
                        env=env, cwd=_REPO_ROOT,
                        stdout=logf, stderr=subprocess.STDOUT,
                        start_new_session=True))
                finally:
                    # Popen dup'd the fd into the child; the parent's
                    # handle would otherwise leak one fd per member per
                    # fleet in a long-lived supervisor
                    if logf is not subprocess.DEVNULL:
                        logf.close()
            self._wait_ready(bringup_timeout_s)
        except Exception:
            self.close()
            raise

    # -- supervision ---------------------------------------------------------

    def _wait_ready(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        fps = {}
        for i, port in enumerate(self.http_ports):
            while True:
                dead = self.poll()
                if dead:
                    raise RuntimeError(
                        f"mesh member(s) {dead} died during bring-up "
                        f"(logs: {self.logs})")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"member {i} not ready in {timeout_s}s "
                        f"(logs: {self.logs})")
                try:
                    info = self.info(i, timeout_s=5.0)
                    if info.get("ready"):
                        fps[i] = info.get("fp")
                        break
                except Exception:
                    time.sleep(0.3)
        # the partition-math determinism assertion (ISSUE 12 satellite):
        # every process must place every (term, doc) cell identically
        if len(set(fps.values())) != 1:
            raise RuntimeError(
                f"partition fingerprints diverge across processes: {fps}")
        self.fingerprint = fps[0]

    def poll(self) -> list[int]:
        """Reap exited children; returns the ids of the dead."""
        return [i for i, c in enumerate(self.children)
                if c.poll() is not None]

    def kill_member(self, i: int, sig=signal.SIGKILL) -> None:
        """Chaos surface: hard-kill one mesh process mid-soak."""
        try:
            os.kill(self.children[i].pid, sig)
        except ProcessLookupError:
            pass

    def close(self) -> None:
        """The any-failure-path reaper: TERM the whole process group of
        every child, escalate to KILL, and wait() each so no zombie —
        and no orphaned grandchild — survives the supervisor."""
        if self._closed:
            return
        self._closed = True
        for c in self.children:
            if c.poll() is None:
                try:
                    os.killpg(os.getpgid(c.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.monotonic() + 5.0
        for c in self.children:
            while c.poll() is None and time.monotonic() < deadline:
                time.sleep(0.1)
            if c.poll() is None:
                try:
                    os.killpg(os.getpgid(c.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            try:
                c.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass

    def __enter__(self) -> "MeshFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- client --------------------------------------------------------------

    def _url(self, i: int, endpoint: str) -> str:
        return f"http://127.0.0.1:{self.http_ports[i]}/yacy/" \
               f"{endpoint}.html"

    def search(self, word: str, k: int = 10,
               timeout_s: float = 90.0) -> dict:
        """One query through the coordinator's wire entry: scatter →
        cross-process collective (or committed host fallback) → fused
        ranking."""
        return _post(self._url(0, "meshsearch"),
                     {"word": word, "k": k}, timeout_s=timeout_s)

    def search_ex(self, word: str, k: int = 10,
                  timeout_s: float = 90.0,
                  client: str | None = None) -> tuple[int, dict]:
        """Status-capturing search with an optional per-client identity
        (X-Forwarded-For from loopback — the game-day workload realism
        layer, so token buckets/admission key on the synthetic client
        instead of the universally-exempt 127.0.0.1)."""
        hdrs = {"X-Forwarded-For": client} if client else None
        return _post_ex(self._url(0, "meshsearch"), {"word": word,
                        "k": k}, timeout_s=timeout_s, headers=hdrs)

    def get(self, i: int, page: str, timeout_s: float = 30.0,
            client: str | None = None) -> tuple[int, float]:
        """One regular-servlet GET against member `i` (status,
        wall_ms): the game-day driver for the servlet.serving SLO wall
        — the mesh wire entry bypasses the regular dispatch where that
        failpoint lives."""
        url = f"http://127.0.0.1:{self.http_ports[i]}/{page}"
        req = urllib.request.Request(
            url, headers={"X-Forwarded-For": client} if client else {})
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                r.read()
                code = r.status
        except urllib.error.HTTPError as e:
            e.read()
            code = e.code
        return code, (time.perf_counter() - t0) * 1000.0

    def info(self, i: int, timeout_s: float = 30.0,
             tick_health: bool = False,
             prime_tail_gate: bool = False) -> dict:
        """Member introspection; `tick_health=True` additionally drives
        one health-engine evaluation on the member (the tail-forensics
        harness's incident driver — mesh members run no busy threads);
        `prime_tail_gate=True` drops every histogram family's windowed
        samples so compile-era warmup walls cannot hold the tail
        classifier's cached-p95 exemplar gate (or the SLO burn
        windows) above the live workload (the game-day
        warmup/measurement boundary)."""
        payload: dict = {}
        if tick_health:
            payload["tick_health"] = 1
        if prime_tail_gate:
            payload["prime_tail_gate"] = 1
        return _post(self._url(i, "meshinfo"), payload,
                     timeout_s=timeout_s)

    def fault(self, i: int, point: str, value,
              clear: bool = False) -> dict:
        return _post(self._url(i, "meshfault"),
                     {"point": point, "value": value, "clear": clear})

    def fault_list(self, i: int, n: int = 0) -> dict:
        """Member `i`'s faultinject registry + armed snapshot + the
        timestamped arm/clear/expire schedule (ISSUE 19: the verdict
        engine's one source of truth)."""
        return _post(self._url(i, "meshfault"), {"list": 1, "n": n})


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="bring up a multi-process SPMD mesh (ISSUE 12)")
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--local-devices", type=int, default=2)
    ap.add_argument("--ndocs", type=int, default=512)
    ap.add_argument("--n-term", type=int, default=1)
    ap.add_argument("--run-dir", default=None)
    ap.add_argument("--query", default="meshterm",
                    help="smoke query served after bring-up")
    ap.add_argument("--serve", action="store_true",
                    help="keep the fleet up until Ctrl-C")
    args = ap.parse_args(argv)
    with MeshFleet(procs=args.procs, local_devices=args.local_devices,
                   ndocs=args.ndocs, n_term=args.n_term,
                   run_dir=args.run_dir) as fleet:
        print(f"mesh up: {args.procs} processes x "
              f"{args.local_devices} devices, fp={fleet.fingerprint}")
        for i in range(args.procs):
            info = fleet.info(i)
            print(f"  member {i}: pid={info['pid']} "
                  f"http={fleet.http_ports[i]}")
        rep = fleet.search(args.query)
        print(f"query '{args.query}': mode={rep['mode']} "
              f"top={rep['docids'][:5]} pids={sorted(rep['pids'].values())}")
        if args.serve:
            print("serving; Ctrl-C to stop")
            try:
                while not fleet.poll():
                    time.sleep(1.0)
            except KeyboardInterrupt:
                pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
