"""Device-mesh query execution — the DHT axes as a 2-D TPU mesh.

TPU-first re-design of the reference's inter-node parallelism
(reference: source/net/yacy/cora/federate/yacy/Distribution.java:35-93 —
horizontal term ring x vertical doc partitions; scatter-gather merge in
source/net/yacy/search/query/SearchEvent.java:444-497 and
peers/RemoteSearch.java:172). Instead of one thread per remote peer feeding
a bounded heap, a query executes as ONE jitted SPMD program over a
`jax.sharding.Mesh` with axes:

    term : horizontal DHT axis — query-term columns of the dense tf block
           (BM25 partial scores combine with a psum over this axis)
    doc  : vertical DHT axis — postings rows partitioned by url-hash
           (normalization stats combine with pmin/pmax/psum; candidates
           combine with all_gather + global top-k)

so the reference's per-peer heap inserts become ICI collectives: the
"16 vertical partitions" of the freeworld network are 16-way `doc`
parallelism, and redundancy groups become replica submeshes. The WAN peer
layer (peers/) reuses the same fusion kernel for asynchronous remote
results.

Parity contract: the sharded kernels reuse ops/ranking.local_stats /
cardinal_from_stats, merging the shard-local statistics with
lax.pmin/pmax/psum — results are identical to the single-device
CardinalRanker (tested on the 8-device virtual CPU mesh).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as PS

from ..index import postings as P
from ..ops import ranking as R

NEG_INF_I32 = -(2**31 - 1)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable shard_map: `jax.shard_map` (jax >= 0.5, `check_vma`
    kwarg) with a fallback to `jax.experimental.shard_map` (0.4.x, where
    the same knob is spelled `check_rep`)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def best_devices(need: int | None = None, prefer_cpu: bool = False):
    """Device pool for an n-way mesh.

    Default policy: the default backend, falling back to the virtual CPU
    pool when the default backend has fewer devices than requested
    (single-chip dev box with xla_force_host_platform_device_count set —
    the documented test pattern for multi-chip shardings).

    prefer_cpu=True inverts the preference: take the CPU pool whenever it
    satisfies `need` (the driver's multichip dryrun contract — CPU
    validation that must not couple to default-backend health)."""
    try:
        cpu = jax.devices("cpu")
    except RuntimeError:
        cpu = []
    if prefer_cpu and need is not None and len(cpu) >= need:
        return cpu
    devs = jax.devices()
    if need is not None and len(devs) < need and len(cpu) >= need:
        devs = cpu
    return devs


def make_mesh(n_doc: int | None = None, n_term: int = 1,
              devices=None) -> Mesh:
    """Build a ('term', 'doc') mesh; defaults to all devices on one doc axis."""
    need = n_term * n_doc if n_doc is not None else None
    devs = np.asarray(devices if devices is not None else best_devices(need))
    if n_doc is None:
        n_doc = len(devs) // n_term
    use = devs[: n_term * n_doc].reshape(n_term, n_doc)
    return Mesh(use, axis_names=("term", "doc"))


def pad_to_shards(n: int, shards: int, tile: int = 128) -> int:
    """Round n up so every shard holds a whole number of tiles (min 1)."""
    per = max(tile, ((n + shards - 1) // shards + tile - 1) // tile * tile)
    return per * shards


# ---------------------------------------------------------------------------
# Fused all-gather + top-k — the candidate-fusion collective (ISSUE 12b)
# ---------------------------------------------------------------------------
# The TPU replacement of the reference's per-peer heap-insert merge
# (SearchEvent.java:444-497), factored out of the shard bodies so every
# fusion site shares ONE implementation — and ONE tie discipline.  Each
# shard contributes only its exact local top-k (the meshstore docstring's
# exactness argument: an exact local top-k per shard makes the gathered
# merge exact), so the collective moves k rows per shard, never full
# score rows.  The merge is pinned to (score DESC, docid ASC) — the
# two-key lax.sort idiom the rerank/ANN family pinned node-locally
# (arxiv 1807.05798) — so equal-score candidates arriving from
# DIFFERENT shards (or, through parallel/distributed.py, different OS
# processes) fuse in one deterministic order instead of gather-position
# order, which would flap with the mesh layout.


def tie_topk(scores, docids, k: int):
    """Exact top-k of (scores, docids) under (score DESC, docid ASC).

    Two-key ascending sort on (-score, docid); works for int32 cardinal
    scores and float32 BM25 scores alike (pad rows carry -inf/NEG_INF
    scores, so they sort last regardless of their docid)."""
    _sk, _tk, s, d = lax.sort((-scores, docids, scores, docids),
                              num_keys=2)
    kk = min(k, s.shape[0])
    return s[:kk], d[:kk]


def all_gather_topk(local_s, local_d, axes, k: int):
    """Fused candidate-fusion collective, `lax` implementation: gather
    each shard's (already exact, already tie-ordered) local top-k along
    `axes` and merge under the pinned tie discipline.  Gathered bytes
    scale with k·n_shards (8 B per candidate), not with corpus rows —
    the cost model in ops/roofline.KERNELS counts exactly that."""
    gs = lax.all_gather(local_s, axes, tiled=True)
    gd = lax.all_gather(local_d, axes, tiled=True)
    return tie_topk(gs, gd, k)


def all_gather_topk_full(local_s, local_d, axes):
    """Variant returning the WHOLE tie-ordered gather (no trim): the
    delta-carrying meshstore path needs every gathered row so host-side
    dedup still has k unique docids left."""
    gs = lax.all_gather(local_s, axes, tiled=True)
    gd = lax.all_gather(local_d, axes, tiled=True)
    return tie_topk(gs, gd, gs.shape[0])


def _all_gather_topk_pallas(local_s, local_d, axis, k: int, ndev: int,
                            axis_names: tuple = ()):
    """Pallas remote-DMA variant of the fusion collective for TPU ICI
    (SNIPPETS [1] / pallas guide "Ring All-Gather"): each device's
    (k, 2) candidate block rides `make_async_remote_copy` around the
    ring — double-buffered send/recv slots, DMA semaphores in scratch —
    and the merge reuses the SAME tie_topk epilogue, so the two
    implementations cannot diverge on discipline.  Only reachable when
    the mesh devices are TPU (gate in fused_gather_topk); elsewhere the
    lax path above is the product path."""
    import functools

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def ring_kernel(block_ref, out_ref, comm_ref, send_sem, recv_sem,
                    *, ndev: int):
        my_id = lax.axis_index(axis)
        out_ref[pl.ds(my_id * block_ref.shape[0], block_ref.shape[0])] \
            = block_ref[:]
        comm_ref[0] = block_ref[:]
        for step in range(ndev - 1):
            src_device = (my_id - step - 1) % ndev
            dst_device = (my_id + 1) % ndev
            send_slot = step % 2
            recv_slot = (step + 1) % 2
            # full logical mesh coordinates: the fusion axis carries the
            # ring neighbor, every other axis is size 1 (the dispatch
            # gate guarantees it), so its coordinate is 0
            coords = tuple(dst_device if n == axis else 0
                           for n in (axis_names or (axis,)))
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_ref.at[send_slot],
                dst_ref=comm_ref.at[recv_slot],
                send_sem=send_sem.at[send_slot],
                recv_sem=recv_sem.at[recv_slot],
                device_id=coords,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait()
            out_ref[pl.ds(src_device * block_ref.shape[0],
                          block_ref.shape[0])] = comm_ref[recv_slot]

    kk = local_s.shape[0]
    # scores bit-cast next to docids: ONE (k, 2) int32 block per hop
    block = jnp.stack(
        [lax.bitcast_convert_type(local_s.astype(jnp.float32), jnp.int32)
         if local_s.dtype != jnp.int32 else local_s,
         local_d], axis=1)
    gathered = pl.pallas_call(
        functools.partial(ring_kernel, ndev=ndev),
        out_shape=jax.ShapeDtypeStruct((ndev * kk, 2), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, kk, 2), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(block)
    gs = gathered[:, 0] if local_s.dtype == jnp.int32 else \
        lax.bitcast_convert_type(gathered[:, 0], jnp.float32)
    return tie_topk(gs, gathered[:, 1], k)


def fused_gather_topk(local_s, local_d, axes, k: int,
                      mesh: Mesh | None = None):
    """Dispatch the fusion collective — the PRODUCT entry point of the
    single-axis shard bodies (`_cardinal_shard`, `_bm25_shard`): the
    Pallas remote-DMA ring when the fusion axis spans a TPU ICI mesh
    (every other axis size 1, so the ring IS the device ring), the
    `lax` all-gather everywhere else — CPU meshes, multi-process
    DCN-backed meshes, and the meshstore's two-axis ('term','doc')
    fusions, which are lax-by-design (a ring is a one-axis
    collective)."""
    use_pallas = (mesh is not None and isinstance(axes, str)
                  and all(d.platform == "tpu"
                          for d in mesh.devices.flat)
                  and mesh.shape[axes] == mesh.devices.size)
    if use_pallas:
        try:
            return _all_gather_topk_pallas(local_s, local_d, axes, k,
                                           mesh.shape[axes],
                                           tuple(mesh.axis_names))
        except Exception:   # pragma: no cover - TPU-only path
            import logging
            logging.getLogger("parallel.mesh").exception(
                "pallas fusion collective failed; lax fallback")
    return all_gather_topk(local_s, local_d, axes, k)


# ---------------------------------------------------------------------------
# Sharded cardinal ranking (ReferenceOrder.cardinal over the doc axis)
# ---------------------------------------------------------------------------

def _cardinal_shard(feats, docids, valid, hostids, norm_coeffs, flag_bits,
                    flag_shifts, domlength_coeff, tf_coeff, language_coeff,
                    authority_coeff, language_pref, *, k: int,
                    num_hosts: int, mesh: Mesh | None = None):
    st = R.local_stats(feats, valid, hostids, num_hosts=num_hosts)
    st = {
        "col_min": lax.pmin(st["col_min"], "doc"),
        "col_max": lax.pmax(st["col_max"], "doc"),
        "tf_min": lax.pmin(st["tf_min"], "doc"),
        "tf_max": lax.pmax(st["tf_max"], "doc"),
        "host_counts": lax.psum(st["host_counts"], "doc"),
    }
    scores = R.cardinal_from_stats(
        feats, valid, hostids, st, norm_coeffs, flag_bits, flag_shifts,
        domlength_coeff, tf_coeff, language_coeff, authority_coeff,
        language_pref)
    # local EXACT top-k under the pinned tie discipline, then the fused
    # all-gather+top-k collective — k rows per shard cross the
    # interconnect, the TPU replacement of the reference's per-peer
    # heap-insert merge (heap semantics: only each peer's best k travel)
    local_s, local_d = tie_topk(scores, docids, min(k, scores.shape[0]))
    return fused_gather_topk(local_s, local_d, "doc", k, mesh=mesh)


def build_sharded_cardinal(mesh: Mesh, k: int, num_hosts: int):
    """jit-compiled sharded cardinal+top-k over `mesh` ('doc' axis)."""
    fn = shard_map(
        partial(_cardinal_shard, k=k, num_hosts=num_hosts, mesh=mesh),
        mesh=mesh,
        in_specs=(PS("doc"), PS("doc"), PS("doc"), PS("doc"),
                  PS(), PS(), PS(), PS(), PS(), PS(), PS(), PS()),
        out_specs=(PS(), PS()),
        check_vma=False,  # outputs are replicated by the all_gather+top_k
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Sharded BM25 (dense doc x term block over the full 2-D mesh)
# ---------------------------------------------------------------------------

def _bm25_shard(tf, doclen, df, ndocs, valid, docids, *, k: int,
                k1: float, b: float, mesh: Mesh | None = None):
    tf = tf.astype(jnp.float32)
    dl = doclen.astype(jnp.float32)
    sum_dl = lax.psum(jnp.sum(jnp.where(valid, dl, 0.0)), "doc")
    cnt = lax.psum(jnp.sum(valid.astype(jnp.float32)), "doc")
    avgdl = sum_dl / jnp.maximum(cnt, 1.0)
    idf = jnp.log(1.0 + (ndocs.astype(jnp.float32) - df + 0.5) / (df + 0.5))
    denom = tf + k1 * (1.0 - b + b * (dl / jnp.maximum(avgdl, 1e-6))[:, None])
    partial_score = jnp.sum(
        idf[None, :] * tf * (k1 + 1.0) / jnp.maximum(denom, 1e-9), axis=1)
    score = lax.psum(partial_score, "term")
    score = jnp.where(valid, score, -jnp.inf)
    local_s, local_d = tie_topk(score, docids, min(k, score.shape[0]))
    return fused_gather_topk(local_s, local_d, "doc", k, mesh=mesh)


def build_sharded_bm25(mesh: Mesh, k: int, k1: float = 1.2, b: float = 0.75):
    """jit-compiled sharded BM25+top-k over the ('term','doc') mesh."""
    fn = shard_map(
        partial(_bm25_shard, k=k, k1=k1, b=b, mesh=mesh),
        mesh=mesh,
        in_specs=(PS("doc", "term"), PS("doc"), PS("term"), PS(),
                  PS("doc"), PS("doc")),
        out_specs=(PS(), PS()),
        check_vma=False,  # outputs are replicated by the all_gather+top_k
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Host-side wrappers
# ---------------------------------------------------------------------------

class MeshRanker:
    """Sharded CardinalRanker: pad to shard tiles, place, run, trim.

    The mesh analog of ops/ranking.CardinalRanker; used by the sharded
    segment store and by bench config #3 (8-way sharded BM25/cardinal).
    """

    def __init__(self, mesh: Mesh, profile: R.RankingProfile | None = None,
                 language: str = "en"):
        self.mesh = mesh
        self.n_doc = mesh.shape["doc"]
        self.profile = profile or R.RankingProfile()
        # Every constant is pinned to the mesh's devices with an explicit
        # replicated sharding.  A bare jnp.asarray/jnp.int32 would place on
        # the DEFAULT backend — which may be a (possibly broken/busy) TPU
        # while the mesh is the virtual CPU pool, hermetically coupling a
        # CPU dryrun to TPU health.
        rep = NamedSharding(mesh, PS())
        put = lambda a: jax.device_put(np.asarray(a), rep)  # noqa: E731
        self._norm = put(self.profile.norm_coeffs())
        bits, shifts = self.profile.flag_coeffs()
        self._bits, self._shifts = put(bits), put(shifts)
        self._dl = put(np.int32(self.profile.domlength))
        self._tf = put(np.int32(self.profile.tf))
        self._lang_c = put(np.int32(self.profile.language))
        self._auth = put(np.int32(self.profile.authority))
        self._lang = put(np.int32(P.pack_language(language)))
        self._fns: dict[tuple[int, int], object] = {}

    def _fn(self, k: int, num_hosts: int):
        key = (k, num_hosts)
        if key not in self._fns:
            self._fns[key] = build_sharded_cardinal(self.mesh, k, num_hosts)
        return self._fns[key]

    def place(self, plist: "P.PostingsList", hosthashes=None):
        """Pad + device_put a PostingsList across the doc axis; returns the
        device-resident tuple reused across queries (steady-state path)."""
        n = len(plist)
        npad = pad_to_shards(max(n, 1), self.n_doc)
        feats = np.zeros((npad, P.NF), np.int32)
        docids = np.full(npad, -1, np.int32)
        valid = np.zeros(npad, bool)
        hostids = np.zeros(npad, np.int32)
        if n:
            feats[:n] = plist.feats
            docids[:n] = plist.docids
            valid[:n] = True
            if hosthashes is not None:
                hostids[:n] = R.hostid_array(plist.docids, hosthashes)
        sh_doc = NamedSharding(self.mesh, PS("doc"))
        sh_doc2 = NamedSharding(self.mesh, PS("doc", None))
        return (jax.device_put(feats, sh_doc2),
                jax.device_put(docids, sh_doc),
                jax.device_put(valid, sh_doc),
                jax.device_put(hostids, sh_doc),
                npad)

    def rank_placed(self, placed, k: int = 10):
        feats, docids, valid, hostids, npad = placed
        fn = self._fn(k, npad)
        s, d = fn(feats, docids, valid, hostids, self._norm, self._bits,
                  self._shifts, self._dl, self._tf, self._lang_c, self._auth,
                  self._lang)
        s, d = np.asarray(s), np.asarray(d)
        keep = (d >= 0) & (s > NEG_INF_I32)
        return s[keep][:k], d[keep][:k]

    def rank(self, plist: "P.PostingsList", hosthashes=None, k: int = 10):
        return self.rank_placed(self.place(plist, hosthashes), k=k)


class MeshBM25:
    """Sharded BM25 over a dense [docs, terms] tf block on the 2-D mesh."""

    def __init__(self, mesh: Mesh, k1: float = 1.2, b: float = 0.75):
        self.mesh = mesh
        self.n_doc = mesh.shape["doc"]
        self.n_term = mesh.shape["term"]
        self.k1, self.b = k1, b
        self._fns: dict[int, object] = {}

    def _fn(self, k: int):
        if k not in self._fns:
            self._fns[k] = build_sharded_bm25(self.mesh, k, self.k1, self.b)
        return self._fns[k]

    def place(self, tf: np.ndarray, doclen: np.ndarray, df: np.ndarray,
              ndocs: int, docids: np.ndarray):
        n, t = tf.shape
        npad = pad_to_shards(max(n, 1), self.n_doc)
        tpad = max(self.n_term, ((t + self.n_term - 1) // self.n_term)
                   * self.n_term)
        tf_p = np.zeros((npad, tpad), np.float32)
        tf_p[:n, :t] = tf
        dl_p = np.zeros(npad, np.int32)
        dl_p[:n] = doclen
        df_p = np.zeros(tpad, np.int32)
        df_p[:t] = df
        # padded term columns must not contribute idf: df=ndocs makes
        # idf=log(1 + 0.5/(ndocs+0.5)) ~ 0 but tf=0 zeroes them anyway
        valid = np.zeros(npad, bool)
        valid[:n] = True
        did_p = np.full(npad, -1, np.int32)
        did_p[:n] = docids
        sh = NamedSharding(self.mesh, PS("doc", "term"))
        sh_doc = NamedSharding(self.mesh, PS("doc"))
        sh_term = NamedSharding(self.mesh, PS("term"))
        sh_rep = NamedSharding(self.mesh, PS())
        return (jax.device_put(tf_p, sh),
                jax.device_put(dl_p, sh_doc),
                jax.device_put(df_p, sh_term),
                jax.device_put(np.int32(ndocs), sh_rep),
                jax.device_put(valid, sh_doc),
                jax.device_put(did_p, sh_doc))

    def topk_placed(self, placed, k: int = 10):
        fn = self._fn(k)
        s, d = fn(*placed)
        s, d = np.asarray(s), np.asarray(d)
        keep = (d >= 0) & np.isfinite(s)
        return s[keep][:k], d[keep][:k]

    def topk(self, tf, doclen, df, ndocs, docids, k: int = 10):
        return self.topk_placed(self.place(tf, doclen, df, ndocs, docids), k=k)
