"""True multi-process SPMD mesh serving — the `jax.distributed` runtime.

Every multi-chip number in this repo used to be produced by ONE
interpreter (`tests/test_dryrun_multichip.py` drives the whole mesh
in-process).  This module brings a fleet of OS processes up as ONE
logical SPMD mesh (ISSUE 12 / ROADMAP item 1 — the gap that survived
every re-anchor since round 5):

* **Bootstrap** — ``jax.distributed.initialize`` with the coordinator
  address / process id / process count from env (``YACY_MESH_*``), the
  CPU backend's per-process device pool from
  ``XLA_FLAGS=--xla_force_host_platform_device_count`` (the documented
  CI pattern), and gloo cross-process collectives.
* **Global mesh** — ``jax.devices()`` after distributed init is the
  process-ordered GLOBAL pool; each process owns its local shard of the
  (term, doc) grid.  The partition math (``meshstore.term_shard`` +
  ``docid % n_doc``) is pure arithmetic over the hashes, and
  :func:`partition_fingerprint` digests it over a probe set so the
  processes can ASSERT they agree before serving (a process with a
  divergent placement would silently return wrong rankings, not crash).
* **SPMD discipline over the real HTTP wire** — pjit's multi-process
  contract (SNIPPETS [2]): every process must execute the same program
  in the same order.  Queries arrive at the coordinator over HTTP
  (``/yacy/meshsearch``), and a two-phase scatter keeps the fleet in
  lockstep: phase 1 POSTs the step to every member (the reply carries
  pid + health — the wire IS the liveness probe), phase 2 commits a
  single go/no-go verdict.  Only a committed ``go`` enters the
  cross-process collective (``MeshSegmentStore.rank_term_mp``); any
  member down or device-lost flips the WHOLE fleet to the host answer
  for that step — degraded and counted, never a hang.  Fleet metric
  digests and trace ids ride the same RPCs for free
  (``peers/protocol.Protocol._call``).
* **Per-process survival** — the M82–M84 machinery holds per process:
  ``device.transfer_fail`` injected into ONE member fails only that
  member's fetches; its loss streak declares ITS device lost, the
  coordinator sees the flag on the next scatter, the fleet degrades to
  host serving (100% answered), a flight-recorder incident names the
  member, and the member's background rebuild brings collectives back.

The launcher/supervisor lives in :mod:`yacy_search_server_tpu.parallel.
launcher`; ``python -m yacy_search_server_tpu.parallel.launcher
--procs 3`` is the one-command bring-up.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue as _queue
import threading
import time
from collections import deque

import numpy as np

from ..utils import faultinject, histogram, profiling, tailattr, tracing

log = logging.getLogger("parallel.distributed")

# -- environment contract (set by the launcher before the child's
#    interpreter starts, so XLA flags precede backend discovery) -------------
ENV_COORDINATOR = "YACY_MESH_COORDINATOR"     # host:port of jax coordinator
ENV_NPROCS = "YACY_MESH_NPROCS"
ENV_PROC_ID = "YACY_MESH_PROC_ID"
ENV_LOCAL_DEVICES = "YACY_MESH_LOCAL_DEVICES"
ENV_HTTP_PORTS = "YACY_MESH_HTTP_PORTS"       # comma list, index = proc id
ENV_NDOCS = "YACY_MESH_NDOCS"
ENV_SEED = "YACY_MESH_SEED"
ENV_NTERM = "YACY_MESH_NTERM"
ENV_DATA_DIR = "YACY_MESH_DATA_DIR"
ENV_TESTING = "YACY_MESH_TESTING"             # gates the fault-arming RPC

COMMIT_TIMEOUT_S = 20.0      # commit that never arrives -> host mode
STEP_KINDS = ("rank_term",)

# the deterministic corpus every process builds identically (SPMD: same
# program, same data; device_put then materializes only local shards)
CORPUS_TERMS = ("meshterm", "papaya", "quokka", "banana")
TIE_TERM = "tieterm"         # identical feature rows -> equal scores
                             # spread across doc columns (tie discipline
                             # across process boundaries)


def bootstrap_from_env():
    """``jax.distributed.initialize`` from the YACY_MESH_* contract.
    Must run before any other jax API touches the backend.  Returns
    (process_id, num_processes)."""
    import jax
    coord = os.environ[ENV_COORDINATOR]
    nprocs = int(os.environ[ENV_NPROCS])
    pid = int(os.environ[ENV_PROC_ID])
    try:
        # gloo is the CPU cross-process collective fabric; newer jax
        # defaults to it once distributed-initialized, older spells it
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:
        log.debug("gloo collectives config not available: %r", e)
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nprocs, process_id=pid)
    want = int(os.environ.get(ENV_LOCAL_DEVICES, "0"))
    if want and jax.local_device_count() != want:
        raise RuntimeError(
            f"process {pid}: {jax.local_device_count()} local devices, "
            f"want {want} (XLA_FLAGS must be set before jax imports)")
    return pid, nprocs


def global_mesh_devices():
    """The process-ordered global device pool (jax.devices() after
    distributed init spans every process)."""
    import jax
    return list(jax.devices())


# -- partition-math determinism ---------------------------------------------

def partition_fingerprint(n_term: int, n_doc: int,
                          probes: int = 64) -> str:
    """Digest of the (term, doc) placement over a fixed probe set —
    identical on every process and across restarts iff the partition
    math is deterministic (asserted by the scatter handshake and
    property-tested in tests/test_mesh_multiproc.py)."""
    from ..index.meshstore import term_shard
    from ..utils.hashes import word2hash
    h = hashlib.sha256(f"{n_term}x{n_doc}".encode("ascii"))
    for i in range(probes):
        th = word2hash(f"fingerprint-probe-{i}")
        t = term_shard(th, n_term)
        d = i * 2654435761 % n_doc          # deterministic probe docids
        h.update(bytes([t, d % 251]))
        h.update(th)
    return h.hexdigest()[:16]


# -- the deterministic corpus ------------------------------------------------

def build_corpus(sb, ndocs: int, seed: int, n_doc: int) -> None:
    """Identical on every process for a given (ndocs, seed): metadata
    rows + ONE frozen RWI run with the bench terms and the constructed
    tie term (two identical feature rows whose docids land in DIFFERENT
    doc columns — equal scores must cross a process boundary and still
    fuse as (score DESC, docid ASC))."""
    from ..index import postings as P
    from ..index.postings import PostingsList
    from ..utils.hashes import word2hash
    rng = np.random.default_rng(seed)
    sb.index.metadata.bulk_load(
        [f"{i:06d}h{i % 7:05d}".encode("ascii") for i in range(ndocs)],
        sku=[f"http://h{i % 7}.example/d{i}.html" for i in range(ndocs)],
        title=[f"doc {i}" for i in range(ndocs)],
        host_s=[f"h{i % 7}.example" for i in range(ndocs)],
        size_i=[1000] * ndocs, wordcount_i=[100] * ndocs)
    run: dict = {}
    for t_i, term in enumerate(CORPUS_TERMS):
        n = ndocs - (t_i * ndocs // 8)      # distinct span sizes
        feats = rng.integers(0, 1000, (n, P.NF)).astype(np.int32)
        feats[:, P.F_FLAGS] = rng.integers(0, 2 ** 20, n)
        feats[:, P.F_LANGUAGE] = P.pack_language("en")
        run[word2hash(term)] = PostingsList(
            np.arange(n, dtype=np.int32), feats)
    # the tie construction: 2*n_doc docids carrying the SAME feature
    # row — one per doc column twice over, so equal-score candidates
    # arrive at the fusion collective from every process
    n_tie = 2 * max(n_doc, 1)
    feats = rng.integers(0, 1000, (1, P.NF)).astype(np.int32)
    feats[:, P.F_FLAGS] = 0
    feats[:, P.F_LANGUAGE] = P.pack_language("en")
    run[word2hash(TIE_TERM)] = PostingsList(
        np.arange(n_tie, dtype=np.int32),
        np.repeat(feats, n_tie, axis=0))
    sb.index.rwi.ingest_run(run)


def host_rank(index, termhash: bytes, profile, language: str,
              k: int):
    """The degraded-mode answer: the host ranker over the full merged
    postings — same math, same tie discipline (postings are docid-
    ordered, so positional ties ARE docid ties), bit-identical to the
    mesh answer on a frozen corpus (pinned by the multiproc tests)."""
    from ..ops.ranking import CardinalRanker
    plist = index.rwi.get(termhash)
    if plist is None or len(plist) == 0:
        return np.empty(0, np.int32), np.empty(0, np.int32), 0
    s, d = CardinalRanker(profile, language).rank(plist, None, k=k)
    return s, d, len(plist)


# -- the member runtime ------------------------------------------------------

class MeshMember:
    """One OS process of the logical mesh: a P2PNode speaking the real
    HTTP wire + the shared MeshSegmentStore over the GLOBAL device mesh
    + the step runloop that keeps this process in SPMD lockstep."""

    def __init__(self, process_id: int, num_processes: int,
                 http_ports: list[int], ndocs: int = 512,
                 seed: int = 3, n_term: int = 1,
                 data_dir: str | None = None, devices=None):
        from ..peers.node import P2PNode
        from ..peers.seed import Seed, make_seed_hash
        from ..peers.transport import HttpTransport

        self.process_id = process_id
        self.num_processes = num_processes
        self.http_ports = list(http_ports)
        self.name = f"mesh{process_id}"
        self._stop = threading.Event()
        # bounded: a flooding (or buggy) peer scattering steps faster
        # than the runloop executes them must hit backpressure at the
        # wire, not grow an unbounded step backlog (the coordinator
        # serializes on _serve_lock, so a handful is the healthy depth)
        self._steps: "_queue.Queue" = _queue.Queue(maxsize=512)
        self._pending: dict[int, dict] = {}
        self._plock = profiling.ObservedLock("mesh_plock")
        self._serve_lock = threading.Lock()
        self._seq = 0
        # per-process serving counters (the ISSUE 12 availability
        # contract: every step answers, collective or host)
        self.queries_total = 0
        self.answered_collective = 0
        self.answered_host = 0
        self.step_errors = 0
        self.member_down_steps = 0
        self.commit_timeouts = 0
        self.incidents: list[dict] = []
        self._incident_seq = 0    # monotonic per process (ISSUE 19)
        self._member_state: dict[int, str] = {}     # id -> ok|lost|down
        # tail forensics (ISSUE 15a): every executed step produces a
        # span segment (queue wait / commit [collective-entry] wait /
        # local execution wall).  The coordinator feeds its own
        # segments straight into the process-global MeshTimeline;
        # members park theirs here and ship them INLINE on the next
        # meshstep/meshcommit reply — zero extra RPCs.
        self.timeline = tailattr.MESH if process_id == 0 else None
        self._segs_out: deque = deque(maxlen=128)

        t = HttpTransport(timeout_s=10.0)
        self.node = P2PNode(self.name, t, data_dir=data_dir,
                            port=http_ports[process_id],
                            partition_exponent=1, redundancy=1)
        self.sb = self.node.sb
        self.sb.mesh_member = self       # the PeerServer mesh endpoints
        self.node.serve_http(port=http_ports[process_id])
        # the member address book is fully determined by the env
        # contract (name + 127.0.0.1 + port IS the seed hash)
        self.peers = {}
        for j, port in enumerate(http_ports):
            if j == process_id:
                continue
            s = Seed(make_seed_hash(f"mesh{j}", "127.0.0.1", port),
                     name=f"mesh{j}", ip="127.0.0.1", port=port)
            self.node.seeddb.connected(s)
            t.set_address(s.hash, f"http://127.0.0.1:{port}")
            self.peers[j] = s

        devs = devices if devices is not None else global_mesh_devices()
        self.n_term = n_term
        self.n_doc = len(devs) // n_term
        build_corpus(self.sb, ndocs, seed, self.n_doc)
        self.store = self.sb.index.enable_mesh_serving(
            devices=devs, n_term=n_term)
        self.store.small_rank_n = 0
        self.fingerprint = partition_fingerprint(n_term, self.n_doc)
        self._data_dir = data_dir
        self._runner = threading.Thread(target=self._runloop,
                                        name=f"mesh-runloop-{process_id}",
                                        daemon=True)
        self._runner.start()
        # whitebox conviction evidence (ISSUE 20d): the coordinator
        # hooks the conviction tracker so every conviction edge fetches
        # the convicted member's OWN profile snapshot over the wire and
        # writes a conviction incident embedding it — the verdict stops
        # being "mesh1 was slowest" and starts being "mesh1 was slowest
        # and here is the stack it was burning on"
        if self.timeline is not None:
            tailattr.CONVICTIONS.set_conviction_hook(self._on_convicted)
        self.ready = True
        log.info("mesh member %d/%d up: pid=%d http=%d cells=%d fp=%s",
                 process_id, num_processes, os.getpid(),
                 self.node.http.port, len(devs), self.fingerprint)

    # -- step plumbing (every process, coordinator included) ----------------

    def _health(self) -> dict:
        return {"pid": os.getpid(), "proc": self.process_id,
                "n": self.num_processes, "ready": self.ready,
                "lost": bool(self.store.device_lost),
                "fp": self.fingerprint}

    def _enqueue_local(self, payload: dict) -> dict:
        rec = {"payload": dict(payload),
               "commit": threading.Event(), "go": False,
               "done": threading.Event(), "result": None,
               "mode": "host",
               "t_enq": time.perf_counter(), "ts0": time.time()}
        with self._plock:
            self._pending[int(payload["seq"])] = rec
        self._steps.put(rec)
        return rec

    def _drain_segments(self) -> list[dict]:
        with self._plock:
            segs = list(self._segs_out)
            self._segs_out.clear()
        return segs

    def _note_segment(self, rec: dict) -> None:
        """One executed step's span segment: the coordinator assembles
        it immediately; members park it for the next scatter reply."""
        if not tailattr.enabled():
            return
        seg = {"seq": int(rec["payload"].get("seq", -1)),
               "m": self.process_id,
               "q_ms": round(rec.get("q_ms", 0.0), 3),
               "commit_ms": round(rec.get("commit_ms", 0.0), 3),
               "entry_ms": round(rec.get("entry_ms", 0.0), 3),
               "exec_ms": round(rec.get("exec_ms", 0.0), 3),
               "mode": rec.get("mode", "?"),
               "ts0": round(rec.get("ts0", 0.0), 6)}
        if self.timeline is not None:
            self.timeline.add_segment(seg)
        else:
            with self._plock:
                self._segs_out.append(seg)

    def enqueue_step(self, payload: dict) -> dict:
        """Phase 1 (wire): enqueue, ack with health + any pending step
        segments (ISSUE 15a — completed steps' timelines ride the
        scatter the coordinator already pays for)."""
        self._enqueue_local(payload)
        return {**self._health(), "segs": self._drain_segments()}

    def commit_step(self, seq: int, go: bool) -> dict:
        with self._plock:
            rec = self._pending.get(int(seq))
        if rec is None:
            return {"error": f"unknown seq {seq}", **self._health()}
        rec["go"] = bool(go)
        rec["commit"].set()
        return {**self._health(), "segs": self._drain_segments()}

    def _runloop(self) -> None:
        while not self._stop.is_set():
            try:
                rec = self._steps.get(timeout=0.25)
            except _queue.Empty:
                continue
            if rec is None:
                return
            # segment timing (ISSUE 15a): queue wait = enqueue ->
            # runloop pickup (steps serialized behind earlier ones);
            # commit wait = pickup -> go/no-go decided (the collective-
            # entry wait: no process enters the SPMD program before the
            # fleet-wide verdict lands)
            t_deq = time.perf_counter()
            rec["q_ms"] = (t_deq - rec.get("t_enq", t_deq)) * 1000.0
            if not rec["commit"].wait(timeout=COMMIT_TIMEOUT_S):
                # the commit never arrived (coordinator died between
                # phases): decide LOCALLY for host mode — bounded, and
                # a peer that entered the collective without us errors
                # out of it on the fabric timeout (rank_term_mp catches)
                with self._plock:
                    self.commit_timeouts += 1
                rec["go"] = False
            rec["commit_ms"] = (time.perf_counter() - t_deq) * 1000.0
            try:
                self._execute(rec)
            except Exception:
                # a malformed step (bad hex / profile string off the
                # wire) must cost ONE empty answer, never the runloop
                # thread — a dead runloop wedges every later query on
                # every process (the availability contract's worst
                # enemy is a daemon thread dying quietly)
                log.exception("mesh step execution failed (seq=%s)",
                              rec["payload"].get("seq"))
                rec["result"] = (np.empty(0, np.int32),
                                 np.empty(0, np.int32), 0)
                rec["mode"] = "error"
                with self._plock:
                    self.queries_total += 1
                    self.step_errors += 1
                    self._pending.pop(int(rec["payload"].get("seq", -1)),
                                      None)
            finally:
                self._note_segment(rec)
                rec["done"].set()

    def _execute(self, rec: dict) -> None:
        from ..ops.ranking import RankingProfile
        p = rec["payload"]
        termhash = bytes.fromhex(p["term"])
        profile = RankingProfile.from_external_string(p["profile"])
        lang = p.get("lang", "en")
        k = int(p.get("k", 10))
        t_ex = time.perf_counter()
        # env-gated straggler injection (ISSUE 15): a latency armed in
        # ONE member (via do_meshfault) slows exactly that member's
        # step execution — the deterministic driver for the
        # collective_straggler verdict and the scoreboard tests
        faultinject.sleep("mesh.step")
        # segment split (ISSUE 15a): `entry_ms` is this member's LOCAL
        # pre-dispatch wall — a late member shows its lateness HERE,
        # while the others' stalls land in their exec wall as they
        # block at the collective entry.  In an SPMD collective every
        # member's exec wall inflates identically when one straggles,
        # so entry lateness is the signal that NAMES the straggler.
        t_disp = time.perf_counter()
        rec["entry_ms"] = (t_disp - t_ex) * 1000.0
        out = None
        if rec["go"]:
            out = self.store.rank_term_mp(termhash, profile, lang, k)
        if out is not None:
            rec["mode"] = "collective"
            with self._plock:
                self.answered_collective += 1
        else:
            s, d, considered = host_rank(self.sb.index, termhash,
                                         profile, lang, k)
            out = (s, d, considered)
            rec["mode"] = "host"
            with self._plock:
                self.answered_host += 1
        rec["exec_ms"] = (time.perf_counter() - t_disp) * 1000.0
        with self._plock:
            self.queries_total += 1
            self._pending.pop(int(p["seq"]), None)
        rec["result"] = out

    # -- the coordinator's scatter (process 0) -------------------------------

    def serve_query(self, term_hex: str, profile_ext: str,
                    lang: str = "en", k: int = 10) -> dict:
        """scatter → score → fuse → respond, across process boundaries.

        Phase 1 scatters the step to every member over the HTTP wire
        (the reply doubles as the liveness/health probe and carries the
        partition fingerprint), phase 2 commits one fleet-wide go/no-go,
        then every process — this one included — executes the step: a
        cross-process SPMD collective when committed, the host answer
        when degraded.  100% of queries answer either way."""
        # lint: blocking-ok(SPMD lockstep: the coordinator scatter is
        # deliberately serialized — _serve_lock IS the fleet-wide step
        # ordering, so the RPCs and the step wait belong inside it)
        with self._serve_lock, tracing.trace("mesh.serve"):
            t_q0 = time.perf_counter()
            seq = self._seq
            self._seq += 1
            step = {"seq": seq, "kind": "rank_term", "term": term_hex,
                    "profile": profile_ext, "lang": lang, "k": k}
            pids = {self.process_id: os.getpid()}
            go = not self.store.device_lost
            for j, seed in sorted(self.peers.items()):
                ok, rep = self.node.protocol.mesh_rpc(
                    seed, "meshstep", dict(step))
                if not ok:
                    self._note_member(j, "down", None)
                    self.member_down_steps += 1
                    go = False
                    continue
                self._ingest_segments(rep)
                pids[j] = int(rep.get("pid", -1))
                if rep.get("fp") != self.fingerprint:
                    # divergent partition math would return WRONG
                    # rankings silently: refuse collectives with it
                    self._note_member(j, "down",
                                      rep.get("pid"),
                                      cause="partition_fingerprint")
                    go = False
                elif rep.get("lost"):
                    self._note_member(j, "lost", rep.get("pid"))
                    go = False
                else:
                    self._note_member(j, "ok", rep.get("pid"))
            # cross-process scatter assembly (ISSUE 15a): register the
            # step's timeline record over EXACTLY the processes that
            # acked phase 1 (+ self) — a down member must not hold the
            # waterfall/verdict incomplete forever
            if self.timeline is not None:
                culprit = ""
                if not go:
                    # name the member whose state broke the collective,
                    # self first — the host-fallback verdict carries it
                    if self.store.device_lost:
                        culprit = f"mesh{self.process_id}"
                    else:
                        bad = sorted(j for j, st
                                     in self._member_state.items()
                                     if st != "ok")
                        culprit = f"mesh{bad[0]}" if bad else ""
                self.timeline.note_step(
                    seq, tracing.current_trace_id() or "",
                    pids.keys(), "collective" if go else "host",
                    culprit=culprit)
            for j, seed in sorted(self.peers.items()):
                ok, rep = self.node.protocol.mesh_rpc(
                    seed, "meshcommit", {"seq": seq, "go": go})
                if ok:
                    self._ingest_segments(rep)
            lrec = self._enqueue_local(step)
            self.commit_step(seq, go)
            lrec["done"].wait(timeout=COMMIT_TIMEOUT_S + 40.0)
            if self.timeline is not None:
                self.timeline.finish_step(
                    seq, (time.perf_counter() - t_q0) * 1000.0)
            # deliberately NO mesh.serve histogram family: a scheduled
            # mesh.step straggle slows EVERY collective step, so a
            # cached-p95 exemplar gate would adapt to the fault within
            # one rotation and stop classifying exactly the queries the
            # game day must attribute.  mesh.serve roots gate on the
            # fixed `tail.minMs` floor; deployments whose healthy
            # collective wall exceeds the default floor raise the knob
            # (the game-day bench does).
            s, d, considered = lrec["result"] or \
                (np.empty(0, np.int32), np.empty(0, np.int32), 0)
            return {"seq": seq, "mode": lrec["mode"], "go": bool(go),
                    "scores": np.asarray(s).tolist(),
                    "docids": np.asarray(d).tolist(),
                    "considered": int(considered),
                    "pids": {str(j): p for j, p in pids.items()},
                    "trace": tracing.current_trace_id()}

    def _ingest_segments(self, rep: dict) -> None:
        """Feed step segments a member shipped inline on a scatter
        reply into the coordinator's timeline (members: no-op)."""
        if self.timeline is None or not isinstance(rep, dict):
            return
        segs = rep.get("segs")
        if isinstance(segs, list):
            for seg in segs:
                self.timeline.add_segment(seg)

    def _note_member(self, j: int, state: str, pid,
                     cause: str | None = None) -> None:
        """Edge-triggered member-state tracking: the ok->lost/down edge
        dumps a flight-recorder incident NAMING the member (the ISSUE 12
        acceptance trail); the recovery edge records the return."""
        prev = self._member_state.get(j, "ok")
        self._member_state[j] = state
        if state == prev:
            return
        # post-hoc join keys (ISSUE 19): monotonic per-process seq +
        # the armed-fault snapshot at dump time — wall clocks skew
        # across mesh processes, so the game-day verdict engine orders
        # by (pid, incident_seq) and matches the incident to its
        # scheduled fault by what was armed when it fired
        with self._plock:
            self._incident_seq += 1
            seq_no = self._incident_seq
        inc = {"kind": "incident",
               "name": f"mesh_member_{state}" if state != "ok"
               else "mesh_member_recovered",
               "member": f"mesh{j}", "member_id": j, "pid": pid,
               "cause": cause or state, "ts": round(time.time(), 3),
               "incident_seq": seq_no,
               "armed_faults": faultinject.snapshot()}
        self.incidents.append(inc)
        log.warning("mesh member incident: %s", inc)
        if self._data_dir:
            try:
                hdir = os.path.join(self._data_dir, "HEALTH")
                os.makedirs(hdir, exist_ok=True)
                path = os.path.join(
                    hdir, f"mesh-incident-{int(inc['ts'])}-mesh{j}.jsonl")
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(inc) + "\n")
            except OSError:
                log.exception("incident dump failed")

    # -- info / lifecycle -----------------------------------------------------

    def info(self, tick_health: bool = False,
             prime_tail_gate: bool = False) -> dict:
        eng = getattr(self.sb, "health", None)
        if prime_tail_gate:
            # warmup/measurement boundary: drop every family's
            # windowed samples so compile-era warmup walls (orders of
            # magnitude above the live workload) cannot sit in the
            # merged ring and hold the cached-p95 exemplar gate — and
            # the SLO burn windows — above everything the workload
            # will ever produce.  Until the first live window rotates
            # the tail gate sits at the `tail.minMs` floor.
            histogram.reset_windows()
        if tick_health and eng is not None:
            # node switchboards under the mesh runtime do not run the
            # 15_health busy thread; the wire caller (bench/test) drives
            # evaluation explicitly so burn-rate rules and the flight
            # recorder fire on the member's real histograms
            eng.tick()
        h = histogram.get("mesh.collective")
        hist = {"count": h.count if h else 0,
                "sum_ms": round(h.sum_ms, 3) if h else 0.0,
                "p50_ms": round(h.percentile(0.50), 3) if h else 0.0,
                "p95_ms": round(h.percentile(0.95), 3) if h else 0.0}
        fl = getattr(self.sb, "fleet", None)
        rows = fl.peer_rows() if fl is not None else []
        with self._plock:
            runtime = {
                "queries_total": self.queries_total,
                "answered_collective": self.answered_collective,
                "answered_host": self.answered_host,
                "step_errors": self.step_errors,
                "member_down_steps": self.member_down_steps,
                "commit_timeouts": self.commit_timeouts}
        # tail forensics (ISSUE 15): the coordinator's assembled view —
        # windowed cause histogram, verdict ring, straggler scoreboard
        # and the newest complete cross-process waterfall; members
        # report their local verdicts too
        if self.timeline is not None:
            # an owed verdict whose segments never fully arrived (lull
            # after a burst) finalizes from partial segments now — the
            # info caller is exactly who must not see a silent drop
            self.timeline.flush_pending()
        verdicts = tailattr.verdicts(8)
        strag_wf = None
        if self.timeline is not None:
            # the assembled waterfall OF an over-threshold straggled
            # query (the ISSUE 15 acceptance artifact's exhibit), not
            # just the newest complete step
            for v in verdicts:
                if v.cause == "collective_straggler":
                    strag_wf = self.timeline.waterfall(
                        v.evidence.get("seq"))
                    break
        tail = {
            "causes": tailattr.windowed_causes(),
            "cause_totals": tailattr.cause_totals(),
            "stragglers": tailattr.straggler_totals(),
            "verdicts": [v.to_json() for v in verdicts],
            "scoreboard": tailattr.scoreboard(),
            "waterfall": (self.timeline.waterfall()
                          if self.timeline is not None else None),
            "straggled_waterfall": strag_wf,
            "segments_merged": (self.timeline.segments_merged
                                if self.timeline is not None else 0),
            "pending_partial": (self.timeline.pending_partial
                                if self.timeline is not None else 0),
            # ROADMAP 1c read-only slice (ISSUE 19): conviction edges
            # (member slowest over N consecutive windows) + zero-filled
            # totals over every member this timeline scattered to
            "convictions": tailattr.conviction_totals(),
            "conviction_crumbs": tailattr.conviction_breadcrumbs(10),
        }
        health_incs = []
        incident_tail = None
        if eng is not None:
            for inc in eng.incidents:
                health_incs.append({"name": inc["name"],
                                    "ts": inc.get("ts"),
                                    "seq": inc.get("seq"),
                                    "armed_faults":
                                        inc.get("armed_faults", {}),
                                    "rules": list(inc["rules"])})
            if eng.incidents:
                # the newest incident's embedded tail evidence (the
                # ISSUE 15 acceptance surface: incidents carry causes)
                body = eng.incidents[-1]["body"]
                incident_tail = {}
                for line in body.splitlines():
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        continue
                    if obj.get("kind") in ("tail_causes",
                                           "straggler_scoreboard"):
                        incident_tail[obj["kind"]] = obj
        return {**self._health(),
                "counters": self.store.counters(),
                "runtime": runtime,
                "collective_hist": hist,
                "digest_bytes": fl.last_digest_bytes if fl else 0,
                "fleet_peers": len(rows),
                # the gossiped process identities + arena epochs of the
                # OTHER mesh members (Network_Health_p's mesh columns)
                "peers_proc": [r.get("proc", {}) for r in rows],
                "peers_epoch": [r.get("epoch", 0) for r in rows],
                "incidents": list(self.incidents),
                "tail": tail,
                "health_incidents": health_incs,
                "incident_tail": incident_tail}

    def _on_convicted(self, crumb: dict) -> None:
        """Conviction-edge hook (ISSUE 20d, coordinator only): fetch
        the convicted member's whitebox profile over the wire (or read
        it locally for self-convictions), attach it to the crumb —
        health's flight recorder embeds crumbs verbatim — and write a
        dedicated conviction incident (the _note_member model)."""
        member = str(crumb.get("member", ""))
        try:
            j = int(member[4:]) if member.startswith("mesh") else -1
        except ValueError:
            j = -1
        prof = None
        if j == self.process_id:
            from ..utils import profiling
            prof = profiling.snapshot()
        elif j in self.peers:
            ok, rep = self.node.protocol.fetch_profile(self.peers[j])
            if ok and isinstance(rep.get("profile"), dict):
                prof = rep["profile"]
        if prof is not None:
            crumb["profile"] = prof
        with self._plock:
            self._incident_seq += 1
            seq_no = self._incident_seq
        inc = {"kind": "incident", "name": "straggler_convicted",
               "member": member, "member_id": j,
               "ts": round(time.time(), 3), "incident_seq": seq_no,
               "armed_faults": faultinject.snapshot(),
               "crumb": crumb}
        self.incidents.append(inc)
        log.warning("straggler conviction incident: %s (profile %s)",
                    member, "attached" if prof is not None else "absent")
        if self._data_dir:
            try:
                hdir = os.path.join(self._data_dir, "HEALTH")
                os.makedirs(hdir, exist_ok=True)
                path = os.path.join(
                    hdir,
                    f"mesh-conviction-{int(inc['ts'])}-{member}.jsonl")
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(inc) + "\n")
            except OSError:
                log.warning("conviction incident write failed")

    def close(self) -> None:
        self._stop.set()
        self._steps.put(None)
        self._runner.join(timeout=5.0)
        try:
            self.node.close()
        except Exception:
            log.exception("mesh member close failed")

    def run_until_stopped(self) -> None:
        """Child-process main: serve until the stop flag (wire shutdown
        or parent death) flips."""
        while not self._stop.is_set():
            time.sleep(0.2)


def _parent_death_watch(original_ppid: int, member: MeshMember) -> None:
    """Orphan safety net (ISSUE 12 satellite): if the supervisor dies,
    this process must not linger holding ports and a jax coordinator
    slot — poll the parent pid and exit hard on reparenting."""
    def watch():
        while True:
            if os.getppid() != original_ppid:
                log.error("parent died; mesh member exiting")
                os._exit(3)
            if member._stop.is_set():
                return
            time.sleep(0.5)
    threading.Thread(target=watch, name="mesh-ppid-watch",
                     daemon=True).start()


def main() -> int:
    """Child entry: ``python -m yacy_search_server_tpu.parallel.
    distributed`` with the YACY_MESH_* env contract set (the launcher
    does this; see parallel/launcher.py for the one-command bring-up)."""
    logging.basicConfig(level=logging.INFO)
    ppid = os.getppid()
    pid, nprocs = bootstrap_from_env()
    ports = [int(p) for p in os.environ[ENV_HTTP_PORTS].split(",")]
    member = MeshMember(
        pid, nprocs, ports,
        ndocs=int(os.environ.get(ENV_NDOCS, "512")),
        seed=int(os.environ.get(ENV_SEED, "3")),
        n_term=int(os.environ.get(ENV_NTERM, "1")),
        data_dir=os.environ.get(ENV_DATA_DIR) or None)
    _parent_death_watch(ppid, member)
    print(f"MESH_MEMBER_READY {pid} {os.getpid()} "
          f"{member.node.http.port}", flush=True)
    try:
        member.run_until_stopped()
    finally:
        member.close()
        try:
            import jax
            jax.distributed.shutdown()
        except Exception as e:
            log.debug("jax.distributed shutdown failed: %r", e)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
