"""WebStructureGraph — the host-level link matrix.

Capability equivalent of the reference's web structure accounting
(reference: source/net/yacy/peers/graphics/WebStructureGraph.java:71-159:
per-document host->host link recording into old/new structure maps,
persisted, feeding citation ranking, the webstructure API and the
network graphics). Here: a host adjacency count matrix with jsonl
persistence and the accessors the API layer serves
(outgoing/incoming/references).
"""

from __future__ import annotations

import json
import os
import threading
from collections import defaultdict
from urllib.parse import urlsplit

from .utils.hashes import hosthash, url2hash


def host_of(url: str) -> str:
    return urlsplit(url).netloc.lower()


class WebStructureGraph:
    def __init__(self, data_dir: str | None = None,
                 max_hosts: int = 50_000):
        self.max_hosts = max_hosts
        self._out: dict[str, dict[str, int]] = defaultdict(dict)
        self._lock = threading.Lock()
        self._path = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._path = os.path.join(data_dir, "webstructure.jsonl")
            self._load()

    # lint: unlocked-ok(construction-time: only __init__ calls this,
    # before the graph is shared with any other thread)
    def _load(self) -> None:
        if not (self._path and os.path.exists(self._path)):
            return
        with open(self._path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                    self._out[rec["h"]] = {k: int(v)
                                           for k, v in rec["o"].items()}
                except (json.JSONDecodeError, KeyError, ValueError):
                    continue

    # -- write path (learnrefs / storeDocument hook) ------------------------

    def add_document(self, source_url: str, target_urls: list[str]) -> None:
        src = host_of(source_url)
        if not src:
            return
        with self._lock:
            row = self._out[src]
            for t in target_urls:
                dst = host_of(t)
                if not dst or dst == src:
                    continue
                row[dst] = row.get(dst, 0) + 1
            if len(self._out) > self.max_hosts:
                # evict the smallest rows (the reference caps its maps too)
                victim = min(self._out, key=lambda h: len(self._out[h]))
                del self._out[victim]

    # -- read path -----------------------------------------------------------

    def outgoing(self, host: str) -> dict[str, int]:
        with self._lock:
            return dict(self._out.get(host.lower(), {}))

    def incoming(self, host: str) -> dict[str, int]:
        host = host.lower()
        with self._lock:
            return {src: row[host] for src, row in self._out.items()
                    if host in row}

    def references_count(self, host: str) -> int:
        """Number of distinct hosts linking to `host` (the CRh signal)."""
        return len(self.incoming(host))

    def host_count(self) -> int:
        with self._lock:
            return len(self._out)

    def source_hosts(self) -> list[str]:
        """Every host that has outgoing links recorded."""
        with self._lock:
            return list(self._out.keys())

    def top_hosts(self, n: int = 20) -> list[tuple[str, int]]:
        """Hosts by inbound reference count."""
        counts: dict[str, int] = defaultdict(int)
        with self._lock:
            for row in self._out.values():
                for dst in row:
                    counts[dst] += 1
        return sorted(counts.items(), key=lambda kv: -kv[1])[:n]

    def hosthash(self, host: str) -> bytes:
        # hashes.hosthash slices the host part out of a 12-byte url hash,
        # so the host must be run through url2hash first
        return hosthash(url2hash("http://" + host + "/"))

    # -- persistence ---------------------------------------------------------

    def save(self) -> None:
        if not self._path:
            return
        with self._lock, open(self._path, "w", encoding="utf-8") as f:
            for h, row in self._out.items():
                f.write(json.dumps({"h": h, "o": row}) + "\n")

    def close(self) -> None:
        self.save()
