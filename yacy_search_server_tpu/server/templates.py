"""Streaming template engine — #[x]#, #(alt)#, #{loop}#, #%include%#.

Capability equivalent of the reference's template grammar (reference:
source/net/yacy/server/http/TemplateEngine.java:84-146):

- ``#[key]#``                      → value of ``key`` in the pattern map
- ``#(key)#a::b::c#(/key)#``       → alternative selected by int(key)
  (out-of-range or non-numeric selects alternative 0)
- ``#{key}#body#{/key}#``          → body repeated int(key) times; inside
  iteration i, ``#[field]#`` resolves ``key_i_field`` first (the
  serverObjects loop-row convention), and nested alternatives resolve the
  same prefixed keys
- ``#%path%#``                     → include of another template file,
  resolved against the template root

The reference streams byte-wise; templates here are small enough to
process as strings with one recursive-descent pass, which keeps nesting
of loops and alternatives correct.
"""

from __future__ import annotations

import os
import re

from .objects import ServerObjects

_FIELD_RE = re.compile(r"#\[([A-Za-z0-9_.-]+)\]#")
_INCLUDE_RE = re.compile(r"#%([A-Za-z0-9_./-]+)%#")


class TemplateEngine:
    def __init__(self, roots: list[str] | None = None):
        # template search path: later roots are fallbacks (the reference
        # overlays DATA/HTDOCS over htroot the same way)
        self.roots = list(roots or [])

    def resolve(self, name: str) -> str | None:
        for root in self.roots:
            p = os.path.join(root, name)
            if os.path.isfile(p):
                return p
        return None

    def render_file(self, name: str, props: ServerObjects) -> str:
        path = self.resolve(name)
        if path is None:
            raise FileNotFoundError(name)
        with open(path, encoding="utf-8") as f:
            return self.render(f.read(), props)

    def render(self, template: str, props: ServerObjects) -> str:
        template = self._expand_includes(template, depth=0)
        return self._render(template, props, prefix="")

    # -- internals -----------------------------------------------------------

    def _expand_includes(self, text: str, depth: int) -> str:
        if depth > 8:
            return text

        def repl(m: re.Match) -> str:
            path = self.resolve(m.group(1))
            if path is None:
                return ""
            with open(path, encoding="utf-8") as f:
                return self._expand_includes(f.read(), depth + 1)

        return _INCLUDE_RE.sub(repl, text)

    def _lookup(self, props: ServerObjects, prefix: str, key: str) -> str | None:
        if prefix:
            v = props.get(prefix + key, None) if (prefix + key) in props else None
            if v is not None:
                return v
        return props.get(key) if key in props else None

    def _render(self, text: str, props: ServerObjects, prefix: str) -> str:
        out: list[str] = []
        i = 0
        n = len(text)
        while i < n:
            j = text.find("#", i)
            if j < 0 or j + 1 >= n:
                out.append(text[i:])
                break
            out.append(text[i:j])
            tag = text[j + 1]
            if tag == "[":
                end = text.find("]#", j + 2)
                if end < 0:
                    out.append(text[j:])
                    break
                key = text[j + 2:end]
                v = self._lookup(props, prefix, key)
                out.append(v if v is not None else "")
                i = end + 2
            elif tag == "(":
                end = text.find(")#", j + 2)
                if end < 0:
                    out.append(text[j:])
                    break
                key = text[j + 2:end]
                close = f"#(/{key})#"
                k = text.find(close, end + 2)
                if k < 0:
                    out.append(text[j:])
                    break
                body = text[end + 2:k]
                alts = self._split_alternatives(body)
                v = self._lookup(props, prefix, key) or "0"
                try:
                    sel = int(v)
                except ValueError:
                    sel = 0
                if not 0 <= sel < len(alts):
                    sel = 0
                out.append(self._render(alts[sel], props, prefix))
                i = k + len(close)
            elif tag == "{":
                end = text.find("}#", j + 2)
                if end < 0:
                    out.append(text[j:])
                    break
                key = text[j + 2:end]
                close = f"#{{/{key}}}#"
                k = self._find_matching_loop_close(text, end + 2, key)
                if k < 0:
                    out.append(text[j:])
                    break
                body = text[end + 2:k]
                v = self._lookup(props, prefix, key) or "0"
                try:
                    count = int(v)
                except ValueError:
                    count = 0
                for it in range(count):
                    out.append(self._render(body, props,
                                            prefix=f"{prefix}{key}_{it}_"))
                i = k + len(close)
            else:
                out.append("#")
                i = j + 1
        return "".join(out)

    @staticmethod
    def _split_alternatives(body: str) -> list[str]:
        """Split on :: at nesting depth 0 (alternatives may nest tags)."""
        alts, cur, depth, i, n = [], [], 0, 0, len(body)
        while i < n:
            if body.startswith("#(", i) and not body.startswith("#(/", i):
                depth += 1
                cur.append(body[i:i + 2]); i += 2
            elif body.startswith("#(/", i):
                depth -= 1
                cur.append(body[i:i + 3]); i += 3
            elif depth == 0 and body.startswith("::", i):
                alts.append("".join(cur)); cur = []; i += 2
            else:
                cur.append(body[i]); i += 1
        alts.append("".join(cur))
        return alts

    @staticmethod
    def _find_matching_loop_close(text: str, start: int, key: str) -> int:
        """Index of the #{/key}# matching the loop opened before `start`,
        honoring nested loops with the same key."""
        open_tag = f"#{{{key}}}#"
        close_tag = f"#{{/{key}}}#"
        depth = 1
        i = start
        while True:
            c = text.find(close_tag, i)
            if c < 0:
                return -1
            o = text.find(open_tag, i)
            if 0 <= o < c:
                depth += 1
                i = o + len(open_tag)
                continue
            depth -= 1
            if depth == 0:
                return c
            i = c + len(close_tag)
