"""Threaded HTTP server with servlet dispatch, templates, auth, and the
P2P wire endpoints.

Capability equivalent of the reference's Jetty embedding (reference:
source/net/yacy/http/Jetty9HttpServerImpl.java:112-233 handler chain;
source/net/yacy/http/servlets/YaCyDefaultServlet.java — static files +
template dispatch; source/net/yacy/http/Jetty9YaCySecurityHandler.java —
admin auth with localhost auto-admin).  Dispatch rules:

- ``/yacy/<endpoint>.html``  → the node's PeerServer RPC handler (the
  htroot/yacy/* wire servlets), JSON body in/out (our DCN wire format)
- ``/<Name>.<ext>``          → registered servlet ``Name``; the response
  property map fills template ``<Name>.<ext>`` from the htroot template
  roots; a missing template for ``.json`` serializes the map directly
- anything else             → static file from the template roots
- names ending ``_p``       → admin-only (localhost auto-admin or
  HTTP Basic against config ``adminAccountName``/``adminAccountPassword``)
"""

from __future__ import annotations

import base64
import json
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, unquote, urlsplit

from ..utils import faultinject, histogram, tracing
from .objects import ServerObjects
from .templates import TemplateEngine
from . import servlets

# the servlets the degradation ladder's shed rung refuses with a
# computed Retry-After: the query-serving surface — the load the ladder
# exists to defend.  The live rung is read from the actuator engine
# (act.effective_level(); the serving.degradeLevel config key is its
# write-only operator-visible mirror).  Observability and admin pages
# stay reachable: an operator must be able to SEE a shedding node
# (utils/actuator.py).
SHED_SERVLETS = frozenset({"yacysearch", "gsasearch", "yacysearchitem",
                           "suggest"})

_CONTENT_TYPES = {
    "html": "text/html; charset=utf-8",
    "json": "application/json; charset=utf-8",
    "rss": "application/rss+xml; charset=utf-8",
    "xml": "text/xml; charset=utf-8",
    "csv": "text/plain; charset=utf-8",
    "css": "text/css",
    "js": "application/javascript",
    "png": "image/png",
    "ico": "image/x-icon",
    "txt": "text/plain; charset=utf-8",
}

DEFAULT_HTROOT = os.path.join(os.path.dirname(__file__), "htroot")


class YaCyHttpServer:
    """One node's HTTP face: UI/API servlets + P2P wire endpoints."""

    def __init__(self, sb, port: int = 8090, host: str = "127.0.0.1",
                 peer_server=None, htroot_dirs: list[str] | None = None,
                 https_port: int | None = None,
                 certfile: str | None = None, keyfile: str | None = None,
                 reuse_port: bool = False):
        self.sb = sb
        self.peer_server = peer_server
        roots = list(htroot_dirs or [])
        roots.append(DEFAULT_HTROOT)
        self.templates = TemplateEngine(roots)
        from .security import SecurityHandler
        self.security = SecurityHandler(sb.config)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # one buffered write per response + TCP_NODELAY: the default
            # unbuffered handler emits each header line as its own tiny
            # segment, and Nagle x delayed-ACK stalls every keep-alive
            # response ~40 ms — which silently capped the whole served
            # path (a request costs ~6 ms of actual work)
            wbufsize = 64 * 1024
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                self._javawire = False
                outer._handle(self, {})

            def do_POST(self):
                # reset per REQUEST: one handler serves a whole
                # keep-alive connection
                self._javawire = False
                length = int(self.headers.get("content-length", 0) or 0)
                body = self.rfile.read(length) if length else b""
                ctype = self.headers.get("content-type", "")
                if "application/json" in ctype:
                    try:
                        post = json.loads(body.decode("utf-8"))
                    except ValueError:
                        post = {}
                elif "multipart/form-data" in ctype:
                    # the Java wire posts multipart key=value parts
                    # (reference Protocol.java basicRequestParts). The
                    # marker is OUT-OF-BAND (handler attribute): an
                    # in-band param could be forged via query string
                    from ..peers.javawire import multipart_decode
                    post = multipart_decode(body, ctype)
                    self._javawire = True
                else:
                    post = dict(parse_qsl(body.decode("utf-8", "replace"),
                                          keep_blank_values=True))
                outer._handle(self, post)

        if reuse_port:
            # multi-process serving: N worker processes bind the same
            # port and the kernel load-balances accepts across them
            # (server/rankservice.py)
            import socket as _socket

            class _ReusePortServer(ThreadingHTTPServer):
                def server_bind(self):
                    self.socket.setsockopt(_socket.SOL_SOCKET,
                                           _socket.SO_REUSEPORT, 1)
                    ThreadingHTTPServer.server_bind(self)
            server_cls = _ReusePortServer
        else:
            server_cls = ThreadingHTTPServer
        self.httpd = server_cls((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self.host = host
        self._thread: threading.Thread | None = None

        # HTTPS listener (reference: Jetty9HttpServerImpl.java:112-233
        # mounts an SSL connector beside the plain one when server.https
        # is on). Cert/key paths come from arguments or config; both
        # listeners share the one Handler/dispatch.
        self.httpsd = None
        self.https_port = None
        self.https_error: str | None = None
        self._https_thread: threading.Thread | None = None
        cfg = sb.config
        from_config = https_port is None
        if https_port is None and cfg.get_bool("server.https", False):
            https_port = cfg.get_int("port.ssl", 8443)
        if https_port is not None:
            import ssl
            certfile = certfile or cfg.get("ssl.certPath", "")
            keyfile = keyfile or cfg.get("ssl.keyPath", "") or None
            try:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(certfile, keyfile)
                self.httpsd = ThreadingHTTPServer((host, https_port),
                                                  Handler)
                self.httpsd.socket = ctx.wrap_socket(self.httpsd.socket,
                                                     server_side=True)
                self.https_port = self.httpsd.server_address[1]
            except Exception as e:
                # a misconfigured cert must not kill the plain-HTTP node
                # (the reference's Jetty setup degrades to HTTP-only too);
                # an explicit https_port argument is a programming contract
                # and still raises
                if not from_config:
                    self.httpd.server_close()
                    raise
                self.https_error = f"https disabled: {e}"
                self.httpsd = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "YaCyHttpServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="httpd", daemon=True)
        self._thread.start()
        if self.httpsd is not None:
            self._https_thread = threading.Thread(
                target=self.httpsd.serve_forever, name="httpsd", daemon=True)
            self._https_thread.start()
        # recorded-API replay goes through our own HTTP surface (the
        # reference's WorkTables.execAPICall self-call), so the recorded
        # URL stays the replayable action across restarts
        if getattr(self.sb, "api_executor", None) is None:
            def _exec(path: str) -> bool:
                import urllib.request
                url = self.base_url + (path if path.startswith("/")
                                       else "/" + path)
                try:
                    with urllib.request.urlopen(url, timeout=60) as r:
                        return r.status == 200
                except Exception:
                    return False
            self.sb.api_executor = _exec
        return self

    def close(self) -> None:
        # shutdown() blocks on the serve_forever loop acknowledging — it
        # must only run when that loop was actually started
        if self._thread:
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self.httpsd is not None:
            if self._https_thread:
                self.httpsd.shutdown()
            self.httpsd.server_close()
            if self._https_thread:
                self._https_thread.join(timeout=5)

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def https_url(self) -> str | None:
        return (f"https://{self.host}:{self.https_port}"
                if self.https_port else None)

    # -- auth ----------------------------------------------------------------

    def _is_admin(self, handler) -> bool:
        """Basic/digest/localhost admin check (server/security.py)."""
        return self.security.is_admin(
            handler.client_address[0], handler.headers,
            method=handler.command, uri=urlsplit(handler.path).path)

    def _send_401(self, handler) -> None:
        handler.send_response(401)
        body = b"admin authorization required"
        handler.send_header("Content-Type", "text/plain")
        handler.send_header("Content-Length", str(len(body)))
        # both schemes offered: one WWW-Authenticate header per scheme
        for challenge in self.security.challenges():
            handler.send_header("WWW-Authenticate", challenge)
        handler.end_headers()
        handler.wfile.write(body)

    # -- dispatch ------------------------------------------------------------

    def _handle(self, handler, post_params: dict) -> None:
        try:
            # client allowlist + abuse throttle run before EVERY branch —
            # including the proxy and *.yacy rewrites below, which fetch
            # attacker-supplied URLs and must never be reachable by a
            # client the allowlist rejects (serverAccessTracker +
            # serverClient parity; the reference's Jetty chain puts the
            # monitor/security handlers ahead of the proxy handler)
            tracker = getattr(self.sb, "access_tracker", None)
            act = getattr(self.sb, "actuators", None)
            client_ip = handler.client_address[0]
            # per-client identity behind a LOCAL front (ISSUE 19): when
            # the direct peer is loopback — a reverse proxy on the node,
            # or the game-day workload generator — X-Forwarded-For
            # names the real client for the access tracker and the
            # admission token buckets, which also makes that identity
            # subject to 429 (loopback itself stays exempt).  Never
            # honored from a non-loopback peer, and only the LAST
            # comma-separated entry counts: proxies APPEND the peer
            # they saw, so the last entry is the one written by the
            # trusted proxy on this node, while earlier entries arrive
            # attacker-supplied and would let a remote client spoof an
            # allowlisted identity or launder past the rate limits.
            if client_ip in ("127.0.0.1", "::1"):
                fwd = handler.headers.get(
                    "X-Forwarded-For", "").split(",")[-1].strip()
                if fwd:
                    client_ip = fwd
            if not self.security.client_allowed(client_ip):
                self._send(handler, 403, "text/plain",
                           b"client not allowed")
                return
            if tracker is not None:
                hits = tracker.track_access(client_ip)
                limit = self.sb.config.get_int(
                    "httpd.maxAccessPerHost.600s", 6000)
                # admission control (ISSUE 9): the per-client token
                # bucket decides alongside the windowed host count, and
                # the hard-coded Retry-After 600 becomes the honest
                # wait of WHICHEVER policy denied — the window's own
                # drain time (when the oldest over-limit hit ages out)
                # or the bucket's refill ETA; both tripping takes the
                # longer wait
                over, retry_s = hits > limit, 0.0
                if over:
                    retry_s = max(1.0, tracker.retry_after_s(
                        client_ip, limit))
                if act is not None:
                    admitted, bucket_retry = act.admit(client_ip)
                    if not admitted:
                        over = True
                        retry_s = max(retry_s, bucket_retry)
                if over and client_ip not in ("127.0.0.1", "::1"):
                    # ceil, never truncate: a client honoring the
                    # header exactly must be admitted on its retry
                    self._send(handler, 429, "text/plain",
                               b"too many requests",
                               extra={"Retry-After":
                                      str(max(1, math.ceil(retry_s)))})
                    return

            # forward-proxy request line (GET http://host/path) — the
            # transparent indexing proxy (reference:
            # server/http/HTTPDProxyHandler.java, config proxyURL /
            # proxyIndexing)
            if handler.path.startswith(("http://", "https://")):
                self._handle_forward_proxy(handler, handler.path)
                return
            # *.yacy virtual domains resolve to peers by name (reference:
            # the Jetty domain-rewrite handler + HTTPDProxyHandler)
            host_header = handler.headers.get("Host", "").split(":")[0]
            if host_header.endswith(".yacy"):
                self._handle_yacy_domain(handler, host_header, handler.path)
                return

            parts = urlsplit(handler.path)
            path = unquote(parts.path)
            params = dict(parse_qsl(parts.query, keep_blank_values=True))
            params.update(post_params)

            if path.startswith("/yacy/"):
                self._handle_wire(handler, path, params)
                return

            if path in ("", "/"):
                path = "/index.html"
            name, _, ext = path.lstrip("/").rpartition(".")
            if not name:
                name, ext = ext, "html"

            # per-path protection applies to servlets AND static files
            # (an admin template source must not leak via static serving)
            if self.security.admin_required(name, path) \
                    and not self._is_admin(handler):
                self._send_401(handler)
                return
            fn = servlets.lookup(name)
            if fn is None:
                self._serve_static(handler, path.lstrip("/"))
                return

            # degradation ladder (ISSUE 9): the shed rung refuses the
            # query-serving servlets outright with the recovery-derived
            # Retry-After; lower rungs thread the level through to the
            # search path and stamp every downgraded answer
            lvl = act.effective_level() if act is not None else 0
            if lvl >= 4 and name in SHED_SERVLETS:
                act.note_shed()
                self._send(handler, 429, "text/plain",
                           b"shedding load: serving degraded",
                           extra={"Retry-After": str(max(1, math.ceil(
                               act.shed_retry_after_s()))),
                               "X-YaCy-Degraded": str(lvl)})
                return

            post = ServerObjects(params)
            header = {"ext": ext, "path": path,
                      "client_ip": handler.client_address[0],
                      "method": handler.command,
                      # the ladder rung this request serves under
                      # (searchevent reads it off QueryParams; servlets
                      # may inspect it here)
                      "degrade": lvl,
                      # servlets mounted both public and _p can tighten
                      # behavior for non-admin callers (getpageinfo SSRF
                      # classes, RegexTest limits)
                      "admin": self._is_admin(handler),
                      # content negotiation (the /metrics endpoint
                      # upgrades to OpenMetrics + exemplars on it)
                      "accept": handler.headers.get("Accept", ""),
                      "host": handler.headers.get(
                          "Host", f"{self.host}:{self.port}")}
            # servlet serving wall -> windowed histogram (ISSUE 4): the
            # full dispatch+render wall of EVERY servlet — including
            # ones that raise into the 500 handler below (the finally:
            # a wedged endpoint must not vanish from the very SLO
            # histogram that would page on it).  When the servlet
            # rooted a trace, its id becomes the histogram exemplar so
            # a slow bucket on /metrics links to the waterfall
            tracing.clear_last_trace_id()
            t_sv = time.perf_counter()
            try:
                # env-gated failpoint INSIDE the measured wall: injected
                # latency lands in the very SLO histogram the burn-rate
                # rules read, so ladder tests drive real burns
                faultinject.sleep("servlet.serving")
                prop = fn(header, post, self.sb)
                if isinstance(prop.raw_body, bytes):  # binary (PNG etc.)
                    body = prop.raw_body
                    ctype = prop.raw_ctype or "application/octet-stream"
                else:
                    body = self._render(name, ext, prop).encode("utf-8")
                    ctype = prop.raw_ctype or _CONTENT_TYPES.get(
                        ext, "text/html; charset=utf-8")
            finally:
                histogram.observe("servlet.serving",
                                  (time.perf_counter() - t_sv) * 1000.0,
                                  tracing.last_trace_id())
            # any downgraded answer is stamped (ISSUE 9 satellite): a
            # client/load balancer can tell a degraded 200 from a full
            # one without parsing the body.  A lost device (ISSUE 10c)
            # marks too: results are host-fallback-served until the
            # background rebuild restores device parity.
            ds = getattr(self.sb.index, "devstore", None)
            dlost = ds is not None and getattr(ds, "device_lost", False)
            degr = None
            if lvl > 0:
                degr = (f"{lvl}+device-loss" if dlost else str(lvl))
            elif dlost:
                degr = "device-loss"
            self._send(handler, 200, ctype, body,
                       extra={"X-YaCy-Degraded": degr} if degr else None)
        except BrokenPipeError:
            pass
        except Exception as e:  # CrashProtectionHandler parity
            try:
                self._send(handler, 500, "text/plain",
                           f"server error: {e}".encode("utf-8"))
            except (OSError, ValueError):
                pass  # client hung up (or its wfile closed) before the 500

    def _translation(self):
        """Lazy-loaded translation table for the configured UI language
        (config `locale.language`; reloaded when the setting changes)."""
        from .translation import load_locale
        lang = self.sb.config.get("locale.language", "default")
        cached = getattr(self, "_i18n", None)
        if cached is None or cached.lang != lang:
            locales = os.path.join(self.sb.data_dir, "LOCALES") \
                if getattr(self.sb, "data_dir", None) else None
            cached = load_locale(locales, lang)
            cached.lang = lang
            self._i18n = cached
        return cached

    def _translate_source(self, source: str, section: str) -> str:
        """Shared expand-includes + translate pipeline: includes expand
        FIRST so the shared header chrome translates too; properties
        substitute later, so crawled content is never rewritten."""
        source = self.templates._expand_includes(source, 0)
        i18n = self._translation()
        if not i18n.is_empty():
            source = i18n.translate(source, section)
        return source

    def _render(self, name: str, ext: str, prop: ServerObjects) -> str:
        if prop.raw_body is not None:
            return prop.raw_body
        tmpl = f"{name}.{ext}"
        path = self.templates.resolve(tmpl)
        if path is not None:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            if ext == "html":
                source = self._translate_source(source, tmpl)
            return self.templates.render(source, prop)
        if ext == "html":
            # no bespoke template: render the GENERIC admin page — real
            # chrome + nav + a live property table, so every registered
            # servlet is operator-usable in a browser (VERDICT r2 #5;
            # the reference ships a full HTML page per servlet).
            # CONTRACT: this path ALWAYS html-escapes values. Props a
            # servlet pre-escaped show entity text here (cosmetic); the
            # alternative — trusting every servlet to have escaped —
            # would turn one unescaped put() into stored XSS.
            gen = self.templates.resolve("env/generic_page.html")
            if gen is not None:
                from .objects import escape_html
                page = ServerObjects()
                page.put("servletname", escape_html(name))
                items = sorted(prop.items())
                page.put("rows", len(items))
                for i, (k, v) in enumerate(items):
                    page.put(f"rows_{i}_key", escape_html(str(k)))
                    page.put(f"rows_{i}_value", escape_html(str(v)))
                with open(gen, encoding="utf-8") as f:
                    source = f.read()
                source = self._translate_source(source, f"{name}.html")
                return self.templates.render(source, page)
        # No template: serialize the property map directly. Values follow
        # the template contract — the servlet already escaped them for the
        # output medium — so insert them verbatim (json.dumps would
        # double-escape what escape_json produced).
        rows = ",\n".join(f' {json.dumps(k)}: "{v}"'
                          for k, v in sorted(prop.items()))
        return "{\n" + rows + "\n}"

    # -- transparent proxy ---------------------------------------------------

    def _proxy_profile(self):
        """The crawl profile proxied pages are indexed under (reference:
        the defaultProxyProfile in CrawlSwitchboard)."""
        for p in self.sb.profiles.values():
            if p.name == "proxy":
                return p
        from ..crawler.profile import CrawlProfile
        profile = CrawlProfile("proxy", depth=0, remote_indexing=False)
        self.sb.add_profile(profile)
        return profile

    def _loopback_target(self, url: str) -> bool:
        """Shared SSRF predicate (server/netguard.py): a proxied fetch
        FROM localhost would be granted localhost auto-admin by the
        target, so a remote client must never aim the node at itself."""
        from .netguard import loopback_target
        return loopback_target(url, self.sb.loader)

    def _private_target(self, url: str) -> bool:
        """Non-admin SSRF predicate: also refuses link-local (cloud
        metadata) and RFC1918 targets (server/netguard.py)."""
        from .netguard import private_target
        return private_target(url, self.sb.loader)

    def _handle_forward_proxy(self, handler, url: str) -> None:
        cfg = self.sb.config
        if not cfg.get_bool("proxyURL", False):
            self._send(handler, 403, "text/plain",
                       b"forward proxy disabled (config proxyURL)")
            return
        is_admin = self._is_admin(handler)
        # non-admin clients may not aim the proxy at loopback, link-local
        # (cloud metadata) or LAN targets (netguard; ADVICE r4)
        if self._private_target(url) and not is_admin:
            self._send(handler, 403, "text/plain",
                       b"proxy to this node refused")
            return
        from ..crawler.loader import CacheStrategy
        from ..crawler.request import Request
        # the same guard rides every redirect hop, and the addr_guard
        # pins each connection to a vetted resolution (a hostname that
        # passed the check must not re-resolve to loopback at fetch time)
        url_filter = None if is_admin \
            else (lambda u: not self._private_target(u))
        from .netguard import refuse_addr
        addr_guard = None if is_admin \
            else (lambda a: refuse_addr(a, allow_private=False))
        try:
            resp = self.sb.loader.load(Request(url=url),
                                       CacheStrategy.IFFRESH,
                                       url_filter=url_filter,
                                       addr_guard=addr_guard)
        except Exception as e:
            self._send(handler, 502, "text/plain",
                       f"proxy fetch failed: {e}".encode())
            return
        if resp.status != 200:
            # relay the upstream response (redirects need their Location
            # header to keep browsing working through the proxy)
            extra = {k: v for k, v in resp.headers.items()
                     if k.lower() in ("location", "content-type",
                                      "cache-control", "expires",
                                      "set-cookie", "last-modified")
                     and k.lower() != "content-type"}
            ctype = resp.headers.get("content-type", "text/plain")
            self._send(handler, resp.status or 502, ctype,
                       resp.content or b"", extra=extra)
            return
        # indexing side effect (HTTPDProxyHandler hands fetched pages to
        # the indexer when proxyIndexing is on)
        if cfg.get_bool("proxyIndexing", False) \
                and resp.indexable() is None:
            try:
                self.sb.to_indexer(resp, self._proxy_profile())
            except Exception:
                import logging
                logging.getLogger("httpd.proxy").warning(
                    "proxy page not handed to indexer: %s", resp.url,
                    exc_info=True)
        ctype = resp.headers.get("content-type",
                                 "application/octet-stream")
        self._send(handler, 200, ctype, resp.content)

    def _handle_yacy_domain(self, handler, host: str, path: str) -> None:
        """<peername>.yacy resolves through the seed directory."""
        peer_name = host[:-len(".yacy")]
        # P2PNode publishes the seed directory on the switchboard
        # (peers/node.py: self.sb.seeddb = ...)
        seeddb = getattr(self.sb, "seeddb", None) \
            or getattr(getattr(self.sb, "node", None), "seeddb", None)
        seed = None
        if seeddb is not None:
            for s in seeddb.all_seeds():
                if s.name == peer_name:
                    seed = s
                    break
        if seed is None:
            self._send(handler, 502, "text/plain",
                       f"unknown peer: {peer_name}".encode())
            return
        from ..crawler.loader import CacheStrategy
        from ..crawler.request import Request
        target = f"http://{seed.ip}:{seed.port}{path}"
        # same rule as the forward proxy: a seed claiming a loopback
        # address would make the node fetch localhost services (itself —
        # where auto-admin applies — or anything co-located); non-admin
        # clients are refused
        if self._loopback_target(target) and not self._is_admin(handler):
            self._send(handler, 403, "text/plain",
                       b"peer resolves to this node")
            return
        try:
            resp = self.sb.loader.load(Request(url=target),
                                       CacheStrategy.NOCACHE)
        except Exception as e:
            self._send(handler, 502, "text/plain",
                       f"peer fetch failed: {e}".encode())
            return
        ctype = resp.headers.get("content-type", "text/html")
        self._send(handler, resp.status or 200, ctype, resp.content)

    def _handle_wire(self, handler, path: str, params: dict) -> None:
        if self.peer_server is None:
            self._send(handler, 404, "text/plain", b"p2p disabled")
            return
        # distributed tracing: the originator's trace id arrives in the
        # X-YaCy-Trace header (peers/transport.HttpTransport emits it);
        # hand it to the PeerServer in-band so loopback and HTTP wires
        # share one code path (peers/server.py roots the remote spans)
        from ..utils import tracing
        wire_tid = handler.headers.get(tracing.TRACE_HEADER)
        if wire_tid and tracing.PAYLOAD_KEY not in params:
            params = {**params, tracing.PAYLOAD_KEY: wire_tid}
        endpoint = path[len("/yacy/"):]
        if endpoint.endswith(".html"):
            endpoint = endpoint[:-5]
        if getattr(handler, "_javawire", False) and endpoint == "hello":
            # a REAL YaCy peer greeting us: answer in the Java key=value
            # table format (htroot/yacy/hello.java), with the caller's
            # seed ingested into our directory like our native hello
            from ..peers import javawire
            from ..peers.seed import Seed as _Seed
            # network-unit admission (reference hello.java via
            # Protocol.authentifyRequest:2109): a peer from a foreign
            # network must not pollute this seed directory. An absent
            # netid defaults to "freeworld" EXACTLY like the reference
            # (post.get(NETWORK_NAME, Seed.DFLT_NETWORK_UNIT)).
            cfg = self.sb.config
            unit = cfg.get("network.unit.name", "freeworld")
            if params.get("netid", "freeworld") != unit:
                self._send(handler, 200, "text/plain; charset=utf-8",
                           b"message=wrong network\n")
                return
            magic = cfg.get(
                "network.unit.protocol.request.authentication.essentials",
                "")
            if magic and params.get("magicmd5", "") != javawire.magic_md5(
                    params.get("key", ""), params.get("iam", ""), magic):
                self._send(handler, 200, "text/plain; charset=utf-8",
                           b"message=authentication failed\n")
                return
            # a fleet digest riding the Java wire as the xdigest part
            # (peers/javawire.DIGEST_PART) lands in the fleet table the
            # same way the in-band `_digest` key does on the JSON wire
            fl = getattr(self.sb, "fleet", None)
            if fl is not None and params.get(javawire.DIGEST_PART):
                dig = javawire.decode_digest_part(
                    params[javawire.DIGEST_PART])
                if dig is not None:
                    fl.ingest(dig)
            # translate the Java formats at the edge, then delegate to
            # THE hello implementation (PeerServer.do_hello owns seed
            # ingest, live counts, and the gossip batch)
            payload: dict = {}
            client_seed = None
            try:
                client_seed = javawire.decode_seed(params.get("seed", ""))
                # patch the address to what we actually saw (the
                # reference anti-spoofing rule, Protocol.java:246)
                client_seed.ip = handler.client_address[0]
                payload["seed"] = client_seed.dna()
            except ValueError:
                pass
            reply = self.peer_server.do_hello(payload)
            me = _Seed.from_dna(reply["seed"])
            extra = []
            for dna in reply.get("seeds", []):
                try:
                    s = _Seed.from_dna(dna)
                except (KeyError, ValueError):
                    continue
                if s.hash != me.hash:
                    extra.append(s)
            body = javawire.java_hello_response(
                me, extra, handler.client_address[0], client_seed)
            self._send(handler, 200, "text/plain; charset=utf-8", body)
            return
        if endpoint == "meshsearch":
            # the mesh coordinator's external query entry IS a serving
            # surface (ISSUE 15): its wall lands in the same SLO
            # histogram the burn-rate rules read, with the mesh.serve
            # trace id as the exemplar — so a straggling member burns
            # slo_serving_p95 and the incident can name the cause.
            # Other wire RPCs (DHT shipping, digests, scatter internals)
            # stay out: they are not query serving.
            tracing.clear_last_trace_id()
            t_sv = time.perf_counter()
            try:
                result = self.peer_server.handle(endpoint, params)
            finally:
                histogram.observe("servlet.serving",
                                  (time.perf_counter() - t_sv) * 1000.0,
                                  tracing.last_trace_id())
        else:
            result = self.peer_server.handle(endpoint, params)
        body = json.dumps(result, default=_wire_default).encode("utf-8")
        self._send(handler, 200, "application/json", body)

    def _serve_static(self, handler, relpath: str) -> None:
        if ".." in relpath:
            self._send(handler, 403, "text/plain", b"forbidden")
            return
        path = self.templates.resolve(relpath)
        if path is None:
            self._send(handler, 404, "text/plain", b"not found")
            return
        ext = relpath.rpartition(".")[2]
        with open(path, "rb") as f:
            data = f.read()
        if ext == "html" and (b"#%" in data
                              or not self._translation().is_empty()):
            # static html that uses template includes (the shared
            # chrome), or any page under a non-default locale, runs the
            # expand -> translate -> render pipeline. Plain static pages
            # under the default locale are served BYTE-FOR-BYTE — an
            # operator-dropped file must not be re-encoded or have
            # literal template-syntax text stripped.
            try:
                source = data.decode("utf-8")
            except UnicodeDecodeError:
                source = None       # not UTF-8: serve verbatim
            if source is not None:
                source = self._translate_source(
                    source, os.path.basename(relpath))
                data = self.templates.render(
                    source, ServerObjects()).encode("utf-8")
        self._send(handler, 200, _CONTENT_TYPES.get(ext, "application/octet-stream"), data)

    @staticmethod
    def _send(handler, status: int, ctype: str, body: bytes,
              extra: dict | None = None) -> None:
        handler.send_response(status)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(body)


def _wire_default(obj):
    """JSON fallback for wire payloads: bytes → base64 strings, numpy →
    lists (the HTTP DCN transport's serialization rules)."""
    import numpy as np
    if isinstance(obj, bytes):
        return base64.b64encode(obj).decode("ascii")
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    raise TypeError(f"not serializable: {type(obj)}")
