"""Multi-process serving — N HTTP workers around one device-arena owner.

VERDICT r2 weak #5: a single Python process caps the served path at a
few hundred q/s of host work (parse, drain, render) long before the
kernel saturates — the GIL is the ceiling, not the device. The
reference serves from a Jetty thread pool (reference:
source/net/yacy/http/Jetty9HttpServerImpl.java:112 — real OS threads);
the CPython equivalent is PROCESSES:

- the **owner** process holds the full Switchboard: crawling, indexing,
  the RWI RAM buffer, and the device arena. It exposes
  ``rank_term``/``rank_join`` on a unix socket via ``RankServiceServer``
  (one dispatcher thread per worker connection — the device dispatch
  releases the GIL during the kernel round trip, so concurrent worker
  requests batch in the arena's _QueryBatcher exactly like same-process
  threads).
- **workers** run the HTTP surface + query host work. Each worker opens
  the SAME data dir read-only — the M48 segmented stores are mmap'd
  files, so N workers share one page cache, not N copies — and mounts a
  ``RankServiceClient`` as its serving store: every eligible query's
  device ranking rides the socket to the owner's arena.
- workers bind the same port with SO_REUSEPORT: the kernel load-balances
  connections across worker processes, no proxy needed.

Transport: ``multiprocessing.connection`` (length-prefixed pickle over
AF_UNIX, authkey-authenticated) — numpy arrays round-trip natively and
the hop costs ~50-100 µs, noise against a device dispatch.

Workers see the index as of their start (plus whatever the owner
flushed); after heavy re-indexing the operator bounces workers (the
same restart contract as any mmap-snapshot reader).
"""

from __future__ import annotations

import os
import secrets
import threading
import time
from multiprocessing.connection import Client, Listener

# spawn_worker mutates process-global os.environ around start(): one at
# a time, or concurrent spawns could leave the parent pinned to cpu
_SPAWN_LOCK = threading.Lock()

# the owner dispatches ONLY these store methods — conn.recv() is pickle
# underneath, so the dispatch surface must be a closed set, never getattr
# over attacker-chosen names.  serving_state is the degradation-ladder
# propagation channel (ISSUE 9): workers ask the owner's actuator rung
# so the whole process group degrades together
_METHODS = frozenset({"rank_term", "rank_join", "count_upper",
                      "serving_state"})


def _key_path(socket_path: str) -> str:
    return socket_path + ".key"


def _load_authkey(socket_path: str) -> bytes:
    with open(_key_path(socket_path), "rb") as fh:
        return fh.read()


class RankServiceServer:
    """Expose the owner Switchboard's serving store on a unix socket.

    The wire format (multiprocessing.connection) is pickle, so transport
    auth is the security boundary: a RANDOM per-instance authkey is
    generated at startup and persisted mode-0600 next to the socket for
    workers to read (a hardcoded key would hand any local user an HMAC
    pass and, with it, arbitrary unpickling in the owner process —
    ADVICE r3). The socket itself is also chmod 0600."""

    def __init__(self, store, socket_path: str, state_fn=None):
        self.store = store
        # owner-side serving state for workers (ISSUE 9): usually
        # sb.actuators.serving_state — the ladder rung + Retry-After the
        # whole process group serves under.  None answers level 0.
        self.state_fn = state_fn
        self.socket_path = socket_path
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        self.authkey = secrets.token_bytes(32)
        kp = _key_path(socket_path)
        # O_EXCL on a freshly-unlinked path: a stale key file (whose mode
        # O_CREAT would keep) or a planted symlink must never receive the
        # new secret
        if os.path.lexists(kp):
            os.unlink(kp)
        flags = os.O_WRONLY | os.O_CREAT | os.O_EXCL
        flags |= getattr(os, "O_NOFOLLOW", 0)
        fd = os.open(kp, flags, 0o600)
        try:
            os.write(fd, self.authkey)
        finally:
            os.close(fd)
        self.listener = Listener(socket_path, family="AF_UNIX",
                                 authkey=self.authkey)
        os.chmod(socket_path, 0o600)
        self._stop = False
        self._threads: list[threading.Thread] = []
        self._accept = threading.Thread(target=self._accept_loop,
                                        name="rank-accept", daemon=True)
        self._accept.start()

    def _accept_loop(self) -> None:
        from multiprocessing import AuthenticationError
        while not self._stop:
            try:
                conn = self.listener.accept()
            except AuthenticationError:
                continue    # a rejected client must not kill the acceptor
            except (OSError, EOFError):
                # a client dying MID-HANDSHAKE raises EOF/ECONNRESET out
                # of accept() too — only a real shutdown ends the loop
                if self._stop:
                    return
                time.sleep(0.05)   # broken listener must not spin hot
                continue
            t = threading.Thread(target=self._serve, args=(conn,),
                                 name="rank-conn", daemon=True)
            t.start()
            # reap finished connection threads: one HTTP connection per
            # worker thread means a long-lived owner sees many
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve(self, conn) -> None:
        """One worker connection: sequential request/response (workers
        multiplex with a connection per HTTP thread)."""
        store = self.store
        while not self._stop:
            try:
                method, args, kwargs = conn.recv()
            except (EOFError, OSError):
                return
            try:
                if method not in _METHODS:
                    raise ValueError(f"method not allowed: {method!r}")
                if method == "serving_state":
                    out = self.state_fn() if self.state_fn is not None \
                        else {"level": 0, "retry_after_s": 0.0}
                elif method == "count_upper":
                    out = store.rwi.count_upper(*args)
                else:
                    out = getattr(store, method)(*args, **kwargs)
                conn.send(("ok", out))
            except Exception as e:   # worker falls back to its host path
                try:
                    conn.send(("err", repr(e)))
                except (OSError, EOFError):
                    return

    def close(self) -> None:
        self._stop = True
        try:
            self.listener.close()
        except OSError:
            pass
        for path in (self.socket_path, _key_path(self.socket_path)):
            if os.path.exists(path):
                try:
                    os.unlink(path)
                except OSError:
                    pass


class RankServiceClient:
    """Duck-types the serving store inside a worker process.

    SearchEvent._device_local calls rank_term/rank_join and reads the
    fallback counters; every call forwards over the socket to the
    owner's arena. Connections are per-thread (the server serves each
    sequentially)."""

    small_rank_n: int | None = None

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._local = threading.local()
        self.queries_served = 0
        self.fallbacks = 0
        self.join_served = 0
        self.join_fallbacks = 0
        # probe once so a missing owner fails at construction, not on
        # the first query
        self._conn()

    def _conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = Client(self.socket_path, family="AF_UNIX",
                          authkey=_load_authkey(self.socket_path))
            self._local.conn = conn
        return conn

    def _call(self, method: str, *args, **kwargs):
        try:
            conn = self._conn()
            conn.send((method, args, kwargs))
            status, out = conn.recv()
        except (OSError, EOFError):
            self._local.conn = None
            return None          # owner gone: host path serves
        if status != "ok":
            return None
        return out

    # -- serving-store surface ----------------------------------------------

    def rank_term(self, *args, **kwargs):
        out = self._call("rank_term", *args, **kwargs)
        if out is None:
            self.fallbacks += 1
        else:
            self.queries_served += 1
        return out

    def rank_join(self, *args, **kwargs):
        out = self._call("rank_join", *args, **kwargs)
        if out is None:
            self.join_fallbacks += 1
        else:
            self.join_served += 1
            self.queries_served += 1
        return out

    def count_upper(self, termhash: bytes) -> int:
        out = self._call("count_upper", termhash)
        return out if out is not None else 0

    def serving_state(self) -> dict:
        """The OWNER's degradation-ladder state (ISSUE 9): workers fold
        this into their own effective level so the whole process group
        sheds/degrades together.  TTL-cached — the actuator asks at
        most ~1/s and a socket hop per search would be pure tax."""
        now = time.monotonic()
        cached = getattr(self._local, "state_cache", None)
        if cached is not None and now - cached[0] < 1.0:
            return cached[1]
        out = self._call("serving_state")
        state = out if isinstance(out, dict) else {"level": 0}
        self._local.state_cache = (now, state)
        return state

    def enable_batching(self, **_kw) -> None:
        """Owner-side batching already coalesces concurrent workers."""

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass


def make_worker_switchboard(data_dir: str, socket_path: str,
                            small_rank_n: int | None = None):
    """A read-only worker Switchboard over the owner's data dir, serving
    device ranking through the rank service."""
    from ..switchboard import Switchboard
    from ..utils.config import Config
    cfg = Config()
    cfg.set("index.device.serving", "false")    # no local arena
    sb = Switchboard(data_dir=data_dir, config=cfg)
    # READ-ONLY contract: the data dir belongs to the OWNER. Detach every
    # journal/dump sink so nothing in the worker — including store
    # close() paths, which snapshot and TRUNCATE journals — can write
    # into the owner's live files.
    meta = sb.index.metadata
    if meta._journal is not None:
        meta._journal.close()
        meta._journal = None          # close() skips snapshot without it
    wg = sb.index.webgraph
    if wg._journal is not None:
        wg._journal.close()
        wg._journal = None
    sb.index.dense.data_dir = None    # flush() becomes a no-op
    sb.access_tracker.dump_path = None
    client = RankServiceClient(socket_path)
    client.small_rank_n = small_rank_n
    sb.index.devstore = client
    return sb


def spawn_worker(ctx, data_dir: str, socket_path: str, port: int, **kw):
    """Start a worker Process with JAX pinned to CPU in its environment.

    The override must happen in the PARENT around start(): under the
    spawn method the child re-imports the main module (and with it jax)
    during bootstrap, before any code inside run_worker executes — an
    inherited accelerator platform would either fail to register in the
    child or open a second tunnel client that serializes against the
    owner's."""
    with _SPAWN_LOCK:
        old = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            p = ctx.Process(target=run_worker,
                            args=(data_dir, socket_path, port),
                            kwargs=kw, daemon=True)
            p.start()
        finally:
            if old is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = old
    return p


def run_worker(data_dir: str, socket_path: str, port: int,
               host: str = "127.0.0.1", ready=None, stop=None,
               small_rank_n: int | None = None) -> None:
    """Worker process main: read-only Switchboard + HTTP on a shared
    SO_REUSEPORT port. `ready`/`stop` are optional multiprocessing
    Events for supervised startup/shutdown."""
    # workers never touch the accelerator (device ranking rides the
    # socket to the owner): pin jax to CPU BEFORE anything imports it —
    # an inherited experimental-plugin platform may not survive spawn,
    # and a second tunnel client would serialize against the owner's
    os.environ["JAX_PLATFORMS"] = "cpu"
    from . import YaCyHttpServer
    sb = make_worker_switchboard(data_dir, socket_path,
                                 small_rank_n=small_rank_n)
    srv = YaCyHttpServer(sb, port=port, host=host, reuse_port=True).start()
    if ready is not None:
        ready.set()
    try:
        if stop is not None:
            stop.wait()
        else:                      # standalone: serve until killed
            threading.Event().wait()
    finally:
        srv.close()
        # NO sb.close(): beyond the detached journals, subsystem close
        # paths (frontier, web structure, dense) rewrite files from this
        # worker's possibly-stale view of the owner's live data dir. The
        # process exits here — mmaps and sockets die with it.
        if sb.index.devstore is not None:
            sb.index.devstore.close()
