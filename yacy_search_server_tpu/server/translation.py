"""UI translation — language files applied to rendered pages.

Capability equivalent of the reference's translator (reference:
source/net/yacy/utils/translation/ + Translator.java — `.lng` files under
locales/ hold per-template `source==target` string pairs; the build
translates htroot copies per language, selected by `locale.language`).
Here translation applies at RENDER time (no template copies): a
TranslationTable loads `<lang>.lng`, and the HTTP layer rewrites the
rendered HTML body when a non-default language is configured.

File format (Translator-compatible subset):
    #File: yacysearch.html          -> section: apply to this template
    Search==Suchen                  -> source==target
    #File: *                        -> section: apply everywhere
Lines starting with `#` otherwise are comments.
"""

from __future__ import annotations

import os
import threading


class TranslationTable:
    def __init__(self, lang: str = ""):
        self.lang = lang
        # template name ('*' = global) -> [(source, target)]
        self._sections: dict[str, list[tuple[str, str]]] = {}
        self._merged: dict[str, list[tuple[str, str]]] = {}  # sorted cache
        self._lock = threading.Lock()

    @staticmethod
    def load(path: str) -> "TranslationTable":
        t = TranslationTable(os.path.basename(path).split(".")[0])
        try:
            with open(path, encoding="utf-8") as f:
                t.load_text(f.read())
        except OSError:
            pass
        return t

    def load_text(self, text: str) -> int:
        section = "*"
        n = 0
        with self._lock:
            for raw in text.splitlines():
                line = raw.strip()
                if not line:
                    continue
                if line.lower().startswith("#file:"):
                    section = line.split(":", 1)[1].strip() or "*"
                    continue
                if line.startswith("#"):
                    continue
                if "==" not in line:
                    continue
                src, _, dst = line.partition("==")
                if src:
                    self._sections.setdefault(section, []).append((src, dst))
                    n += 1
            self._merged.clear()
        return n

    def add(self, source: str, target: str, template: str = "*") -> None:
        with self._lock:
            self._sections.setdefault(template, []).append((source, target))
            self._merged.clear()

    def translate(self, body: str, template: str = "*") -> str:
        """Apply global pairs then template-specific pairs (longest source
        first, so overlapping strings replace deterministically). The
        sorted merge is cached per template — .lng files carry thousands
        of pairs and every page render calls this."""
        with self._lock:
            pairs = self._merged.get(template)
            if pairs is None:
                pairs = list(self._sections.get("*", []))
                if template != "*":
                    pairs += self._sections.get(template, [])
                pairs.sort(key=lambda p: -len(p[0]))
                self._merged[template] = pairs
        for src, dst in pairs:
            body = body.replace(src, dst)
        return body

    def is_empty(self) -> bool:
        with self._lock:
            return not self._sections


# languages shipped with the package (reference: locales/*.lng in the
# distribution); a DATA/LOCALES file with the same name overrides it
SHIPPED_LOCALES_DIR = os.path.join(os.path.dirname(__file__), "locales")


def shipped_languages() -> list[str]:
    if not os.path.isdir(SHIPPED_LOCALES_DIR):
        return []
    return sorted(f[:-4] for f in os.listdir(SHIPPED_LOCALES_DIR)
                  if f.endswith(".lng"))


def load_locale(locales_dir: str | None, lang: str) -> TranslationTable:
    """`<locales_dir>/<lang>.lng`, falling back to the shipped locale of
    the same name; empty table for default/english."""
    if not lang or lang in ("en", "default", "browser"):
        return TranslationTable()
    if locales_dir:
        path = os.path.join(locales_dir, lang + ".lng")
        if os.path.exists(path):
            return TranslationTable.load(path)
    shipped = os.path.join(SHIPPED_LOCALES_DIR, lang + ".lng")
    if os.path.exists(shipped):
        return TranslationTable.load(shipped)
    return TranslationTable(lang)
