"""L8 — HTTP server, template engine, servlet dispatch.

Capability equivalent of the reference's web layer (reference:
source/net/yacy/http/Jetty9HttpServerImpl.java,
source/net/yacy/http/servlets/YaCyDefaultServlet.java,
source/net/yacy/server/http/TemplateEngine.java,
source/net/yacy/server/serverObjects.java). The reference embeds Jetty and
dispatches `/<Name>.html` to a compiled htroot class by reflection; here a
stdlib threaded HTTP server dispatches to registered servlet functions and
fills the matching template with the same #[x]# / #(alt)# / #{loop}#
placeholder grammar.
"""

from .objects import ServerObjects
from .templates import TemplateEngine
from .httpd import YaCyHttpServer

__all__ = ["ServerObjects", "TemplateEngine", "YaCyHttpServer"]
