"""SSRF guard — shared loopback/self-target refusal.

Any surface that fetches a USER-SUPPLIED url through the node's loader
(forward proxy, *.yacy rewrite, public getpageinfo) must refuse targets
that resolve to loopback: a fetch FROM localhost is granted localhost
auto-admin by the target, so a remote client could read admin pages
through the node (the round-3 ADVICE high finding). The same predicate
rides every redirect hop via the loader's ``url_filter``.
"""

from __future__ import annotations

import ipaddress
import socket
from urllib.parse import urlsplit


def loopback_target(url: str, loader=None) -> bool:
    """True when the target resolves to loopback/unspecified — refuse.

    With an injected transport (zero-egress tests/simulations) no real
    socket is opened, so DNS proves nothing: only literal loopback
    names/addresses are refusable there."""
    host = urlsplit(url).hostname or ""
    if host.lower() in ("localhost", ""):
        return True
    addrs = []
    try:
        addrs.append(ipaddress.ip_address(host))
    except ValueError:
        if loader is not None and getattr(loader, "transport",
                                          None) is not None:
            return False
        try:
            for info in socket.getaddrinfo(host, None):
                addrs.append(ipaddress.ip_address(info[4][0]))
        except (socket.gaierror, ValueError, OSError):
            return True     # unresolvable: refuse rather than fetch
    return any(a.is_loopback or a.is_unspecified for a in addrs)
