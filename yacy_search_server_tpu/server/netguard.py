"""SSRF guard — shared unsafe-target refusal + DNS-rebinding pin.

Any surface that fetches a USER-SUPPLIED url through the node's loader
(forward proxy, *.yacy rewrite, public getpageinfo) must refuse targets
that resolve to loopback: a fetch FROM localhost is granted localhost
auto-admin by the target, so a remote client could read admin pages
through the node (the round-3 ADVICE high finding). For non-admin
clients the forward proxy and getpageinfo additionally refuse
link-local and RFC1918 targets (169.254.169.254 cloud metadata, LAN
hosts — ADVICE r4 low). The same predicate rides every redirect hop via
the loader's ``url_filter``.

DNS-rebinding TOCTOU: checking a HOSTNAME and then fetching it re-runs
DNS, and a hostile zone can answer differently the second time. The
``addr_guard`` hook closes that hole — the loader's pinned connection
classes resolve once at connect time, apply the guard to the RESOLVED
address, and connect to that same address (crawler/loader.py
``_PinnedHTTPConnection``)."""

from __future__ import annotations

import ipaddress
import socket
from urllib.parse import urlsplit


def refuse_addr(a, allow_private: bool = True) -> bool:
    """Address-level predicate (also used by the loader's connect-time
    pin): loopback/unspecified always refuse; private/link-local refuse
    for surfaces serving non-admin clients."""
    if a.is_loopback or a.is_unspecified:
        return True
    if not allow_private and (a.is_private or a.is_link_local):
        return True
    return False


def unsafe_target(url: str, loader=None, allow_private: bool = True) -> bool:
    """True when the target resolves to a refused address class.

    With an injected transport (zero-egress tests/simulations) no real
    socket is opened, so DNS proves nothing: only literal
    names/addresses are refusable there."""
    host = urlsplit(url).hostname or ""
    if host.lower() in ("localhost", ""):
        return True
    addrs = []
    try:
        addrs.append(ipaddress.ip_address(host))
    except ValueError:
        if loader is not None and getattr(loader, "transport",
                                          None) is not None:
            return False
        try:
            for info in socket.getaddrinfo(host, None):
                addrs.append(ipaddress.ip_address(info[4][0]))
        except (socket.gaierror, ValueError, OSError):
            return True     # unresolvable: refuse rather than fetch
    return any(refuse_addr(a, allow_private) for a in addrs)


def loopback_target(url: str, loader=None) -> bool:
    """The strict predicate (loopback/unspecified only) — used where
    private addresses are legitimate targets, e.g. LAN-federated .yacy
    peers."""
    return unsafe_target(url, loader, allow_private=True)


def private_target(url: str, loader=None) -> bool:
    """The non-admin predicate: loopback + link-local + RFC1918."""
    return unsafe_target(url, loader, allow_private=False)
