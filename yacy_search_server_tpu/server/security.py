"""HTTP security — digest/basic admin auth and per-path access rules.

Capability equivalent of the reference's security handler stack
(reference: source/net/yacy/http/Jetty9YaCySecurityHandler.java:60 —
computes per-path admin requirements from config; YaCyLoginService /
YaCyLegacyCredential — BASIC and DIGEST admin credentials, with the
stored secret being the MD5 of "user:realm:password"; serverClient
config key — client-IP allowlist, defaults/yacy.init:440-445).

Rules implemented here:
- client allowlist: config ``serverClient`` ("*" or comma-separated IP
  prefixes) gates every request (403 otherwise);
- admin paths: servlet names ending ``_p`` plus any globs in config
  ``security.adminPaths``; when ``publicSearchpage`` is false the search
  surface needs admin too (defaults/yacy.init:1143);
- localhost auto-admin when ``adminAccountForLocalhost`` is true;
- HTTP Basic against ``adminAccountName``/``adminAccountPassword`` or
  the stored HA1 digest ``adminDigestHA1``;
- HTTP Digest (RFC 7616, MD5 + qop=auth) against the same credentials.
  Nonces are HMAC-signed timestamps: stateless verification, 10-minute
  validity window (no server-side nonce table; the nc replay counter is
  not tracked — a design degradation vs RFC 7616 noted here).
"""

from __future__ import annotations

import fnmatch
import hashlib
import hmac
import os
import threading
import time


def _md5(s: str) -> str:
    return hashlib.md5(s.encode("utf-8")).hexdigest()


def ha1(user: str, realm: str, password: str) -> str:
    """The stored digest credential (YaCyLegacyCredential shape:
    MD5 of "user:realm:password")."""
    return _md5(f"{user}:{realm}:{password}")


_AUTH_PARAM_RE = None


def _parse_auth_params(header: str) -> dict[str, str]:
    """Parse the comma-separated k=v digest fields. Quoted values may
    contain commas (RFC 7616 quoted-string — e.g. a uri with a comma in
    its query), so this must not naively split on ','."""
    import re
    global _AUTH_PARAM_RE
    if _AUTH_PARAM_RE is None:
        _AUTH_PARAM_RE = re.compile(
            r'([a-zA-Z0-9_-]+)\s*=\s*("(?:[^"\\]|\\.)*"|[^,]*)')
    out: dict[str, str] = {}
    for k, v in _AUTH_PARAM_RE.findall(header):
        v = v.strip()
        if v.startswith('"') and v.endswith('"') and len(v) >= 2:
            v = v[1:-1].replace('\\"', '"')
        out[k.lower()] = v
    return out


class SecurityHandler:
    NONCE_MAX_AGE_S = 600

    def __init__(self, config):
        self.config = config
        self._nonce_key = os.urandom(16)
        # highest nc seen per nonce (bounded LRU): a captured
        # Authorization header must not replay within the nonce validity
        # window
        self._nonce_nc: dict[str, int] = {}
        self._nonce_nc_lock = threading.Lock()

    # -- per-path rules ------------------------------------------------------

    @property
    def realm(self) -> str:
        return self.config.get("adminRealm", "YaCy-AdminUI")

    def client_allowed(self, client_ip: str) -> bool:
        """serverClient allowlist (defaults/yacy.init:440: comma-separated
        client IPs that may connect; '*' = everyone). Localhost is always
        allowed — an operator must never lock themself out of their node."""
        if client_ip in ("127.0.0.1", "::1"):
            return True
        allow = self.config.get("serverClient", "*").strip()
        if allow in ("*", ""):
            return True
        # entries match exactly unless they end with '*' (explicit prefix
        # glob) — '10.0.0.1' must NOT admit 10.0.0.10x by string prefix
        for p in (x.strip() for x in allow.split(",")):
            if not p:
                continue
            if p.endswith("*"):
                if client_ip.startswith(p[:-1]):
                    return True
            elif client_ip == p:
                return True
        return False

    # admin-gated by default beyond the `_p` convention (operators can
    # re-open any of these via security.adminPaths="-Name"):
    # - RegexTest runs re.fullmatch over a fully user-supplied pattern
    #   and CPython's backtracking engine has no timeout — a
    #   catastrophic pattern is a cheap public-CPU DoS (ADVICE r4; the
    #   reference mounts it publicly, a deliberate divergence)
    # - share writes uploaded surrogates into the indexer's intake
    # - CrawlStartSite starts a depth-99 site crawl
    # - ynetSearch relays fetches; ViewImage fetches user urls
    DEFAULT_ADMIN_PATHS = ("RegexTest", "share", "CrawlStartSite",
                           "ynetSearch")

    def admin_required(self, name: str, path: str) -> bool:
        """Does this servlet need admin rights?
        (Jetty9YaCySecurityHandler.checkUrlProtection equivalent)."""
        if name.endswith("_p"):
            return True
        extra = self.config.get("security.adminPaths", "")
        unprotect = {p.strip()[1:].strip() for p in extra.split(",")
                     if p.strip().startswith("-")}
        if name in self.DEFAULT_ADMIN_PATHS and name not in unprotect:
            return True
        for pattern in extra.split(","):
            pattern = pattern.strip()
            if pattern and (fnmatch.fnmatch(name, pattern)
                            or fnmatch.fnmatch(path, pattern)):
                return True
        if not self.config.get_bool("publicSearchpage", True) and \
                name.startswith(("yacysearch", "suggest", "select",
                                 "solr/select", "gsa/search", "opensearch")):
            return True
        return False

    # -- authentication ------------------------------------------------------

    def is_admin(self, client_ip: str, headers, method: str = "GET",
                 uri: str = "/") -> bool:
        if client_ip in ("127.0.0.1", "::1") and self.config.get_bool(
                "adminAccountForLocalhost", True) \
                and self._referer_local(headers):
            return True
        auth = headers.get("authorization", "") or ""
        return self._check_auth_header(auth, method, uri)

    @staticmethod
    def _referer_local(headers) -> bool:
        """The localhost auto-admin grant additionally requires the
        Referer (when present) to name localhost — a browser on the node
        navigated to an attacker page could otherwise drive admin
        requests via DNS rebinding / CSRF (reference:
        Jetty9YaCySecurityHandler referer check)."""
        ref = (headers.get("referer", "") or "").strip()
        if not ref:
            return True
        from urllib.parse import urlsplit
        host = (urlsplit(ref).hostname or "").lower()
        return host in ("localhost", "127.0.0.1", "::1", "")

    def _check_auth_header(self, auth: str, method: str, uri: str) -> bool:
        if auth.lower().startswith("basic "):
            return self._check_basic(auth[6:].strip())
        if auth.lower().startswith("digest "):
            return self._check_digest(auth[7:], method, uri)
        return False

    def _credential_ha1(self, user: str) -> str | None:
        """The HA1 the node compares against: the stored digest if set,
        else derived from the plaintext password config."""
        if user != self.config.get("adminAccountName", "admin"):
            return None
        stored = self.config.get("adminDigestHA1", "")
        if stored:
            return stored.lower()
        pw = self.config.get("adminAccountPassword", "")
        if not pw:
            return None
        return ha1(user, self.realm, pw)

    def _check_basic(self, b64: str) -> bool:
        import base64
        try:
            user, _, pw = base64.b64decode(b64).decode("utf-8").partition(":")
        except Exception:
            return False
        want = self._credential_ha1(user)
        return (want is not None and pw != ""
                and hmac.compare_digest(ha1(user, self.realm, pw), want))

    def _check_digest(self, header: str, method: str, uri: str) -> bool:
        p = _parse_auth_params(header)
        user = p.get("username", "")
        want_ha1 = self._credential_ha1(user)
        if want_ha1 is None:
            return False
        if p.get("realm") != self.realm:
            return False
        nonce = p.get("nonce", "")
        if not self._nonce_valid(nonce):
            return False
        # the client computes the response against the URI it sent; verify
        # against the client's own uri field but require path agreement
        req_uri = p.get("uri", uri)
        if req_uri.split("?", 1)[0] != uri.split("?", 1)[0]:
            return False
        ha2 = _md5(f"{method}:{req_uri}")
        if p.get("qop") == "auth":
            expect = _md5(":".join((want_ha1, nonce, p.get("nc", ""),
                                    p.get("cnonce", ""), "auth", ha2)))
        else:   # RFC 2069 compatibility
            expect = _md5(f"{want_ha1}:{nonce}:{ha2}")
        if not hmac.compare_digest(expect, p.get("response", "")):
            return False
        # replay guard: the nc counter must strictly increase per nonce
        # (RFC 7616 §5.12); only enforced after the response verified so
        # a forged header can't burn a legitimate client's counter.
        # The qop-less RFC 2069 form carries no nc — each success
        # consumes its nonce outright (the client re-auths against the
        # fresh challenge on the next 401).
        if p.get("qop") == "auth":
            try:
                nc = int(p.get("nc", ""), 16)
            except ValueError:
                return False
        else:
            nc = 1 << 62
        with self._nonce_nc_lock:
            if nc <= self._nonce_nc.get(nonce, 0):
                return False
            # move-to-end on update: the cap must evict the LEAST
            # recently used nonce, never an active one still inside its
            # validity window (that would re-open replay under load)
            self._nonce_nc.pop(nonce, None)
            self._nonce_nc[nonce] = nc
            while len(self._nonce_nc) > 1024:
                self._nonce_nc.pop(next(iter(self._nonce_nc)))
        return True

    # -- nonces --------------------------------------------------------------

    def mint_nonce(self) -> str:
        # per-mint randomness: concurrent clients challenged in the same
        # second must get DISTINCT nonces, or the strictly-increasing nc
        # replay counter would 401 whichever client's nc lags
        ts = str(int(time.time()))
        rand = os.urandom(6).hex()
        sig = hmac.new(self._nonce_key, f"{ts}.{rand}".encode(),
                       "sha256").hexdigest()[:24]
        return f"{ts}.{rand}.{sig}"

    def _nonce_valid(self, nonce: str) -> bool:
        ts, _, rest = nonce.partition(".")
        rand, _, sig = rest.partition(".")
        if not ts.isdigit():
            return False
        want = hmac.new(self._nonce_key, f"{ts}.{rand}".encode(),
                        "sha256").hexdigest()[:24]
        if not hmac.compare_digest(want, sig):
            return False
        return (time.time() - int(ts)) <= self.NONCE_MAX_AGE_S

    def challenges(self) -> list[str]:
        """The WWW-Authenticate header values for a 401 (both schemes
        offered, like the reference's DIGEST+legacy-BASIC login service)."""
        return [
            (f'Digest realm="{self.realm}", qop="auth", algorithm=MD5, '
             f'nonce="{self.mint_nonce()}"'),
            f'Basic realm="{self.realm}"',
        ]
