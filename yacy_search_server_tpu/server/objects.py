"""serverObjects — the request/response property multimap.

Capability equivalent of the reference's `serverObjects`
(reference: source/net/yacy/server/serverObjects.java): a string→string
property map shared between servlet and template, with XSS-safe putters
(putHTML/putXML/putJSON escape for their output medium) and loop counters
(put(key, n) + put(f"{key}_{i}_{field}", v) backs the #{key}# template
loop grammar).
"""

from __future__ import annotations

import html
from typing import Any, Iterator


def escape_html(s: str) -> str:
    return html.escape(str(s), quote=True)


def escape_xml(s: str) -> str:
    return (str(s).replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;").replace('"', "&quot;")
            .replace("'", "&apos;"))


def escape_json(s: str) -> str:
    out = []
    for ch in str(s):
        if ch == '"':
            out.append('\\"')
        elif ch == "\\":
            out.append("\\\\")
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\r":
            out.append("\\r")
        elif ch == "\t":
            out.append("\\t")
        elif ord(ch) < 0x20:
            out.append("\\u%04x" % ord(ch))
        else:
            out.append(ch)
    return "".join(out)


class ServerObjects:
    """String-keyed property map; values are stored as strings."""

    def __init__(self, initial: dict | None = None):
        self._map: dict[str, str] = {}
        # when set, the HTTP layer sends this body verbatim instead of
        # rendering a template (structured responses like Solr-shape JSON
        # or PNG graphics, the reference's custom response writers);
        # bytes bodies use raw_ctype as their content type
        self.raw_body: str | bytes | None = None
        self.raw_ctype: str | None = None
        if initial:
            for k, v in initial.items():
                self.put(k, v)

    # -- putters ------------------------------------------------------------

    def put(self, key: str, value: Any) -> None:
        if isinstance(value, bool):
            value = "1" if value else "0"
        self._map[str(key)] = str(value)

    def put_html(self, key: str, value: Any) -> None:
        self._map[str(key)] = escape_html(value)

    def put_xml(self, key: str, value: Any) -> None:
        self._map[str(key)] = escape_xml(value)

    def put_json(self, key: str, value: Any) -> None:
        self._map[str(key)] = escape_json(value)

    def put_num(self, key: str, value) -> None:
        """Grouped-digits number formatting (putNum parity)."""
        if isinstance(value, float):
            self._map[str(key)] = f"{value:,.3f}"
        else:
            self._map[str(key)] = f"{int(value):,}"

    # -- getters ------------------------------------------------------------

    def get(self, key: str, default: str = "") -> str:
        return self._map.get(str(key), default)

    def get_int(self, key: str, default: int = 0) -> int:
        try:
            return int(self._map.get(str(key), ""))
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._map.get(str(key))
        if v is None:
            return default
        return v.lower() in ("1", "true", "on", "yes")

    def __contains__(self, key: str) -> bool:
        return str(key) in self._map

    def __iter__(self) -> Iterator[str]:
        return iter(self._map)

    def items(self):
        return self._map.items()

    def __len__(self) -> int:
        return len(self._map)

    def as_dict(self) -> dict[str, str]:
        return dict(self._map)
