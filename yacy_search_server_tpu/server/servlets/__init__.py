"""Servlet registry — the htroot dispatch table.

The reference compiles `htroot/<Name>.java` classes and invokes their
static `respond(RequestHeader, serverObjects, serverSwitch)` by reflection
(reference: source/net/yacy/http/servlets/YaCyDefaultServlet.java:658,
765-785). Here servlets are plain functions with the same signature,
registered by name; `/<Name>.<ext>` dispatches to the function and then
fills the `<Name>.<ext>` template.
"""

from __future__ import annotations

from typing import Callable

from ..objects import ServerObjects

Servlet = Callable[[dict, ServerObjects, object], ServerObjects]

_REGISTRY: dict[str, Servlet] = {}


def servlet(name: str):
    def deco(fn: Servlet) -> Servlet:
        _REGISTRY[name] = fn
        return fn
    return deco


def lookup(name: str) -> Servlet | None:
    _ensure_loaded()
    return _REGISTRY.get(name)


def names() -> list[str]:
    _ensure_loaded()
    return sorted(_REGISTRY)


_loaded = False


def _ensure_loaded() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    from . import (yacysearch, status, admin, api, boards,  # noqa: F401
                   breadth, federate, gameday, graphics, health, ingest,
                   operator, proxy, monitoring, tail)
