"""Status + network overview servlets.

Capability equivalent of the reference's dashboards (reference:
htroot/Status.java — peer/index/memory summary; htroot/Network.java —
peer table; htroot/api/status_p.java — machine-readable status).
"""

from __future__ import annotations

import time

from ... import __version__
from ..objects import ServerObjects, escape_json
from . import servlet


@servlet("Status")
def respond(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    prop.put("versionpp", __version__)
    prop.put("uptime", int(time.time() - getattr(sb, "started", time.time())))
    prop.put("urlpublictext", sb.index.doc_count())
    prop.put("rwipublictext", sb.index.rwi_size())
    prop.put("indexedcount", getattr(sb, "indexed_count", 0))
    seeddb = getattr(sb, "seeddb", None)
    prop.put("peername",
             escape_json(seeddb.my_seed.name) if seeddb else "localpeer")
    prop.put("activepeers", len(seeddb.active_seeds()) if seeddb else 0)
    noticed = getattr(sb, "noticed", None)
    from ...crawler.frontier import StackType
    prop.put("crawlqueuesize",
             noticed.size(StackType.LOCAL) if noticed else 0)
    import os
    try:
        import resource
        mem = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
    except Exception:
        mem = 0
    prop.put("usedmemory", mem)
    prop.put("pid", os.getpid())
    seed = getattr(getattr(sb, "node", None), "my_seed", None)
    prop.put("myip", getattr(seed, "ip", "") or "127.0.0.1")
    return prop


@servlet("Network")
def respond_network(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    seeddb = getattr(sb, "seeddb", None)
    seeds = list(seeddb.active_seeds()) if seeddb else []
    prop.put("table", len(seeds))
    for i, s in enumerate(seeds):
        p = f"table_{i}_"
        prop.put(p + "hash", s.hash.decode("ascii", "replace"))
        prop.put(p + "name", escape_json(s.name))
        prop.put(p + "address", escape_json(f"{s.ip}:{s.port}"))
        prop.put(p + "urls", getattr(s, "link_count", 0))
        prop.put(p + "rwis", getattr(s, "word_count", 0))
        prop.put(p + "eol", 1 if i < len(seeds) - 1 else 0)
    prop.put("activecount", len(seeds))
    return prop
