"""Admin servlets — crawl control, index control, config, performance.

Capability equivalents of the reference's admin surface (reference:
htroot/Crawler_p.java:89 — crawl start/stop; htroot/IndexControlURLs_p.java
— per-URL index inspection/deletion; htroot/IndexControlRWIs_p.java — term
index control; htroot/ConfigProperties_p.java — raw config editor;
htroot/PerformanceQueues_p.java — pipeline/busy-thread introspection;
htroot/HostBrowser.java — index browsing by host).  The `_p` suffix marks
admin-protected pages, enforced by the HTTP layer exactly as the
reference's security handler does by path.
"""

from __future__ import annotations

from ...utils.hashes import url2hash, word2hash
from ..objects import ServerObjects, escape_json
from . import servlet


@servlet("Crawler_p")
def respond_crawler(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    if "crawlingstart" in post and post.get("crawlingURL"):
        url = post.get("crawlingURL")
        depth = post.get_int("crawlingDepth", 0)
        kwargs = {}
        if post.get("mustmatch"):
            kwargs["crawler_url_must_match"] = post.get("mustmatch")
        if post.get("mustnotmatch"):
            kwargs["crawler_url_must_not_match"] = post.get("mustnotmatch")
        try:
            profile = sb.start_crawl(url, depth=depth, **kwargs)
            prop.put("started", 1)
            prop.put("handle", profile.handle)
            prop.put("info", "")
            # record the action for replay/scheduling (WorkTables parity:
            # every admin action lands in the api table)
            from urllib.parse import quote
            replay = (f"/Crawler_p.html?crawlingstart=1&crawlingURL="
                      f"{quote(url)}&crawlingDepth={depth}")
            # the replay URL must carry the full crawl spec, or scheduled
            # re-crawls would run unfiltered
            if kwargs.get("crawler_url_must_match"):
                replay += ("&mustmatch="
                           + quote(kwargs["crawler_url_must_match"]))
            if kwargs.get("crawler_url_must_not_match"):
                replay += ("&mustnotmatch="
                           + quote(kwargs["crawler_url_must_not_match"]))
            sb.work_tables.record_api_call(
                replay, "Crawler_p", f"crawl start for {url}",
                repeat_count=post.get_int("repeat_count", 0),
                repeat_unit=post.get("repeat_unit", "days"))
        except ValueError as e:
            prop.put("started", 0)
            prop.put("info", escape_json(str(e)))
    else:
        prop.put("started", 0)
        prop.put("info", "")
    profiles = list(sb.profiles.values())
    prop.put("crawlProfiles", len(profiles))
    for i, p in enumerate(profiles):
        pre = f"crawlProfiles_{i}_"
        prop.put(pre + "handle", p.handle)
        prop.put(pre + "name", escape_json(p.name))
        prop.put(pre + "depth", p.depth)
        prop.put(pre + "eol", 1 if i < len(profiles) - 1 else 0)
    from ...crawler.frontier import StackType
    prop.put("localCrawlSize", sb.noticed.size(StackType.LOCAL))
    return prop


@servlet("Steering_p")
def respond_steering(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Shutdown/restart control (reference: htroot/Steering.java; the
    -shutdown CLI verb POSTs here, yacy.java:503-509)."""
    prop = ServerObjects()
    if post.get("shutdown"):
        # delay so this response can leave the socket first
        import threading
        threading.Timer(0.5, sb.shutdown_event.set).start()
        prop.put("info", "shutdown in 0.5s")
    else:
        prop.put("info", "")
    prop.put("uptime_s", int(__import__("time").time() - sb.started))
    return prop


@servlet("IndexControlURLs_p")
def respond_urlcontrol(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    prop.put("found", 0)
    prop.put("deleted", 0)
    url = post.get("urlstring")
    urlhash = post.get("urlhash")
    if url and not urlhash:
        urlhash = url2hash(url).decode("ascii")
    if urlhash:
        h = urlhash.encode("ascii")
        meta = sb.index.metadata.get_by_urlhash(h)
        if meta is not None:
            prop.put("found", 1)
            prop.put("url", escape_json(meta.get("sku", "")))
            prop.put("title", escape_json(meta.get("title", "")))
            prop.put("hash", urlhash)
            prop.put("wordcount", meta.get("wordcount_i", 0))
            if "urldelete" in post:
                sb.index.remove_document(h)
                prop.put("deleted", 1)
    prop.put("urlcount", sb.index.doc_count())
    return prop


@servlet("IndexControlRWIs_p")
def respond_rwicontrol(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    word = post.get("keystring", "").strip().lower()
    prop.put("keystring", escape_json(word))
    prop.put("count", 0)
    prop.put("urls", 0)
    if word:
        th = word2hash(word)
        prop.put("keyhash", th.decode("ascii", "replace"))
        if "deleteterm" in post:
            removed = sb.index.rwi.remove_term(th)
            prop.put("deletedrefs", len(removed))
        plist = sb.index.rwi.get(th)
        prop.put("count", len(plist))
        n = min(len(plist), post.get_int("maxlisted", 25))
        prop.put("urls", n)
        for i in range(n):
            docid = int(plist.docids[i])
            meta = sb.index.get_metadata(docid)
            prop.put(f"urls_{i}_url",
                     escape_json(meta.get("sku", "") if meta else ""))
            prop.put(f"urls_{i}_eol", 1 if i < n - 1 else 0)
    prop.put("rwicount", sb.index.rwi_size())
    return prop


@servlet("ConfigProperties_p")
def respond_config(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    if post.get("key") and "set" in post:
        sb.config.set(post.get("key"), post.get("value", ""))
    keys = sorted(sb.config.keys())
    prop.put("options", len(keys))
    for i, k in enumerate(keys):
        prop.put(f"options_{i}_key", escape_json(k))
        prop.put(f"options_{i}_value", escape_json(sb.config.get(k)))
        prop.put(f"options_{i}_eol", 1 if i < len(keys) - 1 else 0)
    return prop


@servlet("PerformanceQueues_p")
def respond_queues(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    procs = [sb._parse_proc, sb._condense_proc, sb._structure_proc,
             sb._store_proc]
    prop.put("table", len(procs))
    for i, p in enumerate(procs):
        pre = f"table_{i}_"
        m = p.metrics
        prop.put(pre + "name", p.name)
        prop.put(pre + "queued", p.queue.qsize())
        prop.put(pre + "executed", m.processed)
        prop.put(pre + "errors", m.errors)
        prop.put(pre + "avgexecms", f"{m.avg_exec_ms:.3f}")
        prop.put(pre + "workers", m.workers)
        prop.put(pre + "eol", 1 if i < len(procs) - 1 else 0)
    threads = getattr(sb, "threads", None)
    names = threads.names() if threads else []
    prop.put("busythreads", len(names))
    for i, name in enumerate(names):
        bt = threads.get(name)
        pre = f"busythreads_{i}_"
        prop.put(pre + "name", name)
        prop.put(pre + "busycycles", bt.busy_cycles)
        prop.put(pre + "idlecycles", bt.idle_cycles)
        prop.put(pre + "errors", bt.errors)
        prop.put(pre + "eol", 1 if i < len(names) - 1 else 0)
    return prop


@servlet("HostBrowser")
def respond_hostbrowser(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    wanted = post.get("path", "").strip()
    store = sb.index.metadata
    hosts: dict[str, int] = {}
    urls: list[str] = []
    for d in range(store.capacity()):
        m = store.get(d)
        if m is None:
            continue
        h = m.get("host_s", "")
        hosts[h] = hosts.get(h, 0) + 1
        if wanted and h == wanted:
            urls.append(m.get("sku", ""))
    if not wanted:
        top = sorted(hosts.items(), key=lambda t: -t[1])
        prop.put("hosts", len(top))
        for i, (h, c) in enumerate(top):
            prop.put(f"hosts_{i}_host", escape_json(h))
            prop.put(f"hosts_{i}_count", c)
            prop.put(f"hosts_{i}_eol", 1 if i < len(top) - 1 else 0)
        prop.put("files", 0)
    else:
        prop.put("hosts", 0)
        prop.put("files", len(urls))
        for i, u in enumerate(urls):
            prop.put(f"files_{i}_url", escape_json(u))
            prop.put(f"files_{i}_eol", 1 if i < len(urls) - 1 else 0)
    return prop
